"""Asynchronous federated learning (FedAsync-style) over the comm layer.

New capability: the reference's server blocks until EVERY sampled worker
has uploaded before it aggregates (check_whether_all_receive,
fedml_api/distributed/fedavg/FedAVGAggregator.py:50-57), so one straggler
stalls the round for the whole fleet. Here the server updates the global
model on EVERY arrival (Xie et al. 2019, "Asynchronous Federated
Optimization"):

    alpha_eff = alpha / (1 + staleness)^a
    global <- (1 - alpha_eff) * global + alpha_eff * client_net

where staleness = server_version - version_the_client_trained_on. Each
worker gets the fresh global back immediately and keeps training — no
barrier, no idle time. With one worker (or zero staleness and alpha = 1)
this degenerates to sequential SGD on shuffled client shards.

Message flow per worker is strictly request/response (upload -> new model
or done), which makes shutdown deterministic: the server answers every
in-flight upload, so no rank can block on a model that never comes — as
long as every worker LIVES to upload once more. A crash-stop worker used
to hang exactly the terminal handshake (``done_workers == size - 1``
never reached); with ``done_timeout_s > 0`` the server now runs the same
heartbeat-driven bounded termination as the synchronous control plane
(algos/fedavg_distributed.py): workers beat, silent ranks are evicted
from the done-wait, and the run always ends.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Set

import jax
import jax.numpy as jnp

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_TYPE_C2S_HEARTBEAT,
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
    MSG_TYPE_SRV_TICK,
    build_federation_setup,
)
from fedml_tpu.comm import codec as wire_codec
from fedml_tpu.comm.loopback import run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import ChaosSpec, HeartbeatSender
from fedml_tpu.core.compression import tree_spec
from fedml_tpu.core.faults import HeartbeatMonitor
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.obs import trace as obs_trace
from fedml_tpu.obs.registry import MetricsRegistry, payload_nbytes
from fedml_tpu.trainer.local import softmax_ce

MSG_ARG_KEY_MODEL_VERSION = "model_version"
# Strictly increasing per-worker assignment id, echoed in uploads: the
# dedupe key on BOTH ends (the model version cannot serve — the buffered
# tier re-assigns at an unchanged version until the buffer flushes).
MSG_ARG_KEY_TASK_SEQ = "task_seq"

log = logging.getLogger(__name__)


def staleness_weight(alpha: float, staleness: int, a: float = 0.5) -> float:
    """Polynomial staleness discount: alpha / (1 + s)^a."""
    return alpha / float((1 + max(staleness, 0)) ** a)


class FedAsyncServerManager(ServerManager):
    """Mixes every arriving model into the global immediately; the model
    version counts server updates (the async analogue of the round index).
    """

    #: Negotiated delta capability (comm/codec.py DELTA_OK_KEY): whether
    #: this server's ``_ingest`` folds uploads as DELTAS against the
    #: model the client pulled. The pure-async mix consumes FULL models
    #: (``net <- (1-w)·net + w·upload``); the buffered subclass
    #: (fedbuff.py) consumes deltas. Advertised on every init/assignment
    #: handshake, and a stamped upload whose framing mismatches is
    #: REFUSED (evict-and-release) instead of mis-folded — a delta mixed
    #: as a full model (or vice versa) corrupts the global with no error
    #: anywhere.
    _accepts_delta_frames = False

    def __init__(self, args, net, cfg: FedConfig, size: int,
                 backend: str = "LOOPBACK", alpha: float = 0.6,
                 staleness_exp: float = 0.5, eval_fn=None, test_data=None,
                 *, done_timeout_s: Optional[float] = None,
                 metrics=None, flight_dir: Optional[str] = None,
                 clock=time.monotonic, directory=None):
        super().__init__(args, rank=0, size=size, backend=backend)
        # Optional data.directory.ClientDirectory: the production cohort
        # sampler (PR 7) — client assignment draws from its O(clients)
        # count metadata instead of the flat sample_clients law, so a
        # million-client fleet drill samples the same ids a re-sharded
        # deployment would (re-sharding invariance is pinned in
        # tests/test_directory.py).
        self._directory = directory
        self._cohort_cache = None  # (version, sampled ids) memo
        self.net = net
        self.cfg = cfg
        self.alpha = alpha
        self.staleness_exp = staleness_exp
        self.eval_fn = eval_fn
        self.test_data = test_data
        self.version = 0
        self.codec_refusals = 0
        self._spec = tree_spec(net)
        self._wire_decoders = wire_codec.CodecCache()  # spec → WireCodec
        self.staleness_history: List[int] = []
        # Recent OFFERED staleness (admitted or not), bounded: the
        # windowed guard-band signal for the adaptive controller. The
        # registry histogram is cumulative — its p95 can neither recover
        # after a load spike ends nor be read windowed, so a feedback
        # loop keyed on it would latch its emergency posture forever.
        self._stale_recent: Deque[int] = collections.deque(maxlen=64)
        # Accepted-upload order, (worker, base_version) per arrival — the
        # aggregation order the trace-determinism tests pin (sim/).
        self.arrival_log: List[tuple] = []
        self.test_history: List[dict] = []
        self.evictions = 0
        self.duplicate_drops = 0
        self.reassignments = 0
        self.admission_drops = 0
        # Admission cap (fedml_tpu.ctrl): an upload staler than this many
        # versions is refused at the door (still replied — the worker gets
        # a fresh assignment, never a silent drop). 0 = unlimited, the
        # default — bit-equal to the pre-controller tier; the adaptive
        # controller arms/relaxes it through the actuation seam.
        self.max_staleness = 0
        # Stamped by the runners after the run (the sync tier's
        # convention): the final health() snapshot.
        self.final_health: Dict[str, int] = {}
        self._members: Set[int] = set(range(1, size))
        self._done_set: Set[int] = set()
        # Per-worker high-water mark of the ASSIGNMENT SEQUENCE its
        # uploads answer: every assignment carries a strictly increasing
        # per-worker task id, so a repeat upload (ChaosTransport
        # duplication, sender retry after a lost ACK) is dropped WITHOUT
        # reply — mixing it twice would double-count one real update and
        # hand the worker a second live assignment. The id must be the
        # task, not the model version: the buffered tier (fedbuff.py)
        # legitimately re-assigns a worker at an UNCHANGED version until
        # the buffer flushes, so version-keyed dedupe would starve it.
        # Uploads without the task key (older peers, hand-built test
        # messages) fall back to the version — exact pure-async
        # equivalence, where versions do strictly increase per worker.
        self._last_upload_task: Dict[int, int] = {}
        self._task_seq: Dict[int, int] = {}
        # Wall-clock of the last time each worker made request/response
        # progress (assignment sent or upload arrived). The strict
        # request/response flow means a LOST server reply leaves an
        # alive-but-idle worker with nothing to do forever — its beats
        # keep it heartbeat-alive, so the watchdog never fires. Beats
        # from a worker stalled past done_timeout_s get a fresh
        # assignment instead (see _handle_heartbeat).
        self._last_progress: Dict[int, float] = {}
        self._clock = clock
        self._lock = threading.Lock()
        self._stopped = False
        # Ingest observability — the SAME ctrl/ stream and latency
        # histograms as the sync tier (docs/OBSERVABILITY.md; the sync
        # server logged health per round but the async tiers used to
        # stamp only a final snapshot): ``metrics`` gets one ctrl/ row
        # per model-version bump, the flight recorder dumps the recent
        # control-plane ring to ``flight_dir`` on eviction/refusal, and
        # the occupancy clock lives in comm.managers.ServerManager.
        self.metrics = metrics
        self.registry = MetricsRegistry()
        self._h_decode = self.registry.histogram("decode_ms")
        self._h_fold = self.registry.histogram("fold_ms")
        self._h_bytes = self.registry.histogram("bytes_per_upload", lo=1.0)
        self._h_stale = self.registry.histogram("staleness", lo=1.0)
        self._g_queue = self.registry.gauge("ingest_queue_depth")
        # Parallel ingest pool (comm/ingest.py, cfg.ingest_workers > 0).
        # Pure async mixes every arrival into the global immediately —
        # an inherently sequential fold — so HERE the pool only hosts
        # the numpy frame decode (strict request/response semantics and
        # the mix order are unchanged, and any worker count is trivially
        # bit-equal to inline). The buffered subclass (fedbuff.py)
        # defers decode AND fold into the pool and reaps the
        # parallelism; see _defer_decode.
        shards = int(getattr(cfg, "agg_shards", 0) or 0)
        if shards > 0:
            # The sharded aggregation plane (comm/shardplane.py) is a
            # sync-FedAvg capability: pure async mixes every arrival into
            # the global SEQUENTIALLY (order-dependent), and fedbuff's
            # buffer_k barriers on GLOBAL arrival order — neither has an
            # associative partition for M shards to merge. Refuse loudly
            # rather than run an unsharded server under a sharded flag.
            raise ValueError(
                f"agg_shards={shards} is a synchronous-FedAvg capability "
                "(comm/shardplane.py): the async tiers' sequential mix / "
                "global-arrival buffer cannot be partitioned across "
                "aggregator shards — run with agg_shards=0")
        if getattr(cfg, "secagg", False):
            # Pairwise masks only cancel inside ONE summed cohort whose
            # roster is pinned before anyone uploads. The async tiers mix
            # each arrival into the global immediately (pure async) or
            # barrier on global arrival ORDER (fedbuff) — there is no
            # roster-complete sum for the masks to cancel in, so a masked
            # upload would publish mask-sized garbage into the global.
            raise ValueError(
                "secagg is a synchronous-FedAvg capability "
                "(comm/secagg.py): the async tiers have no "
                "roster-complete cohort sum for pairwise masks to cancel "
                "in — run with secagg disabled or the sync tier")
        workers = int(getattr(cfg, "ingest_workers", 0) or 0)
        if workers > 0:
            from fedml_tpu.comm.ingest import IngestPool

            self._pool = IngestPool(workers, registry=self.registry)
            self._g_pool_queue = self.registry.gauge(
                "ingest_pool_queue_depth")
        else:
            self._pool = None
        self.flight = obs_trace.FlightRecorder(
            clock=clock,
            path=(os.path.join(flight_dir, "flight_recorder.jsonl")
                  if flight_dir else None))
        self.done_timeout_s = (cfg.round_timeout_s if done_timeout_s is None
                               else done_timeout_s)
        self.heartbeat = HeartbeatMonitor(
            range(1, size), timeout_s=self.done_timeout_s or 30.0,
            clock=clock)
        self._mix = jax.jit(
            lambda g, c, w: jax.tree.map(
                lambda a_, b_: ((1.0 - w) * a_.astype(jnp.float32)
                                + w * b_.astype(jnp.float32)).astype(a_.dtype),
                g, c))
        # Actuation seam (fedml_tpu.ctrl): the validated, boundary-gated
        # knob surface an attached controller tunes. Building it is inert
        # — knobs only move when something calls apply(); with no
        # controller attached the tier is bit-equal to a build without
        # this subsystem. The mix weight ``w`` is a traced argument of
        # the jitted _mix, so retuning alpha/staleness_exp costs no
        # recompile. done_timeout_s is a knob only when the watchdog was
        # armed at construction — the watchdog thread starts (or not) at
        # run(), so arming it later would be a silent no-op.
        from fedml_tpu.ctrl.actuator import ActuationSeam, Knob

        knobs = [
            Knob("alpha", lambda: self.alpha,
                 lambda v: setattr(self, "alpha", v), 1e-6, 1.0),
            Knob("staleness_exp", lambda: self.staleness_exp,
                 lambda v: setattr(self, "staleness_exp", v), 0.0, 8.0),
            Knob("max_staleness", lambda: self.max_staleness,
                 lambda v: setattr(self, "max_staleness", v),
                 0, 1_000_000, cast=int),
        ]
        if self.done_timeout_s and self.done_timeout_s > 0:
            knobs.append(Knob(
                "done_timeout_s", lambda: self.done_timeout_s,
                self._set_done_timeout, 1e-3, 86400.0))
        if self._pool is not None:
            knobs.append(Knob(
                "ingest_workers", lambda: self._pool.workers,
                lambda v: self._pool.resize(v), 1, 64, cast=int,
                constraint=lambda v: ("pool_shrink_unsupported"
                                      if v < self._pool.workers else None)))
        self.ctrl = ActuationSeam(
            type(self).__name__, knobs, registry=self.registry,
            flight=self.flight, busy=self._ctrl_busy,
            progress=lambda: self.version)

    def _set_done_timeout(self, v: float) -> None:
        # The watchdog loop reads done_timeout_s live each pass; the
        # heartbeat monitor's silence threshold must track it or an
        # extended deadline would still evict on the old one.
        self.done_timeout_s = v
        self.heartbeat.timeout_s = v

    def _ctrl_busy(self) -> Optional[str]:
        """Seam busy probe: the pure-async tier is quiescent between
        handler invocations, and actuations run on the dispatch thread —
        never unsafe. The buffered subclass reports ``mid_flush``."""
        return None

    @property
    def done_workers(self) -> int:
        return len(self._done_set)

    def health(self) -> Dict[str, int]:
        """Control-plane counters + byte ledger — the async twin of the
        sync server's ``health()`` (same stable key names where the
        concept is shared; ``version`` is the async round index,
        ``reassignments`` the async analogue of re-admissions)."""
        ledger = getattr(self.com_manager, "bytes_ledger", None)
        with self._lock:
            return {
                "members": len(self._members),
                "evictions": self.evictions,
                "reassignments": self.reassignments,
                "duplicate_drops": self.duplicate_drops,
                "admission_drops": self.admission_drops,
                "codec_refusals": self.codec_refusals,
                "version": self.version,
                "done_workers": len(self._done_set),
                "send_retries": getattr(self.com_manager, "retry_count", 0),
                "bytes_tx": ledger.total_tx if ledger is not None else 0,
                "bytes_rx": ledger.total_rx if ledger is not None else 0,
            }

    def _log_round_health(self, staleness: int) -> None:
        """One ctrl/ row per model-version bump — the async "round". The
        sync tier logs the same stream per barrier round; emitting it
        here too means a dashboard reads one schema across tiers."""
        if self.metrics is None:
            return
        self.metrics.log({**self.health(), **self.registry.snapshot(),
                          "staleness": staleness},
                         step=self.version, prefix="ctrl")

    def run(self) -> None:
        self.register_message_receive_handlers()
        with self._lock:
            members = sorted(self._members)
        for r in members:  # liveness clocks start when the run starts
            self.heartbeat.beat(r)
        self.send_init_msg()
        if self.done_timeout_s and self.done_timeout_s > 0:
            threading.Thread(target=self._watchdog_loop, daemon=True).start()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self._stopped = True
        if self._pool is not None:
            self._pool.close()
        super().finish()

    def _defer_decode(self) -> bool:
        """True when the pooled path defers the frame decode into its
        ingest task (the buffered tier) instead of decoding before
        ``_ingest`` — the base async tier decodes up front (via the pool
        when one exists, synchronously) because its mix is sequential."""
        return False

    def _decode_upload(self, wcodec: str, payload, **meta):
        """Frame decode, routed through the ingest pool when one is
        configured (the numpy decode releases the GIL there); raises
        :class:`~fedml_tpu.comm.codec.CodecError` either way, so the
        caller's refusal policy is path-independent."""
        if self._pool is None:
            return self._wire_decoders.decode(wcodec, payload, self._spec)
        return self._pool.run(
            lambda: self._wire_decoders.decode(wcodec, payload, self._spec),
            **meta)

    # -- bounded termination (the sync control plane's watchdog, scoped to
    # the done handshake: async progress never blocks on one worker, but
    # the terminal barrier used to) ----------------------------------------
    def _watchdog_loop(self) -> None:
        poll = max(0.005, min(0.05, self.done_timeout_s / 10))
        while not self._stopped:
            with self._lock:
                members = sorted(self._members)
            if not members or self._version_snapshot() >= self.cfg.comm_round:
                failed = self.heartbeat.wait_all_or_failed(
                    members,
                    have=lambda: (members if self._stopped
                                  else self._done_snapshot()),
                    poll_s=poll, deadline_s=self.done_timeout_s)
                if not self._stopped and (failed or not members):
                    self._post_tick(failed)
            else:
                # Mid-run: async progress tolerates any minority of dead
                # workers, but ALL of them dead means the version counter
                # can never reach comm_round — bound that too.
                failed = set(self.heartbeat.failed())
                if failed >= set(members):
                    self._post_tick(sorted(failed))
            time.sleep(poll)

    def _done_snapshot(self) -> List[int]:
        # The watchdog thread reads while the dispatch thread mutates —
        # iterating the live set can raise "Set changed size during
        # iteration", killing the daemon watchdog and silently disabling
        # bounded termination.
        with self._lock:
            return sorted(self._done_set)

    def _version_snapshot(self) -> int:
        # The version counter commits on the dispatch thread (_ingest);
        # the watchdog's termination test must read it under the same
        # lock or it can act on a torn view of the commit.
        with self._lock:
            return self.version

    def _post_tick(self, failed) -> None:
        msg = Message(MSG_TYPE_SRV_TICK, 0, 0)
        msg.add("failed", [int(w) for w in failed])
        try:
            self.send_message(msg)
        except (ConnectionError, OSError):
            pass  # next watchdog pass re-ticks

    def _handle_tick(self, msg: Message) -> None:
        failed = set(msg.get("failed") or [])
        with self._lock:
            evict = [w for w in failed
                     if w in self._members and w not in self._done_set]
            for w in evict:
                self._members.discard(w)
                self.evictions += 1
        if evict:
            log.warning("async server: evicting silent ranks %s", evict)
            self.flight.record("eviction", ranks=evict,
                               version=self.version)
            self.flight.dump()
        self._maybe_finish()

    def _handle_heartbeat(self, msg: Message) -> None:
        worker = msg.get_sender_id()
        self.heartbeat.beat(worker)
        self.flight.record("beat", sender=worker)
        if not (self.done_timeout_s and self.done_timeout_s > 0):
            return
        if self.version >= self.cfg.comm_round:
            # A beat past the target version means the worker never got
            # its done (lost reply, or evicted-but-alive) — re-send it so
            # it can exit instead of beating until idle_timeout_s.
            self._send_done(worker)
            return
        stalled = (self._clock() - self._last_progress.get(worker, 0.0)
                   > self.done_timeout_s)
        if stalled:
            # Request/response recovery: the worker is alive but has no
            # live assignment (its reply was lost, or it was evicted and
            # came back). Hand it fresh work at the CURRENT version —
            # the per-worker upload high-water mark keeps any late
            # original upload idempotent.
            log.warning("async server: worker %d alive but idle past "
                        "done_timeout_s — re-assigning at version %d",
                        worker, self.version)
            self.reassignments += 1
            self.flight.record("reassignment", sender=worker,
                               version=self.version)
            self._send_assignment(worker, recovery=True)

    def _refuse_upload(self, worker: int, err, *, codec=None,
                       task_seq=None) -> None:
        """The async tiers' ONE evict-and-release refusal policy, shared
        by the inline decode path (handle_upload) and the buffered
        tier's pooled flush barrier (fedbuff._flush_buffer): a refusal
        is a deterministic encoder mismatch (resends are bit-identical),
        so neither waiting nor re-assigning can recover the worker —
        evict it and send done=True so it exits cleanly; the run
        finishes when no members remain. (The sync tier keeps its own
        twin with round-completion/abort semantics this tier lacks.)"""
        self.codec_refusals += 1
        log.error("rank %d: codec %r frame refused (%s) — evicting and "
                  "releasing the worker (a mismatched encoder can never "
                  "upload a usable model)", worker, codec, err)
        fields = {"sender": worker, "error": str(err)[:200]}
        if task_seq is not None:
            fields["task_seq"] = task_seq
        if codec is not None:
            fields["codec"] = str(codec)
        self.flight.record("codec_refusal", **fields)
        with self._lock:
            if worker in self._members:
                self._members.discard(worker)
                self.evictions += 1
        self.flight.dump()
        self._send_done(worker)  # release; finishes when empty

    def _evict_dead(self, worker: int, err: BaseException, what: str) -> None:
        """A send failed past the retry policy: evict — guarded, so
        repeated failures to an already-evicted rank don't inflate the
        eviction counter the fault drills assert on."""
        log.warning("%s to worker %d failed (%s): evicting", what, worker, err)
        evicted = False
        with self._lock:
            if worker in self._members:
                self._members.discard(worker)
                self.evictions += 1
                evicted = True
        if evicted:
            self.flight.record("eviction", ranks=[worker],
                               version=self.version, what=what)
            self.flight.dump()

    def _send_done(self, worker: int) -> None:
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add("done", True)
        try:
            self.send_message(out)
            with self._lock:
                self._done_set.add(worker)
        except (ConnectionError, OSError) as err:
            self._evict_dead(worker, err, "done")
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        with self._lock:
            done = self._done_set >= self._members
        if done and not self._stopped:
            self.finish()

    def _next_task(self, worker: int) -> int:
        with self._lock:
            seq = self._task_seq.get(worker, 0)
            self._task_seq[worker] = seq + 1
        return seq

    def _assign_client(self, worker: int) -> int:
        """Deterministic per-(version, worker) client assignment — the
        async analogue of the reference's seeded per-round sampling.
        With a ClientDirectory attached, the draw rides the directory's
        count metadata (the production sampler; invariant under
        re-sharding). The sampled cohort is MEMOIZED per version: the
        draw is O(client_num_in_total) — ~16 ms of dispatch-thread work
        at 2^20 clients — and every worker assigned at one version gets
        a slice of the SAME deterministic cohort, so re-drawing it per
        reply burned a model-fold's worth of GIL per upload for
        identical values (caught by the serving_1m saturation drill)."""
        n = min(self.size - 1, self.cfg.client_num_in_total)
        cache = self._cohort_cache
        if cache is None or cache[0] != self.version:
            if self._directory is not None:
                idx = self._directory.sample_cohort(self.version, n)
            else:
                idx = sample_clients(self.version,
                                     self.cfg.client_num_in_total, n)
            cache = self._cohort_cache = (self.version, idx)
        return int(cache[1][(worker - 1) % len(cache[1])])

    def send_init_msg(self) -> None:
        for worker in range(1, self.size):
            msg = Message(MSG_TYPE_S2C_INIT_CONFIG, 0, worker)
            msg.add(MSG_ARG_KEY_MODEL_PARAMS, self.net)
            msg.add(MSG_ARG_KEY_CLIENT_INDEX, self._assign_client(worker))
            msg.add(MSG_ARG_KEY_MODEL_VERSION, 0)
            msg.add(MSG_ARG_KEY_TASK_SEQ, self._next_task(worker))
            msg.add(wire_codec.OFFER_KEY, wire_codec.codec_offer())
            msg.add(wire_codec.DELTA_OK_KEY, self._accepts_delta_frames)
            self._last_progress[worker] = self._clock()
            try:
                self.send_message(msg)
            except (ConnectionError, OSError) as err:
                # A silo dead at startup must not crash the whole async
                # server (the sync control plane's send_init_msg is
                # evict-and-continue too); the survivors run the
                # federation, a later beat/upload re-admits the rank.
                self._evict_dead(worker, err, "init")
        self._maybe_finish()

    def _send_assignment(self, worker: int, *, recovery: bool = False) -> None:
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add("done", False)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self.net)
        out.add(MSG_ARG_KEY_CLIENT_INDEX, self._assign_client(worker))
        out.add(MSG_ARG_KEY_MODEL_VERSION, self.version)
        out.add(MSG_ARG_KEY_TASK_SEQ, self._next_task(worker))
        out.add(wire_codec.OFFER_KEY, wire_codec.codec_offer())
        out.add(wire_codec.DELTA_OK_KEY, self._accepts_delta_frames)
        if recovery:
            # Stalled-worker recovery: tell the client which TASK we
            # last ACCEPTED from it, so a worker that is merely SLOW (its
            # upload still in flight, or lost) resends its cached upload
            # instead of training this extra assignment — beats arriving
            # every done_timeout_s during one long local round must not
            # backlog an unbounded queue of live assignments.
            out.add("recovery", True)
            with self._lock:
                out.add("expected", self._last_upload_task.get(worker, -1))
        self._last_progress[worker] = self._clock()
        try:
            self.send_message(out)
        except (ConnectionError, OSError) as err:
            self._evict_dead(worker, err, "assignment")
            self._maybe_finish()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_upload)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_HEARTBEAT, self._handle_heartbeat)
        self.register_message_receive_handler(
            MSG_TYPE_SRV_TICK, self._handle_tick)

    def handle_upload(self, msg: Message) -> None:
        worker = msg.get_sender_id()
        self.heartbeat.beat(worker)
        with self._lock:
            if worker not in self._members:
                self._members.add(worker)  # returned after eviction
        if self.version >= self.cfg.comm_round:
            # Target version reached while this upload was in flight:
            # discard it (mixing would overshoot comm_round) and release
            # the worker.
            self._send_done(worker)
            return
        base_ver = int(msg.get(MSG_ARG_KEY_MODEL_VERSION))
        task = msg.get(MSG_ARG_KEY_TASK_SEQ)
        task = base_ver if task is None else int(task)
        with self._lock:
            if task <= self._last_upload_task.get(worker, -1):
                self.duplicate_drops += 1
                self.flight.record("duplicate_drop", sender=worker,
                                   task_seq=task)
                # Deliberate reply-less drop: the FIRST copy of this
                # task was already answered with an assignment —
                # replying again would hand the worker two live tasks.
                # fedlint: disable=P2(duplicate delivery; the first copy was replied to, a second reply double-assigns)
                return
            self._last_upload_task[worker] = task
        # Negotiated delta capability (PR 15): a STAMPED upload whose
        # framing mismatches what this tier's _ingest consumes would be
        # silently mis-folded (a delta mixed as a full model, or a full
        # model buffered as a delta) — refuse it like a corrupt frame.
        # Unstamped (legacy / hand-built protocol-test) messages keep
        # the tier's historical interpretation.
        stamped_delta = msg.get(wire_codec.DELTA_KEY)
        if (stamped_delta is not None
                and bool(stamped_delta) != self._accepts_delta_frames):
            self._refuse_upload(worker, ValueError(
                f"upload framed {'delta' if stamped_delta else 'full-model'}"
                f" but this server ingests "
                f"{'deltas' if self._accepts_delta_frames else 'full models'}"
                " — negotiate the delta capability (DELTA_OK_KEY) or run "
                "the matching tier"), task_seq=task)
            return
        tr = obs_trace.active()
        ck = obs_trace.corr(round=self.version, sender=worker,
                            task_seq=task)
        self._h_bytes.record(
            payload_nbytes(msg.get(MSG_ARG_KEY_MODEL_PARAMS)))
        depth = getattr(self.com_manager, "inbox_depth", None)
        if depth is not None:
            depth = depth()
            if depth is not None:
                self._g_queue.set(depth)
        if self._pool is not None:
            self._g_pool_queue.set(self._pool.queue_depth())
        wcodec = msg.get(wire_codec.CODEC_KEY)
        if wcodec and not self._defer_decode():
            # Wire-codec frame (comm/codec.py): self-described, decoded
            # pickle-free against the server's model spec. A corrupt
            # frame is REFUSED (never mixed); the transport guarantees
            # frame integrity, so a refusal means a mismatched/corrupt
            # ENCODER whose every future upload would refuse too —
            # re-assigning would spin train→refuse→reassign forever.
            # Evict AND RELEASE the worker (done=True → clean exit);
            # the run finishes when no members remain (sync-tier
            # policy, fedavg_distributed.py).
            try:
                t0 = time.perf_counter()
                with tr.span("ingest.decode", cat="ingest", corr=ck,
                             codec=wcodec):
                    msg.add(MSG_ARG_KEY_MODEL_PARAMS,
                            self._decode_upload(
                                wcodec, msg.get(MSG_ARG_KEY_MODEL_PARAMS),
                                sender=worker, task_seq=task))
                self._h_decode.record((time.perf_counter() - t0) * 1e3)
            except (wire_codec.CodecError, ValueError) as err:
                self._refuse_upload(worker, err, codec=wcodec,
                                    task_seq=task)
                return
        staleness = self.version - base_ver
        # Offered staleness is recorded for EVERY arrival, admitted or
        # not — the controller's guard band must see the load the fleet
        # offers, not the load the cap lets through (a cap-filtered p95
        # would collapse the moment the cap arms and thrash the loop).
        self._h_stale.record(staleness)
        self._stale_recent.append(staleness)
        cap = self.max_staleness
        if cap and staleness > cap:
            # Admission control (fedml_tpu.ctrl): staler than the armed
            # cap — refuse at the door instead of paying decode+fold for
            # an update whose discounted weight is noise. Reply
            # discipline still holds: the worker gets a fresh assignment
            # at the current version, never a silent drop.
            self.admission_drops += 1
            self.registry.counter("admission_drops").inc()
            self.flight.record("admission_drop", sender=worker,
                               staleness=staleness, cap=cap,
                               version=self.version)
            self.flight.dump()
            self._send_assignment(worker)
            return
        self.staleness_history.append(staleness)
        self.arrival_log.append((worker, base_ver))
        v0 = self.version
        t0 = time.perf_counter()
        with tr.span("ingest.fold", cat="ingest", corr=ck,
                     staleness=staleness):
            self._ingest(msg, staleness)
        self._h_fold.record((time.perf_counter() - t0) * 1e3)
        if self.version != v0:
            self.flight.record("version_commit", version=self.version,
                               sender=worker)
            self._log_round_health(staleness)
        if (self.version != v0 and self.eval_fn is not None
                and self.test_data is not None and
                (self.version % self.cfg.frequency_of_the_test == 0
                 or self.version >= self.cfg.comm_round)):
            m = self.eval_fn(self.net, *self.test_data)
            self.test_history.append(
                {"version": self.version, "staleness": staleness,
                 **{k: float(v) for k, v in m.items()}})
        if self.version != v0:
            # Safe actuation boundary: the version just committed (for
            # the buffered subclass, the flush completed inside _ingest),
            # telemetry and eval are current, and we are on the dispatch
            # thread — knob mutations cannot race a fold.
            self._ctrl_boundary()
        if self.version >= self.cfg.comm_round:
            self._send_done(worker)
            return
        with self._lock:
            if worker not in self._members:
                # Evicted during _ingest (the buffered tier's pooled
                # flush refuses corrupt frames at its barrier and
                # releases the sender with a done) — don't hand a
                # released worker fresh work.
                return
        self._send_assignment(worker)

    def _ingest(self, msg: Message, staleness: int) -> None:
        """Fold one accepted upload into the server state. The async
        server mixes immediately (every arrival is a model version); the
        buffered subclass (algos/fedbuff.py) accumulates and bumps the
        version only every ``buffer_k``-th arrival — the surrounding
        protocol (dedupe, terminal handshake, recovery) is shared."""
        w = staleness_weight(self.alpha, staleness, self.staleness_exp)
        self.net = self._mix(self.net, msg.get(MSG_ARG_KEY_MODEL_PARAMS),
                             jnp.float32(w))
        # Commit the version under the lock: the watchdog's termination
        # test (_version_snapshot) races this increment otherwise.
        with self._lock:
            self.version += 1


class FedAsyncClientManager(ClientManager):
    """Train on the latest received model, upload tagged with the model
    version it was based on, wait for the next model (or done). Beats
    every ``cfg.heartbeat_interval_s`` (or ``beat_interval_s``) so the
    server's bounded-termination watchdog sees it alive, and self-
    terminates after ``idle_timeout_s`` without server contact."""

    #: Whether ``_upload_payload`` ships a DELTA against the pulled model
    #: (fedbuff) or the full trained model (async). Sparsifying codecs
    #: are only sound on deltas — top-k of full weights would zero most
    #: of the model — so the constructor gates on this.
    _payload_is_delta = False

    def __init__(self, args, rank: int, size: int, train_fed: FederatedArrays,
                 local_train, cfg: FedConfig, backend: str = "LOOPBACK",
                 wire_codec_spec: str = "none", *,
                 beat_interval_s: Optional[float] = None,
                 idle_timeout_s: float = 0.0):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.train_fed = train_fed
        self.local_train = local_train
        self.cfg = cfg
        self.steps = 0
        self.duplicate_drops = 0
        self.upload_resends = 0
        # Wire codec (comm/codec.py), negotiated against the server's
        # handshake offer on the first assignment. Validated eagerly.
        probe = wire_codec.make_wire_codec(wire_codec_spec)
        if probe.error_feedback and not self._payload_is_delta:
            raise ValueError(
                f"wire codec {wire_codec_spec!r}: sparsifying codecs need "
                "delta uploads — the async tier ships full models (use "
                "bf16/fp16/int8 here, or the FedBuff tier for top-k/"
                "randmask with error feedback)")
        self._codec_requested = wire_codec_spec or "none"
        self._codec = None  # set by negotiation on the first assignment
        # Per-worker error-feedback residual: the async tiers' EF stream
        # is the worker's own upload sequence (one delta per assignment).
        self._ef_residual = None
        # Assigned TASK ids strictly increase, so an assignment at or
        # below the high-water mark is a transport duplicate — dropped
        # without retraining (the sync client's round dedupe, keyed on
        # the round counter instead). The id must be the task, not the
        # model version: the buffered tier re-assigns at an unchanged
        # version until the buffer flushes (assignments without the key
        # fall back to the version — pure-async equivalence).
        self._last_task = -1
        # Cached last upload + the task it answers: a recovery
        # assignment whose ``expected`` is below that task means the
        # server never saw our latest upload (in flight, or lost) —
        # resend the cache instead of training the recovery assignment.
        self._last_upload: Optional[Message] = None
        self._last_upload_task = -1
        self._beats = HeartbeatSender(
            self._send_beat,
            interval_s=(cfg.heartbeat_interval_s if beat_interval_s is None
                        else beat_interval_s),
            idle_timeout_s=idle_timeout_s,
            on_idle=self.finish)

    def run(self) -> None:
        self._beats.start()
        super().run()

    def finish(self) -> None:
        self._beats.stop()
        super().finish()

    def _send_beat(self) -> None:
        self.send_message(Message(MSG_TYPE_C2S_HEARTBEAT, self.rank, 0))

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_model)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_model)

    def handle_model(self, msg: Message) -> None:
        self._beats.touch()
        if msg.get("done"):
            self.finish()
            return
        c = int(msg.get(MSG_ARG_KEY_CLIENT_INDEX))
        version = int(msg.get(MSG_ARG_KEY_MODEL_VERSION))
        task = msg.get(MSG_ARG_KEY_TASK_SEQ)
        task = version if task is None else int(task)
        if msg.get("recovery"):
            exp = msg.get("expected")
            exp = int(exp) if exp is not None else -1
            if self._last_upload is not None and self._last_upload_task > exp:
                # The server thinks we are idle, but our latest upload
                # postdates what it has accepted: it is in flight or was
                # lost. Resend the cache (idempotent at the server's
                # per-worker version high-water mark) instead of training
                # the recovery assignment — a slow worker must not
                # accumulate a backlog of live assignments, one per
                # done_timeout_s of a long local round.
                self.upload_resends += 1
                self.send_message(self._last_upload)
                return
        if task <= self._last_task:
            # Transport duplicate (ChaosTransport dup of an assignment):
            # retraining it would upload a copy the server drops anyway.
            self.duplicate_drops += 1
            return
        self._last_task = task
        if self._codec is None:
            # Negotiate once per connection against the server's offer
            # (absent offer = codec-ignorant peer → loud fallback).
            self._codec = wire_codec.negotiated_codec(
                self._codec_requested, msg.get(wire_codec.OFFER_KEY),
                peer="server")
            if self._payload_is_delta:
                # Delta capability (PR 15): this client's uploads are
                # deltas against the pulled model — a server that never
                # advertised delta acceptance would mix them as full
                # models. No safe fallback exists; refuse loudly.
                wire_codec.require_delta_peer(
                    msg.get(wire_codec.DELTA_OK_KEY), peer="server")
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.steps),
            self.rank)
        self.steps += 1
        global_net = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        net, loss = self.local_train(
            global_net,
            self.train_fed.x[c], self.train_fed.y[c], self.train_fed.mask[c],
            rng)
        out = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        payload = self._upload_payload(net, global_net)
        if self._codec is not None and self._codec.name != "none":
            # Frame seed keyed on (run seed, rank, task): a cached resend
            # re-ships identical bytes; every new task gets fresh
            # stochastic rounding / mask draws.
            payload, self._ef_residual = self._codec.encode(
                payload, self._ef_residual,
                wire_codec.frame_seed(self.cfg.seed, self.rank, task))
            out.add(wire_codec.CODEC_KEY, self._codec.name)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, payload)
        # Self-describing framing (PR 15): the server refuses a stamp
        # that mismatches its ingest instead of mis-folding it.
        out.add(wire_codec.DELTA_KEY, self._payload_is_delta)
        out.add(MSG_ARG_KEY_NUM_SAMPLES, int(self.train_fed.counts[c]))
        out.add(MSG_ARG_KEY_MODEL_VERSION, version)
        out.add(MSG_ARG_KEY_TASK_SEQ, task)
        self._last_upload = out
        self._last_upload_task = task
        self.send_message(out)

    def _upload_payload(self, net, global_net):
        """What goes on the wire: the async protocol ships the full
        trained model; the buffered subclass ships the client-side DELTA
        against the model it trained from (the server keeps no version
        history, so only the client can form it)."""
        return jax.device_get(net)


def FedML_FedAsync_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    alpha: float = 0.6,
    staleness_exp: float = 0.5,
    *,
    wire_codec: str = "none",
    loopback_wire: str = "none",
    chaos: Optional[ChaosSpec] = None,
    done_timeout_s: Optional[float] = None,
    idle_timeout_s: float = 0.0,
    metrics=None,
    trace_dir: Optional[str] = None,
    pretrained_params=None,
    controller=None,
):
    """Run the async federation: ``cfg.comm_round`` server model updates
    (arrivals, not barrier rounds) across ``cfg.client_num_per_round``
    workers. Returns the server manager (net, staleness/test history).
    ``done_timeout_s`` (default ``cfg.round_timeout_s``) bounds the
    terminal handshake against crash-stop workers; ``chaos`` installs the
    fleet-wide fault-injecting transport; ``wire_codec`` compresses the
    uploads (full models here, so casts/quantization only — comm/codec.py)
    and ``loopback_wire`` makes loopback serialize for real. ``metrics``
    (a MetricsLogger) gets one ctrl/ health row per model version;
    ``trace_dir`` arms the flight recorder + span tracer exactly as on
    the sync tier (obs/trace.py)."""
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn, chaos=chaos,
        loopback_wire=loopback_wire, pretrained_params=pretrained_params)
    server = FedAsyncServerManager(args, net0, cfg, size, backend=backend,
                                   alpha=alpha, staleness_exp=staleness_exp,
                                   eval_fn=eval_fn, test_data=test_global,
                                   done_timeout_s=done_timeout_s,
                                   metrics=metrics, flight_dir=trace_dir)
    if controller is not None:
        # Adaptive control (fedml_tpu.ctrl): the same controller object
        # that drove the fleet simulator drives this live run — it steps
        # from the server's safe-boundary hook, owning no thread itself.
        server.attach_controller(controller)
    clients = [
        FedAsyncClientManager(args, rank, size, train_fed, local_train, cfg,
                              backend=backend, wire_codec_spec=wire_codec,
                              idle_timeout_s=idle_timeout_s)
        for rank in range(1, size)
    ]
    with obs_trace.tracing_to(trace_dir):
        run_workers([server.run] + [c.run for c in clients])
    server.final_health = server.health()
    server.adapter_holder = args.adapter_holder
    return server
