"""Asynchronous federated learning (FedAsync-style) over the comm layer.

New capability: the reference's server blocks until EVERY sampled worker
has uploaded before it aggregates (check_whether_all_receive,
fedml_api/distributed/fedavg/FedAVGAggregator.py:50-57), so one straggler
stalls the round for the whole fleet. Here the server updates the global
model on EVERY arrival (Xie et al. 2019, "Asynchronous Federated
Optimization"):

    alpha_eff = alpha / (1 + staleness)^a
    global <- (1 - alpha_eff) * global + alpha_eff * client_net

where staleness = server_version - version_the_client_trained_on. Each
worker gets the fresh global back immediately and keeps training — no
barrier, no idle time. With one worker (or zero staleness and alpha = 1)
this degenerates to sequential SGD on shuffled client shards.

Message flow per worker is strictly request/response (upload -> new model
or done), which makes shutdown deterministic: the server answers every
in-flight upload, so no rank can block on a model that never comes.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
    build_federation_setup,
)
from fedml_tpu.comm.loopback import run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.trainer.local import softmax_ce

MSG_ARG_KEY_MODEL_VERSION = "model_version"


def staleness_weight(alpha: float, staleness: int, a: float = 0.5) -> float:
    """Polynomial staleness discount: alpha / (1 + s)^a."""
    return alpha / float((1 + max(staleness, 0)) ** a)


class FedAsyncServerManager(ServerManager):
    """Mixes every arriving model into the global immediately; the model
    version counts server updates (the async analogue of the round index).
    """

    def __init__(self, args, net, cfg: FedConfig, size: int,
                 backend: str = "LOOPBACK", alpha: float = 0.6,
                 staleness_exp: float = 0.5, eval_fn=None, test_data=None):
        super().__init__(args, rank=0, size=size, backend=backend)
        self.net = net
        self.cfg = cfg
        self.alpha = alpha
        self.staleness_exp = staleness_exp
        self.eval_fn = eval_fn
        self.test_data = test_data
        self.version = 0
        self.done_workers = 0
        self.staleness_history: List[int] = []
        self.test_history: List[dict] = []
        self._mix = jax.jit(
            lambda g, c, w: jax.tree.map(
                lambda a_, b_: ((1.0 - w) * a_.astype(jnp.float32)
                                + w * b_.astype(jnp.float32)).astype(a_.dtype),
                g, c))

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_init_msg()
        self.com_manager.handle_receive_message()

    def _assign_client(self, worker: int) -> int:
        """Deterministic per-(version, worker) client assignment — the
        async analogue of the reference's seeded per-round sampling."""
        idx = sample_clients(self.version, self.cfg.client_num_in_total,
                             min(self.size - 1, self.cfg.client_num_in_total))
        return int(idx[(worker - 1) % len(idx)])

    def send_init_msg(self) -> None:
        for worker in range(1, self.size):
            msg = Message(MSG_TYPE_S2C_INIT_CONFIG, 0, worker)
            msg.add(MSG_ARG_KEY_MODEL_PARAMS, self.net)
            msg.add(MSG_ARG_KEY_CLIENT_INDEX, self._assign_client(worker))
            msg.add(MSG_ARG_KEY_MODEL_VERSION, 0)
            self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_upload)

    def handle_upload(self, msg: Message) -> None:
        worker = msg.get_sender_id()
        if self.version >= self.cfg.comm_round:
            # Target version reached while this upload was in flight:
            # discard it (mixing would overshoot comm_round) and release
            # the worker.
            out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
            out.add("done", True)
            self.send_message(out)
            self.done_workers += 1
            if self.done_workers == self.size - 1:
                self.finish()
            return
        staleness = self.version - int(msg.get(MSG_ARG_KEY_MODEL_VERSION))
        w = staleness_weight(self.alpha, staleness, self.staleness_exp)
        self.net = self._mix(self.net, msg.get(MSG_ARG_KEY_MODEL_PARAMS),
                             jnp.float32(w))
        self.version += 1
        self.staleness_history.append(staleness)
        if (self.eval_fn is not None and self.test_data is not None and
                (self.version % self.cfg.frequency_of_the_test == 0
                 or self.version >= self.cfg.comm_round)):
            m = self.eval_fn(self.net, *self.test_data)
            self.test_history.append(
                {"version": self.version, "staleness": staleness,
                 **{k: float(v) for k, v in m.items()}})
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        if self.version >= self.cfg.comm_round:
            out.add("done", True)
            self.send_message(out)
            self.done_workers += 1
            if self.done_workers == self.size - 1:
                self.finish()
            return
        out.add("done", False)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self.net)
        out.add(MSG_ARG_KEY_CLIENT_INDEX, self._assign_client(worker))
        out.add(MSG_ARG_KEY_MODEL_VERSION, self.version)
        self.send_message(out)


class FedAsyncClientManager(ClientManager):
    """Train on the latest received model, upload tagged with the model
    version it was based on, wait for the next model (or done)."""

    def __init__(self, args, rank: int, size: int, train_fed: FederatedArrays,
                 local_train, cfg: FedConfig, backend: str = "LOOPBACK"):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.train_fed = train_fed
        self.local_train = local_train
        self.cfg = cfg
        self.steps = 0

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_model)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_model)

    def handle_model(self, msg: Message) -> None:
        if msg.get("done"):
            self.finish()
            return
        c = int(msg.get(MSG_ARG_KEY_CLIENT_INDEX))
        version = int(msg.get(MSG_ARG_KEY_MODEL_VERSION))
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.steps),
            self.rank)
        self.steps += 1
        net, loss = self.local_train(
            msg.get(MSG_ARG_KEY_MODEL_PARAMS),
            self.train_fed.x[c], self.train_fed.y[c], self.train_fed.mask[c],
            rng)
        out = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, jax.device_get(net))
        out.add(MSG_ARG_KEY_NUM_SAMPLES, int(self.train_fed.counts[c]))
        out.add(MSG_ARG_KEY_MODEL_VERSION, version)
        self.send_message(out)


def FedML_FedAsync_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    alpha: float = 0.6,
    staleness_exp: float = 0.5,
):
    """Run the async federation: ``cfg.comm_round`` server model updates
    (arrivals, not barrier rounds) across ``cfg.client_num_per_round``
    workers. Returns the server manager (net, staleness/test history)."""
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn)
    server = FedAsyncServerManager(args, net0, cfg, size, backend=backend,
                                   alpha=alpha, staleness_exp=staleness_exp,
                                   eval_fn=eval_fn, test_data=test_global)
    clients = [
        FedAsyncClientManager(args, rank, size, train_fed, local_train, cfg,
                              backend=backend)
        for rank in range(1, size)
    ]
    run_workers([server.run] + [c.run for c in clients])
    return server
