"""Hierarchical FedAvg: clients → groups → global.

Parity: fedml_api/standalone/hierarchical_fl/ — per global round, sampled
clients are grouped; each group runs ``group_comm_round`` inner FedAvg
rounds over its sampled clients (group.py:24-46), then the global model is
the sample-count-weighted average of group models (trainer.py:43-69).
(The reference snapshot's import of ``fedavg_trainer`` is broken —
SURVEY.md §2.4; the semantics implemented here are the documented ones.)

Invariant carried from the reference CI (CI-script-fedavg.sh:49-56): with
full participation + full batch + 1 local epoch, a fixed product of
global×group rounds yields the same model regardless of group count
(asserted exactly in tests/test_algos.py).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.sampling import pad_to_multiple
from fedml_tpu.core.tree import tree_weighted_mean
from fedml_tpu.data.batching import gather_clients


class HierarchicalFedAvgAPI(FedAvgAPI):
    """``group_ids[client] -> group`` assigns every client to a group;
    ``cfg.group_comm_round`` controls the inner loop."""

    supports_streaming = False  # per-group device gathers bypass run_round

    def __init__(self, model, train_fed, test_global, cfg, group_ids: Sequence[int],
                 mesh=None, **kwargs):
        super().__init__(model, train_fed, test_global, cfg, mesh=mesh, **kwargs)
        self.group_ids = np.asarray(group_ids)
        if len(self.group_ids) != cfg.client_num_in_total:
            raise ValueError("group_ids must have one entry per client")
        if cfg.group_comm_round < 1:
            raise ValueError(f"group_comm_round must be >= 1, got {cfg.group_comm_round}")

    def train_one_round(self, round_idx: int):
        idx, wmask = self.sample_round(round_idx)
        idx = idx[np.asarray(wmask) > 0]  # grouping handles padding itself
        group_nets, group_weights, losses = [], [], []
        for g in np.unique(self.group_ids[idx]):
            g_idx = idx[self.group_ids[idx] == g]
            # Pad to a power-of-two multiple of n_shards: bounds the number
            # of distinct XLA programs at O(log client_num_per_round)
            # instead of one recompile per distinct group size per round.
            target = self.n_shards
            while target < len(g_idx):
                target *= 2
            g_idx_p, g_mask = pad_to_multiple(g_idx, target)
            sub = gather_clients(self.train_fed, g_idx_p)
            weights = sub.counts.astype(jnp.float32) * jnp.asarray(g_mask)
            net_g = self.net
            for _ in range(self.cfg.group_comm_round):
                # fedlint: disable=R1(deliberate round-order chain: group sub-rounds consume the same stream the flat host loop would, in round order; prefix-stable in the round count)
                self.rng, rnd_rng = jax.random.split(self.rng)
                net_g, loss = self.round_fn(
                    net_g, sub.x, sub.y, sub.mask, weights, weights, rnd_rng
                )
            group_nets.append(net_g)
            group_weights.append(float(np.asarray(weights).sum()))
            losses.append(float(loss))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *group_nets)
        self.net = tree_weighted_mean(stacked, jnp.asarray(group_weights))
        w = np.asarray(group_weights) / max(sum(group_weights), 1e-12)
        return {"round": round_idx, "train_loss": float((w * np.asarray(losses)).sum())}
