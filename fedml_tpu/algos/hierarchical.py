"""Hierarchical FedAvg: clients → groups → global.

Parity: fedml_api/standalone/hierarchical_fl/ — per global round, sampled
clients are grouped; each group runs ``group_comm_round`` inner FedAvg
rounds over its sampled clients (group.py:24-46), then the global model is
the sample-count-weighted average of group models (trainer.py:43-69).
(The reference snapshot's import of ``fedavg_trainer`` is broken —
SURVEY.md §2.4; the semantics implemented here are the documented ones.)

Invariant carried from the reference CI (CI-script-fedavg.sh:49-56): with
full participation + full batch + 1 local epoch, a fixed product of
global×group rounds yields the same model regardless of group count
(asserted exactly in tests/test_algos.py).

Beyond the reference, this is the HOST-SIDE half of the hierarchical
sparse reduction (arXiv:1903.05133 shape; the mesh half is
``parallel/shard.make_sharded_round(group_reduce=True)``):

- **Streaming**: per-group cohorts gather through the layout-agnostic
  ``_group_cohort`` — ``FederatedStore.gather_cohort`` on a host store
  (including the sharded million-client ``ShardedFederatedStore``,
  data/directory.py), device ``gather_clients`` on the resident layout —
  so hierarchical rounds stream like every other algorithm (equivalence
  vs the resident path tested).
- **Sparse global step**: only the groups that SAMPLED clients this
  round produce partials and enter the global reduction — at
  reference-cohort sizes (50 of 342k clients) that is a handful of the
  G groups, and the global step touches exactly those.
- **Composable robust aggregation**: with a ``group_composable``
  ``cfg.aggregator`` (coord_median, trimmed_mean<beta>) each group's
  inner rounds aggregate its clients robustly (the aggregator is baked
  into ``round_fn``) and the global step applies the SAME statistic
  across the group partials — median-of-medians / trim-of-trims, the
  hierarchical robust construction. Non-composable aggregators (krum,
  geometric_median) are refused loudly at construction: their exact
  semantics need the flat FedAvg family's full-cohort path.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.sampling import pad_to_multiple
from fedml_tpu.core.tree import tree_weighted_mean
from fedml_tpu.data.batching import gather_clients


class HierarchicalFedAvgAPI(FedAvgAPI):
    """``group_ids[client] -> group`` assigns every client to a group;
    ``cfg.group_comm_round`` controls the inner loop."""

    supports_streaming = True  # group cohorts ride _group_cohort
    composes_group_aggregation = True  # two-stage robust aggregation

    #: Carry capability record: opted out with the reason every scan-tier
    #: guard raises. The global reduce is pure, but the ROUND is a host
    #: loop over a per-round-variable set of groups, each running
    #: group_comm_round inner rounds — no fixed-shape step exists to scan.
    window_protocol = None
    window_exclusion = (
        "each round trains a data-dependent number of groups for "
        "group_comm_round inner rounds on host — the per-round work has "
        "no fixed scan shape; the mesh-shard analogue (cfg.group_reduce "
        "on the flat FedAvg family) rides every tier instead")

    def __init__(self, model, train_fed, test_global, cfg, group_ids: Sequence[int],
                 mesh=None, **kwargs):
        super().__init__(model, train_fed, test_global, cfg, mesh=mesh, **kwargs)
        self.group_ids = np.asarray(group_ids)
        if len(self.group_ids) != cfg.client_num_in_total:
            raise ValueError("group_ids must have one entry per client")
        if cfg.group_comm_round < 1:
            raise ValueError(f"group_comm_round must be >= 1, got {cfg.group_comm_round}")
        if getattr(cfg, "group_reduce", False):
            raise NotImplementedError(
                "HierarchicalFedAvgAPI already groups host-side; "
                "cfg.group_reduce (the mesh-shard grouping) would nest a "
                "second grouping inside each group's round — drop one")

    def _group_cohort(self, g_idx_p):
        """The group's padded cohort as a ``FederatedArrays`` — host
        gather on a (possibly sharded) ``FederatedStore``, device gather
        on the resident layout. The streaming seam that used to force
        ``supports_streaming = False``."""
        if self._streaming:
            return self.train_fed.gather_cohort(np.asarray(g_idx_p))
        return gather_clients(self.train_fed, jnp.asarray(g_idx_p))

    def _global_reduce(self, group_nets, group_weights):
        """The sparse global step over the ROUND's participating groups:
        weighted mean (the reference semantics, bit-equal to the
        pre-refactor path) or, with a composable ``cfg.aggregator``, the
        same robust statistic across group partials — each group one
        vote, ``weight > 0`` the participation gate (a group whose
        sampled clients were all empty drops out)."""
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *group_nets)
        gw = jnp.asarray(group_weights, jnp.float32)
        if self._aggregator.is_mean:
            return tree_weighted_mean(stacked, gw)
        agg = self._aggregator(stacked, gw)
        any_ok = jnp.sum(jnp.where(gw > 0, 1.0, 0.0)) > 0
        return jax.tree.map(lambda a, p: jnp.where(any_ok, a, p),
                            agg, self.net)

    def train_one_round(self, round_idx: int):
        from fedml_tpu.obs import trace as obs_trace
        from fedml_tpu.obs.registry import payload_nbytes

        tr = obs_trace.active()
        traced = tr is not obs_trace.NULL
        idx, wmask = self.sample_round(round_idx)
        idx = idx[np.asarray(wmask) > 0]  # grouping handles padding itself
        group_nets, group_weights, losses = [], [], []
        # Sparse: only groups that sampled clients this round train and
        # enter the global reduction.
        ck = obs_trace.corr(round=round_idx)
        for g in np.unique(self.group_ids[idx]):
            g_idx = idx[self.group_ids[idx] == g]
            # Pad to a power-of-two multiple of n_shards: bounds the number
            # of distinct XLA programs at O(log client_num_per_round)
            # instead of one recompile per distinct group size per round.
            target = self.n_shards
            while target < len(g_idx):
                target *= 2
            g_idx_p, g_mask = pad_to_multiple(g_idx, target)
            sub = self._group_cohort(g_idx_p)
            weights = sub.counts.astype(jnp.float32) * jnp.asarray(g_mask)
            net_g = self.net
            # Stage-1 span: this group's within-group training +
            # aggregation — the host-side twin of the mesh tier's
            # ICI-local stage. Only fenced (block_until_ready) when a
            # tracer is installed: honest span ends cost a device sync
            # that the traced-off path must not pay.
            with tr.span("reduce.stage1", cat="reduce", corr=ck,
                         group=int(g), clients=int(len(g_idx))):
                for _ in range(self.cfg.group_comm_round):
                    # fedlint: disable=R1(deliberate round-order chain: group sub-rounds consume the same stream the flat host loop would, in round order; prefix-stable in the round count)
                    self.rng, rnd_rng = jax.random.split(self.rng)
                    net_g, loss = self.round_fn(
                        net_g, sub.x, sub.y, sub.mask, weights, weights,
                        rnd_rng
                    )
                if traced:
                    jax.block_until_ready(net_g)
            group_nets.append(net_g)
            group_weights.append(float(np.asarray(weights).sum()))
            losses.append(float(loss))
        if sum(group_weights) <= 0:
            # Every sampled client empty: no group trained a real step —
            # keep the previous global model (a zero-total reduction
            # would zero or inf-poison the params).
            return {"round": round_idx, "train_loss": 0.0}
        # Stage-2 span: the sparse global step over the round's G group
        # partials — the bytes that would cross DCN in a pod deployment
        # (G × payload, the O(G)-traffic observable).
        with tr.span("reduce.stage2", cat="reduce", corr=ck,
                     groups=len(group_nets),
                     nbytes=(len(group_nets) * payload_nbytes(self.net)
                             if traced else 0)):
            self.net = self._global_reduce(group_nets, group_weights)
            if traced:
                jax.block_until_ready(self.net)
        w = np.asarray(group_weights) / max(sum(group_weights), 1e-12)
        return {"round": round_idx, "train_loss": float((w * np.asarray(losses)).sum())}
