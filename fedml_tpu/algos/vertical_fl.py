"""Classical vertical (feature-partitioned) federated learning.

Parity target: reference fedml_api/standalone/classical_vertical_fl/ +
fedml_api/distributed/classical_vertical_fl/ —
- the guest holds the labels and a feature slice; each host holds only a
  feature slice (vfl.py:1-40, party_models.py:12);
- per batch, every party runs its local extractor + linear head and sends
  its logit contribution to the guest (host_trainer.py:43);
- the guest sums the contributions, computes the sigmoid-BCE loss and the
  **common gradient** dL/dlogit, and returns it; every party backprops the
  common gradient through its own nets and steps SGD(momentum 0.9, wd 0.01)
  (guest_trainer._compute_common_gradient_and_loss party_models.py:57,
  _bp_classifier guest_trainer.py:113).

TPU-native: each party's forward is a separate ``jax.vjp`` — the pulled-back
cotangent IS the common gradient of the wire protocol, so simulation math
equals the distributed protocol exactly. All parties' updates happen in one
jit per batch; cross-silo deployment moves the logit/cotangent arrays onto
fedml_tpu.comm messages without touching the math.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algos.capability import ExcludedScanTiers
from fedml_tpu.models.vfl import VFLDenseModel, VFLLocalModel


class VflParty:
    """One party's stacked (local extractor → dense head) pair."""

    def __init__(self, feature_dim: int, rep_dim: int, use_bias: bool, rng):
        self.local = VFLLocalModel(output_dim=rep_dim)
        self.dense = VFLDenseModel(output_dim=1, use_bias=use_bias)
        r1, r2 = jax.random.split(rng)
        x = jnp.zeros((1, feature_dim), jnp.float32)
        self.params = {
            "local": self.local.init(r1, x)["params"],
            "dense": self.dense.init(
                r2, jnp.zeros((1, rep_dim), jnp.float32))["params"],
        }

    def forward(self, params, x):
        rep = self.local.apply({"params": params["local"]}, x)
        return self.dense.apply({"params": params["dense"]}, rep)


class VflAPI(ExcludedScanTiers):
    """Two-or-more-party VFL with a logistic top (reference
    VerticalMultiplePartyLogisticRegressionFederatedLearning, vfl.py:1).

    ``x_parties``: list of per-party feature matrices ``[N, d_p]`` with the
    guest first; ``y``: binary labels ``[N]`` held by the guest only."""

    window_protocol = None
    window_exclusion = (
        "vertical FL partitions FEATURES, not clients: every party "
        "joins every batch and the guest's common gradient crosses "
        "trust domains per batch — no client-cohort round exists to "
        "publish as a carry record")

    def __init__(self, feature_dims: Sequence[int], rep_dim: int = 32,
                 lr: float = 0.01, seed: int = 0):
        rng = jax.random.PRNGKey(seed)
        rngs = jax.random.split(rng, len(feature_dims))
        # Guest keeps the bias; hosts don't (party_models.py builds guest
        # DenseModel with bias and host without, so the sum has one bias).
        self.parties: List[VflParty] = [
            VflParty(d, rep_dim, use_bias=(i == 0), rng=rngs[i])
            for i, d in enumerate(feature_dims)
        ]
        # Reference SGD(momentum=0.9, weight_decay=0.01)
        # (vfl_models_standalone.py:13).
        self.opt = optax.chain(
            optax.add_decayed_weights(0.01), optax.sgd(lr, momentum=0.9))
        self.opt_states = [self.opt.init(p.params) for p in self.parties]
        self._step = jax.jit(self._build_step())
        self._predict = jax.jit(self._build_predict())

    def _build_step(self):
        parties, opt = self.parties, self.opt

        def step(params_list, opt_list, xs, y):
            # Party-local forwards, each with its own VJP (the protocol's
            # send-logit / receive-common-gradient pair).
            logits, vjps = [], []
            for party, p, x in zip(parties, params_list, xs):
                out, vjp = jax.vjp(lambda pp, px=x, pt=party: pt.forward(pp, px), p)
                logits.append(out)
                vjps.append(vjp)
            total = sum(logits)[:, 0]
            # Guest: loss + common gradient.
            loss = jnp.mean(optax.sigmoid_binary_cross_entropy(total, y))
            common_grad = ((jax.nn.sigmoid(total) - y) /
                           y.shape[0])[:, None]  # dL/dlogit
            new_params, new_opts = [], []
            for p, vjp, st in zip(params_list, vjps, opt_list):
                (grads,) = vjp(common_grad)
                updates, st2 = opt.update(grads, st, p)
                new_params.append(optax.apply_updates(p, updates))
                new_opts.append(st2)
            return new_params, new_opts, loss

        return step

    def _build_predict(self):
        parties = self.parties

        def predict(params_list, xs):
            total = sum(
                party.forward(p, x)
                for party, p, x in zip(parties, params_list, xs))[:, 0]
            return jax.nn.sigmoid(total)

        return predict

    def fit(self, x_parties: Sequence[np.ndarray], y: np.ndarray,
            epochs: int = 5, batch_size: int = 64) -> List[float]:
        """Mirrors vfl.py fit(): epoch × batch loop over aligned samples."""
        n = len(y)
        params = [p.params for p in self.parties]
        opts = self.opt_states
        losses = []
        # Residual partial batch included (reference vfl_fixture.py:41-45
        # computes N//bs + 1 batches when N % bs != 0). The short batch is
        # one extra jit trace, reused every epoch.
        steps = max(1, (n + batch_size - 1) // batch_size)
        for _ in range(epochs):
            for s in range(steps):
                sl = slice(s * batch_size, min(n, (s + 1) * batch_size))
                xs = [jnp.asarray(x[sl]) for x in x_parties]
                params, opts, loss = self._step(
                    params, opts, xs, jnp.asarray(y[sl], jnp.float32))
                losses.append(float(loss))
        for p, new in zip(self.parties, params):
            p.params = new
        self.opt_states = opts
        return losses

    def predict(self, x_parties: Sequence[np.ndarray]) -> np.ndarray:
        params = [p.params for p in self.parties]
        xs = [jnp.asarray(x) for x in x_parties]
        return np.asarray(self._predict(params, xs))

    def evaluate(self, x_parties, y) -> Dict[str, float]:
        prob = self.predict(x_parties)
        acc = float(np.mean((prob > 0.5).astype(np.int32) == y))
        return {"accuracy": acc}
