"""Buffered semi-synchronous aggregation (FedBuff-style) — the tier
between sync first-k and pure async.

Sync first-k (algos/fedavg_distributed.py) pays a round barrier: the
fleet idles while the k-th upload is in flight, and every straggler's
work is DISCARDED at the catch-up. Pure async (algos/fedasync.py) pays
maximal staleness: the model version advances on every arrival, so a
slow device's update lands against a model that moved `W-1` versions
under it. FedBuff (Nguyen et al. 2022, "Federated Learning with Buffered
Asynchronous Aggregation") sits between: clients train continuously with
no barrier (async's request/response flow), but the server folds uploads
into the global model only every ``buffer_k``-th arrival, each update
discounted polynomially in its staleness (the same
``fedasync.staleness_weight`` — why averaging stale local updates still
converges is Parallel Restarted SGD, arXiv:1807.06629):

    disc_i = 1 / (1 + s_i)^a                 (s_i = versions since pull)
    delta  = Agg(stack(d_1..d_k), disc)       (cfg-pluggable aggregator)
    global <- global + alpha * delta          (alpha = server step size)

**Accumulate on arrival.** For the mean aggregator (the default) the
server never stores the buffered updates: it keeps one running
``acc += disc_i * d_i`` and ``wsum += disc_i`` — O(model) server memory
regardless of ``buffer_k`` or the fleet size (the server ingest path is
the engineering bottleneck at scale — arXiv:2307.06561). A non-mean
aggregator from :mod:`fedml_tpu.core.robust_agg` (coord_median, trimmed
mean, Krum, geometric median) needs the k updates side by side, so that
path retains the k-deep buffer — O(buffer_k × model), still independent
of the fleet size. Both paths share the weight semantics of the
Aggregator protocol: ``disc_i`` is the weight VALUE for mean/geometric
median and the participation gate for the order statistics, and a
non-finite delta (a diverged or NaN-corrupted client —
``core/faults.UpdateCorruptor``) is weight-zeroed exactly like the
windowed tier's ``nan_guard``, so robust-vs-Byzantine and
buffered-vs-stale compose (docs/ROBUSTNESS.md "Serving under churn").

Everything else — per-worker upload dedupe, heartbeat-driven recovery of
stalled workers, the bounded terminal handshake, chaos drills — is
INHERITED from the async control plane: :class:`FedBuffServerManager`
overrides only the ``_ingest`` hook, and :class:`FedBuffClientManager`
only the wire payload (the client ships ``net - global_received``, the
delta against the exact model it trained from; the server keeps no
version history, so only the client can form it). ``cfg.comm_round``
counts server AGGREGATIONS (model versions), matching the async tier's
"server updates, not barrier rounds" contract.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedasync import (
    MSG_ARG_KEY_TASK_SEQ,
    FedAsyncClientManager,
    FedAsyncServerManager,
    staleness_weight,
)
from fedml_tpu.algos.fedavg_distributed import (
    MSG_ARG_KEY_MODEL_PARAMS,
    build_federation_setup,
)
from fedml_tpu.comm import codec as wire_codec
from fedml_tpu.comm.loopback import run_workers
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import ChaosSpec
from fedml_tpu.core.robust_agg import make_aggregator
from fedml_tpu.ctrl.actuator import Knob
from fedml_tpu.core.tree import tree_sub
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.obs import trace as obs_trace
from fedml_tpu.trainer.local import softmax_ce

log = logging.getLogger(__name__)


def _tree_finite(tree) -> bool:
    """Host-side finiteness gate for one arriving delta — the buffered
    tier's ``nan_guard``: cheap next to the deserialize the upload just
    paid, and it keeps a poisoned update out of BOTH aggregation paths."""
    return all(bool(np.isfinite(np.asarray(l)).all())
               for l in jax.tree.leaves(tree))


class FedBuffServerManager(FedAsyncServerManager):
    """Aggregate every ``buffer_k`` accepted arrivals with polynomial
    staleness discounting; the model version counts AGGREGATIONS.

    ``alpha`` is the server step size on the aggregated delta (1.0 =
    apply the discounted-mean update as-is), NOT the async mixing rate;
    ``staleness_exp`` is the discount exponent shared with fedasync.
    ``aggregator`` is any :func:`core.robust_agg.make_aggregator` spec —
    ``mean`` keeps the O(model) accumulate-on-arrival fast path.

    ``cfg.agg_shards`` is refused (inherited from FedAsyncServerManager):
    the buffer barriers on GLOBAL arrival order — the k-th arrival
    triggers the aggregation wherever it lands — so there is no
    per-partition partial for the sharded plane (comm/shardplane.py) to
    merge without changing which uploads share a buffer.
    """

    #: The buffered tier folds DELTAS (client ships net − pulled model);
    #: advertised via the negotiated delta capability (PR 15) — a
    #: full-model-stamped upload is refused instead of buffered as a
    #: delta.
    _accepts_delta_frames = True

    def __init__(self, args, net, cfg: FedConfig, size: int,
                 backend: str = "LOOPBACK", alpha: float = 1.0,
                 staleness_exp: float = 0.5, buffer_k: int = 2,
                 aggregator="mean", eval_fn=None, test_data=None, *,
                 nan_guard: bool = True,
                 done_timeout_s: Optional[float] = None,
                 metrics=None, flight_dir=None,
                 clock=time.monotonic, directory=None):
        super().__init__(args, net, cfg, size, backend=backend, alpha=alpha,
                         staleness_exp=staleness_exp, eval_fn=eval_fn,
                         test_data=test_data, done_timeout_s=done_timeout_s,
                         metrics=metrics, flight_dir=flight_dir,
                         clock=clock, directory=directory)
        if buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {buffer_k}")
        self.buffer_k = buffer_k
        self.aggregator = make_aggregator(aggregator)
        if self._pool is not None and not self.aggregator.is_mean:
            # super().__init__ already started the pool's worker
            # threads — close them before refusing, or every failed
            # construction leaks N blocked daemon threads.
            self._pool.close()
            raise ValueError(
                f"ingest_workers={cfg.ingest_workers} needs the mean "
                f"aggregator: {self.aggregator.name!r} reduces the k-deep "
                "buffer side by side (stack-then-reduce), which is "
                "inherently serialized — run it with ingest_workers=0 "
                "(comm/ingest.py)")
        self.nan_guard = nan_guard
        self.guard_drops = 0  # non-finite deltas weight-zeroed out
        # Actuation discipline (fedml_tpu.ctrl): buffer_k is read once
        # per arrival (_ingest), so mutating it BETWEEN flushes merely
        # moves the next flush point — exact. Mutating it DURING a flush
        # could re-enter the barrier; the seam's busy probe refuses any
        # actuation while this bit is set.
        self._in_flush = False
        self.ctrl.add_knob(Knob(
            "buffer_k", lambda: self.buffer_k,
            lambda v: setattr(self, "buffer_k", v),
            1, max(1, size - 1), cast=int))
        # Mean fast path: running discounted sum + weight, O(model).
        self._acc = None
        self._wsum = 0.0
        # Robust path: the k-deep buffer of (delta, discount) pairs.
        self._pending: List[Tuple[object, float]] = []
        self._count = 0
        self._accum = jax.jit(
            lambda acc, d, w: jax.tree.map(
                lambda a_, d_: a_ + w * d_.astype(jnp.float32), acc, d))
        self._lift = jax.jit(
            lambda d, w: jax.tree.map(
                lambda d_: w * d_.astype(jnp.float32), d))
        self._apply = jax.jit(
            lambda g, d, s: jax.tree.map(
                lambda g_, d_: (g_.astype(jnp.float32)
                                + s * d_.astype(jnp.float32)
                                ).astype(g_.dtype), g, d))

    @property
    def aggregations(self) -> int:
        return self.version

    def health(self):
        """The async tier's health row plus the buffered tier's own
        observables: current buffer fill and nan-guard drops."""
        h = super().health()
        h["buffer_depth"] = self._count
        h["guard_drops"] = self.guard_drops
        return h

    def _ctrl_busy(self) -> Optional[str]:
        # Seam busy probe: no knob may move while the flush barrier is
        # draining/merging — a buffer_k change there could re-enter the
        # flush, an alpha change would split one commit across two
        # step sizes.
        return "mid_flush" if self._in_flush else None

    def _defer_decode(self) -> bool:
        # With a pool, the buffered tier moves frame decode AND the
        # discounted fold into its ingest task (the window between
        # flushes is where the parallelism lives: the net only changes
        # at the flush, so deferral changes no reply a worker sees).
        return self._pool is not None

    def _submit_buffered(self, msg: Message, disc: float) -> None:
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        wcodec = msg.get(wire_codec.CODEC_KEY)
        spec = self._spec
        guard = self.nan_guard
        sender = msg.get_sender_id()
        task_seq = msg.get(MSG_ARG_KEY_TASK_SEQ)

        def task():
            delta = (self._wire_decoders.decode(wcodec, payload, spec)
                     if wcodec else payload)
            leaves = [np.asarray(l) for l in jax.tree.leaves(delta)]
            w = disc
            if guard and not all(np.isfinite(l).all() for l in leaves):
                # Weight-zeroed like the inline tier's nan_guard; the
                # exact accumulator maps non-finite entries to 0, so a
                # poisoned delta contributes nothing either way.
                with self._lock:
                    self.guard_drops += 1
                w = 0.0
            return leaves, w

        self._pool.submit(task, sender=sender,
                          **({"task_seq": int(task_seq)}
                             if task_seq is not None else {}))

    def _ingest(self, msg: Message, staleness: int) -> None:
        disc = staleness_weight(1.0, staleness, self.staleness_exp)
        if self._pool is not None:
            # Pooled path: decode + guard + discounted fold run on the
            # pool; the slot is consumed NOW (the arrival happened — a
            # frame that later refuses weighs 0 in this window, the
            # participation-gate semantics of a guard drop, and its
            # sender is evict-and-released at the flush barrier).
            self._submit_buffered(msg, disc)
            self._count += 1
            if self._count >= self.buffer_k:
                self._flush()
            return
        delta = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        if self.nan_guard and not _tree_finite(delta):
            # Weight-zeroed like the windowed tier's nan_guard: the slot
            # still fills its buffer position (the arrival happened) but
            # is EXCLUDED from the statistics — disc=0 is the Aggregator
            # protocol's participation gate.
            self.guard_drops += 1
            disc = 0.0
        if self.aggregator.is_mean:
            if disc > 0.0:
                self._acc = (self._lift(delta, jnp.float32(disc))
                             if self._acc is None
                             else self._accum(self._acc, delta,
                                              jnp.float32(disc)))
                self._wsum += disc
        else:
            if disc <= 0.0:
                # A guard-dropped delta must not enter the stacked
                # buffer as raw NaN/inf: weight 0 excludes it from every
                # aggregator's STATISTICS, but 0 x NaN = NaN would still
                # poison the weighted recombination (krum / geometric
                # median; the windowed tier zeroes via where for the
                # same reason, parallel/shard.py).
                delta = jax.tree.map(
                    lambda l: jnp.zeros_like(jnp.asarray(l, jnp.float32)),
                    delta)
            self._pending.append((delta, disc))
        self._count += 1
        if self._count >= self.buffer_k:
            self._flush()

    def _flush(self) -> None:
        """Apply the buffered aggregate and bump the model version. An
        all-excluded buffer (every delta weight-zeroed) keeps the
        previous net, mirroring the round builders' all-excluded
        contract — the version still advances (the k arrivals were
        consumed)."""
        flushed = self._count
        self._in_flush = True
        try:
            with obs_trace.active().span(
                    "round.commit", cat="round",
                    corr=obs_trace.corr(round=self.version),
                    buffered=flushed):
                self._flush_buffer()
        finally:
            self._in_flush = False
        # The ctrl/ row is emitted at the version bump, i.e. right AFTER
        # this flush reset the fill to 0 — report the depth the flush
        # CONSUMED (normally buffer_k), which is the meaningful
        # per-version observable; ``health()``'s buffer_depth stays the
        # live fill.
        self.registry.gauge("buffer_depth").set(flushed)

    def _flush_buffer(self) -> None:
        if self._pool is not None:
            # Barrier on the window's pending decode+fold tasks, apply
            # the refusal policy to failures, then merge the per-worker
            # exact partials: mean delta = Σ disc·d / Σ disc, identical
            # bits for any worker count / interleaving (comm/ingest.py).
            for meta, err in self._pool.drain():
                # The shared async-tier refusal policy (fedasync.
                # _refuse_upload), applied at the flush barrier where
                # pooled failures surface.
                self._refuse_upload(int(meta.get("sender", -1)), err,
                                    task_seq=meta.get("task_seq"))
            mean_delta, _ = self._pool.finalize_mean(self.net,
                                                     dtype=np.float32)
            if mean_delta is not None:
                self.net = self._apply(self.net, mean_delta,
                                       jnp.float32(self.alpha))
            self._count = 0
            self.version += 1
            return
        if self.aggregator.is_mean:
            if self._wsum > 0.0:
                delta = self._lift(self._acc, jnp.float32(1.0 / self._wsum))
                self.net = self._apply(self.net, delta,
                                       jnp.float32(self.alpha))
            self._acc = None
            self._wsum = 0.0
        else:
            weights = jnp.asarray([w for _, w in self._pending],
                                  jnp.float32)
            if bool(jnp.any(weights > 0)):
                stacked = jax.tree.map(
                    lambda *ls: jnp.stack(
                        [jnp.asarray(l, jnp.float32) for l in ls]),
                    *[d for d, _ in self._pending])
                delta = self.aggregator(stacked, weights)
                self.net = self._apply(self.net, delta,
                                       jnp.float32(self.alpha))
            self._pending = []
        self._count = 0
        self.version += 1


class FedBuffClientManager(FedAsyncClientManager):
    """The async client with a delta wire format: uploads
    ``net - global_received`` (the update against the exact model it
    trained from). Because the payload IS a delta, the full wire-codec
    menu applies — including top-k/randmask with per-worker error
    feedback (the async base refuses sparsifiers on full-model uploads).
    ``corruptor`` (a :class:`core.faults.UpdateCorruptor`) marks this
    rank Byzantine for attack-vs-defense drills: the trained model is
    corrupted BEFORE the delta is formed — the same threat order as the
    windowed tier's device-side drill."""

    _payload_is_delta = True

    def __init__(self, *args_, corruptor=None, **kw):
        super().__init__(*args_, **kw)
        self.corruptor = corruptor

    def _upload_payload(self, net, global_net):
        if self.corruptor is not None:
            net = self.corruptor.corrupt(net, global_net)
        return jax.device_get(tree_sub(net, global_net))


def FedML_FedBuff_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    alpha: float = 1.0,
    staleness_exp: float = 0.5,
    buffer_k: int = 2,
    aggregator="mean",
    *,
    wire_codec: str = "none",
    loopback_wire: str = "none",
    chaos: Optional[ChaosSpec] = None,
    done_timeout_s: Optional[float] = None,
    idle_timeout_s: float = 0.0,
    corrupt_ranks=(),
    corruptor=None,
    metrics=None,
    trace_dir=None,
    pretrained_params=None,
    controller=None,
):
    """Run the buffered federation: ``cfg.comm_round`` server
    AGGREGATIONS (each consuming ``buffer_k`` arrivals) across
    ``cfg.client_num_per_round`` workers. Returns the server manager
    (net, staleness/arrival history, test history). ``corrupt_ranks`` +
    ``corruptor`` flag Byzantine workers for drills; ``aggregator`` is
    the server-side defense (core/robust_agg spec). ``metrics`` gets one
    ctrl/ health row (incl. buffer depth + staleness) per aggregation;
    ``trace_dir`` arms the flight recorder + span tracer (obs/trace.py)."""
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn, chaos=chaos,
        loopback_wire=loopback_wire, pretrained_params=pretrained_params)
    server = FedBuffServerManager(
        args, net0, cfg, size, backend=backend, alpha=alpha,
        staleness_exp=staleness_exp, buffer_k=buffer_k,
        aggregator=aggregator, eval_fn=eval_fn, test_data=test_global,
        done_timeout_s=done_timeout_s, metrics=metrics,
        flight_dir=trace_dir)
    if controller is not None:
        # Same-object portability: the controller that tuned its policies
        # in the fleet simulator drives this live run unchanged.
        server.attach_controller(controller)
    clients = [
        FedBuffClientManager(args, rank, size, train_fed, local_train, cfg,
                             backend=backend, wire_codec_spec=wire_codec,
                             idle_timeout_s=idle_timeout_s,
                             corruptor=(corruptor if rank in set(corrupt_ranks)
                                        else None))
        for rank in range(1, size)
    ]
    with obs_trace.tracing_to(trace_dir):
        run_workers([server.run] + [c.run for c in clients])
    server.final_health = server.health()
    server.adapter_holder = args.adapter_holder
    return server
