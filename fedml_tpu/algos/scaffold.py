"""SCAFFOLD — stochastic controlled averaging (Karimireddy et al. 2020).

New capability: under heterogeneous clients, FedAvg's local epochs drift
toward each client's own optimum ("client drift") and the average stalls.
SCAFFOLD corrects every local step with control variates:

    y   <- y - lr * (grad f_k(y) + c - c_k)          (local steps)
    c_k' = c_k - c + (x - y) / (K_k * lr)            (option II)
    x   <- x + mean_k(y_k - x)
    c   <- c + (|S| / N) * mean_k(c_k' - c_k)

where x is the global model, c the server control, c_k the client
controls, and K_k the client's true optimizer-step count.

TPU design: the N client controls are ONE client-stacked pytree on
device (like Ditto's personal models); the corrected local run is a
dedicated ``lax.scan`` trainer (the correction enters every step, which
the generic trainer's parameter-space ``extra_grad_fn`` cannot express —
that hook has no per-client input). K_k is computed from the mask
(padded trailing batches are no-op steps, trainer/local.py), so ragged
clients get exact control updates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import tree_select


def make_scaffold_local_train(apply_fn, lr: float, local_epochs: int,
                              loss_fn, remat: bool = False):
    """``local_train(net, correction, x, y, mask, rng) -> (net', loss, K)``
    — plain SGD with the SCAFFOLD per-step correction ``c - c_k`` added to
    every gradient; ``K`` is the true number of non-empty optimizer steps.
    Built on the shared corrected-SGD trainer (trainer/local.py)."""
    from fedml_tpu.trainer.local import make_corrected_local_train

    def step_update(params, grads, correction):
        return jax.tree.map(lambda p, g, corr: p - lr * (g + corr),
                            params, grads, correction)

    return make_corrected_local_train(apply_fn, local_epochs, loss_fn,
                                      step_update, remat=remat,
                                      with_step_count=True)


class ScaffoldAPI(FedAvgAPI):
    """FedAvg + control variates. Plain-SGD clients only (the SCAFFOLD
    correction is defined on the SGD update; cfg.client_optimizer must be
    'sgd'). Sampling/eval/loop scaffolding is inherited.

    Streams from a ``FederatedStore`` too: the client CONTROLS stay a
    device-resident ``[N, ...]`` stack (per-client state, not data), but
    the round's training cohort arrives through the shared
    :meth:`FedAvgAPI._cohort` path — host-gathered and double-buffered at
    reference client scales. On the store, the windowed tier
    (``train_rounds_windowed``) runs W rounds per dispatch through the
    "custom" carry protocol below."""

    #: Windowed carry protocol: the round itself consumes/produces the
    #: carried state (server control + client-control stack), so the
    #: step is custom — see _build_fused_step, which serves the fused
    #: host round, the pipelined loop AND the windowed scan (the
    #: capability record derives all three from it).
    window_protocol = "custom"
    window_carry = "server control + client-control stack"

    def __init__(self, *args, server_lr: float = 1.0, **kw):
        super().__init__(*args, **kw)
        # Reject (rather than silently ignore) cfg knobs the corrected
        # local step does not implement — a user who sets --dp_clip must
        # not believe DP is active. cfg.wd is NOT rejected: the generic
        # sgd client optimizer ignores it too (reference parity — the
        # reference pairs weight decay with Adam only, MyModelTrainer.py:
        # 26-31), so behavior matches FedAvg exactly.
        self._require_plain_sgd_round("ScaffoldAPI's corrected SGD step")
        self.server_lr = server_lr
        n = int(self.train_fed.num_clients)
        zeros = jax.tree.map(jnp.zeros_like, self.net.params)
        self.server_control = zeros
        self.client_controls = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), zeros)
        self._scaffold_jit = None

    def _on_client_lr_change(self):
        self._scaffold_jit = None

    def _scaffold_update(self, net, c_server, ck_sub, trained, losses,
                         k_steps, weights, cross):
        """The SCAFFOLD server update, shared by the vmap and sharded
        rounds. ``cross(x)`` reduces a locally-summed quantity across
        shards — identity on one device, ``lax.psum`` under shard_map —
        so the control/averaging math is written once and cannot drift."""
        lr = self._client_lr
        server_lr = self.server_lr
        n_total = float(self.train_fed.num_clients)

        active = (weights > 0).astype(jnp.float32)
        # Option II client-control update:
        #   c_k' = c_k - c + (x - y_k) / (K_k * lr)
        inv_klr = 1.0 / (k_steps * lr)
        ck_new = jax.tree.map(
            lambda ck, c, xg, yk: (
                ck - c[None]
                + (xg.astype(jnp.float32)[None] - yk.astype(jnp.float32))
                * inv_klr.reshape((-1,) + (1,) * (xg.ndim))),
            ck_sub, c_server, net.params, trained.params)

        # Server model: x + server_lr * weighted mean of (y_k - x). An
        # all-inactive round (every sampled client empty/weight-masked)
        # keeps the previous model: wn_w would be all-zero, the "average"
        # the zero tree, and with server_lr=1 the global would be zeroed.
        w = weights.astype(jnp.float32)
        total_w = cross(jnp.sum(w))
        wn_w = w / jnp.maximum(total_w, 1e-12)
        avg = jax.tree.map(
            lambda p: cross(jnp.einsum(
                "c,c...->...", wn_w, p.astype(jnp.float32))).astype(p.dtype),
            trained)
        new_net = jax.tree.map(
            lambda xg, a: (xg.astype(jnp.float32) * (1 - server_lr)
                           + server_lr * a.astype(jnp.float32)
                           ).astype(xg.dtype),
            net, avg)
        new_net = tree_select(total_w > 0, new_net, net)
        # Server control: c + (|S|/N) * mean_k Δc_k (active mean).
        total_active = cross(jnp.sum(active))
        wn = active / jnp.maximum(total_active, 1e-12)
        frac = total_active / n_total
        c_new = jax.tree.map(
            lambda c, ckn, ck: c + frac * cross(jnp.einsum(
                "c,c...->...", wn, ckn - ck)),
            c_server, ck_new, ck_sub)
        # wn_w is already the normalized sample weighting — reuse it for
        # the loss (recomputing would add a redundant psum per round).
        return new_net, c_new, ck_new, cross(jnp.sum(losses * wn_w))

    def _scaffold_round_fn(self):
        if self._scaffold_jit is not None:
            return self._scaffold_jit
        local_train = make_scaffold_local_train(
            self.fns.apply, self._client_lr, self.cfg.epochs, self._loss_fn,
            remat=self.cfg.remat)

        def body(net, c_server, ck_sub, x, y, mask, weights, rngs, cross):
            corrections = jax.tree.map(
                lambda c, ck: c[None] - ck, c_server, ck_sub)
            trained, losses, k_steps = jax.vmap(
                local_train, in_axes=(None, 0, 0, 0, 0, 0)
            )(net, corrections, x, y, mask, rngs)
            return self._scaffold_update(net, c_server, ck_sub, trained,
                                         losses, k_steps, weights, cross)

        from fedml_tpu.parallel.shard import make_stateful_client_round

        from fedml_tpu.parallel.shard import client_axis
        axis = None if self.mesh is None else client_axis(self.mesh)
        round_fn = make_stateful_client_round(
            body, self.mesh, axis or "clients")
        self._scaffold_jit = jax.jit(round_fn)
        return self._scaffold_jit

    # --- carry capability record ("custom"): controls ride every tier ----
    def _build_fused_step(self):
        """ONE SCAFFOLD round as one donated dispatch: cohort control
        gather + the stateful round + the masked scatter-merge, carry
        ``(net, (server_control, client_controls))``. The same step
        scanned W-deep IS the windowed tier (``_build_window_scan``
        derives from it), so a client sampled twice in one window sees
        its own earlier control update (bit-equality with the host
        loop). The scatter gate: only clients that actually trained
        update their control — a sampled EMPTY client runs zero real
        steps, so writing its ``ck - c + 0`` "update" would drift its
        stored control by ``-c`` each time it is sampled (the paper
        updates controls only for clients that computed updates)."""
        from fedml_tpu.parallel.shard import make_fused_stateful_round_step

        return make_fused_stateful_round_step(self._scaffold_round_fn())

    def _window_carry_init(self):
        return (self.server_control, self.client_controls)

    def _window_carry_commit(self, extra) -> None:
        self.server_control, self.client_controls = extra

    def _window_scan_extras(self, idx2d, wmask2d):
        from fedml_tpu.obs.sanitizer import planned_transfer

        # The step needs each round's cohort index map (control
        # gather/scatter) and its trained mask (empty clients must not
        # write their slot). Both are host gathers over counts
        # (layout-agnostic — the resident host loop and the store-backed
        # windowed scan consume the same operands); the H2D rides the
        # window's planned staging copies.
        trained = self._window_update_mask(idx2d, wmask2d)
        with planned_transfer():
            return (jnp.asarray(np.asarray(idx2d), jnp.int32),
                    jnp.asarray(trained, jnp.float32))

    # -- checkpoint/resume: controls are run state ------------------------
    def checkpoint_extra_state(self):
        return {"server_control": self.server_control,
                "client_controls": self.client_controls}

    def load_checkpoint_extra_state(self, extra) -> None:
        self.server_control = extra["server_control"]
        self.client_controls = extra["client_controls"]
