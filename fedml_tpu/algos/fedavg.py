"""FedAvg — the canonical synchronous federated-averaging loop.

Capability parity with both reference implementations:
- standalone simulator ``FedAvgAPI`` (fedml_api/standalone/fedavg/fedavg_api.py:12-116)
- distributed MPI pipeline (fedml_api/distributed/fedavg/FedAvgAPI.py:20 +
  FedAVGAggregator.py + manager classes)

On TPU both collapse into one object: sampled clients are a leading array
axis (vmap on one chip, shard_map over the ``clients`` mesh axis on many),
and the server aggregation is a weighted-mean reduction (psum over ICI).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.loop import FederatedLoop, eval_segments
from fedml_tpu.core.robust_agg import make_aggregator
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.obs.sanitizer import planned_transfer
from fedml_tpu.parallel.shard import (
    client_axes,
    client_axis,
    client_shards,
    make_sharded_round,
    make_vmap_round,
    mesh_dcn_axis,
)
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)


def plan_window_spans(buckets, window: int):
    """Split a run of rounds (given each round's cohort step bucket) into
    execution spans ``(offset, length, steps-or-None)`` covering the
    rounds in order: consecutive chunks of exactly ``window`` rounds
    become scan spans whose shared step bucket is the chunk's MAX bucket
    (every round's cohort fits; smaller rounds get extra masked pad —
    exact training no-ops under the trainer's prefix-stable rng streams,
    see ``trainer.local.make_epoch_shuffle``); the remainder (< window
    rounds) falls to the per-round host loop (``steps=None``).

    Fixing every scan's length at ``window`` and quantizing its step
    shape to the chunk-max power-of-two bucket bounds compilation at one
    scan executable per DISTINCT max bucket — a handful, like the
    per-round path's shape buckets."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    spans, n = [], len(buckets)
    lo = 0
    while n - lo >= window:
        spans.append((lo, window, max(buckets[lo:lo + window])))
        lo += window
    if lo < n:
        spans.append((lo, n - lo, None))
    return spans


class FedAvgAPI(FederatedLoop):
    """Federated trainer. ``mesh=None`` → single-device vmap simulator;
    with a mesh, clients are sharded over ``mesh.axis_names[0]``.

    ``train_fed`` may be a device-resident ``FederatedArrays`` (small
    client counts) or a host-resident ``data.store.FederatedStore``
    (reference-scale client counts — 3,400-writer FEMNIST, 342k-user
    StackOverflow): the store streams only each round's sampled cohort
    to the device, double-buffered against the round's compute."""

    #: Subclasses that read client-stacked arrays outside run_round
    #: (persistent per-client device state, direct gather_clients) set
    #: this False; FedAvgAPI raises at construction instead of failing
    #: deep inside their round.
    supports_streaming = True

    #: Subclasses whose round aggregates WITHIN groups and then ACROSS
    #: group partials (HierarchicalFedAvgAPI) set this True to accept
    #: group-composable robust aggregators (coord_median, trimmed_mean)
    #: through the custom-round guard below — the two-stage statistic is
    #: their documented semantics, not a silent drift. Non-composable
    #: aggregators (krum, geometric_median) are still refused loudly.
    #: The guard reads this from the concrete class's __dict__ — the
    #: opt-in is NOT inherited: a further subclass that re-customizes
    #: the round must re-declare it (or be refused).
    composes_group_aggregation = False

    def __init__(
        self,
        model,
        train_fed: FederatedArrays,
        test_global,  # (x, y, mask) batched [S, B, ...] or None
        cfg: FedConfig,
        mesh=None,
        loss_fn=softmax_ce,
        pad_id: int = 0,
        nan_guard: bool = False,
    ):
        """``pad_id`` marks padding positions in sequence-task labels
        (excluded from eval accuracy); it must match the pad id baked into a
        sequence ``loss_fn`` (e.g. ``partial(seq_softmax_ce, pad_id=...)``).
        Irrelevant for flat classification tasks.

        ``nan_guard``: zero-weight any client whose local training diverged
        to non-finite params (fedml_tpu.core.faults failure containment)."""
        from fedml_tpu.data.store import FederatedStore

        self.cfg = cfg
        self.mesh = mesh
        self.train_fed = train_fed
        self.test_global = test_global
        if getattr(cfg, "adapter_rank", 0) and not self._consumes_adapter_cfg:
            # PR 4 convention: cfg.adapter_rank configures the frozen-
            # base adapter finetune (FedAdapterAPI on the simulator
            # tiers; the message-passing setups read it directly) — on
            # any other class the flag would silently train the DENSE
            # arm while the user believes adapters are on.
            raise NotImplementedError(
                f"cfg.adapter_rank={cfg.adapter_rank} configures frozen-"
                "base adapter finetuning; use FedAdapterAPI (algos/"
                f"fedadapter.py) — on {type(self).__name__} the flag "
                "would be silently inert")
        self.fns = self._model_fns(model)
        self._streaming = isinstance(train_fed, FederatedStore)
        if self._streaming and not type(self).supports_streaming:
            raise NotImplementedError(
                f"{type(self).__name__} keeps per-client state device-"
                "resident (or gathers clients on device) and does not "
                "support FederatedStore streaming; use the resident "
                "FederatedArrays layout")
        if cfg.batch_size != train_fed.batch_size:
            raise ValueError(
                f"cfg.batch_size={cfg.batch_size} != packed client batch size "
                f"{train_fed.batch_size}; build_federated_arrays with the same "
                "batch_size as the config"
            )

        if getattr(cfg, "wire_codec", "none") not in ("", "none"):
            # PR 4 convention: refuse a flag nothing here reads. The
            # simulator's on-device analogue is cfg.compress; the wire
            # codec belongs to the message-passing tiers.
            raise NotImplementedError(
                f"cfg.wire_codec={cfg.wire_codec!r} is a message-passing-"
                "tier capability (cross-silo / FedAsync / FedBuff, "
                "comm/codec.py); the simulator tiers compress on device "
                "via cfg.compress")
        if getattr(cfg, "ingest_workers", 0):
            # Same convention: the parallel ingest pool unblocks a
            # message-passing server's dispatch thread; the simulator
            # tiers aggregate inside the jitted round and have no such
            # thread to unblock.
            raise NotImplementedError(
                f"cfg.ingest_workers={cfg.ingest_workers} is a message-"
                "passing server capability (cross-silo / FedAsync / "
                "FedBuff, comm/ingest.py); the simulator tiers have no "
                "dispatch thread to parallelize")
        self._loss_fn = loss_fn
        self._nan_guard = nan_guard
        # Byzantine-robust server aggregation (core/robust_agg): resolved
        # once; "mean" keeps the existing weighted-mean reduction
        # bit-equal on every tier. Guards mirror the windowed carry
        # protocol's philosophy — refuse loudly instead of silently
        # keeping a subclass's own aggregation.
        self._aggregator = make_aggregator(getattr(cfg, "aggregator", "mean"))
        if not self._aggregator.is_mean:
            # The opt-in must be declared ON the concrete class itself
            # (__dict__, not inheritance): a subclass of an opted-in
            # class that customizes the round again would otherwise
            # inherit the exemption and silently drop the aggregator —
            # the exact drift the strict branch below exists to refuse.
            if type(self).__dict__.get("composes_group_aggregation", False):
                # The subclass runs the TWO-STAGE (within-group → across-
                # group) aggregation (HierarchicalFedAvgAPI): only group-
                # composable aggregators keep their semantics there.
                if not getattr(self._aggregator, "group_composable", False):
                    raise NotImplementedError(
                        f"cfg.aggregator={cfg.aggregator!r} does not "
                        "compose group-wise (krum needs pairwise client "
                        "distances, geometric_median a joint fixpoint); "
                        f"{type(self).__name__} aggregates within groups "
                        "then across group partials — use a composable "
                        "aggregator (coord_median, trimmed_mean<beta>) "
                        "here, or the flat FedAvg family for the exact "
                        "full-cohort all_gather path")
            else:
                # Capability-record facts: a custom round, custom round
                # BUILDERS, or a custom fused step (SCAFFOLD/FedDyn's
                # stateful one-dispatch rounds) all mean the aggregation
                # is not the shared builders' — the flag would silently
                # keep the algorithm's own reduction.
                rec = self.capability()
                if rec.custom_round or rec.custom_builders or rec.custom_step:
                    raise NotImplementedError(
                        f"{type(self).__name__} customizes the round or its "
                        f"aggregation; cfg.aggregator={cfg.aggregator!r} only "
                        "rides the FedAvg family's shared round builders (a "
                        "custom round would silently keep its own "
                        "aggregation)")
        self._group_reduce = bool(getattr(cfg, "group_reduce", False))
        if self._group_reduce:
            if mesh is None:
                raise NotImplementedError(
                    "cfg.group_reduce shrinks the client-mesh collective "
                    "(shard-local partials + a G-sized gather); on a "
                    "single device there are no shards to group — drop "
                    "the flag, or use HierarchicalFedAvgAPI for host-side "
                    "grouping")
            if not self._aggregator.is_mean and not getattr(
                    self._aggregator, "group_composable", False):
                raise NotImplementedError(
                    f"cfg.aggregator={cfg.aggregator!r} does not compose "
                    "group-wise; set group_reduce=False to keep the exact "
                    "full client-stack all_gather path (krum, "
                    "geometric_median), or pick a composable aggregator "
                    "(mean, coord_median, trimmed_mean<beta>)")
        if (getattr(cfg, "corrupt_mode", "none") != "none"
                and type(self)._corruptor is FedAvgAPI._corruptor):
            raise NotImplementedError(
                f"cfg.corrupt_mode={cfg.corrupt_mode!r} drives the device-"
                "side corruption drill, which needs adversary wiring "
                "(per-round adversary masks); use FedAvgRobustAPI — on "
                f"{type(self).__name__} the flag would be silently inert")
        self.n_shards = client_shards(mesh)
        # Pod-scale reduction observability (docs/OBSERVABILITY.md): on
        # a DCN×ICI mesh the O(G)-inter-host-traffic claim is an
        # OBSERVABLE — per-round ctrl/ gauges of how many model-sized
        # partials cross the DCN axis — not a comment. 0 = flat mesh /
        # single device (no emission, no registry).
        d = mesh_dcn_axis(mesh)
        self._dcn_groups = int(mesh.shape[d]) if d else 0
        sample_x = (train_fed.example_input() if self._streaming
                    else np.asarray(train_fed.x[0, 0]))
        # Hook for models whose init input is NOT a data batch (FedGAN's
        # generator initializes from latent noise). Default: identity.
        sample_x = self._net_init_input(sample_x)
        # Lane-fill compute layout (parallel/layout.py): the jitted
        # client step trains a lane-PADDED physical twin; everything
        # above the step — self.net, aggregation, checkpoints, the wire
        # — keeps the logical shapes. Resolved before the round builders
        # so _build_local_train can wrap the trainer.
        self._layout = None
        layout_cfg = getattr(cfg, "compute_layout", "none") or "none"
        if layout_cfg != "none":
            if layout_cfg not in ("auto", "im2col"):
                raise ValueError(
                    f"cfg.compute_layout={layout_cfg!r}: expected "
                    "'none', 'auto' or 'im2col'")
            if type(self)._build_local_train \
                    is not FedAvgAPI._build_local_train:
                raise NotImplementedError(
                    f"{type(self).__name__} builds its own local trainer; "
                    "cfg.compute_layout wraps the shared "
                    "_build_local_train only (the flag would otherwise "
                    "be silently inert)")
            if getattr(cfg, "dp_noise_multiplier", 0.0) > 0:
                # Same failure mode layout.py refuses dropout for: the
                # DP Gaussian draw's shapes follow the PHYSICAL layout
                # (per-parameter noise over padded leaves), so the
                # logical block gets different noise than a layout-off
                # run AND nonzero noise lands in the pad channels,
                # breaking the pad-stays-zero exactness invariant.
                # (dp_clip alone is exact: padded per-example grads are
                # zero, so clip norms are unchanged.)
                raise NotImplementedError(
                    "cfg.compute_layout cannot compose with DP noise "
                    "(dp_noise_multiplier > 0): the per-parameter noise "
                    "draw shapes follow the physical layout, which "
                    "breaks the padded-vs-logical exactness contract — "
                    "run DP-SGD at the logical layout")
            from fedml_tpu.parallel.layout import (compute_layout,
                                                   im2col_layout)

            layout = (im2col_layout(model, sample_x)
                      if layout_cfg == "im2col"
                      else compute_layout(model, sample_x))
            if not layout.is_identity:
                self._layout = layout
                self._phys_fns = model_fns(layout.physical_model)
        # bf16 client-step compute (parallel/layout.step_dtype_model):
        # the TRAINER's apply computes in bf16; params/grads/optimizer/
        # aggregation/eval all stay fp32. Resolved before set_client_lr
        # so _build_local_train sees it.
        self._step_dtype = None
        sd = getattr(cfg, "client_step_dtype", "fp32") or "fp32"
        if sd not in ("fp32", "bf16"):
            raise ValueError(
                f"cfg.client_step_dtype={sd!r}: expected 'fp32' or 'bf16'")
        if sd == "bf16":
            if type(self)._build_local_train \
                    is not FedAvgAPI._build_local_train:
                raise NotImplementedError(
                    f"{type(self).__name__} builds its own local trainer; "
                    "cfg.client_step_dtype wraps the shared "
                    "_build_local_train only (the flag would otherwise "
                    "be silently inert)")
            from fedml_tpu.parallel.layout import step_dtype_model

            # Refusal happens here (construction), not first trace: the
            # twin builder raises for families without a compute-dtype
            # field. Composed with the layout: the PHYSICAL twin is the
            # one the trainer applies, so it is the one cloned to bf16.
            base = (self._layout.physical_model if self._layout is not None
                    else model)
            self._step_fns = model_fns(
                step_dtype_model(base, jnp.bfloat16))
            self._step_dtype = jnp.bfloat16
        self._client_lr = None
        self._fused_step_fn = None
        self.set_client_lr(cfg.lr)
        self.eval_fn = jax.jit(make_eval_fn(self.fns.apply, loss_fn, pad_id=pad_id))

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_rng = jax.random.split(rng)
        self.net = self.fns.init(init_rng, sample_x)

        if cfg.client_selection == "oort":
            rec = self.capability()
            if (rec.custom_round or rec.custom_step
                    or self.window_protocol != "round"):
                # The utility-update hook lives in FedAvgAPI's round; a
                # custom round/step that skips it would silently
                # degenerate oort to pure exploration (= uniform
                # sampling).
                raise NotImplementedError(
                    f"{type(self).__name__} runs a custom round (capability "
                    "record) and would skip oort's per-round utility "
                    "update; oort serves the FedAvg family's shared round "
                    "only")
            # Eager init: the checkpoint template must match the saved
            # structure (lazy init would save oort state but restore
            # against an empty template).
            n = cfg.client_num_in_total
            self._oort_utility = np.zeros(n, np.float64)
            self._oort_last = np.full(n, -1, np.int64)

    def set_client_lr(self, lr: float):
        """(Re)build the jitted round for a new client learning rate —
        the hook the round-level LR schedulers use (fed_launch
        schedulers decay the client LR across comm rounds). A no-op when
        the lr is unchanged; each distinct lr value costs one re-jit, so
        schedulers should quantize to a few buckets."""
        if lr == self._client_lr:
            return
        self._client_lr = lr
        self._rounds_scan_fn = None  # round_fn changes → cached scan stale
        self._window_scan_fn = None  # windowed scan rides round_fn too
        self._fused_step_fn = None  # fused round step rides round_fn too
        self._on_client_lr_change()  # subclasses drop their own cached jits
        cfg, mesh = self.cfg, self.mesh
        optimizer = make_client_optimizer(
            cfg.client_optimizer, lr, cfg.wd, cfg.grad_clip
        )
        self.local_train = self._build_local_train(optimizer, self._loss_fn)
        transform = self._client_transform()
        guard = self._nan_guard
        if mesh is None:
            round_fn = self._make_vmap_round(
                self.local_train, transform, guard
            )

            if not self._streaming and self._corruptor() is None:
                # (The corruption drill's rounds take a trailing per-
                # round adversary-mask operand run_round computes host-
                # side; the fused gather-inside-jit path has no slot for
                # it, so drilled rounds use the plain round_fn path.)
                # Single-device: fuse the client gather + weight
                # computation into the jitted round. Dispatching the takes
                # eagerly costs ~40% of the round wall-clock on a real chip
                # (4 un-jitted device ops + host sync per round).
                # FederatedArrays is a struct.dataclass pytree, so it
                # traces straight through jit. (The streaming store
                # gathers on HOST — its cohort arrives pre-gathered, so
                # the plain round_fn path below is the fast path.)
                from fedml_tpu.data.batching import gather_clients

                def fused(net, fed, idx, wmask, rng):
                    sub = gather_clients(fed, idx)
                    w = sub.counts.astype(jnp.float32) * wmask
                    return round_fn(net, sub.x, sub.y, sub.mask, w, w, rng)

                self.round_fn_fused = jax.jit(fused)
        else:
            # Pad the sampled set to the CLIENT axis size only (a 2-D mesh's
            # model axis does not multiply the client shards). Gather stays
            # outside the jit: arbitrary sampled indices cross client
            # shards, so the resharding take must run before shard_map.
            round_fn = self._make_sharded_round(
                self.local_train, mesh, transform, guard
            )
        self.round_fn = jax.jit(round_fn)

    # --- hooks subclasses override (FedOpt/FedProx/...) -------------------
    #: Set True by the one subclass that READS cfg.adapter_rank
    #: (FedAdapterAPI); everyone else refuses the flag at construction.
    _consumes_adapter_cfg = False

    def _model_fns(self, model):
        """The functional model interface every round/eval builder uses.
        FedAdapterAPI overrides this to return the adapter-level fns
        (``init`` → the trainable ADAPTER tree, ``apply`` → frozen base
        merged with the adapters per call), so the whole FedAvg
        machinery — aggregation, codecs, checkpoints, the scan tiers —
        operates on the adapter tree without modification."""
        return model_fns(model)

    def _net_init_input(self, sample_x):
        """The array handed to ``fns.init`` (and the compute layout).
        Defaults to a sample data batch; models initialized from a
        different input shape (FedGAN's latent noise) override this."""
        return sample_x

    def _on_client_lr_change(self):
        """Called whenever the client lr actually changes (lr schedules).
        Subclasses holding their OWN lr-dependent jitted functions (Ditto's
        personal trainer, SCAFFOLD's corrected round) invalidate them here
        — forgetting this is how a subclass silently trains at a stale lr
        under --lr_schedule."""

    def _make_vmap_round(self, local_train, transform, guard):
        """Single-device round construction; q-FedAvg swaps in a
        loss-reweighted aggregation here. Under oort selection the round
        additionally returns the per-client training losses (the
        utility observable, Lai et al. §5) — run_round captures them so
        no post-round eval pass is needed."""
        return make_vmap_round(
            local_train, client_transform=transform, nan_guard=guard,
            with_client_losses=self.cfg.client_selection == "oort",
            aggregator=self._round_aggregator(),
            corruptor=self._corruptor())

    def _make_sharded_round(self, local_train, mesh, transform, guard):
        return make_sharded_round(
            local_train, mesh, client_axis(mesh),
            client_transform=transform, nan_guard=guard,
            with_client_losses=self.cfg.client_selection == "oort",
            aggregator=self._round_aggregator(),
            corruptor=self._corruptor(),
            group_reduce=self._group_reduce)

    def _round_aggregator(self):
        """The aggregator handed to the round builders: ``None`` for mean
        (the builders' weighted-mean fast path — per-shard partial sums +
        psum on a mesh — stays byte-for-byte the compiled program it was
        before the protocol existed), the resolved ``core.robust_agg``
        callable otherwise."""
        return None if self._aggregator.is_mean else self._aggregator

    def _corruptor(self):
        """Device-side update-corruption hook for the attack drill
        (``None`` = no corruption; rounds keep their 7-operand
        signature). FedAvgRobustAPI builds
        ``UpdateCorruptor.device_fn()`` from ``cfg.corrupt_mode`` and
        supplies the per-round adversary masks via ``_round_aux`` /
        ``_window_scan_extras``."""
        return None

    def _build_local_train(self, optimizer, loss_fn):
        # bf16 client step: the trainer applies the compute-dtype twin
        # (of the physical model when a layout is active — the two
        # levers compose); everything else in this method is unchanged
        # because the twin's PARAM TREE is the fp32 one.
        apply = (self._step_fns.apply if self._step_dtype is not None
                 else None)
        if self._layout is not None:
            # Lane-fill layout: the trainer runs the PHYSICAL twin's
            # apply; the wrapper pads the incoming logical net and
            # slices the logical block back out, so every caller of
            # local_train (vmap round, sharded round, window scan) keeps
            # the logical-shape contract untouched.
            from fedml_tpu.parallel.layout import wrap_local_train

            inner = make_local_train_fn_from_cfg(
                apply or self._phys_fns.apply, optimizer, self.cfg,
                loss_fn)
            return wrap_local_train(inner, self._layout)
        return make_local_train_fn_from_cfg(apply or self.fns.apply,
                                            optimizer, self.cfg, loss_fn)

    def _server_update(self, old_net, avg_net):
        """FedAvg: the new global model is the client average."""
        return avg_net

    def _client_transform(self):
        """Optional ``(global_net, client_net) -> client_net`` applied to
        each trained client before averaging (robust clipping etc.). The
        base builds the simulated-compression transform from
        ``cfg.compress``; subclasses that replace this hook (robust
        clipping) must reject ``cfg.compress`` rather than drop it."""
        return self._compress_transform()

    def _compress_transform(self):
        """``cfg.compress`` → on-device transform applied to each
        client's delta before aggregation (simulates communication-
        constrained FL inside the jitted round): ``"topk<r>"``
        sparsifies to the top-k entries; ``"q<bits>"`` runs QSGD-style
        stochastic uniform quantization (unbiased — the per-client rng
        stream arrives via run_clients_guarded's 3-arg transform form).
        Error feedback lives on the cross-silo wire path, which carries
        state between rounds."""
        name = self.cfg.compress or "none"
        if name == "none":
            return None
        from fedml_tpu.core.compression import (
            dequantize,
            quantize_stochastic,
            topk_compress,
            topk_decompress,
            tree_spec,
            tree_to_vector,
            vector_to_tree,
        )
        from fedml_tpu.trainer.local import NetState

        if name.startswith("topk"):
            try:
                ratio = float(name[len("topk"):])
            except ValueError:
                raise ValueError(
                    f"cfg.compress={name!r}: expected 'topk<ratio>' with a "
                    f"numeric ratio, e.g. 'topk0.05'") from None
            if not 0 < ratio <= 1:
                raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")

            def transform(global_net, client_net):
                gvec = tree_to_vector(global_net.params)
                delta = tree_to_vector(client_net.params) - gvec
                k = max(1, int(round(ratio * delta.shape[0])))
                values, idx, _ = topk_compress(delta, k)
                recon = topk_decompress(values, idx, delta.shape[0])
                params = vector_to_tree(gvec + recon,
                                        tree_spec(global_net.params))
                return NetState(params, client_net.model_state)

            return transform
        if name.startswith("q"):
            try:
                bits = int(name[1:])
            except ValueError:
                raise ValueError(
                    f"cfg.compress={name!r}: expected 'q<bits>', e.g. "
                    f"'q8'") from None
            from fedml_tpu.core.compression import _check_bits

            _check_bits(bits)  # fail at construction, not first-round trace

            def transform(global_net, client_net, rng):
                gvec = tree_to_vector(global_net.params)
                delta = tree_to_vector(client_net.params) - gvec
                q, scale = quantize_stochastic(delta, bits, rng)
                params = vector_to_tree(gvec + dequantize(q, scale),
                                        tree_spec(global_net.params))
                return NetState(params, client_net.model_state)

            transform.wants_rng = True  # run_clients_guarded's 3-arg form
            return transform
        raise ValueError(
            f"cfg.compress={name!r}: simulator rounds support "
            "'topk<ratio>' or 'q<bits>'")

    # ----------------------------------------------------------------------
    # sample_round/run_round come from FederatedLoop (shared scaffold).

    def sample_round(self, round_idx: int):
        """Adds Power-of-Choice selection (cfg.client_selection="pow_d",
        Cho et al. 2020) on top of the inherited uniform sampling: draw d
        candidates uniformly, evaluate the current global on their local
        shards (one vmapped pass), keep the highest-loss
        ``client_num_per_round``.

        The result is memoized per round: pow_d depends on the CURRENT
        net, so a subclass that samples again mid-round (Ditto's personal
        step runs after the global update) must see the same set the
        global round trained — recomputing would silently select a
        different cohort."""
        cached = getattr(self, "_sample_cache", None)
        if cached is not None and cached[0] == round_idx:
            return cached[1], cached[2]
        idx, wmask = self._sample_round_uncached(round_idx)
        self._sample_cache = (round_idx, idx, wmask)
        return idx, wmask

    def _sample_round_uncached(self, round_idx: int):
        if self.cfg.client_selection == "random":
            return super().sample_round(round_idx)
        if self.cfg.client_selection == "oort":
            return self._sample_oort(round_idx)
        if self.cfg.client_selection != "pow_d":
            raise ValueError(
                f"unknown client_selection {self.cfg.client_selection!r}; "
                "use 'random', 'pow_d' or 'oort'")
        from fedml_tpu.core.sampling import (
            pad_to_multiple,
            sample_clients_weighted,
        )

        cfg = self.cfg
        d = cfg.pow_d_candidates or 2 * cfg.client_num_per_round
        d = min(d, cfg.client_num_in_total)
        m = min(cfg.client_num_per_round, cfg.client_num_in_total)
        if d < m:
            raise ValueError(
                f"pow_d needs at least client_num_per_round candidates "
                f"(d={d} < m={m}); raise --pow_d_candidates")
        # Cho et al. 2020 draw the candidate set proportional to client
        # data fraction, not uniformly (matters on power-law partitions).
        # A sharded store's ClientDirectory serves the same draw from its
        # count metadata (identical stream — it delegates here).
        directory = getattr(self.train_fed, "directory", None)
        if directory is not None \
                and directory.num_clients == cfg.client_num_in_total:
            candidates = directory.sample_cohort_weighted(round_idx, d)
        else:
            candidates = sample_clients_weighted(
                round_idx, cfg.client_num_in_total, d, self.train_fed.counts)
        if self._streaming:
            # Store path: host-gather the candidate cohort, one vmapped
            # eval pass (same kernel the resident path jits the gather
            # into). d is small (~2x clients/round), so the extra H2D is
            # one cohort's worth.
            sub = self.train_fed.gather_cohort(candidates)
            losses = np.asarray(self._per_client_eval()(
                self._eval_net(), sub.x, sub.y, sub.mask)["loss"])
            order = np.argsort(-losses, kind="stable")[:m]
            idx = candidates[np.sort(order)]
            return pad_to_multiple(idx, self.n_shards)
        losses = self._cohort_losses_resident(candidates)
        order = np.argsort(-losses, kind="stable")[:m]
        idx = candidates[np.sort(order)]
        idx, wmask = pad_to_multiple(idx, self.n_shards)
        return idx, wmask

    def _stream_cohort(self, round_idx: int, idx):
        """Fetch the round's cohort from the host store (prefetched when
        possible) and kick off the NEXT round's gather + H2D transfer so
        it overlaps this round's compute. Only seeded-random selection can
        prefetch — pow_d depends on the current net."""
        from fedml_tpu.data.store import CohortPrefetcher

        pf = getattr(self, "_cohort_prefetcher", None)
        if pf is None:
            pf = self._cohort_prefetcher = CohortPrefetcher(self.train_fed)
        sub = pf.get(round_idx, idx)
        # Post-round consumers (oort's utility eval) reuse this instead of
        # paying a second synchronous host gather of the same cohort.
        self._stream_last = (round_idx, np.asarray(idx), sub)
        if (self.cfg.client_selection == "random"
                and round_idx + 1 < self.cfg.comm_round):
            from fedml_tpu.core.sampling import pad_to_multiple, sample_clients

            nidx, _ = pad_to_multiple(
                sample_clients(round_idx + 1, self.cfg.client_num_in_total,
                               self.cfg.client_num_per_round),
                self.n_shards)
            pf.prefetch(round_idx + 1, nidx)
        return sub

    # --- Oort utility-based selection (Lai et al., OSDI'21) --------------
    def _sample_oort(self, round_idx: int):
        """Epsilon-greedy utility selection. Exploit: the highest-utility
        previously-seen clients, utility = observed loss x sqrt(n_i)
        (Oort's statistical utility) + staleness bonus
        ``oort_staleness_coef * sqrt(rounds since last seen)``. Explore:
        a seeded-uniform draw over never-seen clients. Utilities update
        from each trained cohort's IN-ROUND training losses, captured
        from the jitted round's outputs
        (:meth:`_update_oort_state`), so the very first rounds are pure
        exploration. Exploration is SUSTAINED (Oort §4's epsilon-greedy):
        once every client has been seen, the epsilon slice is drawn
        uniformly from seen-but-not-exploited clients rather than silently
        dropping to zero. Deterministic given round index and history."""
        from fedml_tpu.core.sampling import pad_to_multiple

        cfg = self.cfg
        n = cfg.client_num_in_total
        k = min(cfg.client_num_per_round, n)
        seen = self._oort_last >= 0
        rs = np.random.RandomState(round_idx)

        n_exploit = min(k - int(np.ceil(cfg.oort_epsilon * k)),
                        int(seen.sum()))
        n_explore = k - n_exploit  # epsilon slice + any exploit shortfall

        chosen = []
        if n_exploit:
            staleness = np.sqrt(np.maximum(round_idx - self._oort_last, 0))
            score = np.where(
                seen,
                self._oort_utility + cfg.oort_staleness_coef * staleness,
                -np.inf)
            chosen.append(np.argsort(-score, kind="stable")[:n_exploit])
        if n_explore:
            # Never-seen clients first; when they run short (everyone —
            # or nearly everyone — already seen) the remainder comes
            # uniformly from seen clients outside the exploit set, so the
            # epsilon fraction of each cohort keeps exploring forever.
            unseen_pool = np.flatnonzero(~seen)
            take_unseen = min(len(unseen_pool), n_explore)
            if take_unseen:
                chosen.append(rs.choice(unseen_pool, take_unseen,
                                        replace=False))
            rest = n_explore - take_unseen
            if rest:
                exploited = (chosen[0] if n_exploit
                             else np.array([], np.int64))
                pool = np.setdiff1d(np.flatnonzero(seen), exploited)
                chosen.append(rs.choice(pool, rest, replace=False))
        idx = np.sort(np.concatenate(chosen).astype(np.int32))
        return pad_to_multiple(idx, self.n_shards)

    def _update_oort_state(self, round_idx: int, idx, wmask) -> None:
        """Refresh utilities for the just-trained cohort from the
        IN-ROUND training losses (Lai et al. §5's exact observable): the
        round is built with ``with_client_losses`` under oort, so
        ``run_round`` captured each client's local training loss and no
        extra eval pass runs. Fallback for subclasses whose custom round
        doesn't expose per-client losses (q-FedAvg's fair round): one
        vmapped eval of the new global on the cohort's shards — the
        documented r2 proxy. Updates mask padded slots out either way."""
        idx = np.asarray(idx)
        active_mask = np.asarray(wmask) > 0
        captured = getattr(self, "_round_client_losses", None)
        if captured is not None:
            self._round_client_losses = None  # one round's observable
            losses = np.asarray(captured, np.float64)
            # A diverged client (nan_guard off) must not write NaN into
            # its utility: argsort ranks NaN last forever, silently
            # blacklisting the client from exploitation. Zero matches the
            # nan_guard convention (deprioritized, staleness bonus still
            # recovers it).
            losses = np.where(np.isfinite(losses), losses, 0.0)
        elif self._streaming:
            cached = getattr(self, "_stream_last", None)
            if cached is not None and cached[0] == round_idx and \
                    np.array_equal(cached[1], idx):
                sub = cached[2]
            else:
                sub = self.train_fed.gather_cohort(idx)
            losses = np.asarray(self._per_client_eval()(
                self._eval_net(), sub.x, sub.y, sub.mask)["loss"], np.float64)
        else:
            losses = self._cohort_losses_resident(idx).astype(np.float64)
        counts = self._host_counts()[idx].astype(np.float64)
        util = losses * np.sqrt(np.maximum(counts, 1))
        active = idx[active_mask]
        self._oort_utility[active] = util[active_mask]
        self._oort_last[active] = round_idx

    def _host_counts(self) -> np.ndarray:
        """Per-client sample counts as host numpy (fetched once)."""
        c = getattr(self, "_host_counts_np", None)
        if c is None:
            c = self._host_counts_np = np.asarray(self.train_fed.counts)
        return c

    def _cohort_losses_resident(self, idx) -> np.ndarray:
        """Per-client loss of the current net on a resident-layout cohort
        — gather traced INSIDE the jit (an eager gather would pay the
        multi-dispatch host sync the fused round path exists to avoid).
        Shared by pow_d candidate scoring and oort utility updates."""
        from fedml_tpu.data.batching import gather_clients

        fn = getattr(self, "_cohort_losses_jit", None)
        if fn is None:
            per_client = self._per_client_eval()  # shared cached kernel

            def losses_fn(net, fed, idx):
                sub = gather_clients(fed, idx)
                return per_client(net, sub.x, sub.y, sub.mask)["loss"]

            fn = jax.jit(losses_fn)
            self._cohort_losses_jit = fn
        return np.asarray(fn(self._eval_net(), self.train_fed,
                             jnp.asarray(idx)))

    # -- checkpoint/resume: oort utilities are run state ------------------
    def checkpoint_extra_state(self):
        if self.cfg.client_selection == "oort":
            return {"oort_utility": self._oort_utility,
                    "oort_last": self._oort_last}
        return {}

    def load_checkpoint_extra_state(self, extra) -> None:
        if extra and "oort_utility" in extra:
            self._oort_utility = np.asarray(extra["oort_utility"])
            self._oort_last = np.asarray(extra["oort_last"])

    def _require_plain_sgd_round(self, what: str) -> None:
        """Shared constructor guard for corrected-SGD algorithms
        (SCAFFOLD, FedDyn): their dedicated local steps implement plain
        SGD plus the correction, so cfg knobs the generic trainer honors
        must be rejected loudly instead of silently dropped."""
        if self.cfg.client_optimizer != "sgd":
            raise ValueError(
                f"{what} applies to plain SGD local steps; got "
                f"client_optimizer={self.cfg.client_optimizer!r}")
        unsupported = {
            "grad_clip": self.cfg.grad_clip,
            "dp_clip": self.cfg.dp_clip,
            "dp_noise_multiplier": self.cfg.dp_noise_multiplier,
            "compress": (self.cfg.compress
                         if self.cfg.compress != "none" else None),
            # The corrected-SGD algorithms build their trainers outside
            # _build_local_train, where the lane-fill layout and the
            # bf16 step dtype are wired.
            "compute_layout": (
                getattr(self.cfg, "compute_layout", "none")
                if getattr(self.cfg, "compute_layout", "none") != "none"
                else None),
            "client_step_dtype": (
                getattr(self.cfg, "client_step_dtype", "fp32")
                if getattr(self.cfg, "client_step_dtype", "fp32")
                not in ("fp32", "") else None),
        }
        bad = [k for k, v in unsupported.items() if v]
        if self._nan_guard:
            bad.append("nan_guard")
        if bad:
            raise ValueError(
                f"{what} does not support: " + ", ".join(bad))

    def _cohort(self, round_idx: int, idx):
        """The round's sampled clients as a ``FederatedArrays``: device
        gather on the resident layout, host gather (double-buffered) on
        the streaming store. Subclasses that materialize the cohort
        themselves (FedNova's τ algebra, TurboAggregate's MPC) go through
        this so they stream for free."""
        if self._streaming:
            return self._stream_cohort(round_idx, idx)
        from fedml_tpu.data.batching import gather_clients

        return gather_clients(self.train_fed, jnp.asarray(idx))

    # --- pod-reduce observability (DCN×ICI mesh only) --------------------
    def _emit_reduce_obs(self, n_rounds: int = 1) -> None:
        """Per-round ``ctrl/`` gauges for the inter-host reduction: how
        many model-sized partials crossed the DCN axis this round
        (``dcn_partials``) and the byte payload they carry
        (``dcn_partials × payload_nbytes``). With ``group_reduce`` (or
        the mean fast path, which is hierarchical by construction) the
        partial count is G = n_hosts — INDEPENDENT of the cohort size;
        the flat non-mean ``all_gather`` fallback ships the whole padded
        cohort, C partials. ``dcn_flat_bytes_per_round`` is the flat
        fallback's cost for the same round — the ruler the O(G) claim is
        measured against. Also mirrors the numbers onto the active
        ``SpanTracer`` as a ``reduce.dcn`` instant event (null-tracer
        cheap when tracing is off)."""
        if not self._dcn_groups:
            return
        reg = getattr(self, "_reduce_registry", None)
        if reg is None:
            from fedml_tpu.obs.registry import (MetricsRegistry,
                                                payload_nbytes)

            reg = self._reduce_registry = MetricsRegistry()
            self._reduce_payload = payload_nbytes(self.net)
            self._g_dcn_parts = reg.gauge("dcn_partials")
            self._g_dcn_bytes = reg.gauge("dcn_bytes_per_round")
            self._g_dcn_flat = reg.gauge("dcn_flat_bytes_per_round")
            self._c_dcn_rounds = reg.counter("dcn_rounds")
        grouped = (self._aggregator.is_mean or self._group_reduce)
        cpr = min(self.cfg.client_num_per_round,
                  self.cfg.client_num_in_total)
        flat_parts = -(-cpr // self.n_shards) * self.n_shards  # padded C
        parts = self._dcn_groups if grouped else flat_parts
        self._g_dcn_parts.set(parts)
        self._g_dcn_bytes.set(parts * self._reduce_payload)
        self._g_dcn_flat.set(flat_parts * self._reduce_payload)
        self._c_dcn_rounds.inc(n_rounds)
        from fedml_tpu.obs import trace as obs_trace

        obs_trace.active().instant(
            "reduce.dcn", cat="reduce", partials=parts,
            nbytes=parts * self._reduce_payload, groups=self._dcn_groups,
            rounds=n_rounds)

    def reduce_profile(self) -> Dict[str, float]:
        """Snapshot of the pod-reduce gauges (empty off a DCN mesh, or
        before the first round emitted)."""
        reg = getattr(self, "_reduce_registry", None)
        return reg.snapshot() if reg is not None else {}

    # --- capability record (algos/capability.py) ------------------------
    def capability(self):
        """This algorithm's :class:`~fedml_tpu.algos.capability.
        CarryCapability` record — derived once per class from the carry
        protocol declarations; every scan-tier guard below keys on it
        (and refuses with the record-derived message)."""
        from fedml_tpu.algos.capability import record_for

        return record_for(type(self))

    def _build_fused_step(self):
        """The UNJITTED one-round step this algorithm publishes —
        ``step(net, extra, x, y, mask, weights, key, *extras) ->
        ((net', extra'), loss)`` — the SINGLE function both the fused
        host round (jitted with donation, W=1) and the windowed scan
        (``lax.scan`` over its leading-axis-W twin) execute, so the two
        tiers are bit-equal by construction.

        "round"-protocol algorithms get it for free from ``round_fn`` +
        the pure ``_window_server_update``; "custom"-protocol algorithms
        override this (SCAFFOLD/FedDyn wrap their stateful round with
        ``make_fused_stateful_round_step``; Ditto/FedBN build bespoke
        steps over their per-client state stacks)."""
        if self.window_protocol != "round":
            from fedml_tpu.algos.capability import refusal

            raise NotImplementedError(
                refusal(type(self), "the fused round step"))
        from fedml_tpu.parallel.shard import make_fused_round_step

        return make_fused_round_step(self.round_fn,
                                     self._window_server_update())

    def _fused_round_extras(self, round_idx: int, idx, wmask):
        """Per-round trailing operands for the fused step. "round"
        protocol: the ``_round_aux`` hook (the corruption drill's
        adversary mask, FedNova's τ-normalized weights). "custom"
        protocol: the W=1 slice of ``_window_scan_extras`` — the same
        cohort index maps / scatter masks the windowed scan feeds, so
        the fused host round and the scanned round consume identical
        operands."""
        if self.window_protocol == "custom":
            return tuple(
                a[0] for a in self._window_scan_extras(
                    np.asarray(idx)[None], np.asarray(wmask)[None]))
        return self._round_aux(round_idx, idx, wmask)

    # --- fused round step (one donated dispatch per host-loop round) ---
    def _fused_round_step(self):
        """The cached donated FUSED round step — client training +
        aggregation + the algorithm's carry update in ONE dispatch (the
        windowed scan's donation discipline at W=1) — or ``None`` when
        this algorithm/config must keep the separate ``run_round`` +
        ``_server_update`` procedure (capability record says no fused
        step; oort's three-output round). Returns ``(pre, gather)``:
        ``pre`` takes pre-gathered cohort operands; ``gather`` (resident
        single-device "round" protocol only) traces the client gather
        inside the same dispatch."""
        if not self.capability().fused:
            return None
        if self.cfg.client_selection == "oort":
            return None  # with_client_losses: 3-output round
        fn = self._fused_step_fn
        if fn is None:
            step = self._build_fused_step()
            # Donate the (net, extra) carry: the caller always rebinds
            # self.net and commits the carry before anything reads the
            # donated originals — XLA reuses the old model's buffers
            # instead of holding old net + round average + new net live
            # (obs.sanitizer.donation_audit pins the 1-copy steady
            # state). For custom-protocol carries this also donates the
            # client-state STACK — one live copy instead of two.
            pre = jax.jit(step, donate_argnums=(0, 1))
            gather = None
            if (self.mesh is None and not self._streaming
                    and self.window_protocol == "round"):
                from fedml_tpu.data.batching import gather_clients

                def gather_step(net, extra, fed, idx, wmask, key):
                    sub = gather_clients(fed, idx)
                    w = sub.counts.astype(jnp.float32) * wmask
                    return step(net, extra, sub.x, sub.y, sub.mask, w, key)

                gather = jax.jit(gather_step, donate_argnums=(0, 1))
            fn = self._fused_step_fn = (pre, gather)
        return fn

    def _train_round_fused(self, round_idx: int):
        """One host-loop round through the fused step: the same sample/
        gather/rng prelude as ``run_round``, then ONE donated dispatch
        with the carry committed back (``_window_carry_commit``) — so
        checkpoints and remainder/eval host work read the new state.
        Returns the round's (device) loss."""
        pre, gather = self._fused_round_step()
        self.rng, rnd_rng = jax.random.split(self.rng)
        self._last_round_key = rnd_rng
        idx, wmask = self.sample_round(round_idx)
        aux = self._fused_round_extras(round_idx, idx, wmask)
        extra = self._window_carry_init()
        if self._streaming:
            sub = self._stream_cohort(round_idx, idx)
            weights = sub.counts.astype(jnp.float32) * jnp.asarray(wmask)
            (self.net, extra), loss = pre(
                self.net, extra, sub.x, sub.y, sub.mask, weights, rnd_rng,
                *aux)
        elif gather is not None and not aux:
            (self.net, extra), loss = gather(
                self.net, extra, self.train_fed, jnp.asarray(idx),
                jnp.asarray(wmask), rnd_rng)
        else:
            from fedml_tpu.data.batching import gather_clients

            sub = gather_clients(self.train_fed, idx)
            weights = sub.counts.astype(jnp.float32) * jnp.asarray(wmask)
            (self.net, extra), loss = pre(
                self.net, extra, sub.x, sub.y, sub.mask, weights, rnd_rng,
                *aux)
        self._window_carry_commit(extra)
        self._emit_reduce_obs()
        return loss

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        if self._fused_round_step() is not None:
            loss = self._train_round_fused(round_idx)
            return {"round": round_idx, "train_loss": float(loss)}
        if self.window_protocol == "custom":
            # A custom-protocol class without its fused step must not
            # silently fall through to plain run_round rounds — that is
            # the exact drift the capability record exists to refuse.
            from fedml_tpu.algos.capability import refusal

            raise NotImplementedError(
                refusal(type(self), "train_one_round"))
        avg, loss = self.run_round(round_idx)
        self.net = self._server_update(self.net, avg)
        self._emit_reduce_obs()
        if self.cfg.client_selection == "oort":
            # Memoized — returns the cohort this round actually trained.
            idx, wmask = self.sample_round(round_idx)
            self._update_oort_state(round_idx, idx, wmask)
        return {"round": round_idx, "train_loss": float(loss)}

    def train_rounds_pipelined(self, n_rounds: int, start_round: int = 0):
        """Run ``n_rounds`` host-loop rounds back-to-back WITHOUT the
        per-round host sync: ``train_one_round``'s ``float(loss)`` blocks
        until the round finishes, serializing device compute against the
        next round's host work. Here every round's jitted dispatch is
        enqueued as soon as its cohort is ready — async dispatch chains
        the net dependency, so the device trains round r while the host
        samples/gathers round r+1 (with the streaming store's prefetcher
        this pipelines host gather + H2D + compute three-deep). Losses
        are fetched once at the end. Per-round semantics are identical to
        calling ``train_one_round`` in a loop (tested bit-equal) — use
        this between eval points; it skips the eval-cadence bookkeeping.
        Works for every subclass whose round rides ``run_round``
        (server updates are device math, so they pipeline too).

        Measured caveat: through a REMOTE device tunnel the synced
        per-round loop can be faster — the streaming prefetcher already
        overlaps the next gather with the loss wait, and a flood of
        unsynced dispatches costs the tunnel more than the syncs save
        (A/B on the 3400-client FEMNIST bench config: ~8.8 vs ~5.5
        rounds/sec). Prefer this method on directly-attached devices."""
        # Capability-record guard: "round"-protocol algorithms pipeline
        # whenever their per-round procedure is run_round +
        # _server_update (stateful host-side _server_update overrides
        # like FedOpt's included — purity only matters inside the
        # windowed scan); "custom"-protocol algorithms pipeline through
        # their fused one-dispatch step. Everything else refuses with
        # the record-derived reason.
        if not self.capability().pipelined:
            from fedml_tpu.algos.capability import refusal

            raise NotImplementedError(
                refusal(type(self), "train_rounds_pipelined"))
        if self.cfg.client_selection == "oort":
            raise NotImplementedError(
                "oort updates per-client utilities after every round "
                "(train_one_round); the pipelined loop skips that hook — "
                "use the per-round loop")
        losses = []
        fused = self._fused_round_step()
        for r in range(start_round, start_round + n_rounds):
            if fused is not None:
                # One donated dispatch per round (train + aggregate +
                # server update) — same async-dispatch pipelining, one
                # fewer dispatch and no undonated intermediates.
                losses.append(self._train_round_fused(r))
            else:
                avg, loss = self.run_round(r)
                self.net = self._server_update(self.net, avg)
                self._emit_reduce_obs()
                losses.append(loss)
        return [float(l) for l in losses]

    # --- windowed carry protocol ------------------------------------------
    #: How (whether) this algorithm rides the multi-round scan tiers
    #: (``train_rounds_windowed`` / ``train_rounds_pipelined``):
    #:
    #: - ``"round"`` — the per-round procedure is exactly ``run_round``
    #:   + ``_server_update``. The windowed scan replays ``round_fn``
    #:   with the PURE server update from :meth:`_window_server_update`
    #:   folded between rounds (plain FedAvg and FedProx need no carry;
    #:   FedOpt carries its server optimizer state).
    #: - ``"custom"`` — the subclass builds its own scan body
    #:   (:meth:`_build_window_scan`) and threads its own carry
    #:   (SCAFFOLD: server control + the full client-control stack,
    #:   gathered/scattered per scanned round). Custom rounds do not
    #:   pipeline — their per-round host procedure IS the round.
    #: - ``None`` — host loop only.
    #:
    #: The guards key on THIS declaration (plus a consistency check that
    #: a "round" declarer really left the round alone), not on
    #: ``type(self)`` identity lists — so a subclass that overrides only
    #: ``_server_update`` opts in by providing its pure windowed form
    #: instead of being rejected wholesale.
    window_protocol: Optional[str] = "round"

    def _window_server_update(self):
        """The PURE form of :meth:`_server_update` for the windowed scan:
        ``None`` means plain FedAvg (``net' = round average``, no carry);
        otherwise a jit-traceable ``(net, avg, extra, key) ->
        (net', extra')`` with ``extra`` the carried server state and
        ``key`` the round's rng key (the same key ``run_round`` split for
        that round — randomized server updates fold_in from it, see
        FedAvgRobustAPI's weak-DP noise; deterministic updates like
        FedOpt's ignore it). A subclass that overrides
        ``_server_update`` (host-loop, may touch ``self``) MUST also
        override this hook — inheriting the plain-average fold would
        silently change its semantics inside the scan."""
        if type(self)._server_update is not FedAvgAPI._server_update:
            raise NotImplementedError(
                f"{type(self).__name__} overrides _server_update without "
                "providing its pure windowed form; override "
                "_window_server_update (and the carry init/commit hooks) "
                "or set window_protocol = None")
        return None

    def _window_carry_init(self):
        """Extra carry entering the window scan (read from instance
        state). Plain FedAvg/FedProx carry nothing."""
        return None

    def _window_carry_commit(self, extra) -> None:
        """Write the scanned-out carry back to instance state, so host
        rounds / checkpoints after a window see it (FedOpt: the server
        optimizer state; SCAFFOLD: server + client controls)."""

    def _window_scan_extras(self, idx2d, wmask2d):
        """Extra per-round scanned inputs, as a tuple of ``[W, ...]``
        device arrays — "custom" protocol aux (SCAFFOLD passes the
        window's cohort index map and its scatter mask) OR trailing
        round operands for a "round"-protocol round built with extras
        (the corruption drill's ``[W, C]`` adversary mask, forwarded by
        ``make_window_scan`` into each scanned ``round_fn`` call).
        Default: none."""
        return ()

    def _window_update_mask(self, idx2d, wmask2d) -> np.ndarray:
        """``[W, k]`` float32 mask of slots that actually TRAIN in their
        round: active (un-padded) AND non-empty — the scatter gate for
        per-client state carried through the scan (SCAFFOLD's controls,
        FedDyn's corrections). Layout-agnostic: host counts serve both
        the resident arrays and the store (where it equals
        ``FederatedStore.window_trained_mask`` by construction)."""
        counts = self._host_counts()
        return (np.asarray(wmask2d, np.float32)
                * (counts[np.asarray(idx2d)] > 0).astype(np.float32))

    def _get_window_put(self):
        """The (cached) mesh layout ``put`` for window-scoped device
        arrays — the superbatch, the per-window weights, and any
        ``_window_scan_extras`` that must arrive client-sharded. ``None``
        on a single device (plain ``jnp.asarray`` suffices there)."""
        if self.mesh is None:
            return None
        put = getattr(self, "_window_put", None)
        if put is None:
            from fedml_tpu.parallel.shard import window_put

            put = self._window_put = window_put(
                self.mesh, client_axis(self.mesh))
        return put

    def _build_window_scan(self):
        """The UNJITTED window scan for this algorithm —
        ``scan(net, extra, x, y, mask, weights, keys, *extras) ->
        ((net', extra'), losses)``. Derived from the ONE fused step the
        algorithm publishes (:meth:`_build_fused_step`), so the windowed
        scan and the fused host round execute the same function and
        cannot drift."""
        from fedml_tpu.parallel.shard import make_step_window_scan

        return make_step_window_scan(self._build_fused_step())

    def _check_round_protocol(self, what: str) -> None:
        """Consistency guard for the tiers that replay the STANDARD
        round: the per-round procedure must be exactly ``run_round`` +
        ``_server_update`` — a subclass with its own round would
        silently run plain rounds here. Refusal text comes from the
        capability record."""
        if self.capability().custom_round:
            from fedml_tpu.algos.capability import refusal

            raise NotImplementedError(refusal(type(self), what))

    def _check_windowed_supported(self):
        """Shared guard for the windowed streaming tier — keyed on the
        capability record (algos/capability.py), not type identity."""
        from fedml_tpu.algos.capability import refusal

        if self.window_protocol not in (None, "round", "custom"):
            raise NotImplementedError(
                f"unknown window_protocol {self.window_protocol!r}; "
                "declare 'round', 'custom', or None")
        if (self.window_protocol == "custom"
                and type(self)._window_carry_init
                is not FedAvgAPI._window_carry_init
                and type(self)._window_carry_commit
                is FedAvgAPI._window_carry_commit):
            # State flows INTO the scan but the no-op default commit
            # would silently drop the scanned-out result — remainder
            # rounds/eval/checkpoints would read stale instance
            # state with no error (a forgotten init at least fails
            # loudly at trace time; a forgotten commit never does).
            raise NotImplementedError(
                f"{type(self).__name__} overrides _window_carry_init "
                "without _window_carry_commit; the scanned-out carry "
                "would be silently discarded")
        if not self.capability().windowed:
            raise NotImplementedError(
                refusal(type(self), "train_rounds_windowed"))
        if self.window_protocol == "round":
            self._window_server_update()  # raises when no pure form exists
        if not self._streaming:
            raise NotImplementedError(
                "windowed execution streams window superbatches from a "
                "FederatedStore; the resident layout already has the "
                "stronger train_rounds_on_device scan")
        if self.cfg.client_selection != "random":
            raise NotImplementedError(
                "windowed execution gathers the next W rounds' cohorts in "
                "advance, which only seeded-random selection permits; "
                "pow_d/oort depend on the current net — use the per-round "
                "host loop")

    def _get_window_scan(self):
        fn = self._window_scan_fn
        if fn is None:
            # Donate the incoming carry — net AND extra are always
            # replaced by the scan's outputs, so XLA reuses the old
            # buffers (the driver rebinds/commits before anything reads
            # the donated originals again).
            fn = jax.jit(self._build_window_scan(), donate_argnums=(0, 1))
            self._window_scan_fn = fn
        return fn

    def train_rounds_windowed(self, n_rounds: int, start_round: int = 0,
                              window: int = 8):
        """Windowed streaming execution: run ``n_rounds`` store-backed
        rounds with host syncs amortized over windows of ``window``
        rounds. Seeded-random selection makes every upcoming cohort known
        in advance, so each window's cohorts are gathered into ONE
        ``[W, k, S, B, ...]`` superbatch (``FederatedStore.gather_window``
        — single fancy-index gather + single H2D transfer, double-
        buffered against the previous window's compute by
        ``WindowPrefetcher``) and the W rounds run in one jitted
        ``lax.scan`` dispatch — host round-trips drop from O(rounds) to
        O(rounds/window).

        Server state rides the scan as the CARRY (the windowed carry
        protocol, see :attr:`window_protocol`): FedOpt's adaptive server
        optimizer threads its optax state between scanned rounds,
        SCAFFOLD carries the server control plus the full client-control
        stack (cohort slots gathered/scattered inside the scan body),
        and plain FedAvg/FedProx carry nothing. The carry is committed
        back to instance state at every window boundary, so
        checkpointing between calls captures it.

        BIT-EQUAL to the per-round host loop under the same seeds (tested,
        including on a client mesh and with a window the round count
        doesn't divide). Precisely: the TRAINING TRAJECTORY — params,
        carried server state, SCAFFOLD's controls — is bit-exact at every
        round (the per-step update math is sequential and identical);
        the reported per-round LOSS scalar is bit-equal at the pinned
        test shapes but can differ by ~1 ulp at some shapes, because XLA
        may reassociate the loss-reduction sum differently inside the
        scan than in the standalone round dispatch (telemetry only —
        observed on plain FedAvg as well, never feeding back into
        training). Each window forces its rounds onto the window's
        MAX step bucket, which is an exact training no-op — pad slots all
        hold the client's own (masked) first sample, all-masked tail
        steps are ``tree_select``-gated out, and the trainer's rng
        streams are prefix-stable in the step count
        (``trainer.local.make_epoch_shuffle``) — and the per-round rng
        chain (``jax.random.split`` per round, in round order) is
        reproduced exactly. Remainder rounds (< window) run through the
        ordinary host loop (``run_round``). Compilation stays bounded at
        one scan executable per distinct window-max bucket.
        ``self._window_stats`` records the split for introspection.

        Returns the per-round losses as floats — ONE host sync at the
        end, like :meth:`train_rounds_pipelined`. Eval-cadence-aware
        splitting lives in :meth:`train_windowed`."""
        from fedml_tpu.data.store import WindowPrefetcher

        self._check_windowed_supported()
        store = self.train_fed

        # Plan: every round's cohort (seeded → known now) and its bucket.
        cohorts = [self.sample_round(start_round + t)
                   for t in range(n_rounds)]
        buckets = [store.cohort_steps(idx) for idx, _ in cohorts]
        spans = plan_window_spans(buckets, window)
        scan_spans = [s for s in spans if s[2] is not None]
        self._window_stats = {
            "windows": len(scan_spans),
            "scanned_rounds": sum(s[1] for s in scan_spans),
            "host_rounds": n_rounds - sum(s[1] for s in scan_spans),
        }

        put = self._get_window_put()
        pf = getattr(self, "_window_prefetcher", None)
        if pf is None or pf.store is not store or pf.put is not put:
            pf = self._window_prefetcher = WindowPrefetcher(store, put=put)

        def span_args(span):
            off, length, steps = span
            idx2d = np.stack([cohorts[off + t][0] for t in range(length)])
            return start_round + off, idx2d, steps

        if scan_spans:  # overlap the first gather with nothing-yet: cheap
            pf.prefetch(*span_args(scan_spans[0]))

        losses = []
        extra = self._window_carry_init()
        for off, length, steps in spans:
            if steps is None:  # host-loop leftover rounds (the per-round
                # path splits the rng chain itself); the carry was
                # committed after the last scan span, so these rounds see
                # fresh instance state.
                for t in range(length):
                    r = start_round + off + t
                    if self._fused_round_step() is not None:
                        # The fused donated step (the scan's discipline
                        # at W=1) — both protocols publish it through
                        # _build_fused_step; keeping the remainder on
                        # the same program as the scan body preserves
                        # host↔windowed bit-equality by construction.
                        # Its per-round prelude H2Ds (wmask, cohort
                        # weights, per-round extras) are the remainder
                        # path's deliberate design — planned, like the
                        # trailing loss fetch.
                        with planned_transfer():
                            losses.append(self._train_round_fused(r))
                    elif self.window_protocol == "round":
                        avg, loss = self.run_round(r)
                        self.net = self._server_update(self.net, avg)
                        self._emit_reduce_obs()
                        losses.append(loss)
                    else:
                        # "custom" without a fused step (scan-only
                        # classes): train_one_round IS the round. Its
                        # per-round host syncs (eager state gather/
                        # scatter scalars, the float(loss) fetch) are
                        # the remainder path's deliberate design — mark
                        # them planned so sanitized() regions accept a
                        # non-dividing window like they accept the
                        # trailing loss fetch.
                        with planned_transfer():
                            losses.append(
                                self.train_one_round(r)["train_loss"])
                continue
            key, idx2d, _ = span_args((off, length, steps))
            batch = pf.get(key, idx2d, steps)
            # Kick the NEXT window's gather + H2D before dispatching this
            # window's scan, so it overlaps the scan's compute.
            later = [s for s in scan_spans if s[0] > off]
            if later:
                pf.prefetch(*span_args(later[0]))
            # Reproduce the host loop's per-round rng chain exactly.
            keys = []
            for _ in range(length):
                # fedlint: disable=R1(round-order chain reproduced on purpose: bit-equality with run_round's per-round split is the windowed tier's contract)
                self.rng, rnd = jax.random.split(self.rng)
                keys.append(rnd)
            wmask2d = np.stack([cohorts[off + t][1] for t in range(length)])
            weights = store.window_weights(idx2d, wmask2d)
            # planned_transfer: the per-window weights H2D rides along
            # with the superbatch as a deliberate staging copy.
            with planned_transfer():
                weights = put(weights) if put is not None \
                    else jnp.asarray(weights)
            extras = self._window_scan_extras(idx2d, wmask2d)
            scan = self._get_window_scan()
            (self.net, extra), span_losses = scan(
                self.net, extra, batch.x, batch.y, batch.mask, weights,
                jnp.stack(keys), *extras)
            # Commit per span: the donated pre-scan carry is dead, and
            # anything host-side that runs next (remainder rounds, a
            # checkpoint at a window boundary, eval in train_windowed)
            # must read the scanned-out state.
            self._window_carry_commit(extra)
            self._emit_reduce_obs(n_rounds=length)
            losses.extend(list(span_losses))
        # ONE end-of-loop host sync for the losses — planned by design
        # (train_rounds_pipelined contract), so mark it for sanitized()
        # regions (the D2H fetch is implicit and would otherwise trip
        # the transfer guard on backends that guard D2H).
        with planned_transfer():
            return [float(l) for l in losses]

    def train_windowed(self, window: int = 8):
        """The full training loop (:meth:`FederatedLoop.train` semantics —
        per-round history, eval every ``frequency_of_the_test`` rounds and
        on the last round) on the windowed streaming tier: rounds between
        eval points run through :meth:`train_rounds_windowed`, with window
        splitting aware of the eval cadence (a scan never crosses a round
        the host must stop at to evaluate)."""
        self._check_windowed_supported()
        history = []
        for lo, hi in eval_segments(self.cfg.comm_round,
                                    self.cfg.frequency_of_the_test):
            seg = self.train_rounds_windowed(hi - lo + 1, start_round=lo,
                                             window=window)
            for i, loss in enumerate(seg):
                history.append({"round": lo + i, "train_loss": loss})
            history[-1].update(self.evaluate())
        return history

    def train_rounds_on_device(self, n_rounds: int):
        """Run ``n_rounds`` WHOLE federated rounds in one jit: a
        ``lax.scan`` over rounds with on-device client sampling — zero
        host round-trips between rounds (the reference pays an MPI
        broadcast + gather per round; even our fused round pays one
        dispatch). Returns the per-round loss array.

        Semantics notes: sampling uses the jax PRNG stream (fold_in per
        round) rather than the reference's ``np.random.seed(round_idx)``
        — with FULL participation both are the identity and this method is
        bit-equal to the host loop (tested); with subsampling the client
        choice differs from host-loop runs. Any "round"-protocol
        algorithm with a PURE server update rides the scan — the carry
        protocol's ``(net, extra)`` threads between scanned rounds
        exactly as in the windowed tier (FedOpt's optimizer state,
        FedAc's acceleration sequences), committed back at the end;
        algorithms needing per-round host-computed aux operands
        (FedNova's τ weights, the corruption drill's masks) refuse with
        the record-derived reason. On a client mesh the scan rides the
        shard_map round under full participation (the gather is the
        identity there; client shards stay pinned to their devices
        across all rounds); subsampled mesh rounds still need the host
        loop's resharding gather.

        The incoming ``self.net`` (and the algorithm's carry) is DONATED
        to the scan (``donate_argnums``): callers that want to compare
        params before vs after must copy ``api.net`` before calling —
        the pre-call reference points at a donated (deleted) buffer
        afterwards."""
        if not self.capability().on_device:
            from fedml_tpu.algos.capability import refusal

            raise NotImplementedError(
                refusal(type(self), "train_rounds_on_device"))
        if self._streaming:
            raise NotImplementedError(
                "train_rounds_on_device needs the whole dataset device-"
                "resident (the scan gathers clients on device each round); "
                "FederatedStore streams cohorts from host — use the host "
                "loop")
        if self.cfg.client_selection != "random":
            raise NotImplementedError(
                "train_rounds_on_device samples uniformly on device; "
                "loss-biased selection (pow_d/oort) needs the host loop")
        cfg = self.cfg
        n_total = int(self.train_fed.num_clients)
        cpr = min(cfg.client_num_per_round, n_total)
        if self.mesh is not None and (cpr != n_total
                                      or n_total % self.n_shards):
            # Subsampled mesh rounds need a resharding gather (arbitrary
            # sampled indices cross client shards), which cannot run inside
            # shard_map; with FULL participation the gather is the
            # identity, so the sharded round rides the scan directly.
            raise NotImplementedError(
                "the sharded scan requires full participation with the "
                "client count divisible by the mesh "
                f"(clients_per_round={cpr}, total={n_total}, "
                f"shards={self.n_shards}); subsampled mesh rounds use the "
                "host loop")

        scan_fn = getattr(self, "_rounds_scan_fn", None)
        if scan_fn is None:
            round_fn = self.round_fn  # jitted; nested jit is fine under scan
            server_update = self._window_server_update()

            from fedml_tpu.data.batching import gather_clients

            def body(fed, net, extra, key):
                if self.mesh is not None or cpr == n_total:
                    sub = fed  # full participation: gather is the identity
                else:
                    idx = jax.random.choice(
                        jax.random.fold_in(key, 0x5A), n_total, (cpr,),
                        replace=False)
                    sub = gather_clients(fed, idx)
                w = sub.counts.astype(jnp.float32)
                # The round key is used AS the host loop uses rnd_rng, so
                # with full participation this scan is bit-equal to it.
                avg, loss = round_fn(net, sub.x, sub.y, sub.mask, w, w, key)
                if server_update is None:
                    return (avg, extra), loss
                # The carry protocol's pure fold — exactly the windowed
                # scan's between-round step, so stateful-server
                # algorithms (FedOpt, FedAc, ServerAvg) ride on-device
                # with their state never leaving the device.
                return server_update(net, avg, extra, key), loss

            # fed and keys are jit ARGUMENTS (FederatedArrays is a struct
            # pytree): the dataset is not baked into the program as
            # constants, and the compiled scan is cached on self — repeat
            # calls with the same n_rounds reuse the executable.
            def scan_fn(net, extra, fed, keys):
                return jax.lax.scan(
                    lambda c, k: body(fed, c[0], c[1], k), (net, extra),
                    keys)

            # Donate the incoming (net, extra) carry: the caller always
            # replaces self.net / commits the carry from the scan
            # result, so XLA may reuse the old buffers instead of
            # holding both copies live.
            scan_fn = jax.jit(scan_fn, donate_argnums=(0, 1))
            self._rounds_scan_fn = scan_fn

        fed = self.train_fed
        if self.mesh is not None:
            # Pin client shards to their devices for the whole scan (the
            # host loop re-lays them out every round via the eager gather).
            # The resharded copy REPLACES self.train_fed so repeat calls
            # don't pay a full-dataset reshard each time or transiently
            # hold two device-resident copies.
            cached = getattr(self, "_mesh_pinned_fed", None)
            if cached is None or cached is not fed:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                shard = NamedSharding(self.mesh, P(client_axes(self.mesh)))
                fed = jax.tree.map(lambda a: jax.device_put(a, shard), fed)
                self.train_fed = self._mesh_pinned_fed = fed
            else:
                fed = cached

        # Reproduce the host loop's per-round rng chain exactly.
        keys = []
        for _ in range(n_rounds):
            # fedlint: disable=R1(round-order chain reproduced on purpose: full-participation bit-equality with the host loop is tested)
            self.rng, rnd = jax.random.split(self.rng)
            keys.append(rnd)
        # Distinct names for the donated operands: the carry that comes
        # BACK is what instance state rebinds to (fedlint R5 discipline
        # — the donated buffers are dead after the call).
        net0, extra0 = self.net, self._window_carry_init()
        carry, losses = scan_fn(net0, extra0, fed, jnp.stack(keys))
        self.net, extra = carry
        self._window_carry_commit(extra)
        return losses

    def _eval_net(self):
        return self.net
