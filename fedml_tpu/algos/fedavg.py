"""FedAvg — the canonical synchronous federated-averaging loop.

Capability parity with both reference implementations:
- standalone simulator ``FedAvgAPI`` (fedml_api/standalone/fedavg/fedavg_api.py:12-116)
- distributed MPI pipeline (fedml_api/distributed/fedavg/FedAvgAPI.py:20 +
  FedAVGAggregator.py + manager classes)

On TPU both collapse into one object: sampled clients are a leading array
axis (vmap on one chip, shard_map over the ``clients`` mesh axis on many),
and the server aggregation is a weighted-mean reduction (psum over ICI).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.loop import FederatedLoop
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.parallel.shard import make_sharded_round, make_vmap_round
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)


class FedAvgAPI(FederatedLoop):
    """Federated trainer. ``mesh=None`` → single-device vmap simulator;
    with a mesh, clients are sharded over ``mesh.axis_names[0]``."""

    def __init__(
        self,
        model,
        train_fed: FederatedArrays,
        test_global,  # (x, y, mask) batched [S, B, ...] or None
        cfg: FedConfig,
        mesh=None,
        loss_fn=softmax_ce,
        pad_id: int = 0,
        nan_guard: bool = False,
    ):
        """``pad_id`` marks padding positions in sequence-task labels
        (excluded from eval accuracy); it must match the pad id baked into a
        sequence ``loss_fn`` (e.g. ``partial(seq_softmax_ce, pad_id=...)``).
        Irrelevant for flat classification tasks.

        ``nan_guard``: zero-weight any client whose local training diverged
        to non-finite params (fedml_tpu.core.faults failure containment)."""
        self.cfg = cfg
        self.mesh = mesh
        self.train_fed = train_fed
        self.test_global = test_global
        self.fns = model_fns(model)
        if cfg.batch_size != train_fed.batch_size:
            raise ValueError(
                f"cfg.batch_size={cfg.batch_size} != packed client batch size "
                f"{train_fed.batch_size}; build_federated_arrays with the same "
                "batch_size as the config"
            )

        self._loss_fn = loss_fn
        self._nan_guard = nan_guard
        self.n_shards = 1 if mesh is None else int(mesh.shape[mesh.axis_names[0]])
        self._client_lr = None
        self.set_client_lr(cfg.lr)
        self.eval_fn = jax.jit(make_eval_fn(self.fns.apply, loss_fn, pad_id=pad_id))

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_rng = jax.random.split(rng)
        sample_x = np.asarray(train_fed.x[0, 0])
        self.net = self.fns.init(init_rng, sample_x)

    def set_client_lr(self, lr: float):
        """(Re)build the jitted round for a new client learning rate —
        the hook the round-level LR schedulers use (fed_launch
        schedulers decay the client LR across comm rounds). A no-op when
        the lr is unchanged; each distinct lr value costs one re-jit, so
        schedulers should quantize to a few buckets."""
        if lr == self._client_lr:
            return
        self._client_lr = lr
        self._rounds_scan_fn = None  # round_fn changes → cached scan stale
        self._on_client_lr_change()  # subclasses drop their own cached jits
        cfg, mesh = self.cfg, self.mesh
        optimizer = make_client_optimizer(
            cfg.client_optimizer, lr, cfg.wd, cfg.grad_clip
        )
        self.local_train = self._build_local_train(optimizer, self._loss_fn)
        transform = self._client_transform()
        guard = self._nan_guard
        if mesh is None:
            round_fn = self._make_vmap_round(
                self.local_train, transform, guard
            )

            # Single-device: fuse the client gather + weight computation
            # into the jitted round. Dispatching the takes eagerly costs
            # ~40% of the round wall-clock on a real chip (4 un-jitted
            # device ops + host sync per round). FederatedArrays is a
            # struct.dataclass pytree, so it traces straight through jit.
            from fedml_tpu.data.batching import gather_clients

            def fused(net, fed, idx, wmask, rng):
                sub = gather_clients(fed, idx)
                w = sub.counts.astype(jnp.float32) * wmask
                return round_fn(net, sub.x, sub.y, sub.mask, w, w, rng)

            self.round_fn_fused = jax.jit(fused)
        else:
            # Pad the sampled set to the CLIENT axis size only (a 2-D mesh's
            # model axis does not multiply the client shards). Gather stays
            # outside the jit: arbitrary sampled indices cross client
            # shards, so the resharding take must run before shard_map.
            round_fn = self._make_sharded_round(
                self.local_train, mesh, transform, guard
            )
        self.round_fn = jax.jit(round_fn)

    # --- hooks subclasses override (FedOpt/FedProx/...) -------------------
    def _on_client_lr_change(self):
        """Called whenever the client lr actually changes (lr schedules).
        Subclasses holding their OWN lr-dependent jitted functions (Ditto's
        personal trainer, SCAFFOLD's corrected round) invalidate them here
        — forgetting this is how a subclass silently trains at a stale lr
        under --lr_schedule."""

    def _make_vmap_round(self, local_train, transform, guard):
        """Single-device round construction; q-FedAvg swaps in a
        loss-reweighted aggregation here."""
        return make_vmap_round(
            local_train, client_transform=transform, nan_guard=guard)

    def _make_sharded_round(self, local_train, mesh, transform, guard):
        return make_sharded_round(
            local_train, mesh, mesh.axis_names[0],
            client_transform=transform, nan_guard=guard)

    def _build_local_train(self, optimizer, loss_fn):
        return make_local_train_fn_from_cfg(self.fns.apply, optimizer,
                                            self.cfg, loss_fn)

    def _server_update(self, old_net, avg_net):
        """FedAvg: the new global model is the client average."""
        return avg_net

    def _client_transform(self):
        """Optional ``(global_net, client_net) -> client_net`` applied to
        each trained client before averaging (robust clipping etc.)."""
        return None

    # ----------------------------------------------------------------------
    # sample_round/run_round come from FederatedLoop (shared scaffold).

    def sample_round(self, round_idx: int):
        """Adds Power-of-Choice selection (cfg.client_selection="pow_d",
        Cho et al. 2020) on top of the inherited uniform sampling: draw d
        candidates uniformly, evaluate the current global on their local
        shards (one vmapped pass), keep the highest-loss
        ``client_num_per_round``.

        The result is memoized per round: pow_d depends on the CURRENT
        net, so a subclass that samples again mid-round (Ditto's personal
        step runs after the global update) must see the same set the
        global round trained — recomputing would silently select a
        different cohort."""
        cached = getattr(self, "_sample_cache", None)
        if cached is not None and cached[0] == round_idx:
            return cached[1], cached[2]
        idx, wmask = self._sample_round_uncached(round_idx)
        self._sample_cache = (round_idx, idx, wmask)
        return idx, wmask

    def _sample_round_uncached(self, round_idx: int):
        if self.cfg.client_selection == "random":
            return super().sample_round(round_idx)
        if self.cfg.client_selection != "pow_d":
            raise ValueError(
                f"unknown client_selection {self.cfg.client_selection!r}; "
                "use 'random' or 'pow_d'")
        from fedml_tpu.core.sampling import pad_to_multiple, sample_clients
        from fedml_tpu.data.batching import gather_clients

        cfg = self.cfg
        d = cfg.pow_d_candidates or 2 * cfg.client_num_per_round
        d = min(d, cfg.client_num_in_total)
        m = min(cfg.client_num_per_round, cfg.client_num_in_total)
        if d < m:
            raise ValueError(
                f"pow_d needs at least client_num_per_round candidates "
                f"(d={d} < m={m}); raise --pow_d_candidates")
        candidates = sample_clients(round_idx, cfg.client_num_in_total, d)
        fn = getattr(self, "_pow_d_losses_jit", None)
        if fn is None:
            per_client = self._per_client_eval()  # shared cached kernel

            def losses_fn(net, fed, idx):
                # Gather traced INSIDE the jit: an eager gather would pay
                # the multi-dispatch host sync the fused round path exists
                # to avoid (see round_fn_fused above).
                sub = gather_clients(fed, idx)
                return per_client(net, sub.x, sub.y, sub.mask)["loss"]

            fn = jax.jit(losses_fn)
            self._pow_d_losses_jit = fn
        losses = np.asarray(
            fn(self._eval_net(), self.train_fed, jnp.asarray(candidates)))
        order = np.argsort(-losses, kind="stable")[:m]
        idx = candidates[np.sort(order)]
        idx, wmask = pad_to_multiple(idx, self.n_shards)
        return idx, wmask

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        avg, loss = self.run_round(round_idx)
        self.net = self._server_update(self.net, avg)
        return {"round": round_idx, "train_loss": float(loss)}

    def train_rounds_on_device(self, n_rounds: int):
        """Run ``n_rounds`` WHOLE federated rounds in one jit: a
        ``lax.scan`` over rounds with on-device client sampling — zero
        host round-trips between rounds (the reference pays an MPI
        broadcast + gather per round; even our fused round pays one
        dispatch). Returns the per-round loss array.

        Semantics notes: sampling uses the jax PRNG stream (fold_in per
        round) rather than the reference's ``np.random.seed(round_idx)``
        — with FULL participation both are the identity and this method is
        bit-equal to the host loop (tested); with subsampling the client
        choice differs from host-loop runs. Only plain FedAvg server
        updates (new = avg) can ride the scan; subclasses with stateful
        server optimizers must use the host loop."""
        if (type(self)._server_update is not FedAvgAPI._server_update
                or type(self).train_one_round is not FedAvgAPI.train_one_round
                or type(self).run_round is not FederatedLoop.run_round):
            raise NotImplementedError(
                "train_rounds_on_device supports plain-FedAvg rounds only; "
                "this subclass customizes the round or server update "
                "(hierarchical grouping, MPC aggregation, server optimizers "
                "cannot ride the scan)")
        if self.mesh is not None:
            raise NotImplementedError(
                "train_rounds_on_device currently targets the single-device "
                "vmap path (the sharded path's resharding gather must run "
                "outside shard_map)")
        if self.cfg.client_selection != "random":
            raise NotImplementedError(
                "train_rounds_on_device samples uniformly on device; "
                "loss-biased selection (pow_d) needs the host loop")
        cfg = self.cfg
        n_total = int(self.train_fed.num_clients)
        cpr = min(cfg.client_num_per_round, n_total)

        scan_fn = getattr(self, "_rounds_scan_fn", None)
        if scan_fn is None:
            round_fn = self.round_fn  # jitted; nested jit is fine under scan

            from fedml_tpu.data.batching import gather_clients

            def body(fed, net, key):
                if cpr == n_total:
                    idx = jnp.arange(n_total)
                else:
                    idx = jax.random.choice(
                        jax.random.fold_in(key, 0x5A), n_total, (cpr,),
                        replace=False)
                sub = gather_clients(fed, idx)
                w = sub.counts.astype(jnp.float32)
                # The round key is used AS the host loop uses rnd_rng, so
                # with full participation this scan is bit-equal to it.
                avg, loss = round_fn(net, sub.x, sub.y, sub.mask, w, w, key)
                return avg, loss

            # fed and keys are jit ARGUMENTS (FederatedArrays is a struct
            # pytree): the dataset is not baked into the program as
            # constants, and the compiled scan is cached on self — repeat
            # calls with the same n_rounds reuse the executable.
            def scan_fn(net, fed, keys):
                return jax.lax.scan(
                    lambda n, k: body(fed, n, k), net, keys)

            scan_fn = jax.jit(scan_fn)
            self._rounds_scan_fn = scan_fn

        # Reproduce the host loop's per-round rng chain exactly.
        keys = []
        for _ in range(n_rounds):
            self.rng, rnd = jax.random.split(self.rng)
            keys.append(rnd)
        self.net, losses = scan_fn(self.net, self.train_fed, jnp.stack(keys))
        return losses

    def _eval_net(self):
        return self.net
