"""FedNova — normalized averaging (Wang et al., NeurIPS'20).

Parity: fedml_api/standalone/fednova/ — the reference implements FedNova as
a torch Optimizer subclass accumulating ``cum_grad`` and a normalizing
vector (fednova.py:10-151), aggregated with ``tau_eff``-normalized averaging
(fednova_trainer.py:97).

TPU formulation (vanilla-SGD case, momentum=0, matching the reference's
default ``gmf=0`` path): client i runs τ_i local steps, producing
``d_i = (w_g − w_i)/τ_i``. The server applies

    w⁺ = w_g − τ_eff · Σ p_i d_i,   p_i = n_i/N,  τ_eff = Σ p_i τ_i.

Algebraically Σ p_i d_i = s · (w_g − avg_q) with q_i ∝ p_i/τ_i and
s = Σ p_i/τ_i — so the existing weighted-average round (weights n_i/τ_i)
is reused unchanged and the server step is one scalar-γ interpolation with
γ = τ_eff · s. When all τ_i are equal, γ = 1 and FedNova reduces exactly to
FedAvg (covered by a test).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState


class FedNovaAPI(FedAvgAPI):
    def _local_steps(self, counts) -> np.ndarray:
        """τ_i = epochs × (non-empty scan steps for client i). Exact because
        the trainer's shuffle keeps padding at the tail (trailing all-masked
        steps are gated no-ops — see make_local_train_fn), so client i runs
        exactly ceil(n_i/B) optimizer updates per epoch."""
        b = self.cfg.batch_size
        return np.maximum(np.ceil(np.asarray(counts) / b), 1.0) * self.cfg.epochs

    def train_one_round(self, round_idx: int):
        idx, wmask = self.sample_round(round_idx)
        sub = self._cohort(round_idx, idx)
        counts = np.asarray(sub.counts, np.float64) * np.asarray(wmask, np.float64)
        tau = self._local_steps(sub.counts)
        n_total = counts.sum()
        p = counts / max(n_total, 1.0)
        tau_eff = float((p * tau).sum())
        s = float((p / tau).sum())
        self._gamma = tau_eff * s

        # Weighted-average round with q-weights ∝ p_i/τ_i; the reported loss
        # stays sample-weighted (comparable with every other algorithm).
        q = counts / tau
        self.rng, rnd_rng = jax.random.split(self.rng)
        avg, loss = self.round_fn(
            self.net, sub.x, sub.y, sub.mask,
            jnp.asarray(q, jnp.float32), jnp.asarray(counts, jnp.float32), rnd_rng,
        )
        self.net = self._server_update(self.net, avg)
        return {"round": round_idx, "train_loss": float(loss)}

    def _server_update(self, old_net, avg_net):
        g = self._gamma
        new_params = jax.tree.map(
            lambda w, a: w - g * (w - a), old_net.params, avg_net.params
        )
        return NetState(new_params, avg_net.model_state)
