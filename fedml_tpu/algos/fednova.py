"""FedNova — normalized averaging (Wang et al., NeurIPS'20).

Parity: fedml_api/standalone/fednova/ — the reference implements FedNova as
a torch Optimizer subclass accumulating ``cum_grad`` and a normalizing
vector (fednova.py:10-151), aggregated with ``tau_eff``-normalized averaging
(fednova_trainer.py:97).

TPU formulation (vanilla-SGD case, momentum=0, matching the reference's
default ``gmf=0`` path): client i runs τ_i local steps, producing
``d_i = (w_g − w_i)/τ_i``. The server applies

    w⁺ = w_g − τ_eff · Σ p_i d_i,   p_i = n_i/N,  τ_eff = Σ p_i τ_i.

Algebraically Σ p_i d_i = s · (w_g − avg_q) with q_i ∝ p_i/τ_i and
s = Σ p_i/τ_i — so the existing weighted-average round (weights n_i/τ_i)
is reused unchanged and the server step is one scalar-γ interpolation with
γ = τ_eff · s. When all τ_i are equal, γ = 1 and FedNova reduces exactly to
FedAvg (covered by a test).

Capability record: FedNova is a "round"-protocol algorithm whose round is
the SHARED builders' round fed per-round ``(q, γ)`` operands — τ_i is a
pure function of the cohort's sample counts, so the q-weights and the
interpolation scalar are host-computed (float64, exactly the pre-record
host loop's math) and ride the aux slot: ``_round_aux`` on the host/fused
tiers, ``_window_scan_extras`` as ``[W, C]``/``[W]`` scanned operands on
the windowed tier. That makes FedNova fused + windowed + pipelined with
no carry at all; only the on-device scan (which samples inside the jit
and has no host-aux slot) refuses, with the record-derived reason.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState


class FedNovaAPI(FedAvgAPI):
    window_carry = "— (per-round q-weights + γ ride the scanned aux slot)"

    def _local_steps(self, counts) -> np.ndarray:
        """τ_i = epochs × (non-empty scan steps for client i). Exact because
        the trainer's shuffle keeps padding at the tail (trailing all-masked
        steps are gated no-ops — see make_local_train_fn), so client i runs
        exactly ceil(n_i/B) optimizer updates per epoch. Zero-count slots
        clamp to one step — their weight is zero everywhere they appear, so
        the clamp only guards the division."""
        b = self.cfg.batch_size
        return np.maximum(np.ceil(np.asarray(counts) / b), 1.0) * self.cfg.epochs

    def _nova_operands(self, counts: np.ndarray):
        """``(q, γ)`` for one round from the cohort's (mask-zeroed) sample
        counts — float64 host math, identical to the pre-record host loop."""
        counts = np.asarray(counts, np.float64)
        tau = self._local_steps(counts)
        n_total = counts.sum()
        p = counts / max(n_total, 1.0)
        tau_eff = float((p * tau).sum())
        s = float((p / tau).sum())
        return counts / tau, np.float32(tau_eff * s)

    def _round_aux(self, round_idx: int, idx, wmask):
        counts = (self._host_counts()[np.asarray(idx)].astype(np.float64)
                  * np.asarray(wmask, np.float64))
        q, gamma = self._nova_operands(counts)
        return (jnp.asarray(q, jnp.float32), jnp.asarray(gamma))

    def _window_scan_extras(self, idx2d, wmask2d):
        from fedml_tpu.obs.sanitizer import planned_transfer

        counts2d = (self._host_counts()[np.asarray(idx2d)].astype(np.float64)
                    * np.asarray(wmask2d, np.float64))
        rows = [self._nova_operands(row) for row in counts2d]
        q = np.stack([r[0] for r in rows]).astype(np.float32)
        gamma = np.stack([r[1] for r in rows])
        put = self._get_window_put()
        with planned_transfer():
            # q is client-shaped [W, C]: on a mesh it arrives client-
            # sharded like the weights operand; γ [W] is replicated.
            return (put(q) if put is not None else jnp.asarray(q),
                    jnp.asarray(gamma))

    def _wrap_nova_round(self, base_round):
        """The shared builders' round re-weighted per FedNova: aggregate
        with the τ-normalized ``q`` weights, report the loss with the
        true sample counts, then apply the scalar-γ interpolation — all
        inside the one (jittable) round, so every tier that replays
        ``round_fn`` gets normalized averaging for free."""

        def round_fn(net, x, y, mask, weights, loss_weights, rng, q, gamma):
            out = base_round(net, x, y, mask, q, loss_weights, rng)
            avg, loss, rest = out[0], out[1], tuple(out[2:])
            new_params = jax.tree.map(
                lambda w, a: w - gamma * (w - a), net.params, avg.params)
            new_net = NetState(new_params, avg.model_state)
            return (new_net, loss) + rest

        return round_fn

    def _make_vmap_round(self, local_train, transform, guard):
        return self._wrap_nova_round(
            super()._make_vmap_round(local_train, transform, guard))

    def _make_sharded_round(self, local_train, mesh, transform, guard):
        return self._wrap_nova_round(
            super()._make_sharded_round(local_train, mesh, transform, guard))
