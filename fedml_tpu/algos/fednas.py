"""FedNAS — federated neural architecture search over the DARTS space.

Parity target: reference fedml_api/distributed/fednas/ —
- clients run local bilevel search: architecture step on a held-out local
  valid split, then weight step on the train split
  (FedNASTrainer.local_search:82, darts/architect.py);
- the server averages BOTH model weights and architecture alphas, weighted
  by sample counts (FedNASAggregator.__aggregate_weight:71,
  __aggregate_alpha:95);
- after search, the genotype is derived from the averaged alphas
  (FedNASAggregator.record_model_global_architecture:173).

TPU-native: weights vs alphas is a partition of ONE flax params pytree
(alphas live at the network root as ``alphas_normal``/``alphas_reduce``),
so the bilevel update is two masked SGD steps inside the same jit-compiled
``lax.scan``; clients are vmapped; aggregation is the standard weighted
tree-mean (which covers w and α jointly, exactly the reference's two loops).
The 2nd-order arch gradient ∇α L_val(w − ξ∇w L_train(w,α), α) is an exact
``jax.grad`` through the unrolled inner step — no finite-difference
Hessian-vector approximation (architect.py:229) needed under XLA.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.loop import FederatedLoop
from fedml_tpu.core.tree import tree_select, tree_weighted_mean
from fedml_tpu.data.batching import FederatedArrays, gather_clients
from fedml_tpu.trainer.local import NetState, make_eval_fn, model_fns, softmax_ce

ALPHA_KEYS = ("alphas_normal", "alphas_reduce")


def _split_mask(params):
    """Bool pytrees selecting (arch alphas, weights)."""
    flat = {k: (k in ALPHA_KEYS) for k in params}
    return flat, {k: not v for k, v in flat.items()}


def _masked(tree, mask):
    """Zero out leaves whose top-level key is masked False."""
    return jax.tree.map(
        lambda m, sub: jax.tree.map(
            (lambda a: a) if m else (lambda a: jnp.zeros_like(a)), sub),
        mask, tree, is_leaf=lambda n: isinstance(n, bool))


class FedNASAPI(FederatedLoop):
    """Federated DARTS search (reference FedNASAPI.py:16).

    Each client's packed batches are split in half: the first ``S//2``
    steps are the train split, the rest the valid split (the reference
    splits each client's local data into train/valid queues,
    FedNASTrainer.py:22-30)."""

    def __init__(self, model, train_fed: FederatedArrays, test_global,
                 cfg: FedConfig, arch_lr: float = 3e-4, xi: float = 0.0,
                 unrolled: bool = False):
        """``xi``/``unrolled``: 2nd-order arch step w − ξ∇L_train lookahead
        (architect.py unrolled mode); ``unrolled=False`` is the reference's
        ``--arch_search_method`` default 1st-order (MiLeNAS-style)."""
        self.cfg = cfg
        self.train_fed = train_fed
        self.test_global = test_global
        self.fns = model_fns(model)
        if int(train_fed.x.shape[1]) < 2:
            raise ValueError(
                "FedNAS needs >= 2 packed steps per client (the local data "
                "is split into train/valid halves, FedNASTrainer.py:22-30); "
                "use a smaller batch_size so each client packs >= 2 batches")
        self.arch_lr = arch_lr
        self.xi = xi if unrolled else 0.0
        self.unrolled = unrolled
        self.n_shards = 1
        # Architecture geometry for genotype() — taken from the model, not
        # re-guessed from alpha shapes.
        self._steps = int(getattr(model, "steps", 4))
        self._multiplier = int(getattr(model, "multiplier", 4))

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_rng = jax.random.split(rng)
        sample_x = np.asarray(train_fed.x[0, 0])
        self.net = self.fns.init(init_rng, sample_x)
        self.round_fn = jax.jit(self._build_round())
        self.eval_fn = jax.jit(make_eval_fn(self.fns.apply))

    # ------------------------------------------------------------------
    def _build_round(self):
        apply_fn = self.fns.apply
        lr_w, lr_a, xi = self.cfg.lr, self.arch_lr, self.xi
        epochs = self.cfg.epochs
        unrolled = self.unrolled

        def ce_loss(p, state, xb, yb, mb, rng):
            logits, new_state = apply_fn(
                NetState(p, state), xb, train=True, rng=rng)
            per = softmax_ce(logits, yb)
            return (jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0),
                    new_state)

        def local_search(net, x, y, mask, rng):
            # Floor split: with odd S the final batch is used by neither
            # half (deliberate — equal-sized train/valid splits, like the
            # reference's 50/50 queue split).
            S = x.shape[0]
            half = S // 2
            amask, wmask = _split_mask(net.params)

            def step(carry, inputs):
                net, step_base = carry
                (xt, yt, mt), (xv, yv, mv), idx = inputs
                # Three per-step keys fork from disjoint children of the
                # fold_in-on-index key (fedlint R1): prefix-stable in the
                # step count, unlike the carried split chain it replaces.
                per_step = jax.random.fold_in(step_base, idx)
                r1 = jax.random.fold_in(per_step, 0)
                r2 = jax.random.fold_in(per_step, 1)
                r3 = jax.random.fold_in(per_step, 2)

                # --- architecture step on the valid half ---------------
                def val_loss_wrt_alpha(p):
                    if unrolled:
                        # exact 2nd-order: lookahead w' = w − ξ∇w L_train
                        gw, _ = jax.grad(ce_loss, has_aux=True)(
                            p, net.model_state, xt, yt, mt, r1)
                        p = jax.tree.map(
                            lambda a, g: a - xi * g, p, _masked(gw, wmask))
                    loss, state = ce_loss(p, net.model_state, xv, yv, mv, r2)
                    return loss, state

                ga, _ = jax.grad(val_loss_wrt_alpha, has_aux=True)(net.params)
                params = jax.tree.map(
                    lambda a, g: a - lr_a * g, net.params, _masked(ga, amask))

                # --- weight step on the train half ---------------------
                (loss, new_state), gw = jax.value_and_grad(
                    ce_loss, has_aux=True)(
                        params, net.model_state, xt, yt, mt, r3)
                params = jax.tree.map(
                    lambda a, g: a - lr_w * g, params, _masked(gw, wmask))

                ns = jnp.sum(mt)
                net = tree_select(ns > 0, NetState(params, new_state), net)
                return (net, step_base), (loss, ns)

            def epoch(carry, e):
                # Sample-weighted epoch loss: padded all-masked steps return
                # loss 0 and must not dilute the reported search_loss.
                net, _ = carry
                step_base = jax.random.fold_in(rng, e)
                carry, (losses, ns) = jax.lax.scan(
                    step, (net, step_base),
                    ((x[:half], y[:half], mask[:half]),
                     (x[half:2 * half], y[half:2 * half], mask[half:2 * half]),
                     jnp.arange(half)))
                return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

            (net, _), losses = jax.lax.scan(
                epoch, (net, rng), jnp.arange(epochs))
            return net, jnp.mean(losses)

        def round_fn(net, x, y, mask, weights, rng):
            rngs = jax.vmap(
                lambda i: jax.random.fold_in(rng, i))(jnp.arange(x.shape[0]))
            client_nets, losses = jax.vmap(
                local_search, in_axes=(None, 0, 0, 0, 0))(net, x, y, mask, rngs)
            avg = tree_weighted_mean(client_nets, weights)
            lw = weights / jnp.maximum(jnp.sum(weights), 1e-12)
            return avg, jnp.sum(losses * lw)

        return round_fn

    # ------------------------------------------------------------------
    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        idx, wmask = self.sample_round(round_idx)
        sub = gather_clients(self.train_fed, idx)
        weights = sub.counts.astype(jnp.float32) * jnp.asarray(wmask)
        self.rng, rnd = jax.random.split(self.rng)
        self.net, loss = self.round_fn(
            self.net, sub.x, sub.y, sub.mask, weights, rnd)
        return {"round": round_idx, "search_loss": float(loss)}

    def _eval_net(self):
        return self.net

    def genotype(self):
        """Derive the searched architecture from the averaged alphas
        (reference record_model_global_architecture, FedNASAggregator.py:173)."""
        from fedml_tpu.models.darts import derive_genotype

        return derive_genotype(
            self.net.params["alphas_normal"],
            self.net.params["alphas_reduce"], steps=self._steps,
            multiplier=self._multiplier)
