"""FedNAS — federated neural architecture search over the DARTS space.

Parity target: reference fedml_api/distributed/fednas/ —
- clients run local bilevel search: architecture step on a held-out local
  valid split, then weight step on the train split
  (FedNASTrainer.local_search:82, darts/architect.py);
- the server averages BOTH model weights and architecture alphas, weighted
  by sample counts (FedNASAggregator.__aggregate_weight:71,
  __aggregate_alpha:95);
- after search, the genotype is derived from the averaged alphas
  (FedNASAggregator.record_model_global_architecture:173).

TPU-native: weights vs alphas is a partition of ONE flax params pytree
(alphas live at the network root as ``alphas_normal``/``alphas_reduce``),
so the bilevel update is two masked SGD steps inside the same jit-compiled
``lax.scan``; clients are vmapped; aggregation is the standard weighted
tree-mean (which covers w and α jointly, exactly the reference's two loops).
The 2nd-order arch gradient ∇α L_val(w − ξ∇w L_train(w,α), α) is an exact
``jax.grad`` through the unrolled inner step — no finite-difference
Hessian-vector approximation (architect.py:229) needed under XLA.

Capability record: since the record refactor ``FedNASAPI`` IS a
``FedAvgAPI`` whose local step is the bilevel search (server update =
plain client average, "round" protocol, no carry) — FedNAS rides the
fused round step, the pipelined loop, the windowed streaming scan and
the on-device scan. For that the train/valid split had to become
MASK-AWARE: the halves are cut at ``n_real // 2`` where ``n_real`` is
the client's true (non-padded) step count, so a store cohort forced onto
a larger window-max step bucket trains on exactly the same batches as
the per-round host loop (all-masked tail steps change nothing — the
prefix-stability contract every windowed algorithm must meet). On the
resident layout, where every cohort shares one fixed S, the split is
identical to the old static ``S // 2``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.tree import tree_select
from fedml_tpu.trainer.local import NetState, softmax_ce

ALPHA_KEYS = ("alphas_normal", "alphas_reduce")


def _split_mask(params):
    """Bool pytrees selecting (arch alphas, weights)."""
    flat = {k: (k in ALPHA_KEYS) for k in params}
    return flat, {k: not v for k, v in flat.items()}


def _masked(tree, mask):
    """Zero out leaves whose top-level key is masked False."""
    return jax.tree.map(
        lambda m, sub: jax.tree.map(
            (lambda a: a) if m else (lambda a: jnp.zeros_like(a)), sub),
        mask, tree, is_leaf=lambda n: isinstance(n, bool))


def make_fednas_local_search(apply_fn, lr_w: float, lr_a: float, xi: float,
                             local_epochs: int, unrolled: bool):
    """``local_search(net, x, y, mask, rng) -> (net', loss)`` — the
    bilevel DARTS step with the shared local-train signature, so the
    FedAvg round builders (vmap, shard_map, fused, windowed, on-device)
    consume it unchanged.

    The local data splits in half by TRUE step count: steps ``[0, h)``
    are the train queue, ``[h, 2h)`` the valid queue, ``h = n_real // 2``
    (the reference's 50/50 queue split, FedNASTrainer.py:22-30; with odd
    counts the final real step feeds neither half, deliberately). The
    scan runs over the STATIC bound ``S // 2`` and gates steps at
    ``i >= h`` off — exact no-ops, so a padded step bucket leaves the
    trajectory bit-identical (windowed == host)."""

    def ce_loss(p, state, xb, yb, mb, rng):
        logits, new_state = apply_fn(
            NetState(p, state), xb, train=True, rng=rng)
        per = softmax_ce(logits, yb)
        return (jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0),
                new_state)

    def local_search(net, x, y, mask, rng):
        S = x.shape[0]
        half = S // 2  # static scan bound (>= the dynamic h)
        amask, wmask_tree = _split_mask(net.params)
        # True (non-padded) step count: the trainer keeps padding at the
        # tail, and a real step always has at least one unmasked sample.
        n_real = jnp.sum(jnp.any(mask > 0, axis=1).astype(jnp.int32))
        h = n_real // 2

        def row(a, i):
            # Dynamic step gather (clipped — garbage rows are gated off
            # below). ``i`` is traced inside the scan.
            return jnp.take(a, i, axis=0, mode="clip")

        def step(carry, i):
            net, step_base = carry
            xt, yt, mt = row(x, i), row(y, i), row(mask, i)
            xv, yv, mv = row(x, h + i), row(y, h + i), row(mask, h + i)
            # Three per-step keys fork from disjoint children of the
            # fold_in-on-index key (fedlint R1): prefix-stable in the
            # step count, whatever bucket the cohort was forced onto.
            per_step = jax.random.fold_in(step_base, i)
            r1 = jax.random.fold_in(per_step, 0)
            r2 = jax.random.fold_in(per_step, 1)
            r3 = jax.random.fold_in(per_step, 2)

            # --- architecture step on the valid half ---------------
            def val_loss_wrt_alpha(p):
                if unrolled:
                    # exact 2nd-order: lookahead w' = w − ξ∇w L_train
                    gw, _ = jax.grad(ce_loss, has_aux=True)(
                        p, net.model_state, xt, yt, mt, r1)
                    p = jax.tree.map(
                        lambda a, g: a - xi * g, p, _masked(gw, wmask_tree))
                loss, state = ce_loss(p, net.model_state, xv, yv, mv, r2)
                return loss, state

            ga, _ = jax.grad(val_loss_wrt_alpha, has_aux=True)(net.params)
            params = jax.tree.map(
                lambda a, g: a - lr_a * g, net.params, _masked(ga, amask))

            # --- weight step on the train half ---------------------
            (loss, new_state), gw = jax.value_and_grad(
                ce_loss, has_aux=True)(
                    params, net.model_state, xt, yt, mt, r3)
            params = jax.tree.map(
                lambda a, g: a - lr_w * g, params, _masked(gw, wmask_tree))

            active = (i < h) & (jnp.sum(mt) > 0)
            ns = jnp.where(active, jnp.sum(mt), 0.0)
            net = tree_select(active, NetState(params, new_state), net)
            return (net, step_base), (loss, ns)

        def epoch(carry, e):
            # Sample-weighted epoch loss: gated steps (beyond the true
            # half, or all-masked) carry weight 0 and must not dilute
            # the reported search loss.
            net, _ = carry
            step_base = jax.random.fold_in(rng, e)
            carry, (losses, ns) = jax.lax.scan(
                step, (net, step_base), jnp.arange(half))
            return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

        (net, _), losses = jax.lax.scan(
            epoch, (net, rng), jnp.arange(local_epochs))
        return net, jnp.mean(losses)

    return local_search


class FedNASAPI(FedAvgAPI):
    """Federated DARTS search (reference FedNASAPI.py:16) as a FedAvg-
    family algorithm: only the local step differs.

    ``xi``/``unrolled``: 2nd-order arch step w − ξ∇L_train lookahead
    (architect.py unrolled mode); ``unrolled=False`` is the reference's
    ``--arch_search_method`` default 1st-order (MiLeNAS-style)."""

    window_carry = "— (alphas average with the weights)"

    def __init__(self, model, train_fed, test_global, cfg,
                 arch_lr: float = 3e-4, xi: float = 0.0,
                 unrolled: bool = False, **kw):
        # Consumed by _build_local_train, which super().__init__ calls
        # through set_client_lr — set first.
        self.arch_lr = arch_lr
        self.xi = xi if unrolled else 0.0
        self.unrolled = unrolled
        # Architecture geometry for genotype() — taken from the model, not
        # re-guessed from alpha shapes.
        self._steps = int(getattr(model, "steps", 4))
        self._multiplier = int(getattr(model, "multiplier", 4))
        super().__init__(model, train_fed, test_global, cfg, **kw)
        # The bilevel step implements its own two plain-SGD updates; cfg
        # knobs the generic trainer honors must refuse, not no-op.
        self._require_plain_sgd_round("FedNASAPI's bilevel search step")
        # EVERY client must pack >= 2 real steps (the local data splits
        # into train/valid halves, FedNASTrainer.py:22-30): a 1-step
        # client has h = n_real // 2 = 0, so it would train NOTHING
        # while keeping full aggregation weight — refuse loudly on both
        # layouts instead of silently diluting every round it joins.
        steps = np.ceil(np.maximum(self._host_counts(), 1)
                        / cfg.batch_size)
        if int(steps.min()) < 2:
            raise ValueError(
                "FedNAS needs >= 2 packed steps for EVERY client (the "
                "local data is split into train/valid halves, "
                "FedNASTrainer.py:22-30); "
                f"min(ceil(count/batch)) = {int(steps.min())} — use a "
                "smaller batch_size so each client packs >= 2 batches")

    def _build_local_train(self, optimizer, loss_fn):
        # The bilevel step is self-contained plain SGD (weight lr = the
        # live client lr, arch lr = arch_lr); the generic optimizer is
        # unused and incompatible knobs were refused above.
        del optimizer, loss_fn
        return make_fednas_local_search(
            self.fns.apply, self._client_lr, self.arch_lr, self.xi,
            self.cfg.epochs, self.unrolled)

    def genotype(self):
        """Derive the searched architecture from the averaged alphas
        (reference record_model_global_architecture, FedNASAggregator.py:173)."""
        from fedml_tpu.models.darts import derive_genotype

        return derive_genotype(
            self.net.params["alphas_normal"],
            self.net.params["alphas_reduce"], steps=self._steps,
            multiplier=self._multiplier)
