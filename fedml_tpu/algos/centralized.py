"""Centralized (non-federated) baseline trainer.

Parity: fedml_api/centralized/centralized_trainer.py:9 — trains the pooled
dataset conventionally; used as the accuracy reference for the federated ==
centralized equivalence test (the reference's CI asserts 3-decimal equality,
CI-script-fedavg.sh:40-45; our pytest asserts it numerically, see
tests/test_equivalence.py).

Mesh data parallelism (the reference's DistributedDataParallel path,
fedml_experiments/centralized/main.py:376) is expressed TPU-natively: the
batch axis of the ``[S, B, ...]`` pack is sharded over the mesh and params
stay replicated — XLA/GSPMD inserts the gradient all-reduce (the psum DDP
does by hand), so the training math is the SAME function, just annotated.
"""

from __future__ import annotations

import jax
import numpy as np

from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)


class CentralizedTrainer:
    """``mesh=None`` → single device. With a mesh, every global batch is
    split over ``mesh.axis_names[0]`` (``cfg.batch_size`` must divide by
    the mesh size); results are bit-for-bit independent of the mesh size
    up to float reduction order."""

    def __init__(self, model, cfg, loss_fn=softmax_ce, mesh=None):
        self.cfg = cfg
        self.mesh = mesh
        self.fns = model_fns(model)
        optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
        train_fn = make_local_train_fn_from_cfg(self.fns.apply, optimizer, cfg, loss_fn)
        eval_fn = make_eval_fn(self.fns.apply, loss_fn)
        if mesh is None:
            self.train_fn = jax.jit(train_fn)
            self.eval_fn = jax.jit(eval_fn)
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from fedml_tpu.parallel.shard import mesh_dcn_axis

            if mesh_dcn_axis(mesh):
                # Batch-axis data parallelism has no client groups to
                # pin per host; a hosts axis here would silently shard
                # the batch over ICI only.
                raise NotImplementedError(
                    "CentralizedTrainer shards the BATCH axis and does "
                    "not ride a DCN×ICI client mesh; pass a flat "
                    "client_mesh")
            axis = mesh.axis_names[0]
            n = int(mesh.shape[axis])
            if cfg.batch_size % n:
                raise ValueError(
                    f"batch_size={cfg.batch_size} must divide by the "
                    f"{n}-device mesh for batch-axis data parallelism")
            repl = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P(None, axis))  # [S, B, ...] → B split
            self.train_fn = jax.jit(
                train_fn,
                in_shardings=(repl, data, data, data, repl),
                out_shardings=(repl, repl),
            )
            # Eval stays unsharded: eval sets arrive with arbitrary batch
            # sizes (divisibility is a TRAIN-loop contract), and replicated
            # eval of a replicated model is correct on any mesh.
            self.eval_fn = jax.jit(eval_fn)
        self.rng, init_rng = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.net = None
        self._init_rng = init_rng

    def init_params(self, sample_x):
        self.net = self.fns.init(self._init_rng, np.asarray(sample_x))
        return self.net

    def train(self, x, y, mask):
        """One pass of ``cfg.epochs`` epochs over batched [S, B, ...] data."""
        if self.net is None:
            self.init_params(x[0])
        self.rng, sub = jax.random.split(self.rng)
        self.net, loss = self.train_fn(self.net, x, y, mask, sub)
        return float(loss)

    def evaluate(self, x, y, mask):
        return {k: float(v) for k, v in self.eval_fn(self.net, x, y, mask).items()}
