"""Centralized (non-federated) baseline trainer.

Parity: fedml_api/centralized/centralized_trainer.py:9 — trains the pooled
dataset conventionally; used as the accuracy reference for the federated ==
centralized equivalence test (the reference's CI asserts 3-decimal equality,
CI-script-fedavg.sh:40-45; our pytest asserts it numerically, see
tests/test_equivalence.py).
"""

from __future__ import annotations

import jax
import numpy as np

from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)


class CentralizedTrainer:
    def __init__(self, model, cfg, loss_fn=softmax_ce):
        self.cfg = cfg
        self.fns = model_fns(model)
        optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
        self.train_fn = jax.jit(
            make_local_train_fn_from_cfg(self.fns.apply, optimizer, cfg, loss_fn)
        )
        self.eval_fn = jax.jit(make_eval_fn(self.fns.apply, loss_fn))
        self.rng, init_rng = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.net = None
        self._init_rng = init_rng

    def init_params(self, sample_x):
        self.net = self.fns.init(self._init_rng, np.asarray(sample_x))
        return self.net

    def train(self, x, y, mask):
        """One pass of ``cfg.epochs`` epochs over batched [S, B, ...] data."""
        if self.net is None:
            self.init_params(x[0])
        self.rng, sub = jax.random.split(self.rng)
        self.net, loss = self.train_fn(self.net, x, y, mask, sub)
        return float(loss)

    def evaluate(self, x, y, mask):
        return {k: float(v) for k, v in self.eval_fn(self.net, x, y, mask).items()}
