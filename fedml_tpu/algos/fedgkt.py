"""FedGKT — Group Knowledge Transfer (He et al. 2020).

Parity target: reference fedml_api/distributed/fedgkt/ —
- clients train a small stump with CE + KL against the server's logits
  (GKTClientTrainer.py:49-106, KD loss :76-80), then sweep their data
  collecting per-batch (features, logits, labels) for the server (:108-120);
- the server trains the big tail on every client's features with
  CE + KL against the client logits (GKTServerTrainer.train_and_distill_
  on_client:110, train_large_model_on_the_server:233) and returns per-client
  server logits (get_global_logits:98);
- ``KL_Loss`` (utils.py:75-94): T² · KL(softmax(teacher/T) ‖
  log_softmax(student/T)), batch-mean.

TPU-native redesign: client phase is vmapped over the client axis (stumps
stacked ``[C, ...]``); the feature transfer is an on-device array handoff
``[C, S, B, 32, 32, 16]`` instead of pickled numpy dicts; the server phase
is a ``lax.scan`` over the flattened client×batch axis. Round 0 has no
server logits yet — the KL term is gated by a ``have_teacher`` flag
(the reference branches on ``len(server_logits_dict) != 0``).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algos.capability import ExcludedScanTiers
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.core.tree import tree_select
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.trainer.local import (
    NetState,
    make_epoch_shuffle,
    model_fns,
    softmax_ce,
)


def kl_loss(student_logits, teacher_logits, temperature: float = 1.0):
    """Per-example distillation KL (reference fedgkt/utils.py:75-94)."""
    t = temperature
    log_p = jax.nn.log_softmax(student_logits / t, axis=-1)
    q = jax.nn.softmax(teacher_logits / t, axis=-1) + 1e-7
    return t * t * jnp.sum(q * (jnp.log(q) - log_p), axis=-1)


class FedGKTAPI(ExcludedScanTiers):
    """Alternating client/server distillation.

    ``client_model``: stump returning ``(logits, features)``
    (fedml_tpu.models.resnet_split.ResNetClientStump).
    ``server_model``: tail mapping features → logits."""

    window_protocol = None
    window_exclusion = (
        "group knowledge transfer alternates TWO models (client stumps "
        "+ server tail) through a feature/logit exchange each round — "
        "the server phase trains on every client's features, so the "
        "round is not a cohort fold with a pure server carry")

    def __init__(self, client_model, server_model, train_fed: FederatedArrays,
                 test_global, cfg: FedConfig, temperature: float = 3.0,
                 epochs_server: int = 1, server_lr: float = 1e-3):
        self.cfg = cfg
        self.train_fed = train_fed
        self.test_global = test_global
        self.client_fns = model_fns(client_model)
        self.server_fns = model_fns(server_model)
        self.temperature = temperature
        self.epochs_server = epochs_server

        C = int(train_fed.x.shape[0])
        S = int(train_fed.x.shape[1])
        B = int(train_fed.x.shape[2])
        self.n_clients, self.n_steps, self.batch = C, S, B
        n_classes = int(client_model.num_classes)
        self.n_classes = n_classes

        # Reference client/server optimizers default to SGD+momentum / Adam
        # chosen by args (GKTServerTrainer.py:31-43); we use cfg.lr SGD-m
        # for clients and Adam(server_lr) for the server tail. server_lr is
        # an explicit ctor param — cfg.server_lr defaults to 1.0 (the FedOpt
        # server-SGD convention), which would blow up Adam.
        self.client_opt = optax.sgd(cfg.lr, momentum=0.9)
        self.server_opt = optax.adam(server_lr)

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, crng, srng = jax.random.split(rng, 3)
        sample_x = np.asarray(train_fed.x[0, 0])
        self.client_nets = jax.vmap(
            lambda r: self.client_fns.init(r, sample_x)
        )(jax.random.split(crng, C))
        one_client = jax.tree.map(lambda a: a[0], self.client_nets)
        (_, sample_feats), _ = self.client_fns.apply(one_client, sample_x)
        self.server_net = self.server_fns.init(srng, np.asarray(sample_feats))
        self.server_state = self.server_opt.init(self.server_net.params)

        # Teacher logits from the previous server phase, per client batch.
        self.server_logits = jnp.zeros((C, S, B, n_classes), jnp.float32)
        self.have_teacher = False

        self.client_phase = jax.jit(self._build_client_phase())
        self.server_phase = jax.jit(self._build_server_phase())
        self.eval_fn = jax.jit(self._build_eval())

    # ------------------------------------------------------------------
    def _build_client_phase(self):
        apply_fn, opt = self.client_fns.apply, self.client_opt
        T, epochs = self.temperature, self.cfg.epochs

        def local_train(net, xc, yc, mc, teacher, have_teacher, rng):
            opt_state = opt.init(net.params)

            def step(carry, inputs):
                net, opt_state, step_base = carry
                xb, yb, mb, tb, idx = inputs
                # Per-step key by fold_in on the STEP INDEX, not a carried
                # split chain: prefix-stable in the step count, same
                # discipline as trainer/local.py (fedlint R1).
                sub = jax.random.fold_in(step_base, idx)

                def loss_fn(p):
                    (logits, _), state = apply_fn(
                        NetState(p, net.model_state), xb, train=True, rng=sub)
                    per = softmax_ce(logits, yb)
                    per = per + have_teacher * kl_loss(logits, tb, T)
                    return (jnp.sum(per * mb) /
                            jnp.maximum(jnp.sum(mb), 1.0), state)

                (loss, state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(net.params)
                updates, new_opt = opt.update(grads, opt_state, net.params)
                nonempty = jnp.sum(mb) > 0
                net = tree_select(
                    nonempty,
                    NetState(optax.apply_updates(net.params, updates), state),
                    net)
                opt_state = tree_select(nonempty, new_opt, opt_state)
                return (net, opt_state, step_base), (loss, jnp.sum(mb))

            def epoch(carry, epoch_rng):
                # fold_in(·, 0)/(·, 1): shuffle keys and step streams fork
                # from DISJOINT children of the epoch key (local.py idiom).
                reshuffle = make_epoch_shuffle(
                    mc, jax.random.fold_in(epoch_rng, 0))
                net, opt_state, _ = carry
                step_base = jax.random.fold_in(epoch_rng, 1)
                carry, (losses, ns) = jax.lax.scan(
                    step, (net, opt_state, step_base),
                    (reshuffle(xc), reshuffle(yc), reshuffle(mc),
                     reshuffle(teacher), jnp.arange(xc.shape[0])))
                # Sample-weighted: padded all-masked steps carry weight 0.
                return carry, jnp.sum(losses * ns) / jnp.maximum(
                    jnp.sum(ns), 1.0)

            rng, shuffle_rng = jax.random.split(rng)
            (net, _, _), losses = jax.lax.scan(
                epoch, (net, opt_state, rng),
                jax.random.split(shuffle_rng, epochs))

            # Post-training sweep: features + logits for the server.
            def sweep(_, inputs):
                xb, _yb = inputs
                (logits, feats), _ = apply_fn(net, xb, train=False)
                return None, (feats, logits)

            _, (feats, logits) = jax.lax.scan(sweep, None, (xc, yc))
            return net, feats, logits, jnp.mean(losses)

        def phase(client_nets, x, y, mask, server_logits, have_teacher, rng):
            rngs = jax.random.split(rng, x.shape[0])
            return jax.vmap(local_train,
                            in_axes=(0, 0, 0, 0, 0, None, 0))(
                client_nets, x, y, mask, server_logits, have_teacher, rngs)

        return phase

    # ------------------------------------------------------------------
    def _build_server_phase(self):
        apply_fn, opt = self.server_fns.apply, self.server_opt
        T, epochs = self.temperature, self.epochs_server

        def phase(server_net, opt_state, feats, client_logits, y, mask, rng):
            # Flatten clients×steps into one scan axis.
            CS = feats.shape[0] * feats.shape[1]
            f = feats.reshape((CS,) + feats.shape[2:])
            cl = client_logits.reshape((CS,) + client_logits.shape[2:])
            yy = y.reshape((CS,) + y.shape[2:])
            mm = mask.reshape((CS,) + mask.shape[2:])

            def step(carry, inputs):
                net, opt_state, step_base = carry
                fb, clb, yb, mb, idx = inputs
                # fold_in on the step index (fedlint R1) — prefix-stable
                # whatever the flattened client x batch axis length.
                sub = jax.random.fold_in(step_base, idx)

                def loss_fn(p):
                    logits, state = apply_fn(
                        NetState(p, net.model_state), fb, train=True, rng=sub)
                    per = softmax_ce(logits, yb) + kl_loss(logits, clb, T)
                    return (jnp.sum(per * mb) /
                            jnp.maximum(jnp.sum(mb), 1.0), state)

                (loss, state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(net.params)
                updates, new_opt = opt.update(grads, opt_state, net.params)
                nonempty = jnp.sum(mb) > 0
                net = tree_select(
                    nonempty,
                    NetState(optax.apply_updates(net.params, updates), state),
                    net)
                opt_state = tree_select(nonempty, new_opt, opt_state)
                return (net, opt_state, step_base), (loss, jnp.sum(mb))

            def epoch(carry, e):
                net, opt_state = carry
                step_base = jax.random.fold_in(rng, e)
                (net, opt_state, _), (losses, ns) = jax.lax.scan(
                    step, (net, opt_state, step_base),
                    (f, cl, yy, mm, jnp.arange(f.shape[0])))
                return (net, opt_state), jnp.sum(losses * ns) / jnp.maximum(
                    jnp.sum(ns), 1.0)

            (server_net, opt_state), losses = jax.lax.scan(
                epoch, (server_net, opt_state), jnp.arange(epochs))

            # Fresh server logits for every client batch (next-round teacher).
            def relabel(_, fb):
                logits, _ = apply_fn(server_net, fb, train=False)
                return None, logits

            _, new_logits = jax.lax.scan(relabel, None, f)
            new_logits = new_logits.reshape(
                feats.shape[:3] + (new_logits.shape[-1],))
            return server_net, opt_state, new_logits, jnp.mean(losses)

        return phase

    # ------------------------------------------------------------------
    def _build_eval(self):
        client_apply, server_apply = self.client_fns.apply, self.server_fns.apply

        def eval_one(client_net, server_net, x, y, mask):
            def step(_, inputs):
                xb, yb, mb = inputs
                (_, feats), _ = client_apply(client_net, xb, train=False)
                logits, _ = server_apply(server_net, feats, train=False)
                correct = (jnp.argmax(logits, -1) == yb).astype(jnp.float32)
                return None, (jnp.sum(correct * mb), jnp.sum(mb))

            _, (c, n) = jax.lax.scan(step, None, (x, y, mask))
            return jnp.sum(c) / jnp.maximum(jnp.sum(n), 1.0)

        def eval_all(client_nets, server_net, x, y, mask):
            accs = jax.vmap(eval_one, in_axes=(0, None, None, None, None))(
                client_nets, server_net, x, y, mask)
            return jnp.mean(accs)

        return eval_all

    # ------------------------------------------------------------------
    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        self.rng, r1, r2 = jax.random.split(self.rng, 3)
        self.client_nets, feats, client_logits, closs = self.client_phase(
            self.client_nets, self.train_fed.x, self.train_fed.y,
            self.train_fed.mask, self.server_logits,
            jnp.float32(1.0 if self.have_teacher else 0.0), r1)
        (self.server_net, self.server_state, self.server_logits,
         sloss) = self.server_phase(
            self.server_net, self.server_state, feats, client_logits,
            self.train_fed.y, self.train_fed.mask, r2)
        self.have_teacher = True
        return {"round": round_idx, "client_loss": float(jnp.mean(closs)),
                "server_loss": float(sloss)}

    def train(self):
        return [self.train_one_round(r) for r in range(self.cfg.comm_round)]

    def evaluate(self) -> Dict[str, float]:
        if self.test_global is None:
            return {}
        x, y, mask = self.test_global
        acc = self.eval_fn(self.client_nets, self.server_net, x, y, mask)
        return {"accuracy": float(acc)}
