"""Turbo-Aggregate — secure aggregation with dropout-tolerant clients.

Parity target: reference fedml_api/standalone/turboaggregate/ (and the
distributed mirror) —
- the MPC library (mpc_function.py) → fedml_tpu.core.mpc;
- ``TA_Client.set_dropout`` (TA_client.py:25): clients may drop out of a
  round and the aggregate must still be recoverable;
- ``TurboAggregateTrainer`` (TA_trainer.py:11): clients organized into
  groups (``TA_topology_vanilla:87`` builds the multi-group ring), model
  updates masked so no single party (server included) sees a raw update.

Protocol here (additive-masking secure aggregation, the Turbo-Aggregate
core): every surviving client quantizes its weighted model delta into the
prime field and splits it into additive shares, one per group; each group
sums the shares it holds (partial sums reveal nothing); the server adds the
group sums and dequantizes. Sum of all shares ≡ sum of secrets (mod p), so
the recovered aggregate equals plain FedAvg up to 1/scale quantization.
Dropouts are handled at share-distribution time: a dropped client
contributes nothing and its weight leaves the normalization (the reference
drops them from the ring the same way).

Local training rides the shared vmapped ``lax.scan`` trainer; only the
aggregation is host-side MPC — the protocol is between trust domains, not a
TPU kernel.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core import mpc


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg with MPC aggregation. ``n_groups`` = Turbo-Aggregate ring
    groups; ``scale`` = fixed-point quantization (2^16 ≈ 1.5e-5 absolute
    error per aggregate — well under SGD noise)."""

    #: Carry capability record: opted out with the reason every scan-tier
    #: guard raises — the aggregation is a host-side multi-party share
    #: protocol, not a device fold the scan could replay.
    window_protocol = None
    window_exclusion = (
        "aggregation is the host-side Turbo-Aggregate MPC protocol "
        "(prime-field additive shares across trust domains, "
        "core/mpc) — there is no pure (carry_init, server_update, "
        "carry_commit) device record to scan")

    def __init__(self, *args, n_groups: int = 2, scale: int = 2 ** 16,
                 prime: int = mpc.DEFAULT_PRIME, **kwargs):
        super().__init__(*args, **kwargs)
        if self.mesh is not None:
            raise ValueError(
                "TurboAggregate aggregates on the host (MPC protocol); "
                "use mesh=None")
        if self.cfg.compress != "none":
            raise ValueError(
                "TurboAggregate's MPC path quantizes updates itself and "
                "bypasses the client-transform hook; cfg.compress would "
                "be silently dropped — unset it")
        self.n_groups = n_groups
        self.scale = scale
        self.prime = prime
        self.dropout_mask: Optional[np.ndarray] = None
        from jax.flatten_util import ravel_pytree

        self._ravel = ravel_pytree

    def set_client_lr(self, lr: float):
        """Rebuild the client-parallel local step (per-client models,
        WITHOUT the fused average — they feed the MPC protocol) whenever the
        base class rebuilds ``local_train``, so LR schedules reach this
        algorithm too."""
        if lr == self._client_lr:
            return
        super().set_client_lr(lr)
        self._local_batch = jax.jit(
            jax.vmap(self.local_train, in_axes=(None, 0, 0, 0, 0)))

    def set_dropout(self, dropped: Optional[Sequence[int]]):
        """Mark clients (by position in the sampled round) as dropped
        (reference TA_client.py:25)."""
        self.dropout_mask = (np.asarray(dropped, np.int64)
                             if dropped is not None else None)

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        idx, wmask = self.sample_round(round_idx)
        sub = self._cohort(round_idx, idx)
        weights = np.asarray(sub.counts, np.float64) * np.asarray(wmask)
        if self.dropout_mask is not None:
            weights[self.dropout_mask] = 0.0
        self.rng, rnd = jax.random.split(self.rng)
        rngs = jax.vmap(
            lambda i: jax.random.fold_in(rnd, i))(jnp.arange(sub.x.shape[0]))
        client_nets, losses = self._local_batch(
            self.net, sub.x, sub.y, sub.mask, rngs)

        # --- secure aggregation over the field ---------------------------
        wsum = weights.sum()
        if wsum == 0.0:
            # Every sampled client dropped: the round is a no-op (plain
            # FedAvg semantics keep the previous global model).
            return {"round": round_idx, "train_loss": float("nan")}
        wn = weights / wsum
        flat0, unravel = self._ravel(self.net)
        group_sums = np.zeros((self.n_groups, flat0.shape[0]), np.int64)
        # Masks must come from secret randomness: derive the share rng from
        # the session PRNG chain (full 128-bit key as seed material), never
        # from public round state. SIMULATION ONLY — MT19937 is not a
        # CSPRNG; a production deployment must draw masks from an OS CSPRNG
        # with pairwise key agreement (mpc.my_key_agreement) instead.
        self.rng, mask_rng = jax.random.split(self.rng)
        key_words = np.asarray(jax.random.key_data(mask_rng)).ravel()
        share_rng = np.random.RandomState(key_words.astype(np.uint32))
        for c in range(len(weights)):
            if wn[c] == 0.0:
                continue  # dropped or padded client: contributes nothing
            flat_c, _ = self._ravel(
                jax.tree.map(lambda a: a[c], client_nets))
            q = mpc.quantize(np.asarray(flat_c, np.float64) * wn[c],
                             self.scale, self.prime)
            shares = mpc.additive_shares(q, self.n_groups, self.prime,
                                         share_rng)
            group_sums = np.mod(group_sums + shares, self.prime)
        total = np.zeros(flat0.shape[0], np.int64)
        for g in range(self.n_groups):
            total = np.mod(total + group_sums[g], self.prime)
        avg_flat = mpc.dequantize(total, self.scale, self.prime)
        self.net = unravel(jnp.asarray(avg_flat, jnp.float32))

        loss = float(np.sum(np.asarray(losses, np.float64) * wn))
        return {"round": round_idx, "train_loss": loss}
