"""Shared federated training-loop policy (round cadence + eval frequency).

One implementation of the loop the reference re-implements in every
``*API``/``*Trainer`` class (e.g. standalone fedavg_api.py:40-82): train a
round, evaluate every ``frequency_of_the_test`` rounds and on the last
round, collect history.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from fedml_tpu.algos.capability import ExcludedScanTiers


def eval_segments(comm_round: int, frequency_of_the_test: int,
                  start: int = 0):
    """Split ``[start, comm_round)`` into inclusive ``(lo, hi)`` spans
    each ending exactly at an eval round — the rounds
    :meth:`FederatedLoop.train` evaluates after (``round_idx % freq == 0``
    or the last round). Windowed execution plans its windows WITHIN these
    spans (``FedAvgAPI.train_windowed``) so a multi-round scan never runs
    past a point where the host must stop and evaluate."""
    freq = max(int(frequency_of_the_test), 1)
    r = start
    while r < comm_round:
        e = r
        while not (e % freq == 0 or e == comm_round - 1):
            e += 1
        yield r, e
        r = e + 1


class FederatedLoop(ExcludedScanTiers):
    """Mixin. Subclasses provide ``cfg``, ``train_one_round(round_idx)``,
    ``eval_fn``, ``test_global``, and ``_eval_net()``. Subclasses that also
    provide ``n_shards``, ``train_fed``, ``net``, ``rng`` and ``round_fn``
    get the shared round scaffold (``sample_round``/``run_round``) for free.

    ``round_fn_fused`` is an optional extension point: a jitted
    ``(net, train_fed, idx, wmask, rng)`` round with the client gather
    traced inside (single-device fast path built by FedAvgAPI).

    The scan-tier entry points come from :class:`ExcludedScanTiers`
    (record-derived refusals keyed on the carry capability
    declarations below); FedAvgAPI overrides both the declarations —
    derived structurally from the carry-protocol hooks — and the entry
    points."""

    round_fn_fused = None

    def _eval_net(self):
        raise NotImplementedError

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        raise NotImplementedError

    def sample_round(self, round_idx: int):
        """Reference-seeded sampling + padding to the shard-count multiple
        (FedAVGAggregator.client_sampling, FedAVGAggregator.py:90-99)."""
        sel = getattr(self.cfg, "client_selection", "random")
        if sel != "random":
            # Loss-biased selection is implemented in FedAvgAPI's override;
            # algorithms landing here would silently sample uniformly
            # while the user believes pow_d is active.
            raise NotImplementedError(
                f"client_selection={sel!r} is not supported by "
                f"{type(self).__name__}; only the FedAvg family implements "
                "loss-biased selection")
        from fedml_tpu.core.sampling import pad_to_multiple, sample_clients

        directory = getattr(self.train_fed, "directory", None)
        if directory is not None \
                and directory.num_clients == self.cfg.client_num_in_total:
            # Sharded store (data/directory.py): the ClientDirectory IS
            # the cohort sampler — a metadata-only service whose draw
            # delegates to the same reference-seeded stream, so the
            # cohort is bit-identical to the flat path (and invariant
            # under re-sharding, tested).
            idx = directory.sample_cohort(round_idx,
                                          self.cfg.client_num_per_round)
        else:
            idx = sample_clients(
                round_idx, self.cfg.client_num_in_total,
                self.cfg.client_num_per_round
            )
        idx, wmask = pad_to_multiple(idx, self.n_shards)
        return idx, wmask

    def _round_aux(self, round_idx: int, idx, wmask):
        """Extra trailing operands for ``round_fn`` beyond the standard
        seven — the hook the device-side corruption drill fills with its
        per-client adversary mask (``FedAvgRobustAPI``). Default: none.
        Rounds built without the matching builder option keep their
        7-operand signature, so this must return ``()`` unless the
        subclass also configured its round to consume the extras."""
        return ()

    def run_round(self, round_idx: int):
        """One sampled round through ``round_fn``: gather client shards,
        sample-count weights (padded slots weight 0), fresh round rng.
        Returns ``(avg_net, mean_loss)`` without touching ``self.net``.

        When the subclass built a fused single-device round
        (``round_fn_fused``), the gather happens inside the jit — one
        dispatch per round instead of five. With a host-resident
        ``FederatedStore`` (``self._streaming``), the cohort was gathered
        on host (double-buffered) and the round consumes it directly."""
        self.rng, rnd_rng = jax.random.split(self.rng)
        # Server updates that need a round-keyed randomness stream
        # (FedAvgRobust's weak-DP noise) fold_in from THIS key instead of
        # splitting self.rng again: the windowed tier reproduces exactly
        # this per-round key chain, so fold_in children are bit-equal
        # across tiers (the PR-2 prefix-stability discipline).
        self._last_round_key = rnd_rng
        idx, wmask = self.sample_round(round_idx)
        aux = self._round_aux(round_idx, idx, wmask)
        if getattr(self, "_streaming", False):
            sub = self._stream_cohort(round_idx, idx)
            weights = sub.counts.astype(jnp.float32) * jnp.asarray(wmask)
            return self._unpack_round(self.round_fn(
                self.net, sub.x, sub.y, sub.mask, weights, weights, rnd_rng,
                *aux
            ))
        if self.round_fn_fused is not None and not aux:
            return self._unpack_round(self.round_fn_fused(
                self.net, self.train_fed,
                jnp.asarray(idx), jnp.asarray(wmask), rnd_rng))
        from fedml_tpu.data.batching import gather_clients

        sub = gather_clients(self.train_fed, idx)
        weights = sub.counts.astype(jnp.float32) * jnp.asarray(wmask)
        return self._unpack_round(self.round_fn(
            self.net, sub.x, sub.y, sub.mask, weights, weights, rnd_rng,
            *aux
        ))

    def _unpack_round(self, out):
        """Rounds built with ``with_client_losses`` return a third,
        per-client-loss output (oort's in-round utility observable);
        capture it on the instance so callers keep the 2-tuple
        contract."""
        if len(out) == 3:
            avg, loss, client_losses = out
            self._round_client_losses = client_losses
            return avg, loss
        return out

    def _per_client_eval(self):
        """Cached jitted vmapped eval over a client-stacked layout —
        shared by evaluate_on_clients and pow_d selection (vmapping the
        jit-wrapped eval_fn inline would re-trace the whole N-client pass
        on every call, and two call sites must not hold two executables
        of the same kernel)."""
        fn = getattr(self, "_clients_eval_fn", None)
        if fn is None:
            fn = jax.jit(jax.vmap(
                lambda n, x, y, mask: self.eval_fn(n, x, y, mask),
                in_axes=(None, 0, 0, 0)))
            self._clients_eval_fn = fn
        return fn

    def evaluate(self) -> Dict[str, float]:
        if self.test_global is None:
            return {}
        x, y, mask = self.test_global
        m = self.eval_fn(self._eval_net(), x, y, mask)
        return {k: float(v) for k, v in m.items()}

    def evaluate_on_clients(self, arrays=None,
                            prefix: str = "clients_train") -> Dict[str, float]:
        """Per-client evaluation of the current global model on every
        client's LOCAL shard — the reference's
        ``_local_test_on_all_clients`` / ``test_on_server_for_all_clients``
        cadence (fedavg_api.py:117, FedAVGAggregator.py:110-161), which it
        runs as a host-side Python loop over clients each eval round; here
        it is one vmapped on-device pass (SURVEY.md §7 hard part #5).
        Returns the sample-weighted mean plus worst-client stats (the
        quantity fairness methods optimize).

        ``arrays`` defaults to the training shards; pass the per-client
        TEST layout (``to_federated_arrays(fed, bs, split="test")`` — the
        reference's ``test_data_local_dict``) with ``prefix=
        "clients_test"`` for the local-test leg of the reference cadence.
        Clients with no samples are excluded from the worst-client stats.
        """
        f = arrays if arrays is not None else self.train_fed
        if arrays is None and getattr(self, "_streaming", False):
            return self._evaluate_on_clients_streaming(prefix)
        net = self._eval_net()
        m = self._per_client_eval()(net, f.x, f.y, f.mask)
        num = m["num"]
        n = jnp.maximum(jnp.sum(num), 1.0)
        present = num > 0
        worst_acc = jnp.min(jnp.where(present, m["accuracy"], jnp.inf))
        worst_loss = jnp.max(jnp.where(present, m["loss"], -jnp.inf))
        return {
            f"{prefix}_acc": float(jnp.sum(m["accuracy"] * num) / n),
            f"{prefix}_loss": float(jnp.sum(m["loss"] * num) / n),
            f"worst_client_{prefix.split('_')[-1]}_acc": float(worst_acc),
            f"worst_client_{prefix.split('_')[-1]}_loss": float(worst_loss),
        }

    def _evaluate_on_clients_streaming(
            self, prefix: str, chunk: int = 256) -> Dict[str, float]:
        """Store-backed variant of evaluate_on_clients: iterate the client
        population in host-gathered chunks (device holds one chunk at a
        time), accumulating the same weighted-mean + worst-client stats.
        The reference walks all 3400 FEMNIST clients per eval the same
        way, one at a time (FedAVGAggregator.py:117-133)."""
        import numpy as np

        store = self.train_fed
        net = self._eval_net()
        per = self._per_client_eval()
        tot_acc = tot_loss = tot_n = 0.0
        worst_acc, worst_loss = float("inf"), float("-inf")
        for lo in range(0, store.num_clients, chunk):
            idx = np.arange(lo, min(lo + chunk, store.num_clients))
            sub = store.gather_cohort(idx)
            m = per(net, sub.x, sub.y, sub.mask)
            num = np.asarray(m["num"])
            acc = np.asarray(m["accuracy"])
            loss = np.asarray(m["loss"])
            present = num > 0
            tot_acc += float((acc * num).sum())
            tot_loss += float((loss * num).sum())
            tot_n += float(num.sum())
            if present.any():
                worst_acc = min(worst_acc, float(acc[present].min()))
                worst_loss = max(worst_loss, float(loss[present].max()))
        n = max(tot_n, 1.0)
        return {
            f"{prefix}_acc": tot_acc / n,
            f"{prefix}_loss": tot_loss / n,
            f"worst_client_{prefix.split('_')[-1]}_acc": worst_acc,
            f"worst_client_{prefix.split('_')[-1]}_loss": worst_loss,
        }

    def train(self) -> List[Dict[str, float]]:
        history = []
        for round_idx in range(self.cfg.comm_round):
            metrics = self.train_one_round(round_idx)
            if (
                round_idx % self.cfg.frequency_of_the_test == 0
                or round_idx == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            history.append(metrics)
        return history
