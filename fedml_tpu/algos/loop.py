"""Shared federated training-loop policy (round cadence + eval frequency).

One implementation of the loop the reference re-implements in every
``*API``/``*Trainer`` class (e.g. standalone fedavg_api.py:40-82): train a
round, evaluate every ``frequency_of_the_test`` rounds and on the last
round, collect history.
"""

from __future__ import annotations

from typing import Dict, List


class FederatedLoop:
    """Mixin. Subclasses provide ``cfg``, ``train_one_round(round_idx)``,
    ``eval_fn``, ``test_global``, and ``_eval_net()``."""

    def _eval_net(self):
        raise NotImplementedError

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        raise NotImplementedError

    def evaluate(self) -> Dict[str, float]:
        if self.test_global is None:
            return {}
        x, y, mask = self.test_global
        m = self.eval_fn(self._eval_net(), x, y, mask)
        return {k: float(v) for k, v in m.items()}

    def train(self) -> List[Dict[str, float]]:
        history = []
        for round_idx in range(self.cfg.comm_round):
            metrics = self.train_one_round(round_idx)
            if (
                round_idx % self.cfg.frequency_of_the_test == 0
                or round_idx == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            history.append(metrics)
        return history
