"""q-FedAvg — fair federated learning (Li et al. 2020, "Fair Resource
Allocation in Federated Learning").

New capability: the reference's only aggregation weighting is sample
counts, so well-fit clients keep dominating the average. q-FedAvg
reweights each round by the clients' local losses — the update direction
leans toward whoever is currently served worst:

    Delta_k = L * (w - w_k)                       (L = 1/lr)
    h_k     = q * F_k^(q-1) * ||Delta_k||^2 + L * F_k^q
    w      <- w - sum_k F_k^q Delta_k / sum_k h_k

with F_k the client's loss AT THE BROADCAST MODEL w^t (a post-adaptation
training loss would underweight disadvantaged clients whose local task is
easy to fit, inverting the fairness objective). ``q = 0`` recovers the
equal-weight FedAvg PARAMETER update exactly (F^0 = 1, h = L); larger q
trades average accuracy for uniformity of per-client performance.
Non-trainable collections (BN running stats) always aggregate with
FedAvg's sample-count weighting — so on stateful models with unequal
counts, q=0 matches FedAvg's state but the equal-weight mean for params.

TPU design: drops into FedAvgAPI's round hooks — client training stays
the same vmapped local_train; only the server combination changes, and it
is a handful of einsums over the client-stacked pytree. One shared core
(``_qffl_update``) serves both the single-device vmap round and the
mesh-sharded round; the only difference is the cross-shard reduction
(identity vs ``lax.psum``), so the fair-update math cannot drift between
the two paths.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.parallel.shard import (client_axis, client_rngs,
                                      run_clients_guarded)
from fedml_tpu.trainer.local import NetState


def _make_loss_at_global(apply_fn, loss_fn):
    """Per-client masked mean loss of the (broadcast) net on one client's
    packed shard ``[S, B, ...]``."""

    def loss_at_global(net, xc, yc, mc):
        def step(_, inp):
            xb, yb, mb = inp
            logits, _ = apply_fn(net, xb, train=False)
            per = loss_fn(logits, yb)
            return None, (jnp.sum(per * mb), jnp.sum(mb))

        _, (ls, ns) = jax.lax.scan(step, None, (xc, yc, mc))
        return jnp.sum(ls) / jnp.maximum(jnp.sum(ns), 1.0)

    return loss_at_global


def _qffl_update(net, client_nets, F_global, losses, weights, loss_weights,
                 active, q: float, L: float, cross):
    """The fair server update, shared by the vmap and sharded rounds.

    ``cross(x)`` reduces a locally-summed quantity across shards —
    identity on a single device, ``lax.psum`` under shard_map. Everything
    else (F clamp, masking, h/denominator, the all-diverged BN-state
    fallback, loss weighting) is written once so the two paths cannot
    silently diverge."""
    F = jnp.maximum(F_global, 1e-12)
    Fq = jnp.where(active > 0, F ** q, 0.0)
    Fq_m1 = jnp.where(active > 0, F ** (q - 1.0), 0.0)

    # Delta_k = L (w - w_k) over trainable params, client-stacked.
    deltas = jax.tree.map(
        lambda w_, wk: L * (w_.astype(jnp.float32)[None] -
                            wk.astype(jnp.float32)),
        net.params, client_nets.params)
    delta_sq = sum(
        jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=1)
        for d in jax.tree.leaves(deltas))
    h = q * Fq_m1 * delta_sq + L * Fq
    denom = jnp.maximum(cross(jnp.sum(h * active)), 1e-12)
    new_params = jax.tree.map(
        lambda w_, d: (w_.astype(jnp.float32)
                       - cross(jnp.einsum("c,c...->...", Fq * active, d))
                       / denom).astype(w_.dtype),
        net.params, deltas)

    # Non-trainable collections (BN stats): sample-count-weighted mean
    # over active clients — the same weighting FedAvg's tree_weighted_mean
    # applies to NetState. (Parameters are governed by the q-update, whose
    # q=0 limit is the UNIFORM client mean — so q=0 equals FedAvg only
    # under equal counts; the state mean matches FedAvg's count weighting
    # always.) An all-diverged round (total weight 0) keeps the PREVIOUS
    # stats: a zero-weight einsum would silently zero the running
    # mean/var and corrupt every later eval. (The parameter update above
    # is already safe in that case — its numerator and h-sum both vanish,
    # leaving w unchanged.)
    w_state = weights.astype(jnp.float32) * active
    total_w = cross(jnp.sum(w_state))
    any_ok = total_w > 0
    wn = w_state / jnp.maximum(total_w, 1e-12)
    new_state = jax.tree.map(
        lambda s, old: jnp.where(
            any_ok,
            cross(jnp.einsum("c,c...->...", wn,
                             s.astype(jnp.float32))).astype(s.dtype),
            old),
        client_nets.model_state, net.model_state)

    lw = loss_weights * active
    lw = lw / jnp.maximum(cross(jnp.sum(lw)), 1e-12)
    return NetState(new_params, new_state), cross(jnp.sum(losses * lw))


def _make_qffl_body(local_train, q, L, apply_fn, loss_fn, client_transform,
                    nan_guard):
    """The whole round given per-client rng streams and a cross-shard
    reduction — shared verbatim by the vmap and sharded wrappers so no
    stage (F_global eval, guarded training, masking, fair update) can
    silently diverge between the two paths."""
    loss_at_global = _make_loss_at_global(apply_fn, loss_fn)

    def body(net, x, y, mask, weights, loss_weights, rngs, cross):
        F_global = jax.vmap(loss_at_global, in_axes=(None, 0, 0, 0))(
            net, x, y, mask)
        client_nets, losses, finite = run_clients_guarded(
            local_train, client_transform, nan_guard,
            net, x, y, mask, rngs)
        active = (weights > 0).astype(jnp.float32) * finite
        return _qffl_update(net, client_nets, F_global, losses, weights,
                            loss_weights, active, q, L, cross)

    return body


def make_qffl_round(local_train, q: float, lr: float, apply_fn, loss_fn,
                    client_transform=None, nan_guard: bool = False):
    """Same signature as ``make_vmap_round`` so FedAvgAPI's fused-gather
    and scan paths work unchanged."""
    body = _make_qffl_body(local_train, q, 1.0 / lr, apply_fn, loss_fn,
                           client_transform, nan_guard)

    def round_fn(net, x, y, mask, weights, loss_weights, rng):
        rngs = client_rngs(rng, x.shape[0], 0)
        return body(net, x, y, mask, weights, loss_weights, rngs,
                    cross=lambda v: v)

    return round_fn


def make_qffl_sharded_round(local_train, q: float, lr: float, apply_fn,
                            loss_fn, mesh, axis: str = "clients",
                            client_transform=None, nan_guard: bool = False):
    """Sharded q-FFL round: clients split over ``mesh[axis]``; the scalar
    reductions (Σ h_k) and the per-leaf numerators (Σ F_k^q Δ_k) become
    psums over ICI, so the fair update is exact regardless of how clients
    land on shards (mirrors make_sharded_round's weighted mean)."""
    from fedml_tpu.parallel.shard import _psum_hier, client_axes

    body = _make_qffl_body(local_train, q, 1.0 / lr, apply_fn, loss_fn,
                           client_transform, nan_guard)
    axes = client_axes(mesh, axis)
    cs = P(axes)
    idx_ax = axes if len(axes) > 1 else axis

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), cs, cs, cs, cs, cs, P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def round_fn(net, x, y, mask, weights, loss_weights, rng):
        shard_idx = jax.lax.axis_index(idx_ax)
        rngs = client_rngs(rng, x.shape[0], shard_idx * x.shape[0])
        return body(net, x, y, mask, weights, loss_weights, rngs,
                    cross=lambda v: _psum_hier(v, axes))

    return round_fn


class QFedAvgAPI(FedAvgAPI):
    """FedAvg with the q-FFL fair aggregation. ``q=0`` ≡ equal-weight
    FedAvg for the parameters (tested; model_state keeps FedAvg's
    sample-count weighting — see module docstring); typical fair settings
    use q in [0.1, 5]. Works on the single-device vmap simulator and
    sharded over a client mesh (tested numerically identical)."""

    window_carry = "— (fair q-update baked into round_fn)"

    def __init__(self, *args, q: float = 1.0, **kw):
        self.q = q
        super().__init__(*args, **kw)

    def _make_vmap_round(self, local_train, transform, guard):
        return make_qffl_round(local_train, self.q, self._client_lr,
                               self.fns.apply, self._loss_fn,
                               client_transform=transform, nan_guard=guard)

    def _make_sharded_round(self, local_train, mesh, transform, guard):
        return make_qffl_sharded_round(
            local_train, self.q, self._client_lr, self.fns.apply,
            self._loss_fn, mesh, client_axis(mesh),
            client_transform=transform, nan_guard=guard)
