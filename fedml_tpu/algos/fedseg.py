"""Federated semantic segmentation (reference fedml_api/distributed/fedseg).

FedAvg aggregation over a segmentation net + the fedseg metric/loss suite
done the TPU way:

- losses: pixel-wise CE and focal loss with an ``ignore_index``
  (SegmentationLosses, fedseg/utils.py:71-123) as pure jax functions usable
  inside the jitted local step;
- metrics: confusion-matrix based pixel accuracy, per-class accuracy, mIoU
  and FWIoU (Evaluator, fedseg/utils.py:246-280) computed ON DEVICE with
  ``jnp.bincount`` over the flattened confusion index — no host sync per
  batch — then reduced to scalars once per eval;
- per-client metric tracking mirroring ``EvaluationMetricsKeeper`` and the
  aggregator's train/test dicts (FedSegAggregator.py:105-160).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI


# ---------------------------------------------------------------------------
# Losses (SegmentationLosses parity)
# ---------------------------------------------------------------------------

def seg_ce_loss(logits, labels, ignore_index: int = 255):
    """Pixel-wise softmax CE over [B, H, W, C] logits / [B, H, W] int labels;
    positions equal to ``ignore_index`` contribute nothing.

    Returns a PER-EXAMPLE loss [B] (each sample's mean over its valid
    pixels) — the ``loss_fn`` contract of ``make_local_train_fn``, whose
    sample mask multiplies per-example losses; a batch-scalar here would let
    padded samples' pixels leak into the gradient."""
    valid = (labels != ignore_index)
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    per_pix = valid.reshape(valid.shape[0], -1)
    per_nll = nll.reshape(nll.shape[0], -1)
    return jnp.sum(per_nll, axis=1) / jnp.maximum(jnp.sum(per_pix, axis=1), 1.0)


def seg_focal_loss(logits, labels, gamma: float = 2.0, alpha: float = 0.5,
                   ignore_index: int = 255):
    """Focal loss: α(1−p)^γ · CE (fedseg/utils.py:97-123). Per-example [B],
    same contract as ``seg_ce_loss``."""
    valid = (labels != ignore_index)
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    focal = alpha * (1.0 - jnp.exp(-nll)) ** gamma * nll
    focal = jnp.where(valid, focal, 0.0)
    per_pix = valid.reshape(valid.shape[0], -1)
    per_f = focal.reshape(focal.shape[0], -1)
    return jnp.sum(per_f, axis=1) / jnp.maximum(jnp.sum(per_pix, axis=1), 1.0)


def build_seg_loss(mode: str = "ce", ignore_index: int = 255):
    """SegmentationLosses.build_loss parity ('ce' | 'focal')."""
    if mode == "ce":
        return partial(seg_ce_loss, ignore_index=ignore_index)
    if mode == "focal":
        return partial(seg_focal_loss, ignore_index=ignore_index)
    raise ValueError(f"unknown segmentation loss mode {mode!r}")


# ---------------------------------------------------------------------------
# Metrics (Evaluator parity, on-device)
# ---------------------------------------------------------------------------

def confusion_matrix(pred, labels, num_classes: int, ignore_index: int = 255):
    """[C, C] confusion counts (rows = ground truth) via one bincount."""
    valid = (labels != ignore_index) & (labels >= 0) & (labels < num_classes)
    idx = jnp.where(valid, labels * num_classes + pred, num_classes * num_classes)
    counts = jnp.bincount(idx.ravel(), length=num_classes * num_classes + 1)
    return counts[:-1].reshape(num_classes, num_classes)


def evaluator_scores(cm) -> Dict[str, jnp.ndarray]:
    """Pixel acc / class acc / mIoU / FWIoU from a confusion matrix
    (Evaluator.{Pixel_Accuracy,...}, fedseg/utils.py:251-280)."""
    cm = cm.astype(jnp.float64) if cm.dtype == jnp.int64 else cm.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(cm), 1.0)
    diag = jnp.diagonal(cm)
    gt = jnp.sum(cm, axis=1)
    pr = jnp.sum(cm, axis=0)
    union = gt + pr - diag
    present = gt > 0
    acc = jnp.sum(diag) / total
    acc_class = jnp.sum(jnp.where(present, diag / jnp.maximum(gt, 1.0), 0.0)) / (
        jnp.maximum(jnp.sum(present), 1.0))
    iou = jnp.where(union > 0, diag / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(jnp.where(present, iou, 0.0)) / jnp.maximum(jnp.sum(present), 1.0)
    freq = gt / total
    fwiou = jnp.sum(jnp.where(present, freq * iou, 0.0))
    return {"acc": acc, "acc_class": acc_class, "mIoU": miou, "FWIoU": fwiou}


class EvaluationMetricsKeeper:
    """Per-client running metric store (fedseg/utils.py:62-69 + the
    aggregator's dicts, FedSegAggregator.py:105-160)."""

    def __init__(self):
        self._store: Dict[int, Dict[str, float]] = {}

    def add(self, client_idx: int, metrics: Dict[str, float]):
        self._store[client_idx] = {k: float(v) for k, v in metrics.items()}

    def aggregate(self) -> Dict[str, float]:
        if not self._store:
            return {}
        keys = next(iter(self._store.values())).keys()
        return {
            k: float(np.mean([m[k] for m in self._store.values()]))
            for k in keys
        }


# ---------------------------------------------------------------------------
# The federated algorithm
# ---------------------------------------------------------------------------

class FedSegAPI(FedAvgAPI):
    """FedAvg over a segmentation model with segmentation losses/metrics.

    ``loss_mode``: 'ce' | 'focal'; labels use ``ignore_index`` for void
    pixels. Eval reports acc/acc_class/mIoU/FWIoU over the global test set
    with a single on-device confusion matrix.
    """

    window_carry = "— (seg loss/metrics live in the local step/eval)"

    def __init__(self, model, train_fed, test_global, cfg, num_classes: int,
                 loss_mode: str = "ce", ignore_index: int = 255, **kw):
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        seg_loss = build_seg_loss(loss_mode, ignore_index)
        super().__init__(model, train_fed, test_global, cfg,
                         loss_fn=seg_loss, **kw)
        self.metrics_keeper = EvaluationMetricsKeeper()

        apply_fn = self.fns.apply
        nc, ig = num_classes, ignore_index

        def eval_cm(net, x, y, mask):
            def step(cm, inputs):
                bx, by, bm = inputs
                logits, _ = apply_fn(net, bx, train=False)
                pred = jnp.argmax(logits, axis=-1)
                # Zero out padded rows via the ignore label.
                by = jnp.where(bm[:, None, None] > 0, by, ig)
                return cm + confusion_matrix(pred, by, nc, ig), None

            cm0 = jnp.zeros((nc, nc), jnp.int32)
            cm, _ = jax.lax.scan(step, cm0, (x, y, mask))
            return cm

        self._eval_cm = jax.jit(eval_cm)

    def evaluate(self) -> Dict[str, float]:
        if self.test_global is None:
            return {}
        x, y, mask = self.test_global
        cm = self._eval_cm(self._eval_net(), x, y, mask)
        scores = evaluator_scores(cm)
        return {k: float(v) for k, v in scores.items()}

    def evaluate_clients(self, test_local: Dict[int, tuple]) -> Dict[str, float]:
        """Per-client evaluation (the aggregator's add_client_test_result /
        output_global_acc_and_loss flow, FedSegAggregator.py:105-160):
        ``test_local`` maps client id → batched ``(x, y, mask)``; each
        client's scores land in ``self.metrics_keeper`` and the unweighted
        client mean is returned (the reference averages per-client metrics
        the same way)."""
        net = self._eval_net()
        for cid, (x, y, mask) in test_local.items():
            cm = self._eval_cm(net, x, y, mask)
            self.metrics_keeper.add(
                cid, {k: float(v) for k, v in evaluator_scores(cm).items()})
        return self.metrics_keeper.aggregate()
