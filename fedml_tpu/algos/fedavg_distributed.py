"""Cross-silo distributed FedAvg over the message-passing comm layer.

Parity with the reference's distributed pipeline
(fedml_api/distributed/fedavg/FedAvgAPI.py:20, FedAVGAggregator.py,
FedAvgServerManager.py, FedAvgClientManager.py, message_define.py:1-12):
one server process + W client processes; per round the server samples
client indices (seeded, FedAVGAggregator.py:90-99), broadcasts the global
model, each worker runs jit-compiled local SGD on its assigned client's
shard, and the server weighted-averages the returned pytrees.

This path exists for TRUE federation (separate hosts/silos over loopback or
the native TCP transport). Simulated federation should use ``FedAvgAPI``,
where clients are a sharded array axis and aggregation is a psum over ICI.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.comm.loopback import LoopbackNetwork, run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.compression import make_compressor, tree_spec
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.core.tree import tree_scale, tree_add, tree_sub
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)

# message_define.py:1-12 parity
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3

MSG_ARG_KEY_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
MSG_ARG_KEY_CLIENT_INDEX = Message.MSG_ARG_KEY_CLIENT_INDEX
MSG_ARG_KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES


class FedAVGAggregator:
    """Server state: buffer per-worker results, weighted-average when the
    round completes (FedAVGAggregator.py:44-88; arrival counting lives in
    the server manager's ``_arrived`` set, which also covers the first-k
    straggler-tolerant mode)."""

    def __init__(self, net, worker_num: int, cfg: FedConfig, eval_fn=None,
                 test_data=None):
        self.net = net
        self.worker_num = worker_num
        self.cfg = cfg
        self.eval_fn = eval_fn
        self.test_data = test_data
        self.model_dict: Dict[int, object] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.test_history: List[dict] = []

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)

    def aggregate(self):
        return self.aggregate_from(range(self.worker_num))

    def aggregate_from(self, indices):
        """Weighted average over a subset of worker slots — the first-k
        straggler-tolerant mode aggregates only the workers that uploaded
        fresh results this round."""
        indices = list(indices)
        total = sum(self.sample_num_dict[i] for i in indices)
        avg = None
        for i in indices:
            w = self.sample_num_dict[i] / max(total, 1e-12)
            scaled = tree_scale(self.model_dict[i], w)
            avg = scaled if avg is None else tree_add(avg, scaled)
        self.net = avg
        return avg

    def client_sampling(self, round_idx: int) -> np.ndarray:
        return sample_clients(
            round_idx, self.cfg.client_num_in_total, self.cfg.client_num_per_round
        )

    def test_on_server(self, round_idx: int) -> Optional[dict]:
        """Global-test-set eval (replaces the reference's per-client loop,
        FedAVGAggregator.py:110-161, which re-evaluates every client's
        local shard each round)."""
        if self.eval_fn is None or self.test_data is None:
            return None
        m = self.eval_fn(self.net, *self.test_data)
        out = {"round": round_idx, **{k: float(v) for k, v in m.items()}}
        self.test_history.append(out)
        return out


class FedAVGServerManager(ServerManager):
    """Synchronous server. ``aggregate_k`` (0 = all workers) enables
    straggler-tolerant first-k rounds: the round aggregates as soon as
    ``k`` FRESH uploads arrive; a straggler's late upload for an older
    round is discarded and the worker is immediately reassigned to the
    current round ("catch-up"), so message flow stays strict
    request/response — every upload gets exactly one reply and no worker
    can hold two assignments. The reference has no straggler story at all
    (check_whether_all_receive blocks on everyone)."""

    def __init__(self, args, aggregator: FedAVGAggregator, cfg: FedConfig,
                 size: int, backend: str = "LOOPBACK", compress: str = "none",
                 aggregate_k: int = 0):
        super().__init__(args, rank=0, size=size, backend=backend)
        if aggregate_k and not 1 <= aggregate_k <= size - 1:
            raise ValueError(
                f"aggregate_k={aggregate_k} outside [1, {size - 1}]")
        self.aggregator = aggregator
        self.cfg = cfg
        self.round_idx = 0
        self.aggregate_k = aggregate_k or (size - 1)
        self._arrived: set = set()
        self.straggler_drops = 0
        self._done_workers = 0
        self._decoders = {}  # codec name → compressor (built lazily)
        self._spec = tree_spec(aggregator.net)
        # The net broadcast this round — compressed uploads are deltas
        # against it, so reconstruction must use the same anchor.
        self._broadcast_net = aggregator.net
        del compress  # server decodes by each frame's self-described codec

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_init_msg()
        self.com_manager.handle_receive_message()

    def send_init_msg(self) -> None:
        client_indexes = self.aggregator.client_sampling(0)
        for worker in range(1, self.size):
            msg = Message(MSG_TYPE_S2C_INIT_CONFIG, 0, worker)
            msg.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
            msg.add(MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[worker - 1]))
            msg.add("round", 0)
            self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )

    def _send_done(self, worker: int) -> None:
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
        out.add("done", True)
        self.send_message(out)
        self._done_workers += 1
        if self._done_workers == self.size - 1:
            self.finish()

    def _send_assignment(self, worker: int, client_indexes=None) -> None:
        if client_indexes is None:
            client_indexes = self.aggregator.client_sampling(self.round_idx)
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self._broadcast_net)
        out.add(MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[worker - 1]))
        out.add("round", self.round_idx)
        out.add("done", False)
        self.send_message(out)

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        if self.round_idx >= self.cfg.comm_round:
            # Terminal: a straggler's in-flight upload after the final
            # aggregation — release it.
            self._send_done(sender)
            return
        tag = msg.get("round")
        if tag is not None and int(tag) != self.round_idx:
            # Stale upload from an older round: discard the model, catch
            # the worker up on the current round.
            self.straggler_drops += 1
            self._send_assignment(sender)
            return
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        codec = msg.get("compression")
        if codec:
            # Dispatch on the frame's self-described codec, not a server
            # flag: per-rank launches may configure compression on the
            # clients only, and ranks could even mix schemes.
            if codec not in self._decoders:
                self._decoders[codec] = make_compressor(codec)
            delta = self._decoders[codec].decode(payload, self._spec)
            payload = tree_add(self._broadcast_net, delta)
        self.aggregator.add_local_trained_result(
            sender - 1, payload, msg.get(MSG_ARG_KEY_NUM_SAMPLES)
        )
        self._arrived.add(sender)
        if len(self._arrived) < self.aggregate_k:
            return
        global_net = self.aggregator.aggregate_from(
            sorted(w - 1 for w in self._arrived))
        self._broadcast_net = global_net
        if (
            self.round_idx % self.cfg.frequency_of_the_test == 0
            or self.round_idx == self.cfg.comm_round - 1
        ):
            self.aggregator.test_on_server(self.round_idx)
        self.round_idx += 1
        arrived, self._arrived = self._arrived, set()
        if self.round_idx >= self.cfg.comm_round:
            for worker in sorted(arrived):
                self._send_done(worker)
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for worker in sorted(arrived):
            self._send_assignment(worker, client_indexes)


class FedAVGClientManager(ClientManager):
    """Worker process: jitted local training on the assigned client's shard
    (FedAvgClientManager.py:34-79)."""

    def __init__(self, args, rank: int, size: int, train_fed: FederatedArrays,
                 local_train, cfg: FedConfig, backend: str = "LOOPBACK",
                 compress: str = "none"):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.train_fed = train_fed
        self.local_train = local_train
        self.cfg = cfg
        self.round_idx = 0
        self._compressor = make_compressor(compress)
        # Latest top-k error-feedback residual: (round, client, residual).
        # EF theory requires the residual to stay with its own data
        # stream, so it is applied only when this rank trains the SAME
        # client in the IMMEDIATELY next round — a stale carry would
        # otherwise spike against a much-evolved model, and one client's
        # carry must never leak into another's update. A rank trains one
        # client per round, so a single triple suffices (a per-client dict
        # would pin one dead model-sized residual per migrated-away client
        # forever). Under full participation assignments are stable and EF
        # is exact; under subsampling the carry drops at migrations.
        self._ef_state: Optional[tuple] = None
        # Dropped-carry visibility (like the server's straggler_drops):
        # each increment is one round whose compression error correction
        # was discarded — top-k is running as plain biased compression in
        # exactly the regimes (first-k rounds, client re-assignment) that
        # cause the drops.
        self.ef_carry_drops = 0

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server,
        )

    def handle_message_init(self, msg: Message) -> None:
        self.round_idx = int(msg.get("round") or 0)
        self._train(msg.get(MSG_ARG_KEY_MODEL_PARAMS), msg.get(MSG_ARG_KEY_CLIENT_INDEX))

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        if msg.get("done"):
            self.finish()
            return
        # The server's round tag, not a local counter: under first-k
        # aggregation a straggler can be reassigned past skipped rounds.
        tag = msg.get("round")
        self.round_idx = int(tag) if tag is not None else self.round_idx + 1
        self._train(msg.get(MSG_ARG_KEY_MODEL_PARAMS), msg.get(MSG_ARG_KEY_CLIENT_INDEX))

    def _train(self, global_net, client_index: int) -> None:
        c = int(client_index)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.round_idx)
        rng = jax.random.fold_in(rng, c)
        net, loss = self.local_train(
            global_net,
            self.train_fed.x[c],
            self.train_fed.y[c],
            self.train_fed.mask[c],
            rng,
        )
        out = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        if self._compressor.name != "none":
            delta = tree_sub(net, global_net)
            rng_c = jax.random.fold_in(rng, 0xC0)
            prev = self._ef_state
            carry = (prev[2] if prev and prev[0] == self.round_idx - 1
                     and prev[1] == c else None)
            if prev is not None and carry is None and prev[2] is not None:
                self.ef_carry_drops += 1
            payload, residual = self._compressor.encode(delta, carry, rng_c)
            self._ef_state = (self.round_idx, c, residual)
            out.add(MSG_ARG_KEY_MODEL_PARAMS, payload)
            out.add("compression", self._compressor.name)
        else:
            out.add(MSG_ARG_KEY_MODEL_PARAMS, jax.device_get(net))
        out.add(MSG_ARG_KEY_NUM_SAMPLES, int(self.train_fed.counts[c]))
        out.add("round", self.round_idx)
        if not (self.cfg.dp_clip and self.cfg.dp_clip > 0):
            # Under DP-SGD the exact train loss is an un-noised function of
            # the private examples; releasing it would void the accounted
            # (eps, delta). Only the noised model leaves the silo.
            out.add("train_loss", float(loss))
        self.send_message(out)


def build_federation_setup(model, train_fed: FederatedArrays, test_global,
                           cfg: FedConfig, backend: str, loss_fn):
    """Shared worker-process scaffolding for the message-passing
    federations (sync FedAvg here, async in fedasync.py): model fns +
    initial net, jitted local trainer / eval, and the backend ``args``
    shim. Returns ``(size, net0, local_train, eval_fn, args)``."""
    size = cfg.client_num_per_round + 1
    fns = model_fns(model)
    sample_x = jnp.zeros((1,) + train_fed.x.shape[3:], train_fed.x.dtype)
    net0 = fns.init(jax.random.PRNGKey(cfg.seed), sample_x)
    optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
    local_train = jax.jit(
        make_local_train_fn_from_cfg(fns.apply, optimizer, cfg, loss_fn=loss_fn)
    )
    eval_fn = jax.jit(make_eval_fn(fns.apply, loss_fn=loss_fn)) if test_global else None

    class Args:
        pass

    args = Args()
    if backend == "LOOPBACK":
        args.network = LoopbackNetwork(size)
    elif backend in ("TCP", "GRPC", "TRPC"):
        # Single-host table on ephemeral ports: bind rank servers first
        # (port 0), then share the resolved table. Multi-host deployments
        # pass an explicit host_table / grpc_ipconfig.csv instead.
        args.host_table = {r: ("127.0.0.1", 0) for r in range(size)}
    return size, net0, local_train, eval_fn, args


def FedML_FedAvg_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    compress: str = "none",
    aggregate_k: int = 0,
):
    """Build server + ``client_num_per_round`` workers on the chosen backend
    and run the full federation (FedAvgAPI.py:20 analogue). Returns the
    aggregator (global model + test history).

    ``compress``: update compression for the client→server uploads —
    ``none`` | ``topk<ratio>`` (error feedback) | ``q<bits>`` (stochastic
    quantization); see fedml_tpu.core.compression.

    ``aggregate_k``: straggler-tolerant first-k rounds (0 = wait for all
    workers; see FedAVGServerManager)."""
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn)
    aggregator = FedAVGAggregator(net0, size - 1, cfg, eval_fn, test_global)
    server = FedAVGServerManager(args, aggregator, cfg, size, backend=backend,
                                 compress=compress, aggregate_k=aggregate_k)
    clients = [
        FedAVGClientManager(args, rank, size, train_fed, local_train, cfg,
                            backend=backend, compress=compress)
        for rank in range(1, size)
    ]
    run_workers([server.run] + [c.run for c in clients])
    return aggregator
