"""Cross-silo distributed FedAvg over the message-passing comm layer.

Parity with the reference's distributed pipeline
(fedml_api/distributed/fedavg/FedAvgAPI.py:20, FedAVGAggregator.py,
FedAvgServerManager.py, FedAvgClientManager.py, message_define.py:1-12):
one server process + W client processes; per round the server samples
client indices (seeded, FedAVGAggregator.py:90-99), broadcasts the global
model, each worker runs jit-compiled local SGD on its assigned client's
shard, and the server weighted-averages the returned pytrees.

This path exists for TRUE federation (separate hosts/silos over loopback or
the native TCP transport). Simulated federation should use ``FedAvgAPI``,
where clients are a sharded array axis and aggregation is a psum over ICI.

Fault-tolerant control plane (docs/ROBUSTNESS.md "Control plane"; the
reference's ``check_whether_all_receive`` blocks unconditionally — one
dead worker hangs its server forever):

- **Heartbeat-driven membership** — workers piggyback liveness on
  uploads plus a lightweight beat while training long rounds; the
  server's watchdog runs the round deadline through
  ``HeartbeatMonitor.wait_all_or_failed`` and EVICTS silent ranks: their
  in-flight round is abandoned and aggregation proceeds over the
  surviving cohort (partial-participation averaging still converges —
  Parallel Restarted SGD, arXiv:1807.06629). A returning rank is
  re-admitted through the stale-round catch-up path (or on a beat, when
  its upload/assignment was lost in transit).
- **Idempotent uploads** — a duplicated upload (ChaosTransport
  duplication, sender retry after a lost ACK) is detected by the
  per-worker round high-water mark and dropped without a reply, so the
  aggregator never double-counts and no worker ever holds two
  assignments.
- **Bounded termination** — done-handshakes are tracked per member and
  watched by the same watchdog, so a permanently dead rank can never
  hang the run; dead-at-terminal ranks are evicted and the server exits.
- **Crash-resume** — the server checkpoints its run state every
  ``cfg.checkpoint_every`` rounds (async orbax save, off the round
  critical path) and stamps a monotonic EPOCH into every message; a
  restarted server restores the latest checkpoint, bumps the epoch, and
  deterministically rejects pre-crash uploads while workers adopt the
  new epoch from its re-broadcast assignments.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Set

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.comm.loopback import LoopbackNetwork, run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import ChaosSpec, HeartbeatSender
from fedml_tpu.core.compression import make_compressor, tree_spec
from fedml_tpu.core.faults import HeartbeatMonitor
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.core.tree import tree_scale, tree_add, tree_sub
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)

# message_define.py:1-12 parity
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
# Control plane (no reference equivalent): worker liveness beats and the
# server watchdog's self-addressed deadline tick.
MSG_TYPE_C2S_HEARTBEAT = 4
MSG_TYPE_SRV_TICK = 5

MSG_ARG_KEY_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
MSG_ARG_KEY_CLIENT_INDEX = Message.MSG_ARG_KEY_CLIENT_INDEX
MSG_ARG_KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES

log = logging.getLogger(__name__)


class FedAVGAggregator:
    """Server state: buffer per-worker results, weighted-average when the
    round completes (FedAVGAggregator.py:44-88; arrival counting lives in
    the server manager's ``_arrived`` set, which also covers the first-k
    straggler-tolerant mode)."""

    def __init__(self, net, worker_num: int, cfg: FedConfig, eval_fn=None,
                 test_data=None):
        self.net = net
        self.worker_num = worker_num
        self.cfg = cfg
        self.eval_fn = eval_fn
        self.test_data = test_data
        self.model_dict: Dict[int, object] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.test_history: List[dict] = []

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)

    def aggregate(self):
        return self.aggregate_from(range(self.worker_num))

    def aggregate_from(self, indices):
        """Weighted average over a subset of worker slots — the first-k
        straggler-tolerant mode aggregates only the workers that uploaded
        fresh results this round. An EMPTY index set (every sampled
        worker evicted/excluded) keeps the previous global net, mirroring
        ``_robust_avg``'s all-excluded behavior — ``self.net = None``
        here would poison every later round."""
        indices = list(indices)
        if not indices:
            return self.net
        total = sum(self.sample_num_dict[i] for i in indices)
        avg = None
        for i in indices:
            w = self.sample_num_dict[i] / max(total, 1e-12)
            scaled = tree_scale(self.model_dict[i], w)
            avg = scaled if avg is None else tree_add(avg, scaled)
        self.net = avg
        return avg

    def client_sampling(self, round_idx: int) -> np.ndarray:
        return sample_clients(
            round_idx, self.cfg.client_num_in_total, self.cfg.client_num_per_round
        )

    def test_on_server(self, round_idx: int) -> Optional[dict]:
        """Global-test-set eval (replaces the reference's per-client loop,
        FedAVGAggregator.py:110-161, which re-evaluates every client's
        local shard each round)."""
        if self.eval_fn is None or self.test_data is None:
            return None
        m = self.eval_fn(self.net, *self.test_data)
        out = {"round": round_idx, **{k: float(v) for k, v in m.items()}}
        self.test_history.append(out)
        return out


class FedAVGServerManager(ServerManager):
    """Synchronous server. ``aggregate_k`` (0 = all workers) enables
    straggler-tolerant first-k rounds: the round aggregates as soon as
    ``k`` FRESH uploads arrive; a straggler's late upload for an older
    round is discarded and the worker is immediately reassigned to the
    current round ("catch-up"), so message flow stays strict
    request/response — every upload gets exactly one reply and no worker
    can hold two assignments. The reference has no straggler story at all
    (check_whether_all_receive blocks on everyone).

    With ``round_timeout_s > 0`` the control plane is live: a watchdog
    thread runs each round's deadline through
    ``HeartbeatMonitor.wait_all_or_failed`` and posts a self-addressed
    TICK message, so evictions execute on the receive-dispatch thread
    like every other state change (handlers stay single-threaded).
    Evicted ranks leave the membership — the first-k threshold shrinks
    with it, a returning rank re-admits via catch-up — and the terminal
    done-handshake is watched the same way, so the run always ends.
    See the module docstring for the full failure model."""

    def __init__(self, args, aggregator: FedAVGAggregator, cfg: FedConfig,
                 size: int, backend: str = "LOOPBACK", compress: str = "none",
                 aggregate_k: int = 0, *,
                 round_timeout_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 done_timeout_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 metrics=None, clock=time.monotonic):
        super().__init__(args, rank=0, size=size, backend=backend)
        if aggregate_k and not 1 <= aggregate_k <= size - 1:
            raise ValueError(
                f"aggregate_k={aggregate_k} outside [1, {size - 1}]")
        self.aggregator = aggregator
        self.cfg = cfg
        self.round_idx = 0
        self.aggregate_k = aggregate_k or (size - 1)
        self._arrived: Set[int] = set()
        self.straggler_drops = 0
        self.duplicate_drops = 0
        self.epoch_drops = 0
        self.evictions = 0
        self.readmissions = 0
        self.aborted = False
        self._members: Set[int] = set(range(1, size))
        self._done_set: Set[int] = set()
        self._last_upload_round: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self._clock = clock
        self.metrics = metrics
        self.round_timeout_s = (cfg.round_timeout_s
                                if round_timeout_s is None else round_timeout_s)
        self.done_timeout_s = (done_timeout_s if done_timeout_s is not None
                               else (self.round_timeout_s or 0.0))
        self.heartbeat = HeartbeatMonitor(
            range(1, size),
            timeout_s=(heartbeat_timeout_s if heartbeat_timeout_s is not None
                       else (self.round_timeout_s or 30.0)),
            clock=clock)
        self._decoders = {}  # codec name → compressor (built lazily)
        self._spec = tree_spec(aggregator.net)
        # Crash-resume: restore the latest checkpoint (if any) and run
        # under a BUMPED epoch — every message carries it, so pre-crash
        # uploads are deterministically rejected.
        self.epoch = 0
        self._ckpt = None
        if checkpoint_dir:
            from fedml_tpu.obs.checkpoint import (CheckpointManager,
                                                  allocate_epoch,
                                                  restore_federation)

            self._ckpt = CheckpointManager(checkpoint_dir)
            restored = restore_federation(self._ckpt, aggregator.net)
            # allocate_epoch, not restored["epoch"] + 1: the restored
            # round's checkpoint step is already durable, so the bumped
            # epoch can't be re-saved there — two crashes inside one
            # checkpoint window would otherwise reuse an epoch and let
            # the previous incarnation's uploads through the fence. The
            # EPOCH sidecar makes every start strictly monotonic (a
            # crash BEFORE the first checkpoint is fenced too).
            self.epoch = allocate_epoch(
                self._ckpt, -1 if restored is None else restored["epoch"])
            if restored is not None:
                aggregator.net = restored["net"]
                self.round_idx = restored["round_idx"]
                log.info("server restored: round %d, epoch %d",
                         self.round_idx, self.epoch)
        # The net broadcast this round — compressed uploads are deltas
        # against it, so reconstruction must use the same anchor.
        self._broadcast_net = aggregator.net
        del compress  # server decodes by each frame's self-described codec

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        # Liveness clocks start when the RUN starts, not at construction:
        # a slow __init__ (orbax import + checkpoint restore) must not
        # make the whole fleet look expired to the first watchdog pass.
        for r in self._members_snapshot():
            self.heartbeat.beat(r)
        self.send_init_msg()
        # Armed by EITHER deadline: done_timeout_s alone still bounds the
        # terminal handshake (the loop guards each branch by its own
        # timeout, so round deadlines stay off when round_timeout_s == 0).
        if ((self.round_timeout_s and self.round_timeout_s > 0)
                or (self.done_timeout_s and self.done_timeout_s > 0)):
            threading.Thread(target=self._watchdog_loop, daemon=True).start()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self._stopped = True
        if self._ckpt is not None:
            try:
                self._save_checkpoint(wait=True)
            except Exception:  # noqa: BLE001 — shutdown must not re-raise
                log.exception("final checkpoint save failed")
            self._ckpt.close()
            self._ckpt = None
        super().finish()

    def send_init_msg(self) -> None:
        if self.round_idx >= self.cfg.comm_round:
            # Restored at (or past) the terminal round: nothing to train.
            for worker in self._members_snapshot():
                self._send_done(worker)
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for worker in self._members_snapshot():
            msg = Message(MSG_TYPE_S2C_INIT_CONFIG, 0, worker)
            msg.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
            msg.add(MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[worker - 1]))
            msg.add("round", self.round_idx)
            msg.add("epoch", self.epoch)
            self._safe_send(msg, worker)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )
        self.register_message_receive_handler(
            MSG_TYPE_C2S_HEARTBEAT, self._handle_heartbeat)
        self.register_message_receive_handler(
            MSG_TYPE_SRV_TICK, self._handle_tick)

    # -- snapshots (watchdog thread reads; handlers mutate under _lock) -----
    def _members_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._members)

    def _arrived_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._arrived)

    def _done_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._done_set)

    def _k_effective(self) -> int:
        return max(1, min(self.aggregate_k, len(self._members)))

    def health(self) -> Dict[str, int]:
        """Control-plane counters, surfaced per round through the metrics
        logger and asserted on by the fault drills."""
        with self._lock:
            return {
                "members": len(self._members),
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "straggler_drops": self.straggler_drops,
                "duplicate_drops": self.duplicate_drops,
                "epoch_drops": self.epoch_drops,
                "epoch": self.epoch,
                "send_retries": getattr(self.com_manager, "retry_count", 0),
            }

    # -- fault-aware sends --------------------------------------------------
    def _safe_send(self, msg: Message, worker: int) -> bool:
        """Send; a transport-level failure (peer dead past the retry
        policy) EVICTS the worker instead of crashing the control plane."""
        try:
            self.send_message(msg)
            return True
        except (ConnectionError, OSError) as err:
            log.warning("send to worker %d failed (%s): evicting", worker, err)
            self._evict([worker])
            return False

    def _evict(self, ranks) -> None:
        # Evicted ranks STAY in the heartbeat monitor: an alive-but-slow
        # rank (e.g. still jit-compiling its first round) keeps beating
        # and is re-admitted by _handle_heartbeat; only ranks whose beats
        # also stop are truly gone.
        with self._lock:
            for w in ranks:
                if w in self._members:
                    self._members.discard(w)
                    self.evictions += 1

    def _send_done(self, worker: int) -> None:
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
        out.add("done", True)
        out.add("epoch", self.epoch)
        if self._safe_send(out, worker):
            with self._lock:
                self._done_set.add(worker)
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        with self._lock:
            done = self._done_set >= self._members
        if done and not self._stopped:
            self.finish()

    def _send_assignment(self, worker: int, client_indexes=None, *,
                         resend: bool = False) -> None:
        if client_indexes is None:
            client_indexes = self.aggregator.client_sampling(self.round_idx)
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self._broadcast_net)
        out.add(MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[worker - 1]))
        out.add("round", self.round_idx)
        out.add("done", False)
        out.add("epoch", self.epoch)
        if resend:
            # Re-admission: the worker's upload (or our assignment) was
            # lost — a client that already trained this round should
            # RESEND its cached upload. Only flagged assignments trigger
            # that, so a plain transport duplicate of a normal assignment
            # is dropped instead of costing a model-sized resend.
            out.add("resend", True)
        self._safe_send(out, worker)

    # -- checkpointing ------------------------------------------------------
    def _save_checkpoint(self, wait: bool) -> None:
        from fedml_tpu.obs.checkpoint import save_federation

        try:
            save_federation(self._ckpt, self.aggregator.net, self.round_idx,
                            self.epoch, wait=wait)
        except Exception:  # noqa: BLE001 — e.g. an async save still in flight
            self._ckpt.wait()
            save_federation(self._ckpt, self.aggregator.net, self.round_idx,
                            self.epoch, wait=wait)

    # -- watchdog: round deadline + bounded done-handshake ------------------
    def _watchdog_loop(self) -> None:
        poll = max(0.005, min(
            0.05, (self.round_timeout_s or self.done_timeout_s) / 10))
        while not self._stopped:
            members = self._members_snapshot()
            if not members:
                # Either everyone is dead (the tick handler aborts) or an
                # eviction storm is healing through beat re-admissions —
                # keep watching either way.
                self._post_tick(self.round_idx, [])
                time.sleep(max(poll, 0.1))
                continue
            r = self.round_idx
            if r >= self.cfg.comm_round:
                if self.done_timeout_s and self.done_timeout_s > 0:
                    failed = self.heartbeat.wait_all_or_failed(
                        members, have=self._done_snapshot, poll_s=poll,
                        deadline_s=self.done_timeout_s)
                    if not self._stopped and failed:
                        self._post_tick(r, failed)
            elif self.round_timeout_s and self.round_timeout_s > 0:
                failed = self.heartbeat.wait_all_or_failed(
                    members,
                    have=lambda m=members, r=r: (
                        m if (self._stopped or self.round_idx != r)
                        else self._arrived_snapshot()),
                    poll_s=poll, deadline_s=self.round_timeout_s)
                if not self._stopped and failed and self.round_idx == r:
                    self._post_tick(r, failed)
            time.sleep(poll)

    def _post_tick(self, round_idx: int, failed) -> None:
        """Self-addressed deadline tick: eviction executes on the receive
        thread, serialized with every other handler."""
        msg = Message(MSG_TYPE_SRV_TICK, 0, 0)
        msg.add("round", int(round_idx))
        msg.add("failed", [int(w) for w in failed])
        msg.add("epoch", self.epoch)
        try:
            self.send_message(msg)
        except (ConnectionError, OSError):
            pass  # next watchdog pass re-ticks

    def _handle_tick(self, msg: Message) -> None:
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return  # tick from a pre-crash instance left in the inbox
        failed = set(msg.get("failed") or [])
        terminal = self.round_idx >= self.cfg.comm_round
        with self._lock:
            if terminal:
                evict = [w for w in failed
                         if w in self._members and w not in self._done_set]
            else:
                if int(msg.get("round", -1)) != self.round_idx:
                    return  # stale: the round advanced while it was queued
                evict = [w for w in failed
                         if w in self._members and w not in self._arrived]
        if evict:
            log.warning("round %d deadline: evicting silent ranks %s",
                        self.round_idx, evict)
            self._evict(evict)
        if terminal:
            self._maybe_finish()
            return
        with self._lock:
            empty = not self._members
            ready = bool(self._arrived) and (
                len(self._arrived) >= self._k_effective())
        if empty:
            if self.heartbeat.alive():
                # Everyone missed the deadline but someone still beats
                # (e.g. the whole fleet is jit-compiling its first
                # round): hold the round open — the next beats re-admit
                # them and their uploads complete it.
                return
            # Every worker is gone; nothing can ever arrive again.
            log.error("all workers evicted at round %d: abandoning the run",
                      self.round_idx)
            self.aborted = True
            self.finish()
            return
        if ready:
            self._complete_round()

    def _handle_heartbeat(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        self.heartbeat.beat(sender)
        if self.round_idx >= self.cfg.comm_round:
            # Any beat at the terminal round gets a done (idempotent: the
            # worker finishes on first receipt). Members and done-set
            # ranks may have lost theirs in transit; an EVICTED-but-alive
            # rank (slow past the done deadline, then resumed beating)
            # has never been sent one at all — with idle_timeout_s=0 it
            # would otherwise block on its receive loop forever.
            self._send_done(sender)
            return
        with self._lock:
            member = sender in self._members
        if not member:
            # Evicted-but-alive: its upload or our assignment was lost,
            # or it was slow past the deadline. Re-admit with the current
            # round's work, resend-flagged: a client that never saw the
            # assignment trains it, one that already trained this round
            # resends its cached upload (idempotent at our high-water
            # mark) instead of dropping the copy.
            with self._lock:
                self._members.add(sender)
                self.readmissions += 1
            log.info("re-admitting rank %d on heartbeat", sender)
            self._send_assignment(sender, resend=True)

    # -- the round ----------------------------------------------------------
    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            # Pre-crash upload: the restarted server already re-broadcast
            # assignments under the new epoch, so this worker has live
            # work — reject deterministically, never reply.
            self.epoch_drops += 1
            return
        self.heartbeat.beat(sender)
        tag = msg.get("round")
        t = int(tag) if tag is not None else self.round_idx
        with self._lock:
            if t <= self._last_upload_round.get(sender, -1):
                # Duplicate delivery (ChaosTransport duplication, sender
                # retry after a lost ACK): the first copy was answered —
                # replying again would hand the worker two assignments.
                self.duplicate_drops += 1
                return
            self._last_upload_round[sender] = t
            if sender not in self._members:
                self._members.add(sender)
                self.readmissions += 1
        if self.round_idx >= self.cfg.comm_round:
            # Terminal: a straggler's in-flight upload after the final
            # aggregation — release it.
            self._send_done(sender)
            return
        if t != self.round_idx:
            # Stale upload from an older round: discard the model, catch
            # the worker up on the current round.
            self.straggler_drops += 1
            self._send_assignment(sender)
            return
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        codec = msg.get("compression")
        if codec:
            # Dispatch on the frame's self-described codec, not a server
            # flag: per-rank launches may configure compression on the
            # clients only, and ranks could even mix schemes.
            if codec not in self._decoders:
                self._decoders[codec] = make_compressor(codec)
            delta = self._decoders[codec].decode(payload, self._spec)
            payload = tree_add(self._broadcast_net, delta)
        self.aggregator.add_local_trained_result(
            sender - 1, payload, msg.get(MSG_ARG_KEY_NUM_SAMPLES)
        )
        with self._lock:
            self._arrived.add(sender)
            ready = len(self._arrived) >= self._k_effective()
        if ready:
            self._complete_round()

    def _complete_round(self) -> None:
        with self._lock:
            arrived = sorted(self._arrived)
            self._arrived = set()
        global_net = self.aggregator.aggregate_from([w - 1 for w in arrived])
        self._broadcast_net = global_net
        if (
            self.round_idx % self.cfg.frequency_of_the_test == 0
            or self.round_idx == self.cfg.comm_round - 1
        ):
            self.aggregator.test_on_server(self.round_idx)
        completed = self.round_idx
        self.round_idx += 1
        self._log_round_health(completed, arrived)
        if self._ckpt is not None and self.cfg.checkpoint_every and (
            self.round_idx % self.cfg.checkpoint_every == 0
        ):
            self._save_checkpoint(wait=False)
        if self.round_idx >= self.cfg.comm_round:
            for worker in arrived:
                self._send_done(worker)
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for worker in arrived:
            self._send_assignment(worker, client_indexes)

    def _log_round_health(self, round_idx: int, arrived) -> None:
        if self.metrics is None:
            return
        self.metrics.log({"arrived": len(arrived), **self.health()},
                         step=round_idx, prefix="ctrl")


class FedAVGClientManager(ClientManager):
    """Worker process: jitted local training on the assigned client's shard
    (FedAvgClientManager.py:34-79). Control-plane duties: adopt the
    server's epoch (resetting the round dedupe on a restart), drop
    duplicated assignments by round tag, beat every
    ``beat_interval_s`` while training keeps the upload path silent, and
    self-terminate after ``idle_timeout_s`` without server contact (a
    crashed-and-never-restarted server must not strand its workers)."""

    def __init__(self, args, rank: int, size: int, train_fed: FederatedArrays,
                 local_train, cfg: FedConfig, backend: str = "LOOPBACK",
                 compress: str = "none", *,
                 beat_interval_s: Optional[float] = None,
                 idle_timeout_s: float = 0.0):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.train_fed = train_fed
        self.local_train = local_train
        self.cfg = cfg
        self.round_idx = 0
        self.epoch = 0
        self.duplicate_drops = 0
        self.upload_resends = 0
        self._last_handled = -1
        # The last upload message, kept until the NEXT round's assignment
        # arrives: a RESEND-flagged re-assignment of the round we already
        # trained means our upload was lost in transit (the server flags
        # re-admission assignments) — resend it instead of dropping the
        # assignment, or a round whose every upload was lost would
        # evict/re-admit/livelock forever. One message of memory; the
        # server's per-worker round high-water mark makes resends
        # idempotent.
        self._last_upload: Optional[Message] = None
        self._compressor = make_compressor(compress)
        self._beats = HeartbeatSender(
            self._send_beat,
            interval_s=(cfg.heartbeat_interval_s if beat_interval_s is None
                        else beat_interval_s),
            idle_timeout_s=idle_timeout_s,
            on_idle=self._idle_quit)
        # Latest top-k error-feedback residual: (round, client, residual).
        # EF theory requires the residual to stay with its own data
        # stream, so it is applied only when this rank trains the SAME
        # client in the IMMEDIATELY next round — a stale carry would
        # otherwise spike against a much-evolved model, and one client's
        # carry must never leak into another's update. A rank trains one
        # client per round, so a single triple suffices (a per-client dict
        # would pin one dead model-sized residual per migrated-away client
        # forever). Under full participation assignments are stable and EF
        # is exact; under subsampling the carry drops at migrations.
        self._ef_state: Optional[tuple] = None
        # Dropped-carry visibility (like the server's straggler_drops):
        # each increment is one round whose compression error correction
        # was discarded — top-k is running as plain biased compression in
        # exactly the regimes (first-k rounds, client re-assignment) that
        # cause the drops.
        self.ef_carry_drops = 0

    def run(self) -> None:
        self._beats.start()
        super().run()

    def finish(self) -> None:
        self._beats.stop()
        super().finish()

    def _send_beat(self) -> None:
        msg = Message(MSG_TYPE_C2S_HEARTBEAT, self.rank, 0)
        msg.add("epoch", self.epoch)
        self.send_message(msg)

    def _idle_quit(self) -> None:
        log.warning("rank %d: no server contact for %.1fs — exiting",
                    self.rank, self._beats.idle_timeout_s)
        self.finish()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server,
        )

    def handle_message_init(self, msg: Message) -> None:
        self._handle_assignment(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._handle_assignment(msg)

    def _handle_assignment(self, msg: Message) -> None:
        self._beats.touch()
        ep = msg.get("epoch")
        if ep is not None:
            ep = int(ep)
            if ep < self.epoch:
                return  # straggler message from a dead server epoch
            if ep > self.epoch:
                # Server restarted: adopt its epoch and reset the round
                # dedupe — the restored run legitimately replays rounds.
                # The cached upload died with the old epoch.
                self.epoch = ep
                self._last_handled = -1
                self._last_upload = None
        if msg.get("done"):
            self.finish()
            return
        # The server's round tag, not a local counter: under first-k
        # aggregation a straggler can be reassigned past skipped rounds.
        tag = msg.get("round")
        if tag is not None:
            t = int(tag)
            if t <= self._last_handled:
                if (t == self._last_handled and msg.get("resend")
                        and self._last_upload is not None):
                    # Resend-flagged re-assignment of the round we
                    # already trained: the server re-admitted us, so our
                    # upload was lost in transit. Resend it — idempotent
                    # at the server's round high-water mark. Unflagged
                    # copies are plain transport duplicates and drop
                    # below, costing nothing on the wire.
                    self.upload_resends += 1
                    self.send_message(self._last_upload)
                    return
                # Transport duplicate of a handled assignment.
                self.duplicate_drops += 1
                return
            self._last_handled = t
            self.round_idx = t
        else:
            self.round_idx += 1
        self._train(msg.get(MSG_ARG_KEY_MODEL_PARAMS), msg.get(MSG_ARG_KEY_CLIENT_INDEX))

    def _train(self, global_net, client_index: int) -> None:
        c = int(client_index)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.round_idx)
        rng = jax.random.fold_in(rng, c)
        net, loss = self.local_train(
            global_net,
            self.train_fed.x[c],
            self.train_fed.y[c],
            self.train_fed.mask[c],
            rng,
        )
        out = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        if self._compressor.name != "none":
            delta = tree_sub(net, global_net)
            rng_c = jax.random.fold_in(rng, 0xC0)
            prev = self._ef_state
            carry = (prev[2] if prev and prev[0] == self.round_idx - 1
                     and prev[1] == c else None)
            if prev is not None and carry is None and prev[2] is not None:
                self.ef_carry_drops += 1
            payload, residual = self._compressor.encode(delta, carry, rng_c)
            self._ef_state = (self.round_idx, c, residual)
            out.add(MSG_ARG_KEY_MODEL_PARAMS, payload)
            out.add("compression", self._compressor.name)
        else:
            out.add(MSG_ARG_KEY_MODEL_PARAMS, jax.device_get(net))
        out.add(MSG_ARG_KEY_NUM_SAMPLES, int(self.train_fed.counts[c]))
        out.add("round", self.round_idx)
        out.add("epoch", self.epoch)
        if not (self.cfg.dp_clip and self.cfg.dp_clip > 0):
            # Under DP-SGD the exact train loss is an un-noised function of
            # the private examples; releasing it would void the accounted
            # (eps, delta). Only the noised model leaves the silo.
            out.add("train_loss", float(loss))
        self._last_upload = out
        self.send_message(out)


def build_federation_setup(model, train_fed: FederatedArrays, test_global,
                           cfg: FedConfig, backend: str, loss_fn,
                           chaos: Optional[ChaosSpec] = None):
    """Shared worker-process scaffolding for the message-passing
    federations (sync FedAvg here, async in fedasync.py): model fns +
    initial net, jitted local trainer / eval, and the backend ``args``
    shim (``chaos`` installs a fleet-wide ChaosTransport wrapper).
    Returns ``(size, net0, local_train, eval_fn, args)``."""
    size = cfg.client_num_per_round + 1
    if getattr(cfg, "compute_layout", "none") not in ("none", ""):
        # The message-passing tiers build their local trainer here,
        # outside FedAvgAPI._build_local_train where the lane-fill
        # layout is wired — refuse loudly rather than leave the flag
        # silently inert (the PR 4 convention).
        raise NotImplementedError(
            f"cfg.compute_layout={cfg.compute_layout!r} is a simulator-"
            "tier capability (FedAvgAPI family); the distributed "
            "message-passing tiers do not wire it yet")
    fns = model_fns(model)
    sample_x = jnp.zeros((1,) + train_fed.x.shape[3:], train_fed.x.dtype)
    net0 = fns.init(jax.random.PRNGKey(cfg.seed), sample_x)
    optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
    local_train = jax.jit(
        make_local_train_fn_from_cfg(fns.apply, optimizer, cfg, loss_fn=loss_fn)
    )
    eval_fn = jax.jit(make_eval_fn(fns.apply, loss_fn=loss_fn)) if test_global else None

    class Args:
        pass

    args = Args()
    args.chaos = chaos
    if backend == "LOOPBACK":
        args.network = LoopbackNetwork(size)
    elif backend == "SIM":
        # Virtual-clock fleet simulation: the FleetSimulator installs
        # args.network (a sim.transport.SimNetwork) and args.chaos_after
        # (the event-queue scheduler for ChaosTransport's timers) itself
        # before constructing the managers.
        pass
    elif backend in ("TCP", "GRPC", "TRPC"):
        # Single-host table on ephemeral ports: bind rank servers first
        # (port 0), then share the resolved table. Multi-host deployments
        # pass an explicit host_table / grpc_ipconfig.csv instead.
        args.host_table = {r: ("127.0.0.1", 0) for r in range(size)}
    return size, net0, local_train, eval_fn, args


def FedML_FedAvg_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    compress: str = "none",
    aggregate_k: int = 0,
    *,
    chaos: Optional[ChaosSpec] = None,
    checkpoint_dir: Optional[str] = None,
    metrics=None,
    idle_timeout_s: float = 0.0,
):
    """Build server + ``client_num_per_round`` workers on the chosen backend
    and run the full federation (FedAvgAPI.py:20 analogue). Returns the
    aggregator (global model + test history).

    ``compress``: update compression for the client→server uploads —
    ``none`` | ``topk<ratio>`` (error feedback) | ``q<bits>`` (stochastic
    quantization); see fedml_tpu.core.compression.

    ``aggregate_k``: straggler-tolerant first-k rounds (0 = wait for all
    workers; see FedAVGServerManager).

    Control plane (docs/ROBUSTNESS.md): ``cfg.round_timeout_s`` arms the
    eviction watchdog, ``cfg.heartbeat_interval_s`` the worker beats,
    ``cfg.checkpoint_every`` + ``checkpoint_dir`` crash-resume, ``chaos``
    a fleet-wide fault-injecting transport wrapper, ``metrics`` a
    MetricsLogger for per-round health counters, ``idle_timeout_s`` the
    workers' no-server-contact self-termination bound."""
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn, chaos=chaos)
    aggregator = FedAVGAggregator(net0, size - 1, cfg, eval_fn, test_global)
    server = FedAVGServerManager(args, aggregator, cfg, size, backend=backend,
                                 compress=compress, aggregate_k=aggregate_k,
                                 checkpoint_dir=checkpoint_dir,
                                 metrics=metrics)
    clients = [
        FedAVGClientManager(args, rank, size, train_fed, local_train, cfg,
                            backend=backend, compress=compress,
                            idle_timeout_s=idle_timeout_s)
        for rank in range(1, size)
    ]
    run_workers([server.run] + [c.run for c in clients])
    return aggregator
