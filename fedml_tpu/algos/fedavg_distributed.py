"""Cross-silo distributed FedAvg over the message-passing comm layer.

Parity with the reference's distributed pipeline
(fedml_api/distributed/fedavg/FedAvgAPI.py:20, FedAVGAggregator.py,
FedAvgServerManager.py, FedAvgClientManager.py, message_define.py:1-12):
one server process + W client processes; per round the server samples
client indices (seeded, FedAVGAggregator.py:90-99), broadcasts the global
model, each worker runs jit-compiled local SGD on its assigned client's
shard, and the server weighted-averages the returned pytrees.

This path exists for TRUE federation (separate hosts/silos over loopback or
the native TCP transport). Simulated federation should use ``FedAvgAPI``,
where clients are a sharded array axis and aggregation is a psum over ICI.

Fault-tolerant control plane (docs/ROBUSTNESS.md "Control plane"; the
reference's ``check_whether_all_receive`` blocks unconditionally — one
dead worker hangs its server forever):

- **Heartbeat-driven membership** — workers piggyback liveness on
  uploads plus a lightweight beat while training long rounds; the
  server's watchdog runs the round deadline through
  ``HeartbeatMonitor.wait_all_or_failed`` and EVICTS silent ranks: their
  in-flight round is abandoned and aggregation proceeds over the
  surviving cohort (partial-participation averaging still converges —
  Parallel Restarted SGD, arXiv:1807.06629). A returning rank is
  re-admitted through the stale-round catch-up path (or on a beat, when
  its upload/assignment was lost in transit).
- **Idempotent uploads** — a duplicated upload (ChaosTransport
  duplication, sender retry after a lost ACK) is detected by the
  per-worker round high-water mark and dropped without a reply, so the
  aggregator never double-counts and no worker ever holds two
  assignments.
- **Bounded termination** — done-handshakes are tracked per member and
  watched by the same watchdog, so a permanently dead rank can never
  hang the run; dead-at-terminal ranks are evicted and the server exits.
- **Crash-resume** — the server checkpoints its run state every
  ``cfg.checkpoint_every`` rounds (async orbax save, off the round
  critical path) and stamps a monotonic EPOCH into every message; a
  restarted server restores the latest checkpoint, bumps the epoch, and
  deterministically rejects pre-crash uploads while workers adopt the
  new epoch from its re-broadcast assignments.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Set

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.comm import codec as wire_codec
from fedml_tpu.comm import secagg as secagg_mod
from fedml_tpu.comm.ingest import (FixedContribution, PartialAccumulator,
                                   finalize_partial_mean, quantize_weight)
from fedml_tpu.comm.loopback import LoopbackNetwork, run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import ChaosSpec, HeartbeatSender
from fedml_tpu.core.compression import make_compressor, tree_spec
from fedml_tpu.core.faults import HeartbeatMonitor
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.core.tree import tree_add, tree_sub
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.obs import trace as obs_trace
from fedml_tpu.obs.registry import MetricsRegistry, payload_nbytes
from fedml_tpu.trainer.local import (
    NetState,
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)

# message_define.py:1-12 parity
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
# Control plane (no reference equivalent): worker liveness beats and the
# server watchdog's self-addressed deadline tick.
MSG_TYPE_C2S_HEARTBEAT = 4
MSG_TYPE_SRV_TICK = 5
# Secure-aggregation control plane (comm/secagg.py): pk handshake,
# roster/share distribution, and the dropout seed-reveal round. Kept
# clear of the shardplane block (20-25).
MSG_TYPE_C2S_SECAGG_PK = 30
MSG_TYPE_S2C_SECAGG_ROSTER = 31
MSG_TYPE_C2S_SECAGG_SHARES = 32
MSG_TYPE_S2C_SEED_REVEAL = 33
MSG_TYPE_C2S_SEED_SHARE = 34

MSG_ARG_KEY_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
MSG_ARG_KEY_CLIENT_INDEX = Message.MSG_ARG_KEY_CLIENT_INDEX
MSG_ARG_KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES
# Sharded aggregation plane (comm/shardplane.py): the assignment stamps
# the rank the worker must UPLOAD to. Absent (the single-server path)
# means rank 0 — the coordinator itself ingests.
MSG_ARG_KEY_SHARD_RANK = "shard_rank"

log = logging.getLogger(__name__)


class FedAVGAggregator:
    """Server state with STREAMING ingest: every accepted upload is folded
    into an O(model) weighted accumulator ON ARRIVAL (the generalization
    of fedbuff's accumulate-on-arrival fast path), so mean aggregation
    holds one model-sized buffer regardless of the fleet size — the
    server ingest path is the engineering bottleneck at scale
    (arXiv:2307.06561). The reference instead buffers every worker's full
    model and reduces at the round barrier (FedAVGAggregator.py:44-88),
    O(clients x model) server memory.

    A non-mean ``aggregator`` spec (:func:`core.robust_agg.make_aggregator`
    — coord_median, trimmed mean, Krum, geometric median) needs the
    cohort side by side, so that path alone retains the stack-then-reduce
    buffer (O(cohort x model)); arrival counting lives in the server
    manager's ``_arrived`` set, which also covers the first-k
    straggler-tolerant mode. ``live_model_buffers`` is the O(model) pin's
    observable, audited by tests/test_wire_codec.py."""

    def __init__(self, net, worker_num: int, cfg: FedConfig, eval_fn=None,
                 test_data=None, aggregator: str = "mean"):
        from fedml_tpu.core.robust_agg import make_aggregator

        self.net = net
        self.worker_num = worker_num
        self.cfg = cfg
        self.eval_fn = eval_fn
        self.test_data = test_data
        self.aggregator = make_aggregator(aggregator)
        self.model_dict: Dict[int, object] = {}  # non-mean stack path ONLY
        self.sample_num_dict: Dict[int, float] = {}
        self.test_history: List[dict] = []
        # Stamped by FedML_FedAvg_distributed after the run: the server's
        # final health() snapshot (control-plane counters + byte ledger)
        # and its ingest profile (dispatch-thread occupancy, decode/fold
        # latency percentiles — the measured baseline for ROADMAP item
        # 1's parallel-ingest attack).
        self.final_health: Dict[str, int] = {}
        self.ingest_profile: Dict[str, object] = {}
        # Mean fast path: running sample-weighted sum + weight, O(model).
        self._acc = None
        self._wsum = 0.0
        self._acc_indices: Set[int] = set()
        self._accum = jax.jit(
            lambda acc, p, w: jax.tree.map(
                lambda a_, p_: a_ + w * jnp.asarray(p_, jnp.float32),
                acc, p))
        self._lift = jax.jit(
            lambda p, w: jax.tree.map(
                lambda p_: w * jnp.asarray(p_, jnp.float32), p))
        self._finalize = jax.jit(
            lambda ref, acc, inv: jax.tree.map(
                lambda r_, a_: (inv * a_).astype(jnp.asarray(r_).dtype),
                ref, acc))

    @property
    def live_model_buffers(self) -> int:
        """Model-sized trees the ingest path holds RIGHT NOW: the running
        accumulator counts one; only the non-mean stack path ever counts
        more. The streaming-memory tests pin this at <= 1 on the mean
        path with any number of arrivals."""
        return (1 if self._acc is not None else 0) + len(self.model_dict)

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        w = float(sample_num)
        if self.aggregator.is_mean:
            if index in self._acc_indices:
                # Idempotent ingest: the manager's round high-water mark
                # already dedupes wire duplicates; this guards direct
                # callers — a streamed accumulator cannot "overwrite" the
                # way the old per-slot dict silently did.
                return
            self._acc_indices.add(index)
            self.sample_num_dict[index] = w
            self._acc = (self._lift(model_params, jnp.float32(w))
                         if self._acc is None
                         else self._accum(self._acc, model_params,
                                          jnp.float32(w)))
            self._wsum += w
        else:
            self.model_dict[index] = model_params
            self.sample_num_dict[index] = w

    def aggregate(self):
        return self.aggregate_from(range(self.worker_num))

    def aggregate_from(self, indices):
        """Aggregate over a subset of worker slots — the first-k
        straggler-tolerant mode aggregates only the workers that uploaded
        fresh results this round. An EMPTY index set (every sampled
        worker evicted/excluded) keeps the previous global net, mirroring
        ``_robust_avg``'s all-excluded behavior — ``self.net = None``
        here would poison every later round.

        On the streaming mean path the set must equal the accumulated
        arrivals (the protocol guarantees it: uploads are accepted and
        accumulated exactly for the ``_arrived`` set) — an O(model)
        accumulator cannot subset post-hoc, so a mismatch is a protocol
        bug and raises instead of silently mis-weighting."""
        indices = list(indices)
        if not indices:
            return self.net
        if self.aggregator.is_mean:
            if set(indices) != self._acc_indices:
                raise ValueError(
                    f"streaming ingest accumulated workers "
                    f"{sorted(self._acc_indices)} but was asked to "
                    f"aggregate {sorted(indices)}: the O(model) mean path "
                    "cannot subset after arrival")
            self.net = self._finalize(self.net, self._acc,
                                      jnp.float32(1.0 / max(self._wsum,
                                                            1e-12)))
            self._acc = None
            self._wsum = 0.0
            self._acc_indices = set()
            return self.net
        # Robust path: the cohort side by side (weights gate participation
        # in the order statistics, value-weight the mean-like reducers).
        weights = jnp.asarray([self.sample_num_dict[i] for i in indices],
                              jnp.float32)
        stacked = jax.tree.map(
            lambda *ls: jnp.stack([jnp.asarray(l, jnp.float32) for l in ls]),
            *[self.model_dict[i] for i in indices])
        agg = self.aggregator(stacked, weights)
        self.net = jax.tree.map(
            lambda r_, a_: jnp.asarray(a_).astype(jnp.asarray(r_).dtype),
            self.net, agg)
        for i in indices:
            self.model_dict.pop(i, None)
        return self.net

    def aggregate_pooled(self, indices, pool, envelope_check=None):
        """The pooled-mean twin of :meth:`aggregate_from`: the ingest
        pool (comm/ingest.py) already holds ``Σ w·x`` in exact fixed
        point across its per-worker partials — merge, divide once, cast
        to the reference dtypes. The pool's task count must equal the
        arrived set (same protocol pin as the streaming subset check: a
        mismatch is a bug, not something to silently mis-weight). An
        empty index set keeps the previous net. ``envelope_check``
        (secagg rounds) runs on the merged total BETWEEN cancellation
        and the division — the only moment mask-domain saturation is
        observable (comm/ingest.py envelope_overflow)."""
        indices = list(indices)
        total = pool.merge_partials()
        if envelope_check is not None:
            envelope_check(total)
        mean, count = finalize_partial_mean(total, self.net)
        if count != len(indices):
            raise ValueError(
                f"ingest pool folded {count} uploads but the round "
                f"arrived {len(indices)}: the pooled mean cannot subset "
                "after arrival")
        if not indices or mean is None:
            return self.net
        self.net = mean
        return self.net

    def client_sampling(self, round_idx: int) -> np.ndarray:
        return sample_clients(
            round_idx, self.cfg.client_num_in_total, self.cfg.client_num_per_round
        )

    def test_on_server(self, round_idx: int) -> Optional[dict]:
        """Global-test-set eval (replaces the reference's per-client loop,
        FedAVGAggregator.py:110-161, which re-evaluates every client's
        local shard each round)."""
        if self.eval_fn is None or self.test_data is None:
            return None
        m = self.eval_fn(self.net, *self.test_data)
        out = {"round": round_idx, **{k: float(v) for k, v in m.items()}}
        self.test_history.append(out)
        return out


class FedAVGServerManager(ServerManager):
    """Synchronous server. ``aggregate_k`` (0 = all workers) enables
    straggler-tolerant first-k rounds: the round aggregates as soon as
    ``k`` FRESH uploads arrive; a straggler's late upload for an older
    round is discarded and the worker is immediately reassigned to the
    current round ("catch-up"), so message flow stays strict
    request/response — every upload gets exactly one reply and no worker
    can hold two assignments. The reference has no straggler story at all
    (check_whether_all_receive blocks on everyone).

    With ``round_timeout_s > 0`` the control plane is live: a watchdog
    thread runs each round's deadline through
    ``HeartbeatMonitor.wait_all_or_failed`` and posts a self-addressed
    TICK message, so evictions execute on the receive-dispatch thread
    like every other state change (handlers stay single-threaded).
    Evicted ranks leave the membership — the first-k threshold shrinks
    with it, a returning rank re-admits via catch-up — and the terminal
    done-handshake is watched the same way, so the run always ends.
    See the module docstring for the full failure model."""

    # The sharded coordinator (comm/shardplane.py) folds on its shard
    # ranks instead of a local ingest pool — it overrides this so the
    # secagg constructor check accepts a pool-less coordinator.
    _secagg_sharded = False

    def __init__(self, args, aggregator: FedAVGAggregator, cfg: FedConfig,
                 size: int, backend: str = "LOOPBACK", compress: str = "none",
                 aggregate_k: int = 0, *,
                 round_timeout_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 done_timeout_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 metrics=None, clock=time.monotonic,
                 flight_dir: Optional[str] = None):
        super().__init__(args, rank=0, size=size, backend=backend)
        if aggregate_k and not 1 <= aggregate_k <= size - 1:
            raise ValueError(
                f"aggregate_k={aggregate_k} outside [1, {size - 1}]")
        self.aggregator = aggregator
        self.cfg = cfg
        self.round_idx = 0
        self.aggregate_k = aggregate_k or (size - 1)
        self._arrived: Set[int] = set()
        self.straggler_drops = 0
        self.duplicate_drops = 0
        self.epoch_drops = 0
        self.codec_refusals = 0
        self.evictions = 0
        self.readmissions = 0
        self.aborted = False
        self._members: Set[int] = set(range(1, size))
        self._done_set: Set[int] = set()
        self._last_upload_round: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self._clock = clock
        self.metrics = metrics
        self.round_timeout_s = (cfg.round_timeout_s
                                if round_timeout_s is None else round_timeout_s)
        self.done_timeout_s = (done_timeout_s if done_timeout_s is not None
                               else (self.round_timeout_s or 0.0))
        self.heartbeat = HeartbeatMonitor(
            range(1, size),
            timeout_s=(heartbeat_timeout_s if heartbeat_timeout_s is not None
                       else (self.round_timeout_s or 30.0)),
            clock=clock)
        self._decoders = {}  # legacy compressor name → compressor
        self._wire_decoders = wire_codec.CodecCache()  # spec → WireCodec
        self._spec = tree_spec(aggregator.net)
        # Ingest observability (docs/OBSERVABILITY.md): per-upload
        # decode/fold latency + payload-size histograms and the
        # dispatch-thread busy clock feed ``ingest_profile()`` and the
        # per-round ctrl/ metrics stream; the flight recorder keeps the
        # last control-plane events and dumps them to ``flight_dir`` on
        # eviction / abort / codec refusal. All of it is registry math on
        # the dispatch thread — spans additionally land in the installed
        # tracer (obs.trace) when one is active, no-op otherwise; the
        # dispatch-occupancy clock lives in comm.managers.ServerManager.
        self.registry = MetricsRegistry()
        self._h_decode = self.registry.histogram("decode_ms")
        self._h_fold = self.registry.histogram("fold_ms")
        self._h_bytes = self.registry.histogram("bytes_per_upload", lo=1.0)
        self._g_queue = self.registry.gauge("ingest_queue_depth")
        # Parallel ingest pool (comm/ingest.py, cfg.ingest_workers > 0):
        # decode + delta reconstruction + the mean fold move to worker
        # threads with per-worker associative-exact partial accumulators;
        # the round flush barriers on the pool and merges. Mean only —
        # the robust aggregators reduce the cohort side by side
        # (stack-then-reduce), which is inherently serialized.
        workers = int(getattr(cfg, "ingest_workers", 0) or 0)
        if workers > 0 and not aggregator.aggregator.is_mean:
            raise ValueError(
                f"ingest_workers={workers} needs the mean aggregator: "
                f"{aggregator.aggregator.name!r} retains the serialized "
                "stack-then-reduce cohort buffer — run it with "
                "ingest_workers=0 (comm/ingest.py)")
        if workers > 0:
            from fedml_tpu.comm.ingest import IngestPool

            self._pool = IngestPool(workers, registry=self.registry)
            self._g_pool_queue = self.registry.gauge(
                "ingest_pool_queue_depth")
        else:
            self._pool = None
        # Secure aggregation (comm/secagg.py, cfg.secagg): masked uploads
        # ride the SAME fixed-point fold the pool (or the shard plane)
        # already runs — integer adds are the only ingest arithmetic
        # whose associativity cancels pairwise masks exactly.
        self.secagg: Optional[secagg_mod.SecAggServer] = None
        self.seed_reveals = 0
        self._secagg_waitroom: Set[int] = set()
        self._secagg_reveal_asked: Set[int] = set()
        self._secagg_reveal_t0: Dict[int, float] = {}
        if getattr(cfg, "secagg", False):
            if not aggregator.aggregator.is_mean:
                raise ValueError(
                    "cfg.secagg masks the pooled MEAN's fixed-point fold; "
                    f"aggregator {aggregator.aggregator.name!r} reduces "
                    "the cohort side by side and would see per-client "
                    "masked frames that never cancel")
            if aggregate_k:
                raise ValueError(
                    "cfg.secagg is all-or-reveal: aggregate_k first-k "
                    "rounds would orphan every straggler's masks and "
                    "force a seed reveal per round — run aggregate_k=0")
            if self._pool is None and not self._secagg_sharded:
                raise ValueError(
                    "cfg.secagg needs the fixed-point ingest path: set "
                    "ingest_workers > 0 (comm/ingest.py) or agg_shards "
                    "> 0 (comm/shardplane.py)")
            self._secagg_init()
        self.flight = obs_trace.FlightRecorder(
            clock=clock,
            path=(os.path.join(flight_dir, "flight_recorder.jsonl")
                  if flight_dir else None))
        # Crash-resume: restore the latest checkpoint (if any) and run
        # under a BUMPED epoch — every message carries it, so pre-crash
        # uploads are deterministically rejected.
        self.epoch = 0
        self._ckpt = None
        if checkpoint_dir:
            from fedml_tpu.obs.checkpoint import (CheckpointManager,
                                                  allocate_epoch,
                                                  restore_federation)

            self._ckpt = CheckpointManager(checkpoint_dir)
            restored = restore_federation(self._ckpt, aggregator.net)
            # allocate_epoch, not restored["epoch"] + 1: the restored
            # round's checkpoint step is already durable, so the bumped
            # epoch can't be re-saved there — two crashes inside one
            # checkpoint window would otherwise reuse an epoch and let
            # the previous incarnation's uploads through the fence. The
            # EPOCH sidecar makes every start strictly monotonic (a
            # crash BEFORE the first checkpoint is fenced too).
            self.epoch = allocate_epoch(
                self._ckpt, -1 if restored is None else restored["epoch"])
            if restored is not None:
                aggregator.net = restored["net"]
                self.round_idx = restored["round_idx"]
                log.info("server restored: round %d, epoch %d",
                         self.round_idx, self.epoch)
        # The net broadcast this round — compressed uploads are deltas
        # against it, so reconstruction must use the same anchor.
        self._broadcast_net = aggregator.net
        del compress  # server decodes by each frame's self-described codec
        # Actuation seam (fedml_tpu.ctrl): validated, boundary-gated knob
        # setters an attached controller tunes between rounds. Building
        # it is inert — with no controller and no external apply() the
        # tier is bit-equal to a build without this subsystem.
        # aggregate_k is read through _k_effective() at each completion
        # check, so a between-rounds mutation moves only the NEXT
        # round's window; the timeout knobs are read live by the
        # watchdog loop, and are knobs only when the watchdog could be
        # armed at run() (else retuning them would be a silent no-op).
        from fedml_tpu.ctrl.actuator import ActuationSeam, Knob

        knobs = [
            Knob("aggregate_k", lambda: self.aggregate_k,
                 lambda v: setattr(self, "aggregate_k", v),
                 1, max(1, size - 1), cast=int),
        ]
        if self.round_timeout_s and self.round_timeout_s > 0:
            knobs.append(Knob(
                "round_timeout_s", lambda: self.round_timeout_s,
                self._set_round_timeout, 1e-3, 86400.0))
        if self.done_timeout_s and self.done_timeout_s > 0:
            knobs.append(Knob(
                "done_timeout_s", lambda: self.done_timeout_s,
                lambda v: setattr(self, "done_timeout_s", v),
                1e-3, 86400.0))
        if self._pool is not None:
            knobs.append(Knob(
                "ingest_workers", lambda: self._pool.workers,
                lambda v: self._pool.resize(v), 1, 64, cast=int,
                constraint=lambda v: ("pool_shrink_unsupported"
                                      if v < self._pool.workers else None)))
        self.ctrl = ActuationSeam(
            type(self).__name__, knobs, registry=self.registry,
            flight=self.flight, progress=lambda: self.round_idx)

    def _set_round_timeout(self, v: float) -> None:
        # The watchdog reads round_timeout_s live each pass; the
        # heartbeat silence threshold tracks it only when it defaulted
        # to the round deadline at construction — an explicit
        # heartbeat_timeout_s stays the operator's choice.
        if self.heartbeat.timeout_s == self.round_timeout_s:
            self.heartbeat.timeout_s = v
        self.round_timeout_s = v

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        # Liveness clocks start when the RUN starts, not at construction:
        # a slow __init__ (orbax import + checkpoint restore) must not
        # make the whole fleet look expired to the first watchdog pass.
        for r in self._members_snapshot():
            self.heartbeat.beat(r)
        self.send_init_msg()
        # Armed by EITHER deadline: done_timeout_s alone still bounds the
        # terminal handshake (the loop guards each branch by its own
        # timeout, so round deadlines stay off when round_timeout_s == 0).
        if ((self.round_timeout_s and self.round_timeout_s > 0)
                or (self.done_timeout_s and self.done_timeout_s > 0)):
            threading.Thread(target=self._watchdog_loop, daemon=True).start()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self._stopped = True
        if self._pool is not None:
            self._pool.close()
        if self._ckpt is not None:
            try:
                self._save_checkpoint(wait=True)
            except Exception:  # noqa: BLE001 — shutdown must not re-raise
                log.exception("final checkpoint save failed")
            self._ckpt.close()
            self._ckpt = None
        super().finish()

    def send_init_msg(self) -> None:
        if self.round_idx >= self.cfg.comm_round:
            # Restored at (or past) the terminal round: nothing to train.
            for worker in self._members_snapshot():
                self._send_done(worker)
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for worker in self._members_snapshot():
            msg = Message(MSG_TYPE_S2C_INIT_CONFIG, 0, worker)
            msg.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
            ci = int(client_indexes[self._worker_slot(worker)])
            msg.add(MSG_ARG_KEY_CLIENT_INDEX, ci)
            msg.add("round", self.round_idx)
            msg.add("epoch", self.epoch)
            msg.add(wire_codec.OFFER_KEY, wire_codec.codec_offer())
            # Negotiated delta capability (PR 15): this server decodes
            # delta-framed uploads against the round's broadcast anchor.
            msg.add(wire_codec.DELTA_OK_KEY, True)
            if self.secagg is not None:
                # Capability stage: no roster yet, so clients DEFER the
                # round and open the pk handshake; the assignment
                # re-arrives roster-stamped once the share matrix lands.
                msg.add(wire_codec.SECAGG_OK_KEY, True)
            self._stamp_routing(msg, ci)
            self._safe_send(msg, worker)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )
        self.register_message_receive_handler(
            MSG_TYPE_C2S_HEARTBEAT, self._handle_heartbeat)
        self.register_message_receive_handler(
            MSG_TYPE_SRV_TICK, self._handle_tick)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SECAGG_PK, self._handle_secagg_pk)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SECAGG_SHARES, self._handle_secagg_shares)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEED_SHARE, self._handle_seed_share)

    # -- snapshots (watchdog thread reads; handlers mutate under _lock) -----
    def _members_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._members)

    def _arrived_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._arrived)

    def _done_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._done_set)

    def _round_snapshot(self) -> int:
        # round_idx commits on the dispatch thread (_complete_round);
        # the watchdog keys its deadline/eviction decisions off it and
        # must read the committed value, not a torn one.
        with self._lock:
            return self.round_idx

    def _k_effective(self) -> int:
        return max(1, min(self.aggregate_k, len(self._members)))

    def _worker_slot(self, worker: int) -> int:
        """Worker rank → its 0-based slot in the round's sampled
        ``client_indexes`` (also the aggregator's worker index). The
        sharded coordinator re-bases this — its worker ranks start after
        the M aggregator-shard ranks (comm/shardplane.py)."""
        return worker - 1

    def _stamp_routing(self, out: Message, client_index: int) -> None:
        """Hook for the sharded aggregation plane: stamp the shard rank
        this worker must upload to. The single-server path routes every
        upload to rank 0 — nothing to stamp."""

    def health(self) -> Dict[str, int]:
        """Control-plane counters, surfaced per round through the metrics
        logger and asserted on by the fault drills. ``bytes_tx``/
        ``bytes_rx`` are the transport's ByteLedger totals (comm/wire.py)
        — bytes-on-wire observability for the codec A/B; 0 on backends
        without wire serialization (plain in-memory loopback).
        ``ingest_saturated`` is the lifetime count of clipped fixed-point
        contributions (comm/ingest.py) — the sharded coordinator overrides
        it with the fleet-wide sum over its shards' gauges."""
        ledger = getattr(self.com_manager, "bytes_ledger", None)
        saturated = 0
        if self._pool is not None:
            saturated = int(sum(p.saturated for p in self._pool.partials))
        with self._lock:
            return {
                "ingest_saturated": saturated,
                "members": len(self._members),
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "straggler_drops": self.straggler_drops,
                "duplicate_drops": self.duplicate_drops,
                "epoch_drops": self.epoch_drops,
                "codec_refusals": self.codec_refusals,
                "seed_reveals": self.seed_reveals,
                "epoch": self.epoch,
                "send_retries": getattr(self.com_manager, "retry_count", 0),
                "bytes_tx": ledger.total_tx if ledger is not None else 0,
                "bytes_rx": ledger.total_rx if ledger is not None else 0,
            }

    # -- fault-aware sends --------------------------------------------------
    def _safe_send(self, msg: Message, worker: int) -> bool:
        """Send; a transport-level failure (peer dead past the retry
        policy) EVICTS the worker instead of crashing the control plane."""
        try:
            self.send_message(msg)
            return True
        except (ConnectionError, OSError) as err:
            log.warning("send to worker %d failed (%s): evicting", worker, err)
            self._evict([worker])
            return False

    def _evict(self, ranks) -> None:
        # Evicted ranks STAY in the heartbeat monitor: an alive-but-slow
        # rank (e.g. still jit-compiling its first round) keeps beating
        # and is re-admitted by _handle_heartbeat; only ranks whose beats
        # also stop are truly gone.
        evicted = []
        with self._lock:
            for w in ranks:
                if w in self._members:
                    self._members.discard(w)
                    self.evictions += 1
                    evicted.append(w)
        if evicted:
            # An eviction is a postmortem trigger: persist the recent
            # control-plane history NOW, while the context that led here
            # is still in the ring.
            self.flight.record("eviction", ranks=evicted,
                               round=self.round_idx)
            self.flight.dump()

    def _send_done(self, worker: int) -> None:
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self.aggregator.net)
        out.add("done", True)
        out.add("epoch", self.epoch)
        if self._safe_send(out, worker):
            with self._lock:
                self._done_set.add(worker)
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        with self._lock:
            done = self._done_set >= self._members
        if done and not self._stopped:
            self.finish()

    def _send_assignment(self, worker: int, client_indexes=None, *,
                         resend: bool = False) -> None:
        if client_indexes is None:
            client_indexes = self.aggregator.client_sampling(self.round_idx)
        out = Message(MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, worker)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, self._broadcast_net)
        ci = int(client_indexes[self._worker_slot(worker)])
        out.add(MSG_ARG_KEY_CLIENT_INDEX, ci)
        out.add("round", self.round_idx)
        out.add("done", False)
        out.add("epoch", self.epoch)
        # Negotiation rides every assignment (not just init): a worker
        # re-admitted after the init was lost still learns the offer.
        out.add(wire_codec.OFFER_KEY, wire_codec.codec_offer())
        out.add(wire_codec.DELTA_OK_KEY, True)
        if self.secagg is not None:
            out.add(wire_codec.SECAGG_OK_KEY, True)
            members = self._members_snapshot()
            if self.secagg.setup_complete(members):
                # Stamp the per-round roster (first stamp wins; resends
                # re-ship the stored snapshot): every member of the
                # round masks against the same peer set, or nothing
                # cancels.
                roster = self.secagg.stamp_roster(self.round_idx, members)
                out.add("secagg_roster", [int(x) for x in roster])
        if resend:
            # Re-admission: the worker's upload (or our assignment) was
            # lost — a client that already trained this round should
            # RESEND its cached upload. Only flagged assignments trigger
            # that, so a plain transport duplicate of a normal assignment
            # is dropped instead of costing a model-sized resend.
            out.add("resend", True)
        self._stamp_routing(out, ci)
        self._safe_send(out, worker)

    # -- checkpointing ------------------------------------------------------
    def _save_checkpoint(self, wait: bool) -> None:
        from fedml_tpu.obs.checkpoint import save_federation

        try:
            save_federation(self._ckpt, self.aggregator.net, self.round_idx,
                            self.epoch, wait=wait)
        except Exception:  # noqa: BLE001 — e.g. an async save still in flight
            self._ckpt.wait()
            save_federation(self._ckpt, self.aggregator.net, self.round_idx,
                            self.epoch, wait=wait)

    # -- watchdog: round deadline + bounded done-handshake ------------------
    def _watchdog_loop(self) -> None:
        poll = max(0.005, min(
            0.05, (self.round_timeout_s or self.done_timeout_s) / 10))
        while not self._stopped:
            members = self._members_snapshot()
            if not members:
                # Either everyone is dead (the tick handler aborts) or an
                # eviction storm is healing through beat re-admissions —
                # keep watching either way.
                self._post_tick(self._round_snapshot(), [])
                time.sleep(max(poll, 0.1))
                continue
            r = self._round_snapshot()
            if r >= self.cfg.comm_round:
                if self.done_timeout_s and self.done_timeout_s > 0:
                    failed = self.heartbeat.wait_all_or_failed(
                        members, have=self._done_snapshot, poll_s=poll,
                        deadline_s=self.done_timeout_s)
                    if not self._stopped and failed:
                        self._post_tick(r, failed)
            elif self.round_timeout_s and self.round_timeout_s > 0:
                failed = self.heartbeat.wait_all_or_failed(
                    members,
                    have=lambda m=members, r=r: (
                        m if (self._stopped or self._round_snapshot() != r)
                        else self._arrived_snapshot()),
                    poll_s=poll, deadline_s=self.round_timeout_s)
                if not self._stopped and failed \
                        and self._round_snapshot() == r:
                    self._post_tick(r, failed)
            time.sleep(poll)

    def _post_tick(self, round_idx: int, failed) -> None:
        """Self-addressed deadline tick: eviction executes on the receive
        thread, serialized with every other handler."""
        msg = Message(MSG_TYPE_SRV_TICK, 0, 0)
        msg.add("round", int(round_idx))
        msg.add("failed", [int(w) for w in failed])
        msg.add("epoch", self.epoch)
        try:
            self.send_message(msg)
        except (ConnectionError, OSError):
            pass  # next watchdog pass re-ticks

    def _handle_tick(self, msg: Message) -> None:
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return  # tick from a pre-crash instance left in the inbox
        failed = set(msg.get("failed") or [])
        terminal = self.round_idx >= self.cfg.comm_round
        with self._lock:
            if terminal:
                evict = [w for w in failed
                         if w in self._members and w not in self._done_set]
            else:
                if int(msg.get("round", -1)) != self.round_idx:
                    return  # stale: the round advanced while it was queued
                evict = [w for w in failed
                         if w in self._members and w not in self._arrived]
        if evict:
            log.warning("round %d deadline: evicting silent ranks %s",
                        self.round_idx, evict)
            self._evict(evict)
            if self.secagg is not None and not terminal:
                # Setup-phase eviction can unblock the handshake: if the
                # missing pk belonged to the corpse, the roster can
                # broadcast to the survivors now.
                self._secagg_nudge()
        if terminal:
            self._maybe_finish()
            return
        with self._lock:
            empty = not self._members
            ready = bool(self._arrived) and (
                len(self._arrived) >= self._k_effective())
        if empty:
            if self.heartbeat.alive():
                # Everyone missed the deadline but someone still beats
                # (e.g. the whole fleet is jit-compiling its first
                # round): hold the round open — the next beats re-admit
                # them and their uploads complete it.
                return
            # Every worker is gone; nothing can ever arrive again.
            log.error("all workers evicted at round %d: abandoning the run",
                      self.round_idx)
            self.aborted = True
            self.flight.record("abort", round=self.round_idx)
            self.flight.dump()
            self.finish()
            return
        if ready:
            self._complete_round()

    def _handle_heartbeat(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        self.heartbeat.beat(sender)
        self.flight.record("beat", sender=sender)
        if self.round_idx >= self.cfg.comm_round:
            # Any beat at the terminal round gets a done (idempotent: the
            # worker finishes on first receipt). Members and done-set
            # ranks may have lost theirs in transit; an EVICTED-but-alive
            # rank (slow past the done deadline, then resumed beating)
            # has never been sent one at all — with idle_timeout_s=0 it
            # would otherwise block on its receive loop forever.
            self._send_done(sender)
            return
        with self._lock:
            member = sender in self._members
        if member:
            if self.secagg is not None:
                self._secagg_redrive(sender)
            return
        if self.secagg is not None and not self._secagg_readmit_ok(sender):
            return  # released or waitroomed by the secagg policy
        # Evicted-but-alive: its upload or our assignment was lost,
        # or it was slow past the deadline. Re-admit with the current
        # round's work, resend-flagged: a client that never saw the
        # assignment trains it, one that already trained this round
        # resends its cached upload (idempotent at our high-water
        # mark) instead of dropping the copy.
        with self._lock:
            self._members.add(sender)
            self.readmissions += 1
        log.info("re-admitting rank %d on heartbeat", sender)
        self.flight.record("readmission", sender=sender,
                           round=self.round_idx, via="beat")
        self._send_assignment(sender, resend=True)

    # -- secure aggregation (comm/secagg.py) --------------------------------
    def _secagg_init(self) -> None:
        """(Re)key the secagg coordinator to the current membership —
        the sharded coordinator re-bases its worker ranks AFTER the base
        constructor ran and calls this again with the corrected set."""
        self.secagg = secagg_mod.SecAggServer(
            self._members_snapshot(),
            t=int(getattr(self.cfg, "secagg_t", 0) or 0))
        self._c_reveals = self.registry.counter("secagg_reveals")
        self._c_mask_overflow = self.registry.counter(
            "secagg_mask_overflow")
        self._h_reveal = self.registry.histogram("secagg_reveal_ms")

    def _secagg_readmit_ok(self, sender: int) -> bool:
        """Re-admission policy for a non-member beat under secagg. True
        → the normal resend-flagged re-admission proceeds; False → this
        call already disposed of the sender (released for the epoch, or
        parked in the waitroom until the next round's roster can take
        it)."""
        sa = self.secagg
        if sa.compromised(sender):
            # Its seeds are revealed (or mid-reveal): every future mask
            # is server-derivable, so re-admission would silently void
            # its privacy. Release it for the epoch.
            self.flight.record("secagg_released", sender=sender,
                               round=self.round_idx)
            self._send_done(sender)
            return False
        if not sa.setup_complete(self._members_snapshot()):
            return True  # the handshake absorbs it like any member
        if sa.setup_roster is not None and sender not in sa.setup_roster:
            # Missed the handshake window: the pair-key mesh froze
            # without it, so no peer can ever cancel against it —
            # release rather than admit a clear upload to a masked
            # round.
            self.flight.record("secagg_locked_out", sender=sender,
                               round=self.round_idx)
            self.flight.dump()
            self._send_done(sender)
            return False
        roster = sa.roster_for(self.round_idx)
        if roster and sender not in roster:
            # The round's roster sealed without it — every member
            # already masked against a peer set that excludes this
            # rank, so a mid-round upload could never cancel. Park it;
            # the commit tail admits it into the next round.
            with self._lock:
                self._secagg_waitroom.add(sender)
            self.flight.record("secagg_waitroom", sender=sender,
                               round=self.round_idx)
            return False
        return True

    def _secagg_redrive(self, sender: int) -> None:
        """Beat-driven secagg repair for a MEMBER: chaos can eat any
        handshake or reveal frame; the member's own liveness beats are
        the retry clock (no extra timers)."""
        sa = self.secagg
        members = self._members_snapshot()
        missing_pks = sa.pks_missing(members)
        if missing_pks:
            if sender in missing_pks:
                # Re-solicit the pk: the resent assignment makes the
                # client defer and re-open the handshake.
                self._send_assignment(sender, resend=True)
            return
        if sender in sa.rows_missing(members):
            self._send_secagg_roster([sender])
            return
        # A reveal round in flight: re-ask this survivor for every share
        # it still owes. Gated on the asked-set — a merely-slow rank
        # must never be revealed before the control plane evicts it.
        for d in sorted(self._secagg_reveal_asked):
            if d != sender and d not in sa.revealed \
                    and not sa.has_share(d, sender):
                self._send_reveal_request(d, sender)

    def _secagg_nudge(self) -> None:
        """Post-eviction handshake re-check: with the corpse's pk no
        longer awaited, the roster may be broadcastable now."""
        members = self._members_snapshot()
        if not members or self.secagg.pks_missing(members):
            return
        need = self.secagg.rows_missing(members)
        if need:
            self._send_secagg_roster(need)

    def _handle_secagg_pk(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            self.epoch_drops += 1
            return
        self.heartbeat.beat(sender)
        if self.secagg is None:
            return
        self.secagg.add_pk(sender, int(msg.get("pk")))
        members = self._members_snapshot()
        if self.secagg.pks_missing(members):
            return  # beats redrive the stragglers
        need = set(self.secagg.rows_missing(members))
        if sender not in self.secagg.rows:
            need.add(sender)
        if need:
            self._send_secagg_roster(sorted(need))

    def _send_secagg_roster(self, workers) -> None:
        body = self.secagg.roster_payload(self._members_snapshot())
        ranks = sorted(body["pks"])
        for w in workers:
            out = Message(MSG_TYPE_S2C_SECAGG_ROSTER, 0, w)
            out.add("epoch", self.epoch)
            out.add("pk_ranks", [int(r) for r in ranks])
            out.add("pk_vals", [int(body["pks"][r]) for r in ranks])
            out.add("t", int(body["t"]))
            out.add("universe", [int(u) for u in body["universe"]])
            self._safe_send(out, w)

    def _handle_secagg_shares(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            self.epoch_drops += 1
            return
        self.heartbeat.beat(sender)
        if self.secagg is None:
            return
        new = sender not in self.secagg.rows
        holders = [int(h) for h in msg.get("row_holders")]
        ciphers = [int(c) for c in msg.get("row_ciphers")]
        self.secagg.add_row(sender, dict(zip(holders, ciphers)))
        members = self._members_snapshot()
        if not (new and self.secagg.setup_complete(members)):
            return
        # The share matrix just completed: release the deferred round —
        # every member that has not already uploaded gets its (now
        # roster-stamped) assignment.
        self.flight.record("secagg_setup", members=len(members),
                           t=int(self.secagg.t))
        if self.round_idx >= self.cfg.comm_round:
            return
        arrived = set(self._arrived_snapshot())
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for w in members:
            if w not in arrived:
                self._send_assignment(w, client_indexes)

    def _send_reveal_request(self, target: int, holder: int) -> None:
        cipher = self.secagg.reveal_request(target, holder)
        if cipher is None:
            return  # the target never shipped a row entry for holder
        out = Message(MSG_TYPE_S2C_SEED_REVEAL, 0, holder)
        out.add("epoch", self.epoch)
        out.add("round", self.round_idx)
        out.add("target", int(target))
        out.add("cipher", int(cipher))
        self._safe_send(out, holder)

    def _secagg_request_reveals(self, targets) -> None:
        """Open (or re-drive) the seed-reveal round for ``targets`` —
        evicted roster ranks whose masks sit orphaned in the folded
        uploads. Survivor shares flow back as SEED_SHARE messages; the
        reveal latency histogram runs from the FIRST ask."""
        now = self._clock()
        survivors = [w for w in self._members_snapshot()
                     if w not in targets]
        for d in targets:
            first = d not in self._secagg_reveal_asked
            self._secagg_reveal_asked.add(d)
            self._secagg_reveal_t0.setdefault(d, now)
            if first:
                self.flight.record("seed_reveal_request", target=int(d),
                                   round=self.round_idx,
                                   survivors=len(survivors))
            for h in survivors:
                if not self.secagg.has_share(d, h):
                    self._send_reveal_request(d, h)
        if targets:
            self.flight.dump()

    def _handle_seed_share(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            # A share from a previous incarnation must never unlock a
            # live seed.
            self.epoch_drops += 1
            self.flight.record("seed_reveal_stale", sender=sender,
                               epoch=int(ep))
            return
        self.heartbeat.beat(sender)
        if self.secagg is None:
            return
        target = int(msg.get("target"))
        tr = obs_trace.active()
        ck = obs_trace.corr(epoch=self.epoch, round=self.round_idx,
                            sender=sender)
        with tr.span("secagg.reveal", cat="secagg", corr=ck,
                     target=target):
            done = self.secagg.add_reveal_share(target, sender,
                                                int(msg.get("share")))
        if not done:
            return
        self.seed_reveals += 1
        self._c_reveals.inc()
        t0 = self._secagg_reveal_t0.pop(target, None)
        if t0 is not None:
            self._h_reveal.record((self._clock() - t0) * 1e3)
        self.flight.record("seed_reveal", target=target,
                           round=self.round_idx,
                           shares=self.secagg.shares_held(target))
        self.flight.dump()
        self._secagg_recheck()

    def _secagg_recheck(self) -> None:
        """A reveal just completed: if the round was blocked on it (the
        precommit gate returned False), re-drive the commit."""
        if self.round_idx >= self.cfg.comm_round:
            return
        with self._lock:
            ready = bool(self._arrived) and (
                len(self._arrived) >= self._k_effective())
        if ready:
            self._complete_round()

    def _secagg_reveals_ready(self) -> bool:
        pending = self.secagg.unreconstructed(self.round_idx,
                                              self._arrived_snapshot())
        if pending:
            self._secagg_request_reveals(pending)
            return False
        return True

    def _secagg_precommit(self) -> bool:
        """The mask-completeness gate between the pool barrier and the
        merge: every roster rank either arrived (its masks cancel in
        the fold) or is an orphan whose reconstructed seeds yield an
        exact int64 correction, folded here as a weight-0 count-0
        contribution. Returns False while reveals are in flight —
        :meth:`_secagg_recheck` re-enters on reconstruction."""
        if not self._secagg_reveals_ready():
            return False
        r = self.round_idx
        arrived = self._arrived_snapshot()
        orphans = self.secagg.orphans(r, arrived)
        if not orphans:
            return True
        shapes = [np.shape(np.asarray(l))
                  for l in jax.tree.leaves(self.aggregator.net)]
        for d in orphans:
            corr = self.secagg.correction(d, r, self.epoch, arrived,
                                          shapes)
            self._pool.submit(
                lambda c=corr: FixedContribution(c, 0, 0),
                epoch=self.epoch, round=r, sender=int(d),
                kind="secagg_correction")
        for meta, err in self._pool.drain():
            log.error("secagg correction task failed: %s (%s)", meta, err)
        self.flight.record("secagg_correction", round=r,
                           targets=[int(d) for d in orphans])
        return True

    def _secagg_envelope_check(self, total) -> None:
        """Post-cancellation headroom audit: a merged masked total whose
        leaves exceed count·2^50 means the masks did NOT fully cancel
        (roster drift, a wrong correction) or the true sum genuinely
        wrapped — count it loudly, never clamp (comm/ingest.py
        envelope_overflow)."""
        over = int(total.envelope_overflow())
        if over:
            self._c_mask_overflow.inc()
            log.error("secagg: %d leaves outside the fixed-point "
                      "envelope after mask cancellation (round %d)",
                      over, self.round_idx)
            self.flight.record("mask_envelope_overflow", leaves=over,
                               round=self.round_idx)
            self.flight.dump()

    def _secagg_commit_tail(self, arrived) -> List[int]:
        """Post-commit membership repair: admit waitroomed ranks into
        the NEXT round's roster, purge compromised members, clear the
        per-round reveal bookkeeping. Returns the admitted ranks that
        still need an assignment fan-out."""
        sa = self.secagg
        with self._lock:
            admit = sorted(w for w in self._secagg_waitroom
                           if sa.can_participate(w))
            self._secagg_waitroom.clear()
            for w in admit:
                if w not in self._members:
                    self._members.add(w)
                    self.readmissions += 1
            for w in [m for m in self._members if sa.compromised(m)]:
                self._members.discard(w)
        self._secagg_reveal_asked.clear()
        self._secagg_reveal_t0.clear()
        for w in admit:
            self.flight.record("readmission", sender=w,
                               round=self.round_idx,
                               via="secagg_waitroom")
        return [w for w in admit if w not in arrived]

    # -- the round ----------------------------------------------------------
    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            # Pre-crash upload: the restarted server already re-broadcast
            # assignments under the new epoch, so this worker has live
            # work — reject deterministically, never reply.
            self.epoch_drops += 1
            self.flight.record("epoch_drop", sender=sender, epoch=int(ep))
            # fedlint: disable=P2(stale-epoch frame; the epoch re-anchor already handed this worker live work, a reply would double-assign)
            return
        self.heartbeat.beat(sender)
        tag = msg.get("round")
        t = int(tag) if tag is not None else self.round_idx
        with self._lock:
            if t <= self._last_upload_round.get(sender, -1):
                # Duplicate delivery (ChaosTransport duplication, sender
                # retry after a lost ACK): the first copy was answered —
                # replying again would hand the worker two assignments.
                self.duplicate_drops += 1
                self.flight.record("duplicate_drop", sender=sender, round=t)
                # fedlint: disable=P2(duplicate delivery; the first copy was replied to, a second reply double-assigns)
                return
            self._last_upload_round[sender] = t
            if sender not in self._members:
                if self.secagg is not None \
                        and self.secagg.compromised(sender):
                    # A rank whose seeds are revealed (or mid-reveal):
                    # its current-round upload still FOLDS below if it
                    # holds a roster slot — arrival and correction are
                    # mutually exclusive, so the sum stays exact — but
                    # membership is gone for the epoch.
                    pass
                else:
                    self._members.add(sender)
                    self.readmissions += 1
                    self.flight.record("readmission", sender=sender,
                                       round=t, via="upload")
        if self.round_idx >= self.cfg.comm_round:
            # Terminal: a straggler's in-flight upload after the final
            # aggregation — release it.
            self._send_done(sender)
            return
        if t != self.round_idx:
            # Stale upload from an older round: discard the model, catch
            # the worker up on the current round — unless its seeds were
            # revealed while the upload was in flight, in which case it
            # is released for the epoch instead of reassigned.
            self.straggler_drops += 1
            self.flight.record("straggler_drop", sender=sender, round=t)
            if self.secagg is not None and self.secagg.compromised(sender):
                self._send_done(sender)
            else:
                self._send_assignment(sender)
            return
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        masked = bool(msg.get(wire_codec.SECAGG_MASKED_KEY))
        if masked and self.secagg is None:
            # A masked int64 frame against an unarmed server could only
            # ever fold as mask noise — the codec-refusal policy (evict
            # AND release) applies verbatim.
            self.codec_refusals += 1
            log.error("rank %d: masked upload but secagg is not armed — "
                      "evicting and releasing the worker", sender)
            self.flight.record("secagg_refusal", sender=sender, round=t)
            self._evict([sender])
            self.flight.dump()
            with self._lock:
                empty = not self._members
                ready = bool(self._arrived) and (
                    len(self._arrived) >= self._k_effective())
            if empty:
                log.error("all workers refused/evicted at round %d: "
                          "abandoning the run", self.round_idx)
                self.aborted = True
            self._send_done(sender)  # release; finishes when empty
            if not empty and ready:
                self._complete_round()
            return
        if masked and sender not in self.secagg.roster_for(t):
            # A masked frame from outside the round's sealed roster can
            # never cancel — protocol violation or a deep chaos
            # reordering. Drop the payload; the sender's beat routes it
            # through the waitroom.
            self.flight.record("secagg_nonroster_drop", sender=sender,
                               round=t)
            self.flight.dump()
            return
        codec = msg.get("compression")
        wcodec = msg.get(wire_codec.CODEC_KEY)
        # The negotiated delta capability (PR 15): a stamped upload
        # self-describes whether its payload is a delta against this
        # round's broadcast anchor. Legacy/unstamped frames keep the
        # historical contract (codec frames are deltas, raw frames full
        # models).
        is_delta = bool(msg.get(wire_codec.DELTA_KEY))
        tr = obs_trace.active()
        ck = obs_trace.corr(epoch=self.epoch, round=t, sender=sender)
        self._h_bytes.record(payload_nbytes(payload))
        depth = getattr(self.com_manager, "inbox_depth", None)
        if depth is not None:
            depth = depth()
            if depth is not None:
                self._g_queue.set(depth)
        if self._pool is not None:
            # Pooled ingest: the dispatch thread only does the accept
            # bookkeeping; decode + delta reconstruction + the exact
            # partial fold run on the pool, and the round flush barriers
            # on it. A frame that refuses in a worker is surfaced at the
            # barrier and evict-and-released there (_settle_pool).
            self._g_pool_queue.set(self._pool.queue_depth())
            self._submit_ingest(sender, t, payload, codec, wcodec,
                                float(msg.get(MSG_ARG_KEY_NUM_SAMPLES)), ck,
                                is_delta=is_delta, masked=masked,
                                clipped=int(msg.get("secagg_clipped") or 0))
            with self._lock:
                self._arrived.add(sender)
                ready = len(self._arrived) >= self._k_effective()
            if ready:
                self._complete_round()
            return
        if codec:
            # Dispatch on the frame's self-described codec, not a server
            # flag: per-rank launches may configure compression on the
            # clients only, and ranks could even mix schemes.
            t0 = time.perf_counter()
            with tr.span("ingest.decode", cat="ingest", corr=ck,
                         codec=codec):
                delta = self._decoder_for(codec).decode(payload, self._spec)
                payload = tree_add(self._broadcast_net, delta)
            self._h_decode.record((time.perf_counter() - t0) * 1e3)
        elif wcodec:
            # Wire-codec frame (comm/codec.py): same self-description
            # discipline, pickle-free numpy decode, and a REFUSAL (not a
            # crash, not a silent zero) on a corrupt/truncated frame.
            # Decode + delta reconstruction are one timed unit — both are
            # O(model) work the dispatch thread pays per upload.
            t0 = time.perf_counter()
            try:
                with tr.span("ingest.decode", cat="ingest", corr=ck,
                             codec=wcodec):
                    delta = self._wire_decoders.decode(wcodec, payload,
                                                       self._spec)
                    payload = tree_add(self._broadcast_net, delta)
            except (wire_codec.CodecError, ValueError) as err:
                # The transport already guarantees frame integrity, so a
                # refusal means a mismatched/corrupt ENCODER — every
                # upload from that rank would refuse forever (resends
                # are bit-identical by frame_seed), so neither waiting
                # nor re-assigning can ever recover it. Evict AND
                # RELEASE the worker (done=True → it exits instead of
                # blocking on its receive loop under the default
                # round_timeout_s=0, or churning through heartbeat
                # re-admission), then complete the round over the
                # survivors — or abort when nobody remains.
                self.codec_refusals += 1
                log.error("rank %d: codec %r frame refused (%s) — "
                          "evicting and releasing the worker (a "
                          "mismatched encoder can never upload a usable "
                          "model)", sender, wcodec, err)
                self.flight.record("codec_refusal", sender=sender,
                                   round=t, codec=str(wcodec),
                                   error=str(err)[:200])
                self._evict([sender])
                self.flight.dump()
                with self._lock:
                    empty = not self._members
                    ready = bool(self._arrived) and (
                        len(self._arrived) >= self._k_effective())
                if empty:
                    log.error("all workers refused/evicted at round %d:"
                              " abandoning the run", self.round_idx)
                    self.aborted = True
                self._send_done(sender)  # release; finishes when empty
                if not empty and ready:
                    self._complete_round()
                return
            self._h_decode.record((time.perf_counter() - t0) * 1e3)
        elif is_delta:
            # Raw tensor-framed delta (the negotiated capability without
            # a codec — e.g. an adapter client on the plain tensor
            # wire): reconstruct against the round's broadcast anchor,
            # same discipline as the codec paths above.
            t0 = time.perf_counter()
            with tr.span("ingest.decode", cat="ingest", corr=ck,
                         codec="delta"):
                payload = tree_add(self._broadcast_net, payload)
            self._h_decode.record((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        with tr.span("ingest.fold", cat="ingest", corr=ck):
            self.aggregator.add_local_trained_result(
                sender - 1, payload, msg.get(MSG_ARG_KEY_NUM_SAMPLES)
            )
        self._h_fold.record((time.perf_counter() - t0) * 1e3)
        with self._lock:
            self._arrived.add(sender)
            ready = len(self._arrived) >= self._k_effective()
        if ready:
            self._complete_round()

    def _decoder_for(self, codec: str):
        """Get-or-create the per-codec decoder under the lock. With the
        ingest pool armed, two workers can miss the cache for the same
        codec at once and construct twin compressors — harmless for
        stateless codecs, state-splitting for error-feedback ones."""
        with self._lock:
            dec = self._decoders.get(codec)
            if dec is None:
                dec = self._decoders[codec] = make_compressor(codec)
        return dec

    def _submit_ingest(self, sender: int, round_idx: int, payload, codec,
                       wcodec, weight: float, ck, *,
                       is_delta: bool = False, masked: bool = False,
                       clipped: int = 0) -> None:
        """Build one upload's decode+fold task and hand it to the pool.
        The closure snapshots this round's broadcast anchor (compressed
        uploads — and raw frames stamped delta — are deltas against it)
        so a late-running task cannot reconstruct against the NEXT
        round's net."""
        anchor = self._broadcast_net
        spec = self._spec
        secagg_on = self.secagg is not None

        # fedlint: twin-of(fedml_tpu/comm/shardplane.py)
        def task():
            if masked:
                # Secagg frame: already exact int64 fixed point (the
                # client ran the identical quantize path before
                # masking) — fold modularly, no decode, no re-clip.
                # The handler refused unarmed masked frames before
                # submit; this pool-side guard keeps the shard twin's
                # invariant (_settle_pool evicts+releases on it).
                if not secagg_on:
                    raise ValueError("masked upload without --secagg")
                return FixedContribution(
                    [np.ascontiguousarray(l, np.int64) for l in payload],
                    quantize_weight(weight), 1, int(clipped))
            if codec:
                delta = self._decoder_for(codec).decode(payload, spec)
            elif wcodec:
                delta = self._wire_decoders.decode(wcodec, payload, spec)
            elif is_delta:
                delta = payload  # raw tensor-framed delta (PR 15)
            else:
                delta = None
            if delta is None:
                return ([np.asarray(l) for l in jax.tree.leaves(payload)],
                        weight)
            # Delta frame: the fold computes w*(anchor + delta) in the
            # accumulator's preallocated scratch — no model-sized
            # temporary on the task path.
            return ([np.asarray(d) for d in jax.tree.leaves(delta)],
                    weight,
                    [np.asarray(a) for a in jax.tree.leaves(anchor)])

        # ck (the correlation key) already carries epoch/round/sender —
        # the span args double as the failure metadata _settle_pool reads.
        self._pool.submit(task, **ck)

    def _settle_pool(self) -> bool:
        """Round-flush barrier on the ingest pool. Failed tasks (corrupt
        codec frames) get the refusal policy HERE — evict AND RELEASE,
        same as the inline path, just deferred to the barrier — and the
        round's readiness is re-checked over the survivors. Returns True
        when the round can complete now."""
        failures = self._pool.drain()
        for meta, err in failures:
            sender = int(meta.get("sender", -1))
            self.codec_refusals += 1
            log.error("rank %d: pooled ingest refused (%s) — evicting and "
                      "releasing the worker (a mismatched encoder can "
                      "never upload a usable model)", sender, err)
            self.flight.record("codec_refusal", sender=sender,
                               round=meta.get("round"),
                               error=str(err)[:200])
            with self._lock:
                self._arrived.discard(sender)
            self._evict([sender])
            self.flight.dump()
        with self._lock:
            empty = not self._members
            ready = bool(self._arrived) and (
                len(self._arrived) >= self._k_effective())
        if failures and empty:
            # Mark the abort BEFORE the releases below: sending the
            # last done finishes the server, and the flag must already
            # be truthful when run() returns (inline-path ordering).
            log.error("all workers refused/evicted at round %d: "
                      "abandoning the run", self.round_idx)
            self.aborted = True
        for meta, _ in failures:
            self._send_done(int(meta.get("sender", -1)))  # release
        return ready and not empty

    def _complete_round(self) -> None:
        if self._pool is not None and not self._settle_pool():
            return  # refusals thinned the round below readiness
        if self.secagg is not None and not self._secagg_precommit():
            return  # seed reveals in flight; _secagg_recheck re-enters
        with self._lock:
            arrived = sorted(self._arrived)
            self._arrived = set()
        with obs_trace.active().span(
                "round.commit", cat="round",
                corr=obs_trace.corr(epoch=self.epoch, round=self.round_idx),
                arrived=len(arrived)):
            if self._pool is not None:
                global_net = self.aggregator.aggregate_pooled(
                    [self._worker_slot(w) for w in arrived], self._pool,
                    envelope_check=(self._secagg_envelope_check
                                    if self.secagg is not None else None))
            else:
                global_net = self.aggregator.aggregate_from(
                    [self._worker_slot(w) for w in arrived])
        self.flight.record("round_commit", round=self.round_idx,
                           arrived=len(arrived))
        self._broadcast_net = global_net
        if (
            self.round_idx % self.cfg.frequency_of_the_test == 0
            or self.round_idx == self.cfg.comm_round - 1
        ):
            self.aggregator.test_on_server(self.round_idx)
        completed = self.round_idx
        # Commit the round under the lock: the watchdog keys deadlines
        # and ticks off _round_snapshot() and must never see a torn
        # increment.
        with self._lock:
            self.round_idx += 1
        extra: List[int] = []
        if self.secagg is not None:
            extra = self._secagg_commit_tail(arrived)
        self._log_round_health(completed, arrived)
        # Safe actuation boundary: the round just committed and eval/
        # telemetry are current; knob mutations here shape the NEXT
        # round's window and deadlines, never a fold in flight.
        self._ctrl_boundary()
        if self._ckpt is not None and self.cfg.checkpoint_every and (
            self.round_idx % self.cfg.checkpoint_every == 0
        ):
            self._save_checkpoint(wait=False)
        if self.round_idx >= self.cfg.comm_round:
            for worker in arrived + extra:
                self._send_done(worker)
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for worker in arrived + extra:
            if self.secagg is not None and self.secagg.compromised(worker):
                # Arrived under a mid-reveal race: its round slot held
                # (the fold stayed exact) but the epoch releases it.
                self._send_done(worker)
                continue
            self._send_assignment(worker, client_indexes)

    def _log_round_health(self, round_idx: int, arrived) -> None:
        if self.metrics is None:
            return
        # Counters + the ingest registry snapshot (decode_ms_p50/p95,
        # fold_ms_*, bytes_per_upload_*, ingest_queue_depth — a STABLE
        # metric-name surface, docs/OBSERVABILITY.md) in one ctrl/ row
        # per round.
        self.metrics.log({"arrived": len(arrived), **self.health(),
                          **self.registry.snapshot()},
                         step=round_idx, prefix="ctrl")


class FedAVGClientManager(ClientManager):
    """Worker process: jitted local training on the assigned client's shard
    (FedAvgClientManager.py:34-79). Control-plane duties: adopt the
    server's epoch (resetting the round dedupe on a restart), drop
    duplicated assignments by round tag, beat every
    ``beat_interval_s`` while training keeps the upload path silent, and
    self-terminate after ``idle_timeout_s`` without server contact (a
    crashed-and-never-restarted server must not strand its workers)."""

    def __init__(self, args, rank: int, size: int, train_fed: FederatedArrays,
                 local_train, cfg: FedConfig, backend: str = "LOOPBACK",
                 compress: str = "none", wire_codec_spec: str = "none", *,
                 beat_interval_s: Optional[float] = None,
                 idle_timeout_s: float = 0.0):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.train_fed = train_fed
        self.local_train = local_train
        self.cfg = cfg
        self.round_idx = 0
        self.epoch = 0
        self.duplicate_drops = 0
        self.upload_resends = 0
        self._last_handled = -1
        # Wire codec (comm/codec.py): the REQUESTED spec, resolved against
        # the server's handshake offer on the first assignment (negotiated
        # per connection; a codec-ignorant server drops us to the plain
        # tensor wire, loudly). Validated eagerly — a typo must fail at
        # construction, not at the first upload.
        if wire_codec_spec not in ("", "none") and compress not in ("",
                                                                    "none"):
            raise ValueError(
                "compress and wire_codec are mutually exclusive (both "
                "would compress the same upload)")
        wire_codec.make_wire_codec(wire_codec_spec)
        self._codec_requested = wire_codec_spec or "none"
        self._codec = None  # set by negotiation on the first assignment
        self._delta_ok = False  # ditto (PR 15 delta capability)
        # Secure aggregation (comm/secagg.py, cfg.secagg): the DH state
        # is built lazily per epoch on the first assignment. Masked
        # uploads ship the QUANTIZED fixed-point contribution, so the
        # legacy on-device float compressors cannot compose — the wire
        # codec family can (the client self-decodes its own frame onto
        # the fixed grid before masking).
        if getattr(cfg, "secagg", False) and compress not in ("", "none"):
            raise ValueError(
                "cfg.secagg masks the quantized fixed-point upload; the "
                "legacy on-device compressor produces float frames "
                f"(compress={compress!r}) — use wire_codec instead")
        self._secagg: Optional[secagg_mod.SecAggClient] = None
        self._secagg_roster: Optional[List[int]] = None
        self._mask_decoders = wire_codec.CodecCache()
        # The last upload message, kept until the NEXT round's assignment
        # arrives: a RESEND-flagged re-assignment of the round we already
        # trained means our upload was lost in transit (the server flags
        # re-admission assignments) — resend it instead of dropping the
        # assignment, or a round whose every upload was lost would
        # evict/re-admit/livelock forever. One message of memory; the
        # server's per-worker round high-water mark makes resends
        # idempotent.
        self._last_upload: Optional[Message] = None
        # Upload destination: rank 0 unless the assignment stamps a shard
        # rank (the sharded aggregation plane, comm/shardplane.py).
        # Control traffic — heartbeats — always goes to rank 0.
        self._upload_to = 0
        self._compressor = make_compressor(compress)
        self._beats = HeartbeatSender(
            self._send_beat,
            interval_s=(cfg.heartbeat_interval_s if beat_interval_s is None
                        else beat_interval_s),
            idle_timeout_s=idle_timeout_s,
            on_idle=self._idle_quit)
        # Latest top-k error-feedback residual: (round, client, residual).
        # EF theory requires the residual to stay with its own data
        # stream, so it is applied only when this rank trains the SAME
        # client in the IMMEDIATELY next round — a stale carry would
        # otherwise spike against a much-evolved model, and one client's
        # carry must never leak into another's update. A rank trains one
        # client per round, so a single triple suffices (a per-client dict
        # would pin one dead model-sized residual per migrated-away client
        # forever). Under full participation assignments are stable and EF
        # is exact; under subsampling the carry drops at migrations.
        self._ef_state: Optional[tuple] = None
        # Dropped-carry visibility (like the server's straggler_drops):
        # each increment is one round whose compression error correction
        # was discarded — top-k is running as plain biased compression in
        # exactly the regimes (first-k rounds, client re-assignment) that
        # cause the drops.
        self.ef_carry_drops = 0

    def run(self) -> None:
        self._beats.start()
        super().run()

    def finish(self) -> None:
        self._beats.stop()
        super().finish()

    def _send_beat(self) -> None:
        msg = Message(MSG_TYPE_C2S_HEARTBEAT, self.rank, 0)
        # fedlint: disable=P1(epoch is a monotonically-adopted small int; a beat stamped with the pre-adoption epoch is indistinguishable from one sent just before adoption and the server accepts both)
        msg.add("epoch", self.epoch)
        self.send_message(msg)

    def _idle_quit(self) -> None:
        log.warning("rank %d: no server contact for %.1fs — exiting",
                    self.rank, self._beats.idle_timeout_s)
        self.finish()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server,
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SECAGG_ROSTER, self._handle_secagg_roster)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SEED_REVEAL, self._handle_seed_reveal)

    def handle_message_init(self, msg: Message) -> None:
        self._handle_assignment(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._handle_assignment(msg)

    def _handle_assignment(self, msg: Message) -> None:
        self._beats.touch()
        ep = msg.get("epoch")
        if ep is not None:
            ep = int(ep)
            if ep < self.epoch:
                return  # straggler message from a dead server epoch
            if ep > self.epoch:
                # Server restarted: adopt its epoch and reset the round
                # dedupe — the restored run legitimately replays rounds.
                # The cached upload died with the old epoch.
                # fedlint: disable=P1(single-writer adoption on the dispatch thread; the beat thread only stamps the value and tolerates the previous epoch)
                self.epoch = ep
                self._last_handled = -1
                self._last_upload = None
                # New incarnation, new pair-key mesh: the old DH state
                # (and its round rosters) died with the old epoch.
                self._secagg = None
                self._secagg_roster = None
        if msg.get("done"):
            self.finish()
            return
        sr = msg.get(MSG_ARG_KEY_SHARD_RANK)
        if sr is not None and int(sr) != self._upload_to:
            # Sharded plane routing (first stamp, or a re-route after a
            # shard eviction). The cached upload re-targets too: a
            # resend-flagged re-assignment after its shard died must
            # re-ship to the SURVIVING shard, not the corpse.
            self._upload_to = int(sr)
            if self._last_upload is not None:
                self._last_upload.receiver_id = self._upload_to
                self._last_upload.add(Message.MSG_ARG_KEY_RECEIVER,
                                      self._upload_to)
        if getattr(self.cfg, "secagg", False):
            # Capability stage: a masked upload against a secagg-
            # ignorant server would fold mask noise into the mean —
            # refuse loudly (comm/codec.py).
            wire_codec.require_secagg_peer(
                msg.get(wire_codec.SECAGG_OK_KEY), peer="server")
            if self._secagg is None:
                self._secagg = secagg_mod.SecAggClient(self.rank,
                                                       self.epoch)
            roster = msg.get("secagg_roster")
            if self._secagg.pair_keys is None or roster is None:
                # Setup incomplete on one side or the other: publish the
                # pk and DEFER the round — no _last_handled bump, so the
                # roster-stamped re-send of this same round still
                # processes; chaos duplicates of the pk are idempotent.
                self._send_secagg_pk()
                return
            roster = [int(x) for x in roster]
            if self.rank not in roster:
                # Defensive: a roster that excludes us means our slot is
                # sealed elsewhere — masking against it could never
                # cancel. Sit the round out; the server's waitroom
                # re-admits us at the next commit.
                log.warning("rank %d: round %s roster %s excludes us — "
                            "sitting out until re-rostered", self.rank,
                            msg.get("round"), roster)
                return
            self._secagg_roster = roster
        # The server's round tag, not a local counter: under first-k
        # aggregation a straggler can be reassigned past skipped rounds.
        tag = msg.get("round")
        if tag is not None:
            t = int(tag)
            if t <= self._last_handled:
                if (t == self._last_handled and msg.get("resend")
                        and self._last_upload is not None):
                    # Resend-flagged re-assignment of the round we
                    # already trained: the server re-admitted us, so our
                    # upload was lost in transit. Resend it — idempotent
                    # at the server's round high-water mark. Unflagged
                    # copies are plain transport duplicates and drop
                    # below, costing nothing on the wire.
                    self.upload_resends += 1
                    self.send_message(self._last_upload)
                    return
                # Transport duplicate of a handled assignment.
                self.duplicate_drops += 1
                return
            self._last_handled = t
            self.round_idx = t
        else:
            self.round_idx += 1
        if self._codec is None:
            # Negotiate once per connection, on the first live assignment:
            # the server's offer (or its absence — a codec-ignorant peer)
            # decides whether the requested codec runs or we fall back to
            # the uncompressed tensor wire, loudly (comm/codec.py).
            self._codec = wire_codec.negotiated_codec(
                self._codec_requested, msg.get(wire_codec.OFFER_KEY),
                peer="server")
            # Delta capability (PR 15): compressed/codec uploads ship
            # DELTAS against the broadcast anchor — a server that never
            # advertised delta acceptance would mis-fold them as full
            # models, so REFUSE loudly instead of corrupting the global.
            self._delta_ok = bool(msg.get(wire_codec.DELTA_OK_KEY))
            if (self._compressor.name != "none"
                    or self._codec.name != "none"):
                wire_codec.require_delta_peer(self._delta_ok, peer="server")
        self._train(msg.get(MSG_ARG_KEY_MODEL_PARAMS), msg.get(MSG_ARG_KEY_CLIENT_INDEX))

    # -- secure aggregation (comm/secagg.py) --------------------------------
    def _send_secagg_pk(self) -> None:
        out = Message(MSG_TYPE_C2S_SECAGG_PK, self.rank, 0)
        out.add("epoch", self.epoch)
        out.add("pk", int(self._secagg.pk))
        self.send_message(out)

    def _handle_secagg_roster(self, msg: Message) -> None:
        self._beats.touch()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            # Either a dead incarnation's roster, or one that OUTRAN the
            # assignment that adopts its epoch — drop; the server's
            # beat-driven redrive re-sends it once we catch up.
            return
        if self._secagg is None:
            return
        pks = dict(zip([int(r) for r in msg.get("pk_ranks")],
                       [int(v) for v in msg.get("pk_vals")]))
        row = self._secagg.build_shares(
            pks, int(msg.get("t")),
            [int(u) for u in msg.get("universe")])
        out = Message(MSG_TYPE_C2S_SECAGG_SHARES, self.rank, 0)
        out.add("epoch", self.epoch)
        out.add("row_holders", sorted(row))
        out.add("row_ciphers", [int(row[h]) for h in sorted(row)])
        self.send_message(out)

    def _handle_seed_reveal(self, msg: Message) -> None:
        self._beats.touch()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return  # stale-epoch ask; the live epoch re-asks with its own cipher
        target = int(msg.get("target"))
        if self._secagg is None or self._secagg.pair_keys is None \
                or target not in self._secagg.pair_keys:
            return
        share = self._secagg.reveal_share(target, int(msg.get("cipher")))
        out = Message(MSG_TYPE_C2S_SEED_SHARE, self.rank, 0)
        out.add("epoch", self.epoch)
        out.add("round", msg.get("round"))
        out.add("target", target)
        out.add("share", int(share))
        self.send_message(out)

    def _masked_contribution(self, net, global_net, c: int, codec):
        """The masked upload body: quantize this round's contribution
        onto the server pool's EXACT fixed-point grid — by running the
        identical decode+fold arithmetic the unmasked server path runs,
        so masked ≡ unmasked is bit-equality by construction, not by
        reimplementation — then add the pairwise masks."""
        w = float(self.train_fed.counts[c])
        acc = PartialAccumulator()
        if codec is not None:
            delta = tree_sub(net, global_net)
            prev = self._ef_state
            carry = (prev[2] if prev and prev[0] == self.round_idx - 1
                     and prev[1] == c else None)
            if prev is not None and carry is None and prev[2] is not None:
                self.ef_carry_drops += 1
            payload, residual = codec.encode(
                jax.device_get(delta), carry,
                wire_codec.frame_seed(self.cfg.seed, self.epoch,
                                      self.round_idx, c))
            self._ef_state = (self.round_idx, c, residual)
            # Self-decode the frame we WOULD have shipped in the clear:
            # the server's unmasked fold is decode → w·(anchor + deltâ)
            # on the fixed grid, so fold the DECODED tree, not the raw
            # delta.
            dhat = self._mask_decoders.decode(codec.name, payload,
                                              tree_spec(global_net))
            acc.add([np.asarray(l) for l in jax.tree.leaves(dhat)], w,
                    base=[np.asarray(a)
                          for a in jax.tree.leaves(global_net)])
        else:
            acc.add([np.asarray(l)
                     for l in jax.tree.leaves(jax.device_get(net))], w)
        leaves = self._secagg.mask(acc.leaves, self.round_idx,
                                   self._secagg_roster)
        return leaves, acc.saturated

    def _train(self, global_net, client_index: int) -> None:
        c = int(client_index)
        tr = obs_trace.active()
        ck = obs_trace.corr(epoch=self.epoch, round=self.round_idx,
                            sender=self.rank)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.round_idx)
        rng = jax.random.fold_in(rng, c)
        with tr.span("client.train", cat="client", corr=ck, client=c):
            net, loss = self.local_train(
                global_net,
                self.train_fed.x[c],
                self.train_fed.y[c],
                self.train_fed.mask[c],
                rng,
            )
            if tr.enabled:
                # Fence so the span measures the device work, not just
                # the async dispatch (RoundTimer's discipline). Traced
                # off this is skipped — device_get below syncs anyway.
                jax.block_until_ready(net)
        t_ser = tr.now()
        out = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self._upload_to)
        codec = (self._codec if self._codec is not None
                 and self._codec.name != "none" else None)
        masked = self._secagg is not None and bool(self._secagg_roster)
        if masked:
            with tr.span("secagg.mask", cat="secagg", corr=ck, client=c):
                leaves, clipped = self._masked_contribution(
                    net, global_net, c, codec)
            out.add(MSG_ARG_KEY_MODEL_PARAMS, leaves)
            out.add(wire_codec.SECAGG_MASKED_KEY, True)
            out.add(wire_codec.DELTA_KEY, False)
            out.add("secagg_clipped", int(clipped))
        elif self._compressor.name != "none" or codec is not None:
            delta = tree_sub(net, global_net)
            prev = self._ef_state
            carry = (prev[2] if prev and prev[0] == self.round_idx - 1
                     and prev[1] == c else None)
            if prev is not None and carry is None and prev[2] is not None:
                self.ef_carry_drops += 1
            if codec is not None:
                # Frame seed keyed on (run seed, epoch, round, client):
                # deterministic — a cached RESEND re-ships identical
                # bytes — and fresh per round for the stochastic
                # rounding / mask expansion.
                payload, residual = codec.encode(
                    jax.device_get(delta), carry,
                    wire_codec.frame_seed(self.cfg.seed, self.epoch,
                                          self.round_idx, c))
                out.add(wire_codec.CODEC_KEY, codec.name)
            else:
                rng_c = jax.random.fold_in(rng, 0xC0)
                payload, residual = self._compressor.encode(delta, carry,
                                                            rng_c)
                out.add("compression", self._compressor.name)
            self._ef_state = (self.round_idx, c, residual)
            out.add(MSG_ARG_KEY_MODEL_PARAMS, payload)
            out.add(wire_codec.DELTA_KEY, True)
        else:
            out.add(MSG_ARG_KEY_MODEL_PARAMS, jax.device_get(net))
            out.add(wire_codec.DELTA_KEY, False)
        if tr.enabled:
            # delta + encode (or the plain device_get) — the client half
            # of the upload lifecycle, correlated with the server's
            # ingest.decode/ingest.fold spans by (epoch, round, sender).
            tr.complete("client.serialize", t_ser, cat="client", corr=ck,
                        client=c)
        out.add(MSG_ARG_KEY_NUM_SAMPLES, int(self.train_fed.counts[c]))
        out.add("round", self.round_idx)
        out.add("epoch", self.epoch)
        if masked:
            # The masked run's contract is "the server learns only the
            # sum" — a clear per-client train loss alongside would leak
            # exactly the per-client signal the masks hide (same rule
            # as DP below).
            pass
        elif not (self.cfg.dp_clip and self.cfg.dp_clip > 0):
            # Under DP-SGD the exact train loss is an un-noised function of
            # the private examples; releasing it would void the accounted
            # (eps, delta). Only the noised model leaves the silo.
            out.add("train_loss", float(loss))
        self._last_upload = out
        self.send_message(out)


def build_federation_setup(model, train_fed: FederatedArrays, test_global,
                           cfg: FedConfig, backend: str, loss_fn,
                           chaos: Optional[ChaosSpec] = None,
                           loopback_wire: str = "none",
                           pretrained_params=None,
                           extra_ranks: int = 0):
    """Shared worker-process scaffolding for the message-passing
    federations (sync FedAvg here, async in fedasync.py): model fns +
    initial net, jitted local trainer / eval, and the backend ``args``
    shim (``chaos`` installs a fleet-wide ChaosTransport wrapper;
    ``loopback_wire`` makes the LOOPBACK backend round-trip every message
    through that real wire format — bytes in the inboxes, ByteLedger
    counters live — so single-host drills measure bytes-on-wire and
    exercise the full serialize path).

    ``pretrained_params`` warm-starts the federation from a dense
    checkpoint's param tree (the finetuning story): dense mode replaces
    ``net0.params`` (structure-checked); adapter mode
    (``cfg.adapter_rank > 0``) freezes it as the BASE while the
    adapters keep their exact-identity init.

    ``extra_ranks`` widens the rank space for non-worker processes — the
    sharded aggregation plane's M aggregator shards at ranks ``1..M``
    (comm/shardplane.py), with workers shifted to ``M+1..size-1``.
    Returns ``(size, net0, local_train, eval_fn, args)``."""
    size = cfg.client_num_per_round + 1 + int(extra_ranks)
    if getattr(cfg, "compute_layout", "none") not in ("none", ""):
        # The message-passing tiers build their local trainer here,
        # outside FedAvgAPI._build_local_train where the lane-fill
        # layout is wired — refuse loudly rather than leave the flag
        # silently inert (the PR 4 convention).
        raise NotImplementedError(
            f"cfg.compute_layout={cfg.compute_layout!r} is a simulator-"
            "tier capability (FedAvgAPI family); the distributed "
            "message-passing tiers do not wire it yet")
    if getattr(cfg, "client_step_dtype", "fp32") not in ("fp32", ""):
        # Same convention for the bf16 client step: this tier's local
        # trainer is built below from the fp32 fns.
        raise NotImplementedError(
            f"cfg.client_step_dtype={cfg.client_step_dtype!r} is a "
            "simulator-tier capability (FedAvgAPI family); the "
            "distributed message-passing tiers train fp32")
    if getattr(cfg, "group_reduce", False):
        # The message-passing servers aggregate on host (per-upload
        # fold); there is no mesh collective to shrink.
        raise NotImplementedError(
            "cfg.group_reduce shrinks the client-MESH collective "
            "(parallel/shard.py); the message-passing tiers aggregate "
            "on the server host — drop the flag")
    adapter_holder = None
    if int(getattr(cfg, "adapter_rank", 0) or 0):
        # Frozen-base adapter finetuning (PR 15, models/adapter.py): the
        # federation's net — on the wire, in the server accumulator, in
        # the codecs' tree_spec — is the ADAPTER tree alone. The base is
        # initialized deterministically once per process and captured by
        # jit as device constants; it never crosses the wire, so
        # bytes/upload shrink by the rank ratio BEFORE any codec runs.
        # adapter_model_fns refuses a dense model loudly (an adapter
        # config silently training the dense arm is the drift the
        # reject_adapter_flags convention exists to prevent).
        from fedml_tpu.models.adapter import adapter_model_fns

        adapter_holder = {}
        fns = adapter_model_fns(model, holder=adapter_holder,
                                base_params=pretrained_params)
    else:
        fns = model_fns(model)
    sample_x = jnp.zeros((1,) + train_fed.x.shape[3:], train_fed.x.dtype)
    net0 = fns.init(jax.random.PRNGKey(cfg.seed), sample_x)
    if pretrained_params is not None and adapter_holder is None:
        # Dense warm start: swap the checkpoint's params in for the
        # fresh init's (same structure or refuse — a silently reshaped
        # warm start would train the wrong geometry).
        want = jax.tree.structure(net0.params)
        got = jax.tree.structure(pretrained_params)
        if want != got:
            raise ValueError(
                f"pretrained_params structure {got} does not match the "
                f"model's param tree {want}")
        net0 = NetState(jax.tree.map(jnp.asarray, pretrained_params),
                        net0.model_state)
    # Exposed for adapter drills (frozen-base invariance pins): the
    # holder's "base" entry is the device-resident frozen tree.
    args_adapter_holder = adapter_holder
    optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
    local_train = jax.jit(
        make_local_train_fn_from_cfg(fns.apply, optimizer, cfg, loss_fn=loss_fn)
    )
    eval_fn = jax.jit(make_eval_fn(fns.apply, loss_fn=loss_fn)) if test_global else None

    class Args:
        pass

    args = Args()
    args.chaos = chaos
    # None for dense federations; adapter mode's {"base": frozen tree}
    # — drills pin the base's bitwise invariance through it, and the
    # runners stamp it onto the returned server/aggregator.
    args.adapter_holder = args_adapter_holder
    if backend == "LOOPBACK":
        args.network = LoopbackNetwork(size, wire=loopback_wire)
    elif backend == "SIM":
        # Virtual-clock fleet simulation: the FleetSimulator installs
        # args.network (a sim.transport.SimNetwork) and args.chaos_after
        # (the event-queue scheduler for ChaosTransport's timers) itself
        # before constructing the managers.
        pass
    elif backend in ("TCP", "GRPC", "TRPC"):
        # Single-host table on ephemeral ports: bind rank servers first
        # (port 0), then share the resolved table. Multi-host deployments
        # pass an explicit host_table / grpc_ipconfig.csv instead.
        args.host_table = {r: ("127.0.0.1", 0) for r in range(size)}
    return size, net0, local_train, eval_fn, args


def FedML_FedAvg_distributed(
    model,
    train_fed: FederatedArrays,
    test_global,
    cfg: FedConfig,
    backend: str = "LOOPBACK",
    loss_fn=softmax_ce,
    compress: str = "none",
    aggregate_k: int = 0,
    *,
    wire_codec: str = "none",
    loopback_wire: str = "none",
    aggregator: str = "mean",
    chaos: Optional[ChaosSpec] = None,
    checkpoint_dir: Optional[str] = None,
    metrics=None,
    idle_timeout_s: float = 0.0,
    trace_dir: Optional[str] = None,
    pretrained_params=None,
    agg_shards: int = 0,
    directory=None,
    controller=None,
):
    """Build server + ``client_num_per_round`` workers on the chosen backend
    and run the full federation (FedAvgAPI.py:20 analogue). Returns the
    aggregator (global model + test history).

    ``compress``: legacy on-device update compression for the
    client→server uploads — ``none`` | ``topk<ratio>`` (error feedback) |
    ``q<bits>`` (stochastic quantization); see fedml_tpu.core.compression.

    ``wire_codec``: the NEGOTIATED wire codec (comm/codec.py) — ``none``
    | ``bf16`` | ``fp16`` | ``int8`` | ``topk<ratio>`` |
    ``randmask<ratio>``, composable as ``sparsifier+value`` (e.g.
    ``topk0.01+int8``); sparsifiers carry per-client error feedback.
    Mutually exclusive with ``compress``. ``loopback_wire`` round-trips
    loopback messages through a real wire format (bytes + ByteLedger).

    ``aggregator``: server reduction (core/robust_agg spec). ``mean``
    keeps the O(model) accumulate-on-arrival streaming ingest; non-mean
    robust aggregators retain the stack-then-reduce cohort buffer.

    ``aggregate_k``: straggler-tolerant first-k rounds (0 = wait for all
    workers; see FedAVGServerManager).

    Control plane (docs/ROBUSTNESS.md): ``cfg.round_timeout_s`` arms the
    eviction watchdog, ``cfg.heartbeat_interval_s`` the worker beats,
    ``cfg.checkpoint_every`` + ``checkpoint_dir`` crash-resume, ``chaos``
    a fleet-wide fault-injecting transport wrapper, ``metrics`` a
    MetricsLogger for per-round health counters, ``idle_timeout_s`` the
    workers' no-server-contact self-termination bound.

    ``trace_dir`` arms the federation flight recorder (obs/trace.py; the
    ``cfg.trace``/``--trace`` CLI flag resolves to it): a span tracer is
    installed for the run and ``trace.chrome.json`` (Perfetto /
    ``chrome://tracing`` loadable) + ``trace.jsonl`` are dumped there,
    and the server's flight-recorder ring lands there on eviction /
    abort / codec refusal. ``None`` (the default) is the no-op path.

    ``agg_shards`` = M > 0 stands up the SHARDED aggregation plane
    (comm/shardplane.py): M aggregator-shard processes at ranks ``1..M``
    ingest the uploads (workers shifted to ``M+1..``), and the rank-0
    coordinator wire-merges their int64 fixed-point partials bit-equal to
    the single-process IngestPool path. ``directory`` (an optional
    data.directory.ClientDirectory) folds data-shard locality into the
    client→shard routing."""
    M = int(agg_shards or (getattr(cfg, "agg_shards", 0) or 0))
    size, net0, local_train, eval_fn, args = build_federation_setup(
        model, train_fed, test_global, cfg, backend, loss_fn, chaos=chaos,
        loopback_wire=loopback_wire, pretrained_params=pretrained_params,
        extra_ranks=M)
    agg = FedAVGAggregator(net0, size - 1 - M, cfg, eval_fn, test_global,
                           aggregator=aggregator)
    shards = []
    if M > 0:
        from fedml_tpu.comm.shardplane import (AggregatorShardManager,
                                               ShardedFedAVGServerManager)

        server = ShardedFedAVGServerManager(
            args, agg, cfg, size, M, backend=backend,
            aggregate_k=aggregate_k, checkpoint_dir=checkpoint_dir,
            metrics=metrics, flight_dir=trace_dir, directory=directory)
        shards = [
            AggregatorShardManager(args, rank, size, cfg, net0,
                                   backend=backend)
            for rank in range(1, M + 1)
        ]
    else:
        server = FedAVGServerManager(args, agg, cfg, size, backend=backend,
                                     compress=compress,
                                     aggregate_k=aggregate_k,
                                     checkpoint_dir=checkpoint_dir,
                                     metrics=metrics, flight_dir=trace_dir)
    if controller is not None:
        # Adaptive control (fedml_tpu.ctrl): steps from the server's
        # between-rounds boundary; the same object may have been tuned
        # in the fleet simulator first.
        server.attach_controller(controller)
    clients = [
        FedAVGClientManager(args, rank, size, train_fed, local_train, cfg,
                            backend=backend, compress=compress,
                            wire_codec_spec=wire_codec,
                            idle_timeout_s=idle_timeout_s)
        for rank in range(M + 1, size)
    ]
    with obs_trace.tracing_to(trace_dir):
        run_workers([server.run] + [sh.run for sh in shards]
                    + [c.run for c in clients])
    # Post-run observability: the managers are finished but callers (the
    # wire_codec bench A/B, drill tests) still need the control-plane
    # counters, ByteLedger totals and the ingest latency profile — stamp
    # the final snapshots onto the returned aggregator.
    agg.final_health = server.health()
    agg.ingest_profile = server.ingest_profile()
    agg.adapter_holder = args.adapter_holder
    return agg
