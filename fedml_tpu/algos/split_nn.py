"""Split learning (SplitNN) — one model cut across clients and a server.

Parity target: reference fedml_api/distributed/split_nn/ —
- each client owns the BOTTOM net and its optimizer (SGD momentum 0.9,
  wd 5e-4; client.py:18-19), the server owns the shared TOP net;
- clients take turns in a relay ring, one local epoch per turn
  (client_manager.py:35-65: semaphore passes to ``node_right`` after eval);
- per minibatch the activations+labels go up and the activation gradients
  come back (server.py:40-61) — the tightest inter-process loop in the
  reference (SURVEY.md §3.3).

TPU-native redesign: the per-batch act/grad exchange is the *definition* of
backprop through the cut, so on one program it is a joint
``jax.grad`` over (bottom_c, top) — mathematically identical to the wire
protocol, with zero host round-trips. The sequential relay (server top is
updated between clients — order matters) becomes a ``lax.scan`` over the
client axis carrying (top, opt_top); client bottoms and their momentum
stay stacked ``[C, ...]`` and are scatter-updated via ``.at[c].set``.

For true cross-silo splits (separate trust domains) the message-passing
variant rides fedml_tpu.comm with ACTS/GRADS/SEMAPHORE message types
(split_nn/message_define.py parity).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.core.tree import tree_select
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.trainer.local import NetState, model_fns, softmax_ce


from fedml_tpu.algos.capability import ExcludedScanTiers


class SplitNNAPI(ExcludedScanTiers):
    """Relay-ring split learning over a packed federated dataset.

    Carry capability record: excluded — see ``window_exclusion``.

    ``client_model``: module whose ``__call__(x, train)`` returns the cut
    activations. ``server_model``: module mapping activations → logits.
    One ``train_one_epoch`` = one full relay cycle (every client trains one
    local epoch, in ring order). ``cfg.epochs`` cycles ≈ the reference's
    MAX_EPOCH_PER_NODE."""

    window_protocol = None
    window_exclusion = (
        "split learning trains ONE model cut across two trust domains "
        "with a sequential relay ring (the server top updates between "
        "clients, order-dependent) — there is no per-round cohort fold "
        "to publish as a (carry_init, server_update, carry_commit) "
        "record")

    def __init__(self, client_model, server_model, train_fed: FederatedArrays,
                 test_global, cfg: FedConfig, loss_fn=softmax_ce):
        self.cfg = cfg
        self.train_fed = train_fed
        self.test_global = test_global
        self.client_fns = model_fns(client_model)
        self.server_fns = model_fns(server_model)
        self.loss_fn = loss_fn

        n_clients = int(train_fed.x.shape[0])
        self.n_clients = n_clients

        # Reference hardcodes client SGD(lr=0.1, momentum=0.9, wd=5e-4)
        # (client.py:18); we take lr from cfg and keep the rest.
        self.opt = optax.chain(
            optax.add_decayed_weights(5e-4),
            optax.sgd(cfg.lr, momentum=0.9),
        )

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, crng, srng = jax.random.split(rng, 3)
        sample_x = np.asarray(train_fed.x[0, 0])
        # Per-client bottoms: stacked init (each client its own weights).
        self.client_nets = jax.vmap(
            lambda r: self.client_fns.init(r, sample_x)
        )(jax.random.split(crng, n_clients))
        sample_acts, _ = self.client_fns.apply(
            jax.tree.map(lambda a: a[0], self.client_nets), sample_x
        )
        self.server_net = self.server_fns.init(srng, np.asarray(sample_acts))
        self.client_opts = jax.vmap(
            lambda _: self.opt.init(
                jax.tree.map(lambda a: a[0], self.client_nets).params)
        )(jnp.arange(n_clients))
        self.server_opt = self.opt.init(self.server_net.params)

        self.cycle_fn = jax.jit(self._build_cycle())
        self.eval_fn = jax.jit(self._build_eval())

    def _build_cycle(self):
        client_apply, server_apply = self.client_fns.apply, self.server_fns.apply
        opt, loss_fn = self.opt, self.loss_fn

        def one_batch(carry, inputs):
            bottom, opt_b, top, opt_t = carry
            xb, yb, mb, rng = inputs

            def joint_loss(bp, tp):
                acts, b_state = client_apply(
                    NetState(bp, bottom.model_state), xb, train=True, rng=rng)
                logits, t_state = server_apply(
                    NetState(tp, top.model_state), acts, train=True, rng=rng)
                per = loss_fn(logits, yb)
                return (jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0),
                        (b_state, t_state))

            (loss, (b_state, t_state)), (gb, gt) = jax.value_and_grad(
                joint_loss, argnums=(0, 1), has_aux=True)(
                    bottom.params, top.params)
            ub, opt_b2 = opt.update(gb, opt_b, bottom.params)
            ut, opt_t2 = opt.update(gt, opt_t, top.params)
            nonempty = jnp.sum(mb) > 0
            bottom = tree_select(
                nonempty,
                NetState(optax.apply_updates(bottom.params, ub), b_state),
                bottom)
            top = tree_select(
                nonempty,
                NetState(optax.apply_updates(top.params, ut), t_state), top)
            opt_b = tree_select(nonempty, opt_b2, opt_b)
            opt_t = tree_select(nonempty, opt_t2, opt_t)
            return (bottom, opt_b, top, opt_t), (loss, jnp.sum(mb))

        def one_client(carry, inputs):
            client_nets, client_opts, top, opt_t = carry
            c, xc, yc, mc, rng = inputs  # xc: [S, B, ...]
            bottom = jax.tree.map(lambda a: a[c], client_nets)
            opt_b = jax.tree.map(lambda a: a[c], client_opts)
            steps = xc.shape[0]
            (bottom, opt_b, top, opt_t), (losses, ns) = jax.lax.scan(
                one_batch, (bottom, opt_b, top, opt_t),
                (xc, yc, mc, jax.random.split(rng, steps)))
            client_nets = jax.tree.map(
                lambda stack, new: stack.at[c].set(new), client_nets, bottom)
            client_opts = jax.tree.map(
                lambda stack, new: stack.at[c].set(new), client_opts, opt_b)
            loss = jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)
            return (client_nets, client_opts, top, opt_t), loss

        def cycle(client_nets, client_opts, top, opt_t, x, y, mask, rng):
            n = x.shape[0]
            carry = (client_nets, client_opts, top, opt_t)
            carry, losses = jax.lax.scan(
                one_client, carry,
                (jnp.arange(n), x, y, mask, jax.random.split(rng, n)))
            return carry, jnp.mean(losses)

        return cycle

    def _build_eval(self):
        client_apply, server_apply = self.client_fns.apply, self.server_fns.apply
        loss_fn = self.loss_fn

        def eval_one(bottom, top, x, y, mask):
            def step(_, inputs):
                xb, yb, mb = inputs
                acts, _ = client_apply(bottom, xb, train=False)
                logits, _ = server_apply(top, acts, train=False)
                per = loss_fn(logits, yb)
                correct = (jnp.argmax(logits, -1) == yb).astype(jnp.float32)
                return None, (jnp.sum(per * mb), jnp.sum(correct * mb),
                              jnp.sum(mb))

            _, (l, c, n) = jax.lax.scan(step, None, (x, y, mask))
            n = jnp.maximum(jnp.sum(n), 1.0)
            return jnp.sum(l) / n, jnp.sum(c) / n

        def eval_all(client_nets, top, x, y, mask):
            losses, accs = jax.vmap(
                eval_one, in_axes=(0, None, None, None, None)
            )(client_nets, top, x, y, mask)
            return jnp.mean(losses), jnp.mean(accs)

        return eval_all

    def train_one_epoch(self, epoch_idx: int) -> Dict[str, float]:
        """One relay cycle: every client trains one epoch, ring order."""
        self.rng, rng = jax.random.split(self.rng)
        (self.client_nets, self.client_opts, self.server_net,
         self.server_opt), loss = self.cycle_fn(
            self.client_nets, self.client_opts, self.server_net,
            self.server_opt, self.train_fed.x, self.train_fed.y,
            self.train_fed.mask, rng)
        return {"epoch": epoch_idx, "train_loss": float(loss)}

    def train(self):
        return [self.train_one_epoch(e) for e in range(self.cfg.epochs)]

    def evaluate(self) -> Dict[str, float]:
        if self.test_global is None:
            return {}
        x, y, mask = self.test_global
        loss, acc = self.eval_fn(self.client_nets, self.server_net, x, y, mask)
        return {"loss": float(loss), "accuracy": float(acc)}
