"""FedAdapter — parameter-efficient federated finetuning of a frozen-base
transformer with low-rank (LoRA-style) adapters.

The cross-device LLM scenario the reference predates (ROADMAP item 3;
FedNLP arXiv:2104.08815, low-rank updates arXiv:2108.06098): the base
transformer is FROZEN — initialized once, device-resident once, fp32
bitwise-unchanged across rounds (test-pinned) — and the federated net IS
the adapter tree. Every layer of the existing machinery then applies
unchanged to a model that is smaller by the rank ratio:

- the jitted client step trains only the adapters (the optimizer inits
  on the adapter tree; gradients never materialize base-param updates),
- aggregation / the fused donated round / the windowed scan / the
  on-device scan all carry the adapter tree (``window_protocol =
  "round"`` with no extra carry — the capability record derives every
  scan tier structurally, PR 13),
- uploads on the message-passing tiers are adapter-only deltas that ride
  the negotiated ``topk+int8`` error-feedback codec path
  (``build_federation_setup`` builds the same adapter-level fns from
  ``cfg.adapter_rank``; the delta capability is negotiated per
  connection — comm/codec.py ``DELTA_OK_KEY``),
- per-client PERSONALIZED adapter state lives host-side in a
  :class:`~fedml_tpu.models.adapter.PersonalAdapterStore` (``[N, D]``
  float32, memmap-spillable) — ditto-style interpolation toward the
  global adapters plus a local finetune, so million-client
  personalization is the storage problem ``ClientDirectory`` /
  ``ShardedFederatedStore`` already solved (PR 7).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState, softmax_ce

#: fold_in child reserved for the personalization pass's per-client rng
#: streams (disjoint from the trainer's slot streams, the transform's
#: 0x7F, the corruptor's 0xC0 and ditto's 0xD1770).
_PERSONAL_TAG = 0xADA77


class FedAdapterAPI(FedAvgAPI):
    """FedAvg over the ADAPTER tree of a frozen-base transformer.

    ``model`` must be built with adapters injected (``create_model(
    "transformer_lm", adapter_rank=r, adapter_scope=...)``); the
    constructor refuses dense models loudly instead of silently training
    the dense arm. ``self.net`` is the adapter tree; ``self.base`` the
    frozen base params (never trained, never uploaded, never donated —
    jit captures it once as device constants).

    Rides fused / pipelined / windowed / on-device execution day one via
    the derived carry capability record ("round" protocol, no carry).
    Personalization: :meth:`personalize_cohort` runs the ditto-style
    interpolated local finetune for a cohort and persists the result in
    the host-side personal store; :meth:`evaluate_personalized` reports
    the personalized-vs-global quality gap."""

    capability_name = "FedAdapter"
    window_carry = "— (adapter tree is the net; base frozen off-scan)"
    supports_streaming = True
    window_protocol = "round"
    _consumes_adapter_cfg = True

    def __init__(self, model, train_fed, test_global, cfg, mesh=None,
                 loss_fn=softmax_ce, pad_id: int = 0,
                 nan_guard: bool = False, personal_interp: float = 0.5,
                 personal_spill_dir: Optional[str] = None,
                 base_params=None):
        if getattr(cfg, "compute_layout", "none") not in ("none", ""):
            raise NotImplementedError(
                "cfg.compute_layout pads the trainable tree, but the "
                "FedAdapter net is the ADAPTER tree while the compute "
                "runs through the merged full model — the lane-fill "
                "twin cannot apply; run the logical layout")
        if getattr(cfg, "client_step_dtype", "fp32") not in ("fp32", ""):
            raise NotImplementedError(
                "cfg.client_step_dtype clones the model handed to "
                "_build_local_train, which for FedAdapter is the merged "
                "frozen-base apply, not a flax module — build the model "
                "with dtype='bf16' instead (the adapter tree stays fp32)")
        if mesh is not None:
            raise NotImplementedError(
                "FedAdapterAPI keeps the frozen base as a jit-captured "
                "constant, which the client-mesh shard_map round does "
                "not thread; run the single-device vmap simulator or "
                "the message-passing tiers (cfg.adapter_rank there)")
        if not 0.0 <= personal_interp <= 1.0:
            raise ValueError(
                f"personal_interp must be in [0, 1], got {personal_interp}")
        self._adapter_holder: dict = {}
        #: Optional PRETRAINED dense params to freeze as the base (the
        #: finetuning story); None = the deterministic fresh init.
        self._base_params = base_params
        super().__init__(model, train_fed, test_global, cfg, mesh=mesh,
                         loss_fn=loss_fn, pad_id=pad_id, nan_guard=nan_guard)
        #: The frozen base params — everything the clients never train.
        #: Pinned fp32-bitwise-invariant across rounds by tests.
        self.base = self._adapter_holder["base"]
        self.personal_interp = float(personal_interp)
        self._personal_spill_dir = personal_spill_dir
        self._personal_store = None
        self._personal_train_jit = None
        self._personal_eval_jit = None

    def _model_fns(self, model):
        from fedml_tpu.models.adapter import adapter_model_fns

        return adapter_model_fns(model, holder=self._adapter_holder,
                                 base_params=self._base_params)

    def _on_client_lr_change(self):
        self._personal_train_jit = None  # bakes in the live optimizer/lr

    # -- introspection ----------------------------------------------------
    def adapter_profile(self) -> Dict[str, float]:
        """The rank-ratio story in numbers: trainable adapter params vs
        the frozen base, and the wire-relevant ratio (uploads carry the
        adapter tree only)."""
        from fedml_tpu.models.adapter import param_count

        a = param_count(self.net.params)
        b = param_count(self.base)
        return {"adapter_params": a, "base_params": b,
                "total_params": a + b,
                "adapter_ratio": a / max(a + b, 1)}

    # -- personalization (ditto-style interpolation + local finetune) -----
    def personal_store(self):
        from fedml_tpu.models.adapter import PersonalAdapterStore

        if self._personal_store is None:
            self._personal_store = PersonalAdapterStore(
                self.cfg.client_num_in_total, self.net.params,
                spill_dir=self._personal_spill_dir)
        return self._personal_store

    def _personal_train_fn(self):
        """Cached jitted vmapped local adapter finetune over a cohort —
        the SAME local step the federated round runs (epochs, masking,
        prefix-stable rng streams), vmapped over per-client starting
        adapters."""
        fn = self._personal_train_jit
        if fn is None:
            local_train = self.local_train

            def rounds(nets, x, y, mask, rngs):
                return jax.vmap(local_train)(nets, x, y, mask, rngs)

            fn = self._personal_train_jit = jax.jit(rounds)
        return fn

    def personalize_cohort(self, clients, seed: int = 0) -> np.ndarray:
        """One personalization pass for ``clients``: start each client
        from the ditto-style interpolation ``interp * global + (1 -
        interp) * personal`` (never-personalized clients start at the
        global), run the standard local adapter finetune on the client's
        own shard, and persist the trained adapters in the personal
        store. Returns the per-client training losses."""
        store = self.personal_store()
        idx = np.asarray(clients, np.int64)
        lam = self.personal_interp
        gvec = store.vec_of(self.net.params)
        start = (1.0 - lam) * store.gather(idx, self.net.params) + \
            lam * gvec[None]
        sub = _gather_shards(self.train_fed, idx)
        nets = _stack_netstates(
            [NetState(store.tree_of(v), self.net.model_state)
             for v in start])
        base = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed),
                                  _PERSONAL_TAG)
        base = jax.random.fold_in(base, seed)
        rngs = jnp.stack([jax.random.fold_in(base, int(c)) for c in idx])
        trained, losses = self._personal_train_fn()(
            nets, sub.x, sub.y, sub.mask, rngs)
        trained_np = np.stack(
            [store.vec_of(jax.tree.map(lambda l, i=i: np.asarray(l[i]),
                                       trained.params))
             for i in range(len(idx))])
        store.scatter(idx, trained_np)
        return np.asarray(losses)

    def _personal_eval_fn(self):
        fn = self._personal_eval_jit
        if fn is None:
            fn = self._personal_eval_jit = jax.jit(jax.vmap(
                lambda net, x, y, mask: self.eval_fn(net, x, y, mask)))
        return fn

    def evaluate_personalized(self, arrays=None, clients=None,
                              chunk: int = 256) -> Dict[str, float]:
        """Sample-weighted per-client quality of the PERSONALIZED
        adapters vs the global adapters on each client's shard.
        ``arrays`` defaults to the training shards; pass per-client
        HELD-OUT arrays for the honest personalization delta (the bench
        does). Clients never personalized evaluate at the global (their
        stored state IS the global default)."""
        f = arrays if arrays is not None else self.train_fed
        store = self.personal_store()
        per = self._personal_eval_fn()
        n = int(getattr(f, "num_clients", None) or np.asarray(f.x).shape[0])
        ids = (np.asarray(clients, np.int64) if clients is not None
               else np.arange(n, dtype=np.int64))
        tot = {"p_acc": 0.0, "p_loss": 0.0, "g_acc": 0.0, "g_loss": 0.0,
               "n": 0.0}
        for lo in range(0, len(ids), chunk):
            idx = ids[lo:lo + chunk]
            sub = _gather_shards(f, idx)
            vecs = store.gather(idx, self.net.params)
            nets = _stack_netstates(
                [NetState(store.tree_of(v), self.net.model_state)
                 for v in vecs])
            pm = per(nets, sub.x, sub.y, sub.mask)
            gm = self._per_client_eval()(self.net, sub.x, sub.y, sub.mask)
            num = np.asarray(pm["num"])
            tot["p_acc"] += float((np.asarray(pm["accuracy"]) * num).sum())
            tot["p_loss"] += float((np.asarray(pm["loss"]) * num).sum())
            tot["g_acc"] += float((np.asarray(gm["accuracy"]) * num).sum())
            tot["g_loss"] += float((np.asarray(gm["loss"]) * num).sum())
            tot["n"] += float(num.sum())
        n = max(tot["n"], 1.0)
        return {
            "personal_accuracy": tot["p_acc"] / n,
            "personal_loss_eval": tot["p_loss"] / n,
            "global_local_accuracy": tot["g_acc"] / n,
            "global_local_loss": tot["g_loss"] / n,
            "personalized_delta": (tot["p_acc"] - tot["g_acc"]) / n,
        }

    # -- checkpoint/resume: personal adapter stacks are run state ---------
    def checkpoint_extra_state(self):
        extra = dict(super().checkpoint_extra_state())
        # Only persist the personal store if one was ever materialized —
        # personal_store() ALLOCATES the full [N, D] stack (or creates
        # the memmap spill file), which a never-personalized run must
        # not pay at every checkpoint; restore tolerates the absent key.
        if self._personal_store is not None:
            extra.update(self._personal_store.state_dict())
        return extra

    def load_checkpoint_extra_state(self, extra) -> None:
        super().load_checkpoint_extra_state(extra)
        if extra and "personal_vecs" in extra:
            self.personal_store().load_state_dict(extra)


def _gather_shards(fed, idx):
    """The cohort's ``[k, S, B, ...]`` shards from either layout: a
    host store (``gather_cohort``) or resident ``FederatedArrays``
    (device gather)."""
    if hasattr(fed, "gather_cohort"):
        return fed.gather_cohort(np.asarray(idx))
    from fedml_tpu.data.batching import gather_clients

    return gather_clients(fed, jnp.asarray(np.asarray(idx)))


def _stack_netstates(nets) -> NetState:
    """[NetState] → one NetState with stacked ``[k, ...]`` leaves (vmap
    layout). Host-side numpy stack — the cohorts here are small."""
    params = jax.tree.map(lambda *ls: jnp.stack(
        [jnp.asarray(l) for l in ls]), *[n.params for n in nets])
    return NetState(params, nets[0].model_state)
