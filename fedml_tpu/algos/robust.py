"""FedAvg with robust aggregation (backdoor defenses) + attack harness.

Parity: fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py —
per-client norm-difference clipping before the weighted average (:179-185)
and weak-DP Gaussian noise on the aggregate (:202-205), both built on
fedml_core/robustness/robust_aggregation.py. Clipping applies to trainable
params only; BatchNorm stats are excluded structurally (they live in
``NetState.model_state``), mirroring the reference's ``is_weight_param``
filter.

The ATTACK side of the reference's harness is here too: with
``cfg.attack_freq = k`` the adversary client(s) — whose data shards the
caller poisons via ``data.loaders.edge_case.make_backdoor_dataset`` — are
forced into the training cohort every k-th round (the reference's
poisoned worker joining every ``attack_freq`` rounds,
main_fedavg_robust.py:120), and :func:`attack_success_rate` measures the
model on a targeted test set (``test_target_accuracy``,
FedAvgRobustAggregator.py:270). tests/test_backdoor.py composes the two
and shows clipping+noise actually suppressing the attack.
"""

from __future__ import annotations

import jax
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.robustness import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.trainer.local import NetState


def attack_success_rate(api, x_targeted, y_target, batch_size: int = 128):
    """Accuracy of the CURRENT global model on a targeted test set
    (triggered inputs labelled with the attack target — e.g. from
    ``make_targeted_test_set``): by construction this equals the backdoor
    attack success rate (FedAvgRobustAggregator.test_target_accuracy)."""
    from fedml_tpu.data.batching import batch_global

    xt, yt, mask = batch_global(np.asarray(x_targeted), np.asarray(y_target),
                                batch_size)
    m = api.eval_fn(api._eval_net(), xt, yt, mask)
    return float(m["accuracy"])


class FedAvgRobustAPI(FedAvgAPI):
    def __init__(self, *args, adversary_clients=None, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.cfg
        if getattr(cfg, "attack_freq", 0) and adversary_clients is None:
            k = max(1, int(getattr(cfg, "attack_num_adversaries", 1)))
            if k > cfg.client_num_in_total:
                # A negative id here would silently gather client 0's
                # (honest) shard — fail loudly instead.
                raise ValueError(
                    f"attack_num_adversaries={k} exceeds "
                    f"client_num_in_total={cfg.client_num_in_total}")
            adversary_clients = range(cfg.client_num_in_total - k,
                                      cfg.client_num_in_total)
        self.adversary_clients = np.asarray(
            list(adversary_clients) if adversary_clients is not None else [],
            np.int64)
        if cfg.compress and cfg.compress != "none":
            # This class replaces the client-transform hook with norm
            # clipping; accepting cfg.compress here would silently drop
            # the compression the user asked for.
            raise ValueError(
                "FedAvgRobustAPI's client transform is the robust norm "
                "clip; combining it with simulated compression is not "
                "supported — drop cfg.compress or use plain FedAvg")
        self._noise = jax.jit(
            lambda p, r: add_gaussian_noise(p, r, cfg.robust_stddev)
        )

    def _sample_round_uncached(self, round_idx: int):
        """On every ``attack_freq``-th round, force the adversary
        client(s) into the cohort (replacing honestly-sampled slots);
        other rounds sample exactly as the parent does."""
        idx, wmask = super()._sample_round_uncached(round_idx)
        freq = getattr(self.cfg, "attack_freq", 0)
        if (not freq or self.adversary_clients.size == 0
                or round_idx % freq != 0):
            return idx, wmask
        from fedml_tpu.core.sampling import pad_to_multiple

        active = np.asarray(idx)[np.asarray(wmask) > 0]
        adv = self.adversary_clients
        n_adv = min(len(adv), len(active))
        # Evict UNIFORMLY at random (seeded by the round, like
        # sample_clients): truncating np.setdiff1d's sorted output would
        # deterministically evict the highest-id honest clients on every
        # attack round — a systematic participation bias. Order-based
        # truncation is no better: selection policies like oort return
        # id-sorted cohorts, where sample order IS id order.
        honest = active[np.isin(active, adv, invert=True)]
        rs = np.random.RandomState(round_idx)
        keep = rs.choice(honest, size=min(len(honest),
                                          len(active) - n_adv),
                         replace=False) if len(honest) else honest
        cohort = np.sort(np.concatenate([keep, adv[:n_adv]])).astype(
            np.asarray(idx).dtype)
        return pad_to_multiple(cohort, self.n_shards)

    def _client_transform(self):
        cfg = self.cfg

        def clip(global_net, client_net):
            clipped = norm_diff_clipping(
                client_net.params, global_net.params, cfg.robust_norm_bound
            )
            return NetState(clipped, client_net.model_state)

        return clip

    def _server_update(self, old_net, avg_net):
        if self.cfg.robust_stddev > 0:
            self.rng, sub = jax.random.split(self.rng)
            return NetState(
                self._noise(avg_net.params, sub), avg_net.model_state
            )
        return avg_net
