"""FedAvg with robust aggregation (backdoor defenses) + attack harness.

Parity: fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py —
per-client norm-difference clipping before the weighted average (:179-185)
and weak-DP Gaussian noise on the aggregate (:202-205), both built on
fedml_core/robustness/robust_aggregation.py. Clipping applies to trainable
params only; BatchNorm stats are excluded structurally (they live in
``NetState.model_state``), mirroring the reference's ``is_weight_param``
filter.

The ATTACK side of the reference's harness is here too: with
``cfg.attack_freq = k`` the adversary client(s) — whose data shards the
caller poisons via ``data.loaders.edge_case.make_backdoor_dataset`` — are
forced into the training cohort every k-th round (the reference's
poisoned worker joining every ``attack_freq`` rounds,
main_fedavg_robust.py:120), and :func:`attack_success_rate` measures the
model on a targeted test set (``test_target_accuracy``,
FedAvgRobustAggregator.py:270). tests/test_backdoor.py composes the two
and shows clipping+noise actually suppressing the attack.

Beyond reference parity, this class is now the algorithm layer of the
Byzantine-robustness stack (docs/ROBUSTNESS.md):

- ``cfg.aggregator`` (inherited from FedAvgAPI) swaps the server
  reduction for a robust one — coord_median / trimmed_mean / krum /
  geometric_median (``core/robust_agg``) — composable with the norm
  clip this class installs as its client transform;
- ``cfg.corrupt_mode`` arms the DEVICE-SIDE corruption drill: the
  adversary clients' trained updates are corrupted inside the jitted
  round (``UpdateCorruptor.device_fn``, mask-driven), so
  attack-vs-defense drills run on every execution tier, including the
  windowed ``lax.scan``;
- the weak-DP noise stream is now keyed by ``fold_in`` on the ROUND's
  rng key instead of a carried ``self.rng`` split chain (the PR-2
  prefix-stability discipline), which is what lets robust runs ride
  ``train_rounds_windowed`` / ``train_rounds_pipelined`` bit-equal to
  the host loop instead of flooring at per-round dispatch RTT.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.robustness import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.trainer.local import NetState

#: fold_in constant reserving the weak-DP noise stream off each round's
#: rng key. The per-client training streams fork at the SAME level as
#: ``fold_in(round_key, slot)`` with slot ∈ [0, cohort) — so this tag
#: sits at the top of the int32 range, unreachable by any cohort slot
#: index (a small constant like 0x3D would be bit-identical to client
#: slot 61's stream root in a 62+-client round). The transform (0x7F)
#: and corruptor (0xC0) forks are second-level (folded on the per-client
#: key), so they cannot collide with this either.
_NOISE_TAG = 0x7FFFFF3D


def attack_success_rate(api, x_targeted, y_target, batch_size: int = 128):
    """Accuracy of the CURRENT global model on a targeted test set
    (triggered inputs labelled with the attack target — e.g. from
    ``make_targeted_test_set``): by construction this equals the backdoor
    attack success rate (FedAvgRobustAggregator.test_target_accuracy)."""
    from fedml_tpu.data.batching import batch_global

    xt, yt, mask = batch_global(np.asarray(x_targeted), np.asarray(y_target),
                                batch_size)
    m = api.eval_fn(api._eval_net(), xt, yt, mask)
    return float(m["accuracy"])


class FedAvgRobustAPI(FedAvgAPI):
    window_carry = ("— (round-keyed weak-DP noise; [W, C] adversary "
                    "mask rides the scanned aux slot)")

    def __init__(self, *args, adversary_clients=None, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.cfg
        armed = (getattr(cfg, "attack_freq", 0)
                 or getattr(cfg, "corrupt_mode", "none") != "none")
        if armed and adversary_clients is None:
            k = max(1, int(getattr(cfg, "attack_num_adversaries", 1)))
            if k > cfg.client_num_in_total:
                # A negative id here would silently gather client 0's
                # (honest) shard — fail loudly instead.
                raise ValueError(
                    f"attack_num_adversaries={k} exceeds "
                    f"client_num_in_total={cfg.client_num_in_total}")
            adversary_clients = range(cfg.client_num_in_total - k,
                                      cfg.client_num_in_total)
        self.adversary_clients = np.asarray(
            list(adversary_clients) if adversary_clients is not None else [],
            np.int64)
        if cfg.compress and cfg.compress != "none":
            # This class replaces the client-transform hook with norm
            # clipping; accepting cfg.compress here would silently drop
            # the compression the user asked for.
            raise ValueError(
                "FedAvgRobustAPI's client transform is the robust norm "
                "clip; combining it with simulated compression is not "
                "supported — drop cfg.compress or use plain FedAvg")
        self._noise = jax.jit(
            lambda p, r: add_gaussian_noise(p, r, cfg.robust_stddev)
        )

    def _sample_round_uncached(self, round_idx: int):
        """On every ``attack_freq``-th round, force the adversary
        client(s) into the cohort (replacing honestly-sampled slots);
        other rounds sample exactly as the parent does."""
        idx, wmask = super()._sample_round_uncached(round_idx)
        freq = getattr(self.cfg, "attack_freq", 0)
        if (not freq or self.adversary_clients.size == 0
                or round_idx % freq != 0):
            return idx, wmask
        from fedml_tpu.core.sampling import pad_to_multiple

        active = np.asarray(idx)[np.asarray(wmask) > 0]
        adv = self.adversary_clients
        n_adv = min(len(adv), len(active))
        # Evict UNIFORMLY at random (seeded by the round, like
        # sample_clients): truncating np.setdiff1d's sorted output would
        # deterministically evict the highest-id honest clients on every
        # attack round — a systematic participation bias. Order-based
        # truncation is no better: selection policies like oort return
        # id-sorted cohorts, where sample order IS id order.
        honest = active[np.isin(active, adv, invert=True)]
        rs = np.random.RandomState(round_idx)
        keep = rs.choice(honest, size=min(len(honest),
                                          len(active) - n_adv),
                         replace=False) if len(honest) else honest
        cohort = np.sort(np.concatenate([keep, adv[:n_adv]])).astype(
            np.asarray(idx).dtype)
        return pad_to_multiple(cohort, self.n_shards)

    def _client_transform(self):
        cfg = self.cfg

        def clip(global_net, client_net):
            clipped = norm_diff_clipping(
                client_net.params, global_net.params, cfg.robust_norm_bound
            )
            return NetState(clipped, client_net.model_state)

        return clip

    # --- device-side corruption drill (cfg.corrupt_mode) -----------------
    def _corruptor(self):
        """Build (once) the mask-driven device corruptor from
        ``cfg.corrupt_mode`` — consulted by the base round builders
        during ``set_client_lr`` (which runs inside ``super().__init__``,
        hence cfg-only: ``adversary_clients`` is not resolved yet; the
        MASKS are computed per round in :meth:`_round_aux` /
        :meth:`_window_scan_extras`, after construction finished)."""
        mode = getattr(self.cfg, "corrupt_mode", "none")
        if mode == "none":
            return None
        fn = getattr(self, "_device_corruptor", None)
        if fn is None:
            from fedml_tpu.core.faults import UpdateCorruptor

            fn = self._device_corruptor = UpdateCorruptor(
                mode, scale=self.cfg.corrupt_scale).device_fn()
        return fn

    def _adv_mask(self, idx, wmask) -> np.ndarray:
        """Host math: 1.0 at cohort slots held by an adversary client
        (padded slots masked out — they repeat slot 0's id with weight 0
        and must not be corrupted into the order statistics)."""
        return (np.isin(np.asarray(idx), self.adversary_clients)
                .astype(np.float32) * np.asarray(wmask, np.float32))

    def _round_aux(self, round_idx: int, idx, wmask):
        if self._corruptor() is None:
            return ()
        return (jnp.asarray(self._adv_mask(idx, wmask)),)

    def _window_scan_extras(self, idx2d, wmask2d):
        if self._corruptor() is None:
            return ()
        from fedml_tpu.obs.sanitizer import planned_transfer

        # The [W, C] adversary mask is scanned alongside the weights and
        # forwarded into each round_fn call (make_window_scan *aux) — on
        # a mesh it ships client-sharded like every per-round [C] input.
        adv = self._adv_mask(idx2d, wmask2d)
        put = self._get_window_put()
        with planned_transfer():
            return ((put(adv) if put is not None else jnp.asarray(adv)),)

    # --- server update: weak-DP noise, round-keyed -----------------------
    def _server_update(self, old_net, avg_net):
        if self.cfg.robust_stddev > 0:
            # fold_in on the ROUND's key (stored by run_round) — not a
            # self.rng split chain: the windowed scan reproduces the same
            # per-round keys, so the noise stream is bit-equal across
            # tiers and never blocks the scan on carried host state.
            key = jax.random.fold_in(self._last_round_key, _NOISE_TAG)
            return NetState(
                self._noise(avg_net.params, key), avg_net.model_state
            )
        return avg_net

    def _window_server_update(self):
        """Windowed carry protocol ("round"): the weak-DP noise is a pure
        fold over the round average, keyed off the scanned round key —
        no carry needed. With ``robust_stddev == 0`` the server update is
        the plain average and the scan folds nothing."""
        if self.cfg.robust_stddev <= 0:
            return None
        noise = self._noise  # jitted; jit-under-scan inlines

        def update(net, avg, extra, key):
            p = noise(avg.params, jax.random.fold_in(key, _NOISE_TAG))
            return NetState(p, avg.model_state), extra

        return update
