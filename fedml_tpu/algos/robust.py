"""FedAvg with robust aggregation (backdoor defenses).

Parity: fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py —
per-client norm-difference clipping before the weighted average (:179-185)
and weak-DP Gaussian noise on the aggregate (:202-205), both built on
fedml_core/robustness/robust_aggregation.py. Clipping applies to trainable
params only; BatchNorm stats are excluded structurally (they live in
``NetState.model_state``), mirroring the reference's ``is_weight_param``
filter.
"""

from __future__ import annotations

import jax

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.robustness import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.trainer.local import NetState


class FedAvgRobustAPI(FedAvgAPI):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.cfg
        if cfg.compress and cfg.compress != "none":
            # This class replaces the client-transform hook with norm
            # clipping; accepting cfg.compress here would silently drop
            # the compression the user asked for.
            raise ValueError(
                "FedAvgRobustAPI's client transform is the robust norm "
                "clip; combining it with simulated compression is not "
                "supported — drop cfg.compress or use plain FedAvg")
        self._noise = jax.jit(
            lambda p, r: add_gaussian_noise(p, r, cfg.robust_stddev)
        )

    def _client_transform(self):
        cfg = self.cfg

        def clip(global_net, client_net):
            clipped = norm_diff_clipping(
                client_net.params, global_net.params, cfg.robust_norm_bound
            )
            return NetState(clipped, client_net.model_state)

        return clip

    def _server_update(self, old_net, avg_net):
        if self.cfg.robust_stddev > 0:
            self.rng, sub = jax.random.split(self.rng)
            return NetState(
                self._noise(avg_net.params, sub), avg_net.model_state
            )
        return avg_net
