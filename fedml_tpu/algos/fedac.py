"""Accelerated server optimizers riding the carry capability record:
FedAc and server averaging.

Both are PURE server-state updates — exactly the shape the windowed
carry protocol scans — so they run fused + windowed + pipelined +
on-device from day one, with their sequences living on device between
rounds. They are the "accuracy-per-round for free" counterpart to the
throughput story: same client compute, better round-for-round progress.

**FedAc** (Yuan & Ma, "Federated Accelerated Stochastic Gradient
Descent", NeurIPS 2020, arXiv:2006.08950): provably accelerates Local
SGD/FedAvg with Nesterov-style sequence coupling. The paper runs the
three-sequence recursion per LOCAL step; this implementation applies the
same recursion at the ROUND level — the aggregate progress of the K
local steps, ``Δ = x_md − avg``, plays the role of the (scaled) gradient
at the coupling point ``x_md``, which is the model the server broadcast:

    x_ag' = x_md − Δ                       (= avg, the FedAvg point)
    x'    = (1 − 1/α)·x + (1/α)·x_md − γ·Δ
    x_md' = (1/β)·x' + (1 − 1/β)·x_ag'     (the next broadcast)

``γ`` (in units of the local progress, γ ≥ 1) is the acceleration knob;
``α``/``β`` default to the FedAc-I couplings ``α = (3γ − 1)/2``,
``β = 2α − 1``. At ``γ = 1`` the recursion collapses to FedAvg
(α = β = 1 → x_md' = avg) — pinned by test.

**Server averaging** (Guo et al., "Server Averaging for Federated
Learning", arXiv:2103.11619): the broadcast model mixes the current
round average with the running mean of PAST global models —
averaging over the optimization path damps client-drift oscillation and
speeds convergence per round. Pure carry ``(acc, count, t)``:

    acc' = acc + avg, count' = count + 1      (from round avg_start on)
    net' = (1 − β)·avg + β·acc'/count'

``β = 0`` is exactly FedAvg (pinned by test).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState


def _f32(x):
    return x.astype(jnp.float32)


class FedAcAPI(FedAvgAPI):
    """FedAvg + round-level FedAc acceleration (arXiv:2006.08950).

    ``gamma`` ≥ 1 scales the accelerated sequence's step in units of the
    round's aggregate local progress; ``alpha``/``beta`` override the
    FedAc-I couplings. All three are STATIC Python floats baked into the
    jitted update (changing them mid-run would recompile — construct a
    new API instead)."""

    window_carry = "(x, x_ag) acceleration sequences"

    def __init__(self, *args, gamma: float = 2.0, alpha: float = None,
                 beta: float = None, **kw):
        super().__init__(*args, **kw)
        if gamma < 1.0:
            raise ValueError(f"fedac gamma must be >= 1 (1 = FedAvg), "
                             f"got {gamma}")
        self.gamma = float(gamma)
        self.alpha = (float(alpha) if alpha is not None
                      else max((3.0 * self.gamma - 1.0) / 2.0, 1.0))
        self.beta = (float(beta) if beta is not None
                     else max(2.0 * self.alpha - 1.0, 1.0))
        if self.alpha < 1.0 or self.beta < 1.0:
            raise ValueError(
                f"fedac couplings must be >= 1, got alpha={self.alpha}, "
                f"beta={self.beta}")
        # Both sequences start at the init point (x = x_ag = x_md = w0).
        # DISTINCT buffers (jnp.array copies): the fused step donates the
        # whole (net, extra) carry, and donating one buffer twice is an
        # XLA error.
        self._fedac_state = (
            jax.tree.map(jnp.array, self.net.params),
            jax.tree.map(jnp.array, self.net.params))

    # --- the pure carry record ------------------------------------------
    def _window_server_update(self):
        inv_a = 1.0 / self.alpha
        inv_b = 1.0 / self.beta
        g = self.gamma

        def update(net, avg, extra, key):
            del key  # deterministic update; protocol slot unused
            x, _x_ag = extra
            # Δ = x_md − avg; x_md is the round's broadcast point (net).
            new_x = jax.tree.map(
                lambda xl, md, av: (
                    (1.0 - inv_a) * _f32(xl) + inv_a * _f32(md)
                    - g * (_f32(md) - _f32(av))).astype(xl.dtype),
                x, net.params, avg.params)
            new_x_ag = avg.params  # x_ag' = x_md − Δ, exactly the average
            md = jax.tree.map(
                lambda xl, agl: (inv_b * _f32(xl)
                                 + (1.0 - inv_b) * _f32(agl)).astype(
                                     agl.dtype),
                new_x, new_x_ag)
            # Non-trainable state (BN stats) keeps the plain client
            # average, like FedOpt.
            return NetState(md, avg.model_state), (new_x, new_x_ag)

        return update

    def _window_carry_init(self):
        return self._fedac_state

    def _window_carry_commit(self, extra) -> None:
        self._fedac_state = extra

    def _server_update(self, old_net, avg_net):
        # Host form = the pure form + commit (the fused tiers never call
        # this; kept consistent for any host path that does).
        new_net, self._fedac_state = self._window_server_update()(
            old_net, avg_net, self._fedac_state, None)
        return new_net

    # -- checkpoint/resume: the sequences are run state -------------------
    def checkpoint_extra_state(self):
        return {"fedac_x": self._fedac_state[0],
                "fedac_x_ag": self._fedac_state[1]}

    def load_checkpoint_extra_state(self, extra) -> None:
        self._fedac_state = (extra["fedac_x"], extra["fedac_x_ag"])


class ServerAvgAPI(FedAvgAPI):
    """FedAvg + server averaging (arXiv:2103.11619): broadcast
    ``(1 − β)·avg + β·mean(past globals)``.

    ``avg_coef`` is β (0 = plain FedAvg); ``avg_start`` skips the first
    rounds (early models are far from the optimum — averaging them in
    drags the iterate; the paper's partial/weighted averaging serves the
    same purpose)."""

    window_carry = "running mean of past globals (acc, count, t)"

    def __init__(self, *args, avg_coef: float = 0.5, avg_start: int = 0,
                 **kw):
        super().__init__(*args, **kw)
        if not 0.0 <= avg_coef < 1.0:
            raise ValueError(
                f"server-averaging avg_coef must be in [0, 1), got "
                f"{avg_coef}")
        self.avg_coef = float(avg_coef)
        self.avg_start = int(avg_start)
        self._savg_state = (
            jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                         self.net.params),
            jnp.zeros((), jnp.float32),   # count of accumulated globals
            jnp.zeros((), jnp.int32),     # rounds seen (gates avg_start)
        )

    # --- the pure carry record ------------------------------------------
    def _window_server_update(self):
        beta = self.avg_coef
        start = self.avg_start

        def update(net, avg, extra, key):
            del net, key
            acc, count, t = extra
            take = (t >= start).astype(jnp.float32)
            acc = jax.tree.map(lambda a, p: a + take * _f32(p),
                               acc, avg.params)
            count = count + take
            denom = jnp.maximum(count, 1.0)
            have_mean = count > 0
            new_params = jax.tree.map(
                lambda p, a: jnp.where(
                    have_mean,
                    ((1.0 - beta) * _f32(p) + beta * (a / denom)),
                    _f32(p)).astype(p.dtype),
                avg.params, acc)
            return (NetState(new_params, avg.model_state),
                    (acc, count, t + 1))

        return update

    def _window_carry_init(self):
        return self._savg_state

    def _window_carry_commit(self, extra) -> None:
        self._savg_state = extra

    def _server_update(self, old_net, avg_net):
        new_net, self._savg_state = self._window_server_update()(
            old_net, avg_net, self._savg_state, None)
        return new_net

    # -- checkpoint/resume: the running mean is run state -----------------
    def checkpoint_extra_state(self):
        acc, count, t = self._savg_state
        return {"savg_acc": acc, "savg_count": count, "savg_t": t}

    def load_checkpoint_extra_state(self, extra) -> None:
        self._savg_state = (extra["savg_acc"], extra["savg_count"],
                            extra["savg_t"])
