"""FedBN — keep normalization layers client-local (Li et al., ICLR 2021).

New capability: under feature-shift heterogeneity (each client's inputs
differently scaled/distributed), averaging normalization parameters mixes
incompatible per-client statistics. FedBN excludes every normalization
layer from aggregation: each client keeps its own norm scale/bias (and
BN running stats), while the rest of the model federates as usual.

TPU design: norm parameters are identified by parameter PATH (flax
module auto-names — GroupNorm/BatchNorm/LayerNorm), the per-client
copies live as one client-stacked pytree (non-norm leaves hold a 0-size
placeholder so the tree structure matches), and a round:

1. grafts each sampled client's norm leaves into the broadcast global,
2. vmaps local training over per-client initial models (in_axes=0),
3. averages ONLY non-norm leaves into the new global,
4. scatters trained norm leaves (and the whole model_state — running
   stats are also per-client) back into the local store.

Evaluation is per-client by construction (a FedBN model is only complete
with a client's own norms): ``evaluate_personalized`` grafts and vmaps.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import jax.tree_util as jtu

from fedml_tpu.algos.ditto import _gather_stacked, _scatter_stacked
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState

_NORM_PREFIXES = ("GroupNorm", "BatchNorm", "LayerNorm", "Norm_")


def _path_is_norm(path) -> bool:
    for k in path:
        name = getattr(k, "key", None) or getattr(k, "name", "")
        if str(name).startswith(_NORM_PREFIXES):
            return True
    return False


def norm_mask(params):
    """Pytree of Python bools: True on leaves belonging to a norm layer."""
    return jtu.tree_map_with_path(lambda p, _: _path_is_norm(p), params)


class FedBNAPI(FedAvgAPI):
    """FedAvg with client-local normalization layers. Requires a model
    that HAS norm layers (raises otherwise — running FedBN on a norm-free
    model is indistinguishable from FedAvg and almost certainly a
    misconfiguration).

    Carry capability record ("custom" protocol): the per-client norm
    store + per-client model state ARE the carry ``(local_norms,
    local_state)``. The published step grafts, trains, averages non-norm
    leaves, and scatter-merges the trained norms/state in one donated
    dispatch — scanned W-deep on the windowed tier. Streams from a
    ``FederatedStore`` (the norm store stays device-resident; the
    cohort arrives through the shared ``_cohort`` path)."""

    supports_streaming = True  # norm store device-resident; cohort streams
    window_protocol = "custom"
    window_carry = "client norm-leaf store + client model-state stack"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        if self.mesh is not None:
            raise NotImplementedError(
                "FedBNAPI currently targets the single-device vmap "
                "simulator (its round bypasses the sharded path, so "
                "accepting a mesh would silently not shard)")
        if self._nan_guard:
            raise ValueError(
                "FedBNAPI's round does not implement nan_guard; "
                "rejecting rather than silently averaging diverged clients")
        if self.cfg.compress != "none":
            raise ValueError(
                "FedBNAPI's round does not apply the compression "
                "transform; rejecting cfg.compress rather than silently "
                "running uncompressed")
        self._norm_mask = norm_mask(self.net.params)
        if not any(jax.tree.leaves(self._norm_mask)):
            raise ValueError(
                "FedBN needs a model with normalization layers "
                "(GroupNorm/BatchNorm/LayerNorm); none found in the "
                "parameter tree")
        n = int(self.train_fed.num_clients)
        # Per-client stores: norm leaves stacked [N, ...]; non-norm leaves
        # a 0-size placeholder (never read — the Python-bool mask picks
        # the branch at trace time).
        self.local_norms = jax.tree.map(
            lambda p, m: (jnp.broadcast_to(p[None], (n,) + p.shape)
                          if m else jnp.zeros((0,), p.dtype)),
            self.net.params, self._norm_mask)
        self.local_state = jax.tree.map(
            lambda s: jnp.broadcast_to(s[None], (n,) + s.shape),
            self.net.model_state)
        self._fedbn_jit = None
        self._eval_clients_jit = None

    def _on_client_lr_change(self):
        self._fedbn_jit = None

    def _graft(self, global_params, norms_sub):
        """Per-client initial params: client norms over the global rest.
        The client count comes from a NORM leaf — non-norm leaves hold the
        0-size placeholder."""
        n_sub = next(
            l.shape[0]
            for l, m in zip(jax.tree.leaves(norms_sub),
                            jax.tree.leaves(self._norm_mask)) if m)

        def leaf(g, l, m):
            if m:
                return l
            return jnp.broadcast_to(g[None], (n_sub,) + g.shape)

        return jax.tree.map(leaf, global_params, norms_sub, self._norm_mask)

    def _fedbn_round_fn(self):
        if self._fedbn_jit is not None:
            return self._fedbn_jit
        local_train = self.local_train
        mask_tree = self._norm_mask

        def round_fn(net, norms_sub, state_sub, x, y, mask, weights, rng):
            from fedml_tpu.parallel.shard import client_rngs

            rngs = client_rngs(rng, x.shape[0], 0)
            init_params = self._graft(net.params, norms_sub)
            init_nets = NetState(init_params, state_sub)
            trained, losses = jax.vmap(local_train)(init_nets, x, y, mask, rngs)

            # Global update: weighted mean over NON-norm leaves only; the
            # global's norm leaves stay at their init (they exist solely to
            # initialize brand-new clients).
            w = weights / jnp.maximum(jnp.sum(weights), 1e-12)

            def agg(g, t, m):
                if m:
                    return g
                return jnp.einsum(
                    "c,c...->...", w, t.astype(jnp.float32)).astype(g.dtype)

            new_params = jax.tree.map(agg, net.params, trained.params, mask_tree)
            # Trained norm leaves (client-stacked) to write back; non-norm
            # keep the placeholder shape.
            new_norms = jax.tree.map(
                lambda t, l, m: t if m else l,
                trained.params, norms_sub, mask_tree)
            return (NetState(new_params, net.model_state), new_norms,
                    trained.model_state, jnp.sum(losses * w))

        self._fedbn_jit = jax.jit(round_fn)
        return self._fedbn_jit

    # --- carry capability record ("custom"): norms/state ride the scan ---
    def _build_fused_step(self):
        """ONE FedBN round as one donated dispatch: masked norm-leaf
        gather + state gather + the graft/train/aggregate round + the
        masked scatter-merge, carry ``(net, (local_norms, local_state))``
        — the same step the windowed scan replays W-deep. The scatter
        gate is the pad mask: an empty sampled client's local training
        is a tree_select no-op, so writing its unchanged norms back is
        bit-identical to skipping it (the pre-record host loop used the
        same ``wmask`` gate)."""
        round_fn = self._fedbn_round_fn()
        mask_tree = self._norm_mask

        def step(net, extra, x, y, mask, weights, key, idx, umask):
            norms, state = extra
            norms_sub = jax.tree.map(
                lambda l, m: jnp.take(l, idx, axis=0) if m else l,
                norms, mask_tree)
            state_sub = _gather_stacked(state, idx)
            new_net, new_norms, new_state, loss = round_fn(
                net, norms_sub, state_sub, x, y, mask, weights, key)
            norms = jax.tree.map(
                lambda store, new, m: (
                    _scatter_stacked(store, idx, new, umask) if m
                    else store),
                norms, new_norms, mask_tree)
            state = _scatter_stacked(state, idx, new_state, umask)
            return (new_net, (norms, state)), loss

        return step

    def _window_carry_init(self):
        return (self.local_norms, self.local_state)

    def _window_carry_commit(self, extra) -> None:
        self.local_norms, self.local_state = extra

    def _window_scan_extras(self, idx2d, wmask2d):
        import numpy as np

        from fedml_tpu.obs.sanitizer import planned_transfer

        with planned_transfer():
            return (jnp.asarray(np.asarray(idx2d), jnp.int32),
                    jnp.asarray(np.asarray(wmask2d), jnp.float32))

    def evaluate(self) -> Dict[str, float]:
        """FedBN's headline metric IS the personalized per-client eval: the
        global net's norm leaves are frozen at init, so evaluating it on
        the global test set (the inherited behavior) would measure a model
        with random-init normalization and silently understate the
        algorithm."""
        return self.evaluate_personalized()

    def evaluate_personalized(self) -> Dict[str, float]:
        """Per-client eval with each client's OWN norms grafted in — the
        only semantically complete evaluation of a FedBN model. On a
        store-backed federation the population is walked in
        host-gathered chunks (device holds one chunk of data + norms at
        a time)."""
        f = self.train_fed
        fn = self._eval_clients_jit
        if fn is None:
            def run(net, norms, state, x, y, mask):
                params = self._graft(net.params, norms)
                return jax.vmap(
                    lambda p, s, xc, yc, mc: self.eval_fn(
                        NetState(p, s), xc, yc, mc)
                )(params, state, x, y, mask)

            fn = jax.jit(run)
            self._eval_clients_jit = fn
        if self._streaming:
            import numpy as np

            tot_acc = tot_loss = tot_n = 0.0
            for lo in range(0, f.num_clients, 256):
                idx = np.arange(lo, min(lo + 256, f.num_clients))
                sub = f.gather_cohort(idx)
                jidx = jnp.asarray(idx)
                norms_c = jax.tree.map(
                    lambda l, m: jnp.take(l, jidx, axis=0) if m else l,
                    self.local_norms, self._norm_mask)
                state_c = _gather_stacked(self.local_state, jidx)
                m = fn(self.net, norms_c, state_c, sub.x, sub.y, sub.mask)
                num = np.asarray(m["num"])
                tot_acc += float((np.asarray(m["accuracy"]) * num).sum())
                tot_loss += float((np.asarray(m["loss"]) * num).sum())
                tot_n += float(num.sum())
            n = max(tot_n, 1.0)
            return {"personal_accuracy": tot_acc / n,
                    "personal_loss_eval": tot_loss / n}
        m = fn(self.net, self.local_norms, self.local_state, f.x, f.y, f.mask)
        num = m["num"]
        n = jnp.maximum(jnp.sum(num), 1.0)
        return {
            "personal_accuracy": float(jnp.sum(m["accuracy"] * num) / n),
            "personal_loss_eval": float(jnp.sum(m["loss"] * num) / n),
        }

    # -- checkpoint/resume: local norms are run state ---------------------
    def checkpoint_extra_state(self):
        # orbax refuses zero-size arrays; swap the non-norm placeholders
        # for (1,)-zeros in the saved tree (restored to placeholders on
        # load — their values are never read).
        norms = jax.tree.map(
            lambda l, m: l if m else jnp.zeros((1,), l.dtype),
            self.local_norms, self._norm_mask)
        return {"local_norms": norms, "local_state": self.local_state}

    def load_checkpoint_extra_state(self, extra) -> None:
        self.local_norms = jax.tree.map(
            lambda cur, saved, m: saved if m else cur,
            self.local_norms, extra["local_norms"], self._norm_mask)
        self.local_state = extra["local_state"]
