"""Run configuration shared by all federated algorithms.

Field names follow the reference's canonical argparse set
(fedml_experiments/distributed/fedavg/main_fedavg.py:46-130) so configs map
1:1 onto reference experiment flags.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class FedConfig:
    client_num_in_total: int = 10
    client_num_per_round: int = 10
    comm_round: int = 10
    epochs: int = 1  # local epochs per round
    batch_size: int = 32
    client_optimizer: str = "sgd"
    lr: float = 0.03
    wd: float = 0.0
    frequency_of_the_test: int = 5
    seed: int = 0
    # FedOpt family (fedml_experiments/distributed/fedopt/main_fedopt.py:54,60)
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # FedProx proximal term (absent from the reference's fedprox snapshot —
    # SURVEY.md §2.3 — implemented properly here)
    fedprox_mu: float = 0.1
    # Robust aggregation (fedml_api/distributed/fedavg_robust/main_fedavg_robust.py
    # flags --norm_bound / --stddev)
    robust_norm_bound: float = 5.0
    robust_stddev: float = 0.0
    # Backdoor attack harness (fedavg_robust: the poisoned client joins
    # every attack_freq rounds, main_fedavg_robust.py:120). 0 = no attack;
    # k > 0 forces the adversary client(s) into the cohort on every
    # round_idx % k == 0. The adversaries default to the LAST
    # attack_num_adversaries client ids (their shards should hold
    # poisoned data, e.g. data.loaders.edge_case.make_backdoor_dataset).
    attack_freq: int = 0
    attack_num_adversaries: int = 1
    # Byzantine-robust server aggregation (core/robust_agg — new
    # capability; the reference's only reduction is the weighted mean):
    # "mean" (the bit-equal fast path), "coord_median",
    # "trimmed_mean<beta>", "krum<f>", "multi_krum<f>-<m>",
    # "geometric_median<iters>". Rides every execution tier (host loop,
    # pipelined, windowed, on-device scan); on a client mesh non-mean
    # aggregators all_gather the cohort. docs/ROBUSTNESS.md.
    aggregator: str = "mean"
    # Hierarchical sparse reduction on a client mesh (parallel/shard.py):
    # group-composable aggregators (mean, coord_median, trimmed_mean)
    # aggregate shard-locally first, then across the G group partials —
    # the mesh collective shrinks from C client models to G ≪ C group
    # partials (arXiv:1903.05133 shape). On a DCN×ICI pod mesh
    # (parallel/multihost.dcn_client_mesh; the mesh carries a "hosts"
    # axis) client groups are pinned PER HOST: stage 1 runs as an
    # ICI-axis-only collective with zero DCN traffic and only
    # G = n_hosts group partials + participation mass cross the DCN
    # axis — O(G·model) inter-host bytes instead of the flat path's
    # O(C·model) (docs/PLATFORMS.md "Multi-host"). Mean keeps its
    # bit-equal partial-sum psum fast path (hierarchically associated
    # on a pod mesh); non-composable aggregators (krum,
    # geometric_median) refuse this flag loudly and keep the exact
    # all_gather path. docs/EXECUTION.md "Scale tiers".
    group_reduce: bool = False
    # Device-side update-corruption drill (core/faults.UpdateCorruptor
    # .device_fn, wired through FedAvgRobustAPI): adversary clients'
    # trained updates are corrupted INSIDE the jitted round — "none",
    # "sign_flip", "scale", "nan", or "random"; corrupt_scale is the
    # mode's magnitude. Pair with cfg.aggregator / nan_guard to run
    # attack-vs-defense drills in the windowed tier.
    corrupt_mode: str = "none"
    corrupt_scale: float = 10.0
    # Hierarchical FL (fedml_experiments/standalone/hierarchical_fl/main.py
    # flag --group_comm_round)
    group_comm_round: int = 1
    # fed_launch extras (fed_launch/main.py:148-165): client-side LR
    # schedule over rounds and gradient clipping.
    lr_schedule: str = "none"  # none | cosine | step
    lr_decay_rate: float = 0.992
    grad_clip: float = 0.0
    # Rematerialize forward activations during backprop (jax.checkpoint):
    # trades ~1.3x FLOPs for depth-independent peak HBM.
    remat: bool = False
    # Client selection strategy (new capability — the reference only has
    # uniform seeded sampling, FedAVGAggregator.py:90-99): "random", or
    # "pow_d" (Power-of-Choice, Cho et al. 2020 — sample pow_d_candidates
    # uniformly, evaluate the CURRENT global model on each, keep the
    # client_num_per_round with the highest local loss; biases rounds
    # toward the worst-served clients for faster convergence).
    # ... or "oort" (Oort, Lai et al. OSDI'21 — epsilon-greedy
    # utility-based selection: exploit clients with high statistical
    # utility loss*sqrt(n) plus a staleness bonus, explore the unseen).
    client_selection: str = "random"
    pow_d_candidates: int = 0  # 0 → 2 * client_num_per_round
    oort_epsilon: float = 0.2  # explore fraction of each oort round
    oort_staleness_coef: float = 0.1  # weight of sqrt(rounds-since-seen)
    # Simulated update compression in the on-device rounds: "none",
    # "topk<ratio>" (e.g. "topk0.05" — each client's delta top-k
    # sparsified), or "q<bits>" (e.g. "q8" — QSGD-style stochastic
    # uniform quantization, unbiased, per-client rng streams), ON device
    # inside the jitted round (studies communication-constrained FL at
    # simulator speed; the cross-silo pipeline's --compress is the real
    # wire-level version with error feedback, fedavg_distributed.py).
    compress: str = "none"
    # Negotiated wire codec for the MESSAGE-PASSING tiers' uploads
    # (comm/codec.py): "none", "bf16", "fp16", "int8", "topk<ratio>",
    # "randmask<ratio>", composable as sparsifier+value (e.g.
    # "topk0.01+int8"). Sparsifiers carry per-client error feedback;
    # negotiation rides the init handshake and falls back loudly against
    # a codec-ignorant peer. The simulator tiers REFUSE this flag (their
    # on-device analogue is cfg.compress); mutually exclusive with
    # compress on the cross-silo path.
    wire_codec: str = "none"
    # Lane-fill compute layout (parallel/layout.py, docs/EXECUTION.md
    # "MFU playbook"): "none", or "auto" — the jitted client step runs a
    # lane-aligned PHYSICAL twin of the model (channel dims padded up to
    # MXU lane/sublane multiples; pad-on-entry / slice-on-exit around the
    # local trainer) while everything above the client step — aggregation,
    # robust aggregators, carry protocol, checkpoints, the wire — keeps
    # the LOGICAL reference shapes. Exact (fp32-bit-exact for the CIFAR
    # ResNet family, tested); supported model families only (refuses
    # loudly otherwise). A no-op when the policy pads nothing.
    # "im2col" — conv lane shaping beyond s2d
    # (parallel/layout.im2col_layout): the 5x5 stem conv is rephrased as
    # patch extraction + a 1x1 conv, growing the MXU contraction dim
    # from Cin to 25·Cin (CNNOriginalFedAvg only; ~1-ulp tolerance, the
    # CNN family's documented class).
    compute_layout: str = "none"
    # bf16 client-step compute (docs/EXECUTION.md "MFU playbook"):
    # "fp32" (default), or "bf16" — the jitted client step's layer
    # compute runs in bfloat16 (flax compute-dtype twin,
    # parallel/layout.step_dtype_model) while the PARAM TREE, gradients,
    # optimizer update, aggregation, and server carry all stay fp32.
    # Eval always runs the fp32 model, so measured accuracy deltas are
    # the training effect, not an eval artifact. Supported model
    # families expose a `dtype` compute field; others refuse loudly.
    # Composes with cfg.compute_layout (the pad-on-entry physical twin
    # is cloned to the bf16 compute dtype).
    client_step_dtype: str = "fp32"
    # Frozen-base adapter finetuning (models/adapter.py +
    # algos/fedadapter.py, --adapter_rank/--adapter_scope): rank of the
    # LoRA pairs injected next to the transformer's scoped projections
    # (0 = dense training, the default). With rank > 0 the federated
    # net IS the adapter tree — the base is frozen (fp32
    # bitwise-invariant, test-pinned) and uploads carry adapter-only
    # deltas that ride the negotiated delta+codec wire path
    # (comm/codec.py DELTA_OK_KEY). Read by FedAdapterAPI (simulator
    # tiers) and build_federation_setup (message-passing tiers); every
    # other driver refuses the flags loudly (exp/args.py
    # reject_adapter_flags, the PR 4/14 convention). adapter_scope:
    # "attn" (qkv + attention out), "mlp", or "all".
    adapter_rank: int = 0
    adapter_scope: str = "attn"
    # Example-level DP-SGD on clients (new capability — the reference only
    # has server-side weak DP, robust_aggregation.py:49-53): per-example
    # gradient clipping at this L2 norm (0 disables) and Gaussian noise of
    # std dp_noise_multiplier * dp_clip added to each summed batch gradient.
    # Account the privacy cost with fedml_tpu.core.privacy.PrivacyAccountant.
    dp_clip: float = 0.0
    dp_noise_multiplier: float = 0.0
    # Distributed control plane (algos/fedavg_distributed.py,
    # docs/ROBUSTNESS.md "Control plane"): checkpoint the server's run
    # state every N completed rounds (0 disables; async orbax save off
    # the round critical path — a killed server restarts from the latest
    # checkpoint and the federation continues), and abandon a round after
    # round_timeout_s wall-clock seconds by EVICTING the silent ranks and
    # aggregating over the survivors (0 = wait forever, reference
    # behavior). Workers beat every heartbeat_interval_s while training
    # long rounds (0 = uploads are the only liveness signal).
    checkpoint_every: int = 0
    round_timeout_s: float = 0.0
    heartbeat_interval_s: float = 0.0
    # Parallel server-ingest pool (comm/ingest.py, --ingest_workers):
    # N decode+fold worker threads pull codec decode / delta
    # reconstruction / accumulator folds off the message-passing
    # servers' single dispatch thread — the measured serving wall
    # (ingest_occupancy 0.78, arXiv:2307.06561). Mean aggregation only
    # (per-worker fixed-point partial accumulators merge associative-
    # exactly, so any worker count is bit-equal to the 1-worker pool
    # regardless of arrival interleaving; non-mean robust aggregators
    # keep the serialized stack-then-reduce path and REFUSE this flag).
    # 0 (default) keeps the legacy inline float fold untouched. The
    # simulator tiers refuse the flag loudly (their rounds have no
    # dispatch thread to unblock).
    ingest_workers: int = 0
    # Sharded aggregation plane (comm/shardplane.py, --agg_shards M):
    # M aggregator-shard processes — each running the full codec
    # negotiation + IngestPool + fixed-point fold over its own client
    # partition — whose serialized int64 partials the rank-0 coordinator
    # wire-merges BIT-EQUAL to the single-process pool (the same
    # associativity proof as ingest_workers, one level up, over the
    # wire). Mean aggregation + sync FedAvg only: FedAsync's sequential
    # server mix and FedBuff's global-arrival-order buffer REFUSE the
    # flag. 0 (default) keeps the single-server ingest path.
    agg_shards: int = 0
    # Dropout-robust secure aggregation (comm/secagg.py, --secagg at the
    # CLI; docs/ROBUSTNESS.md "Secure aggregation"): clients add
    # pairwise seed-expanded masks to their fixed-point int64 uploads so
    # the server only ever materializes the SUM — masks cancel exactly
    # in the pooled fold (and across the sharded plane's wire merge),
    # and a heartbeat eviction triggers a t-of-n Shamir seed reveal that
    # subtracts the orphaned masks. Sync FedAvg + mean aggregation +
    # all-arrive rounds only; needs ingest_workers > 0 or agg_shards > 0
    # (the masks live in the pool's fixed-point domain). The async tiers
    # and every non-supporting driver refuse the flag loudly.
    secagg: bool = False
    # Shamir reveal threshold t: survivors needed to reconstruct an
    # evicted rank's seeds. 0 (default) resolves to a majority
    # (n//2 + 1) of the handshake roster.
    secagg_t: int = 0
    # Federation flight recorder (obs/trace.py, --trace at the CLI;
    # docs/OBSERVABILITY.md): record upload-lifecycle spans (client
    # serialize → wire → codec decode → accumulator fold → round commit,
    # correlated by (epoch, round, sender, task_seq)) and dump a
    # Perfetto-loadable Chrome trace + JSONL into the run directory,
    # plus the server's bounded flight-recorder ring on eviction/abort/
    # codec refusal. Off (the default) is a strict no-op path — the
    # instrumented call sites hit the null tracer, pinned within 2% of
    # uninstrumented in tests/test_trace.py. The CLI layers resolve this
    # flag + --run_dir into the runners' trace_dir parameter.
    trace: bool = False
