"""FedDyn — federated learning with dynamic regularization (Acar et al.,
ICLR 2021, "Federated Learning Based on Dynamic Regularization").

New capability (the reference has no drift-corrected algorithm at all;
this completes the FedProx / SCAFFOLD / FedDyn correction family): each
client k minimizes a DYNAMICALLY regularized local objective

    f_k(w) - <g_k, w> + (alpha/2) ||w - w_t||^2

whose linear term g_k (the client's accumulated first-order correction)
makes the local optima consistent with the global stationary point:

    per-step gradient:  grad f_k(w) - g_k + alpha (w - w_t)
    after local run:    g_k <- g_k - alpha (w_k - w_t)
    server state:       h   <- h - alpha (1/N) sum_{k in S} (w_k - w_t)
    new global:         w   <- mean_{k in S} w_k - (1/alpha) h

Unlike SCAFFOLD there is no control-variate exchange — only the model
crosses the wire; the correction is reconstructed locally.

TPU design mirrors ScaffoldAPI: the N client corrections are ONE
client-stacked pytree on device, the corrected local run is a dedicated
``lax.scan`` trainer (the per-step term needs per-client inputs the
generic ``extra_grad_fn`` hook cannot carry), and one shared update body
serves the single-device vmap round and the shard_map round (psum'd
reductions), so the math cannot drift between paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import NetState


def make_feddyn_local_train(apply_fn, lr: float, alpha: float,
                            local_epochs: int, loss_fn,
                            remat: bool = False):
    """``local_train(net, (g_k, global_params), x, y, mask, rng) ->
    (net', loss)`` — SGD on the dynamically regularized objective; every
    step's gradient carries ``- g_k + alpha (w - w_global)``. Built on
    the shared corrected-SGD trainer (trainer/local.py)."""
    from fedml_tpu.trainer.local import make_corrected_local_train

    def step_update(params, grads, aux):
        g_k, global_params = aux
        return jax.tree.map(
            lambda p, g, gk, w0: p - lr * (g - gk + alpha * (p - w0)),
            params, grads, g_k, global_params)

    return make_corrected_local_train(apply_fn, local_epochs, loss_fn,
                                      step_update, remat=remat)


class FedDynAPI(FedAvgAPI):
    """FedAvg + dynamic regularization. Plain-SGD clients only (the
    correction is defined on the SGD update). ``alpha`` is the paper's
    regularization strength (typical 0.01-0.1).

    Streams from a ``FederatedStore`` too (the SCAFFOLD pattern): the
    client CORRECTIONS stay a device-resident ``[N, ...]`` stack —
    per-client state, not data — while the round's cohort arrives
    through the shared :meth:`FedAvgAPI._cohort` path. The carry
    capability record below is the whole fast-path story: the fused
    one-dispatch round, the pipelined loop, and the W-rounds-per-
    dispatch windowed scan all derive from ONE ``_build_fused_step``,
    with carry ``(net, (server_h, client_grads))``."""

    supports_streaming = True  # corrections device-resident; cohort streams
    window_protocol = "custom"
    window_carry = "server h + client correction stack"

    def __init__(self, *args, alpha: float = 0.01, **kw):
        super().__init__(*args, **kw)
        if alpha <= 0:
            raise ValueError(f"feddyn alpha must be > 0, got {alpha}")
        self._require_plain_sgd_round("FedDynAPI's corrected SGD step")
        self.alpha = alpha
        n = int(self.train_fed.num_clients)
        zeros = jax.tree.map(jnp.zeros_like, self.net.params)
        self.server_h = zeros
        self.client_grads = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), zeros)
        self._feddyn_jit = None

    def _on_client_lr_change(self):
        self._feddyn_jit = None

    def _feddyn_update(self, net, h, gk_sub, trained, losses, weights,
                       cross):
        """The FedDyn server update, shared by the vmap and sharded
        rounds — ``cross`` is identity on one device, psum under
        shard_map (mirrors ScaffoldAPI._scaffold_update)."""
        alpha = self.alpha
        n_total = float(self.train_fed.num_clients)
        active = (weights > 0).astype(jnp.float32)
        total_active = cross(jnp.sum(active))
        any_ok = total_active > 0
        wn = active / jnp.maximum(total_active, 1e-12)

        # g_k' = g_k - alpha (w_k - w_t) for participants.
        gk_new = jax.tree.map(
            lambda gk, wk, w0: gk - alpha * (
                wk.astype(jnp.float32) - w0.astype(jnp.float32)[None]),
            gk_sub, trained.params, net.params)
        # h' = h - alpha (1/N) sum_k (w_k - w_t).
        h_new = jax.tree.map(
            lambda hh, wk, w0: hh - (alpha / n_total) * cross(jnp.einsum(
                "c,c...->...", active,
                wk.astype(jnp.float32) - w0.astype(jnp.float32)[None])),
            h, trained.params, net.params)
        # w' = mean_k w_k - (1/alpha) h' (uniform participant mean, per
        # the paper); model_state keeps FedAvg's sample-count weighting.
        new_params = jax.tree.map(
            lambda wk, hh, w0: jnp.where(
                any_ok,
                (cross(jnp.einsum("c,c...->...", wn,
                                  wk.astype(jnp.float32)))
                 - hh / alpha).astype(w0.dtype),
                w0),
            trained.params, h_new, net.params)
        # weights already carry the active zeros (counts x wmask), so they
        # ARE the sample-count weighting (scaffold's wn_w).
        w = weights.astype(jnp.float32)
        wns = w / jnp.maximum(cross(jnp.sum(w)), 1e-12)
        new_state = jax.tree.map(
            lambda s, old: jnp.where(
                any_ok,
                cross(jnp.einsum("c,c...->...", wns,
                                 s.astype(jnp.float32))).astype(s.dtype),
                old),
            trained.model_state, net.model_state)
        loss = cross(jnp.sum(losses * wns))
        return NetState(new_params, new_state), h_new, gk_new, loss

    def _feddyn_round_fn(self):
        if self._feddyn_jit is not None:
            return self._feddyn_jit
        local_train = make_feddyn_local_train(
            self.fns.apply, self._client_lr, self.alpha, self.cfg.epochs,
            self._loss_fn, remat=self.cfg.remat)

        def body(net, h, gk_sub, x, y, mask, weights, rngs, cross):
            trained, losses = jax.vmap(
                local_train, in_axes=(None, (0, None), 0, 0, 0, 0)
            )(net, (gk_sub, net.params), x, y, mask, rngs)
            return self._feddyn_update(net, h, gk_sub, trained, losses,
                                       weights, cross)

        from fedml_tpu.parallel.shard import make_stateful_client_round

        from fedml_tpu.parallel.shard import client_axis
        axis = None if self.mesh is None else client_axis(self.mesh)
        round_fn = make_stateful_client_round(
            body, self.mesh, axis or "clients")
        self._feddyn_jit = jax.jit(round_fn)
        return self._feddyn_jit

    # --- carry capability record ("custom"): corrections ride every tier -
    def _build_fused_step(self):
        """ONE FedDyn round as one donated dispatch: cohort correction
        gather + the stateful round + the masked scatter-merge, carry
        ``(net, (server_h, client_grads))`` — the same step the windowed
        scan replays W-deep (bit-equality by construction). The scatter
        gate: only clients that actually trained update their correction
        (a sampled empty client ran zero real steps; writing its
        "update" would drift nothing here since alpha*0 = 0, but masking
        keeps PADDED DUPLICATE slots from clobbering real state)."""
        from fedml_tpu.parallel.shard import make_fused_stateful_round_step

        return make_fused_stateful_round_step(self._feddyn_round_fn())

    def _window_carry_init(self):
        return (self.server_h, self.client_grads)

    def _window_carry_commit(self, extra) -> None:
        self.server_h, self.client_grads = extra

    def _window_scan_extras(self, idx2d, wmask2d):
        from fedml_tpu.obs.sanitizer import planned_transfer

        # Per-round cohort index map + trained mask (layout-agnostic
        # count gathers, shared with SCAFFOLD's extras).
        trained = self._window_update_mask(idx2d, wmask2d)
        with planned_transfer():
            return (jnp.asarray(np.asarray(idx2d), jnp.int32),
                    jnp.asarray(trained, jnp.float32))

    # -- checkpoint/resume: corrections are run state ---------------------
    def checkpoint_extra_state(self):
        return {"server_h": self.server_h,
                "client_grads": self.client_grads}

    def load_checkpoint_extra_state(self, extra) -> None:
        self.server_h = extra["server_h"]
        self.client_grads = extra["client_grads"]
