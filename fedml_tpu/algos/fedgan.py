"""FedGAN — federated averaging over a generator+discriminator pair.

Parity targets:
- Local GAN training (reference fedml_api/distributed/fedgan/
  MyModelTrainer.py:32-71): per batch, one Adam discriminator step on
  BCE(real,1)+BCE(fake,0), then one Adam generator step on BCE(D(G(z)),1);
  optimizers recreated each round.
- Joint aggregation of both nets (reference FedGANAggregator.py:58-88, the
  doubly-nested weighted average over ``{'netg':…, 'netd':…}``): here the two
  nets live in ONE params pytree so the standard weighted tree-mean of the
  FedAvg round machinery already aggregates them jointly.

TPU-first: the per-net optimizer split is ``optax.masked`` over the
``netg``/``netd`` subtrees (no Python-level parameter groups); the whole
local loop is a ``lax.scan`` vmapped over clients like every other
algorithm. The discriminator emits logits and losses use
``sigmoid_binary_cross_entropy`` (see fedml_tpu/models/gan.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algos.loop import FederatedLoop
from fedml_tpu.core.tree import tree_select
from fedml_tpu.trainer.local import NetState, make_epoch_shuffle


def _apply(module, net: NetState, method, *args, train: bool):
    """module.apply with mutable-collection plumbing (BN variant support)."""
    variables = {"params": net.params, **net.model_state}
    if train and net.model_state:
        out, new_state = module.apply(
            variables, *args, train=train, method=method,
            mutable=list(net.model_state.keys()),
        )
        return out, dict(new_state)
    out = module.apply(variables, *args, train=train, method=method)
    return out, net.model_state


def make_gan_local_train(module, lr: float, local_epochs: int,
                         latent_dim: int = 100):
    """Build ``local_train(net, x, y, mask, rng) -> (net', mean_loss)`` with
    the round-fn signature shared by all algorithms (``y`` is unused — GANs
    are unsupervised; ``mask [S,B]`` gates padded samples out of both
    losses). Reported loss is d_loss + g_loss, mean over steps."""

    def bce(logits, target):  # target ∈ {0., 1.}
        return optax.sigmoid_binary_cross_entropy(
            logits[:, 0], jnp.full(logits.shape[:1], target))

    # NOTE: optax.masked is wrong here — it passes masked-out leaves' raw
    # gradients through as updates (gradient ascent on the frozen net!);
    # multi_transform + set_to_zero freezes them properly.
    opt_d = optax.multi_transform(
        {"train": optax.adam(lr), "freeze": optax.set_to_zero()},
        {"netg": "freeze", "netd": "train"},
    )
    opt_g = optax.multi_transform(
        {"train": optax.adam(lr), "freeze": optax.set_to_zero()},
        {"netg": "train", "netd": "freeze"},
    )

    def local_train(net: NetState, x, y, mask, rng):
        del y
        d_state = opt_d.init(net.params)
        g_state = opt_g.init(net.params)

        def step(carry, inputs):
            net, d_state, g_state, step_base = carry
            xb, mb, idx = inputs
            # Per-step noise keys by fold_in on the STEP INDEX (fedlint
            # R1): the D and G draws fork from disjoint children of the
            # per-step key, and the streams are prefix-stable in the
            # step count (a forced step bucket never shifts them).
            per_step = jax.random.fold_in(step_base, idx)
            zd = jax.random.fold_in(per_step, 0)
            zg = jax.random.fold_in(per_step, 1)
            nb = jnp.maximum(jnp.sum(mb), 1.0)

            def d_loss_fn(p):
                n = NetState(p, net.model_state)
                real_logits, state1 = _apply(
                    module, n, module.discriminate, xb, train=True)
                noise = jax.random.normal(zd, (xb.shape[0], latent_dim))
                fake, state2 = _apply(
                    module, NetState(p, state1), module.generate, noise,
                    train=True)
                # The netg gradients would be frozen by opt_d anyway;
                # stop_gradient skips the generator backward pass entirely.
                fake = jax.lax.stop_gradient(fake)
                fake_logits, state3 = _apply(
                    module, NetState(p, state2), module.discriminate, fake,
                    train=True)
                per = bce(real_logits, 1.0) + bce(fake_logits, 0.0)
                return jnp.sum(per * mb) / nb, state3

            (d_loss, state_d), d_grads = jax.value_and_grad(
                d_loss_fn, has_aux=True)(net.params)
            d_updates, new_d_state = opt_d.update(d_grads, d_state, net.params)
            p_after_d = optax.apply_updates(net.params, d_updates)

            def g_loss_fn(p):
                n = NetState(p, state_d)
                noise = jax.random.normal(zg, (xb.shape[0], latent_dim))
                fake, state1 = _apply(module, n, module.generate, noise,
                                      train=True)
                fake_logits, state2 = _apply(
                    module, NetState(p, state1), module.discriminate, fake,
                    train=True)
                per = bce(fake_logits, 1.0)
                return jnp.sum(per * mb) / nb, state2

            (g_loss, new_model_state), g_grads = jax.value_and_grad(
                g_loss_fn, has_aux=True)(p_after_d)
            g_updates, new_g_state = opt_g.update(g_grads, g_state, p_after_d)
            new_params = optax.apply_updates(p_after_d, g_updates)

            nonempty = jnp.sum(mb) > 0
            new_net = NetState(new_params, new_model_state)
            net = tree_select(nonempty, new_net, net)
            d_state = tree_select(nonempty, new_d_state, d_state)
            g_state = tree_select(nonempty, new_g_state, g_state)
            return (net, d_state, g_state, step_base), (d_loss + g_loss,
                                                        jnp.sum(mb))

        def epoch(carry, epoch_rng):
            # Shuffle keys and step streams fork from DISJOINT children
            # of the epoch key (trainer/local.py discipline).
            reshuffle = make_epoch_shuffle(
                mask, jax.random.fold_in(epoch_rng, 0))
            net, d_state, g_state, _ = carry
            step_base = jax.random.fold_in(epoch_rng, 1)
            carry, (losses, ns) = jax.lax.scan(
                step, (net, d_state, g_state, step_base),
                (reshuffle(x), reshuffle(mask), jnp.arange(x.shape[0])))
            return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

        rng, shuffle_rng = jax.random.split(rng)
        (net, _, _, _), epoch_losses = jax.lax.scan(
            epoch, (net, d_state, g_state, rng),
            jax.random.split(shuffle_rng, local_epochs))
        return net, jnp.mean(epoch_losses)

    return local_train


class FedGanAPI(FederatedLoop):
    """Federated GAN trainer (reference FedGanAPI.py + FedGANAggregator.py).

    Unlike the classifier APIs the model is initialized from latent noise
    (``[B, latent_dim]``), so this does not subclass FedAvgAPI — it reuses
    the shared round scaffold (FederatedLoop.run_round: vmap/shard_map +
    weighted tree-mean) with a GAN-specific local step. ``train_fed.y`` is
    ignored; GANs have no accuracy eval (the reference logs only losses)."""

    def __init__(self, model, train_fed, cfg, mesh=None, latent_dim: int = None):
        from fedml_tpu.parallel.shard import make_sharded_round, make_vmap_round

        if latent_dim is None:
            latent_dim = getattr(model, "latent_dim", 100)
        self.module = model
        self.cfg = cfg
        self.mesh = mesh
        self.train_fed = train_fed
        self.test_global = None
        self.latent_dim = latent_dim

        local_train = make_gan_local_train(model, cfg.lr, cfg.epochs, latent_dim)
        if mesh is None:
            self.n_shards = 1
            round_fn = make_vmap_round(local_train)
        else:
            self.n_shards = int(mesh.shape[mesh.axis_names[0]])
            round_fn = make_sharded_round(local_train, mesh, mesh.axis_names[0])
        self.round_fn = jax.jit(round_fn)

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_rng = jax.random.split(rng)
        z = jnp.zeros((int(train_fed.x.shape[2]), latent_dim), jnp.float32)
        variables = model.init({"params": init_rng}, z, train=False)
        params = variables["params"]
        state = {k: v for k, v in variables.items() if k != "params"}
        self.net = NetState(params=params, model_state=state)

    def train_one_round(self, round_idx: int):
        avg, loss = self.run_round(round_idx)
        self.net = avg
        return {"round": round_idx, "train_loss": float(loss)}

    def evaluate(self):
        return {}

    def generate(self, n: int, rng=None):
        """Sample n images from the current global generator."""
        if rng is None:
            self.rng, rng = jax.random.split(self.rng)
        z = jax.random.normal(rng, (n, self.latent_dim))
        imgs, _ = _apply(self.module, self.net, self.module.generate, z,
                         train=False)
        return imgs
