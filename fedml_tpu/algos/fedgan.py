"""FedGAN — federated averaging over a generator+discriminator pair.

Parity targets:
- Local GAN training (reference fedml_api/distributed/fedgan/
  MyModelTrainer.py:32-71): per batch, one Adam discriminator step on
  BCE(real,1)+BCE(fake,0), then one Adam generator step on BCE(D(G(z)),1);
  optimizers recreated each round.
- Joint aggregation of both nets (reference FedGANAggregator.py:58-88, the
  doubly-nested weighted average over ``{'netg':…, 'netd':…}``): here the two
  nets live in ONE params pytree so the standard weighted tree-mean of the
  FedAvg round machinery already aggregates them jointly.

TPU-first: the per-net optimizer split is ``optax.multi_transform`` over the
``netg``/``netd`` subtrees (no Python-level parameter groups); the whole
local loop is a ``lax.scan`` vmapped over clients like every other
algorithm. The discriminator emits logits and losses use
``sigmoid_binary_cross_entropy`` (see fedml_tpu/models/gan.py docstring).

Capability record: since the record refactor ``FedGanAPI`` IS a
``FedAvgAPI`` whose local step is the adversarial D/G loop — the server
update is the plain client average ("round" protocol, no carry), so
FedGAN rides the fused round step, the pipelined loop, the windowed
streaming scan and the on-device scan like plain FedAvg (the GAN local
step is prefix-stable in the step count: per-step noise keys fold_in on
the step index, padded steps are tree_select no-ops). Only ``evaluate``
differs: GANs have no accuracy — the reference logs only losses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.tree import tree_select
from fedml_tpu.trainer.local import NetState, make_epoch_shuffle


def _apply(module, net: NetState, method, *args, train: bool):
    """module.apply with mutable-collection plumbing (BN variant support)."""
    variables = {"params": net.params, **net.model_state}
    if train and net.model_state:
        out, new_state = module.apply(
            variables, *args, train=train, method=method,
            mutable=list(net.model_state.keys()),
        )
        return out, dict(new_state)
    out = module.apply(variables, *args, train=train, method=method)
    return out, net.model_state


def make_gan_local_train(module, lr: float, local_epochs: int,
                         latent_dim: int = 100):
    """Build ``local_train(net, x, y, mask, rng) -> (net', mean_loss)`` with
    the round-fn signature shared by all algorithms (``y`` is unused — GANs
    are unsupervised; ``mask [S,B]`` gates padded samples out of both
    losses). Reported loss is d_loss + g_loss, mean over steps."""

    def bce(logits, target):  # target ∈ {0., 1.}
        return optax.sigmoid_binary_cross_entropy(
            logits[:, 0], jnp.full(logits.shape[:1], target))

    # NOTE: optax.masked is wrong here — it passes masked-out leaves' raw
    # gradients through as updates (gradient ascent on the frozen net!);
    # multi_transform + set_to_zero freezes them properly.
    opt_d = optax.multi_transform(
        {"train": optax.adam(lr), "freeze": optax.set_to_zero()},
        {"netg": "freeze", "netd": "train"},
    )
    opt_g = optax.multi_transform(
        {"train": optax.adam(lr), "freeze": optax.set_to_zero()},
        {"netg": "train", "netd": "freeze"},
    )

    def local_train(net: NetState, x, y, mask, rng):
        del y
        d_state = opt_d.init(net.params)
        g_state = opt_g.init(net.params)

        def step(carry, inputs):
            net, d_state, g_state, step_base = carry
            xb, mb, idx = inputs
            # Per-step noise keys by fold_in on the STEP INDEX (fedlint
            # R1): the D and G draws fork from disjoint children of the
            # per-step key, and the streams are prefix-stable in the
            # step count (a forced step bucket never shifts them).
            per_step = jax.random.fold_in(step_base, idx)
            zd = jax.random.fold_in(per_step, 0)
            zg = jax.random.fold_in(per_step, 1)
            nb = jnp.maximum(jnp.sum(mb), 1.0)

            def d_loss_fn(p):
                n = NetState(p, net.model_state)
                real_logits, state1 = _apply(
                    module, n, module.discriminate, xb, train=True)
                noise = jax.random.normal(zd, (xb.shape[0], latent_dim))
                fake, state2 = _apply(
                    module, NetState(p, state1), module.generate, noise,
                    train=True)
                # The netg gradients would be frozen by opt_d anyway;
                # stop_gradient skips the generator backward pass entirely.
                fake = jax.lax.stop_gradient(fake)
                fake_logits, state3 = _apply(
                    module, NetState(p, state2), module.discriminate, fake,
                    train=True)
                per = bce(real_logits, 1.0) + bce(fake_logits, 0.0)
                return jnp.sum(per * mb) / nb, state3

            (d_loss, state_d), d_grads = jax.value_and_grad(
                d_loss_fn, has_aux=True)(net.params)
            d_updates, new_d_state = opt_d.update(d_grads, d_state, net.params)
            p_after_d = optax.apply_updates(net.params, d_updates)

            def g_loss_fn(p):
                n = NetState(p, state_d)
                noise = jax.random.normal(zg, (xb.shape[0], latent_dim))
                fake, state1 = _apply(module, n, module.generate, noise,
                                      train=True)
                fake_logits, state2 = _apply(
                    module, NetState(p, state1), module.discriminate, fake,
                    train=True)
                per = bce(fake_logits, 1.0)
                return jnp.sum(per * mb) / nb, state2

            (g_loss, new_model_state), g_grads = jax.value_and_grad(
                g_loss_fn, has_aux=True)(p_after_d)
            g_updates, new_g_state = opt_g.update(g_grads, g_state, p_after_d)
            new_params = optax.apply_updates(p_after_d, g_updates)

            nonempty = jnp.sum(mb) > 0
            new_net = NetState(new_params, new_model_state)
            net = tree_select(nonempty, new_net, net)
            d_state = tree_select(nonempty, new_d_state, d_state)
            g_state = tree_select(nonempty, new_g_state, g_state)
            return (net, d_state, g_state, step_base), (d_loss + g_loss,
                                                        jnp.sum(mb))

        def epoch(carry, epoch_rng):
            # Shuffle keys and step streams fork from DISJOINT children
            # of the epoch key (trainer/local.py discipline).
            reshuffle = make_epoch_shuffle(
                mask, jax.random.fold_in(epoch_rng, 0))
            net, d_state, g_state, _ = carry
            step_base = jax.random.fold_in(epoch_rng, 1)
            carry, (losses, ns) = jax.lax.scan(
                step, (net, d_state, g_state, step_base),
                (reshuffle(x), reshuffle(mask), jnp.arange(x.shape[0])))
            return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

        rng, shuffle_rng = jax.random.split(rng)
        (net, _, _, _), epoch_losses = jax.lax.scan(
            epoch, (net, d_state, g_state, rng),
            jax.random.split(shuffle_rng, local_epochs))
        return net, jnp.mean(epoch_losses)

    return local_train


class FedGanAPI(FedAvgAPI):
    """Federated GAN trainer (reference FedGanAPI.py + FedGANAggregator.py).

    The model initializes from latent noise (``[B, latent_dim]``) via the
    ``_net_init_input`` hook; the local step is the adversarial D/G loop
    (``_build_local_train``); everything else — sampling, aggregation,
    every execution tier in the capability record — is the inherited
    FedAvg machinery. ``train_fed.y`` is ignored; GANs have no accuracy
    eval (the reference logs only losses), so ``evaluate`` returns {}."""

    def __init__(self, model, train_fed, cfg, mesh=None,
                 latent_dim: int = None):
        if latent_dim is None:
            latent_dim = getattr(model, "latent_dim", 100)
        self.module = model
        self.latent_dim = latent_dim
        super().__init__(model, train_fed, None, cfg, mesh=mesh)
        # The adversarial step builds its own per-net Adam pair; cfg
        # knobs the generic trainer honors (dp_clip/dp_noise/grad_clip/
        # client_optimizer/compress) must refuse, not silently no-op —
        # a user who set dp_noise_multiplier must not believe DP is
        # active (same convention as FedNAS/SCAFFOLD/FedDyn).
        self._require_plain_sgd_round("FedGanAPI's adversarial D/G step")

    def _net_init_input(self, sample_x):
        # One latent batch, matching the packed batch size — the joint
        # G→D __call__ initializes both subtrees from it.
        b = int(np.asarray(sample_x).shape[0])
        return jnp.zeros((b, self.latent_dim), jnp.float32)

    def _build_local_train(self, optimizer, loss_fn):
        # The adversarial step builds its OWN per-net Adam pair from the
        # live client lr; the generic optimizer/loss are unused.
        del optimizer, loss_fn
        return make_gan_local_train(self.module, self._client_lr,
                                    self.cfg.epochs, self.latent_dim)

    def evaluate(self):
        return {}

    def generate(self, n: int, rng=None):
        """Sample n images from the current global generator."""
        if rng is None:
            self.rng, rng = jax.random.split(self.rng)
        z = jax.random.normal(rng, (n, self.latent_dim))
        imgs, _ = _apply(self.module, self.net, self.module.generate, z,
                         train=False)
        return imgs
