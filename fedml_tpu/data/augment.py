"""On-device data augmentation (jax, batched, jit/vmap-safe).

The reference augments on the host per-sample through torchvision transforms
(cifar10/data_loader.py:58-76: RandomCrop(32, padding=4),
RandomHorizontalFlip, Normalize, Cutout(16)). On TPU that would serialize the
input pipeline; here augmentation is a pure jax function on whole batches
applied inside the jitted training step — static shapes, fused by XLA, and
free per-client randomness under vmap via rng folding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_crop(rng, x: jnp.ndarray, padding: int = 4) -> jnp.ndarray:
    """Pad+random-crop a NHWC batch; one offset per sample
    (dynamic_slice over the padded image keeps shapes static)."""
    n, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)), mode="constant")
    k1, k2 = jax.random.split(rng)
    oy = jax.random.randint(k1, (n,), 0, 2 * padding + 1)
    ox = jax.random.randint(k2, (n,), 0, 2 * padding + 1)

    def crop_one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    return jax.vmap(crop_one)(xp, oy, ox)


def random_flip(rng, x: jnp.ndarray) -> jnp.ndarray:
    """Horizontal flip with p=0.5 per sample."""
    flip = jax.random.bernoulli(rng, 0.5, (x.shape[0],))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)


def cutout(rng, x: jnp.ndarray, length: int = 16) -> jnp.ndarray:
    """Zero a random length×length square per sample (DeVries & Taylor;
    the reference's Cutout class, cifar10/data_loader.py:20-44 — centers may
    fall near edges, so the mask is clipped, matching np.clip there)."""
    n, h, w, _ = x.shape
    k1, k2 = jax.random.split(rng)
    cy = jax.random.randint(k1, (n, 1, 1), 0, h)
    cx = jax.random.randint(k2, (n, 1, 1), 0, w)
    ys = jnp.arange(h)[None, :, None]
    xs = jnp.arange(w)[None, None, :]
    mask = (jnp.abs(ys - cy) < length // 2) & (jnp.abs(xs - cx) < length // 2)
    return x * (~mask[..., None]).astype(x.dtype)


def cifar_train_augment(rng, x: jnp.ndarray, use_cutout: bool = True) -> jnp.ndarray:
    """The composed CIFAR policy (crop → flip → cutout). Input is already
    normalized; cutout zeros → the channel mean post-normalisation, same as
    the reference (it also cuts after ToTensor/Normalize)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    x = random_crop(k1, x)
    x = random_flip(k2, x)
    if use_cutout:
        x = cutout(k3, x)
    return x
