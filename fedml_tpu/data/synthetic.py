"""Synthetic dataset generators (host-side numpy).

Used by tests and as the zero-egress stand-in shape-generator for datasets
whose real files are download-gated (SURVEY.md §2.7 — the reference ships
``download_*.sh`` scripts; this environment has no network).

``synthetic_alpha_beta`` reproduces the reference's synthetic(α,β) LR task
(fedml_api/data_preprocessing/synthetic_1_1/ — the LEAF synthetic dataset of
Li et al., FedProx): per-client model W_k ~ N(u_k, 1), u_k ~ N(0, α); inputs
x ~ N(v_k, Σ) with v_k ~ N(B_k, 1), B_k ~ N(0, β); Σ diagonal, Σ_jj = j^-1.2.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_classification(
    n_samples: int,
    n_features: int = 16,
    n_classes: int = 10,
    seed: int = 0,
    noise: float = 0.1,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    w = rng.randn(n_features, n_classes)
    x = rng.randn(n_samples, n_features).astype(np.float32)
    logits = x @ w + noise * rng.randn(n_samples, n_classes)
    y = np.argmax(logits, axis=1).astype(np.int32)
    return x, y


def make_image_classification(
    n_samples: int,
    hwc: Tuple[int, int, int] = (28, 28, 1),
    n_classes: int = 10,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional Gaussian images (NHWC) — enough signal for smoke
    tests to show learning."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, n_classes, size=n_samples).astype(np.int32)
    protos = rng.randn(n_classes, *hwc).astype(np.float32)
    x = protos[y] + 0.5 * rng.randn(n_samples, *hwc).astype(np.float32)
    return x, y


def make_segmentation(
    n_samples: int,
    hw: Tuple[int, int] = (32, 32),
    n_classes: int = 4,
    seed: int = 0,
    ignore_index: int = 255,
    ignore_frac: float = 0.05,
) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic segmentation pairs: images with class-colored blobs, labels
    the blob class map; a small fraction of void pixels (``ignore_index``)
    exercises the ignore path of the fedseg losses/metrics."""
    rng = np.random.RandomState(seed)
    h, w = hw
    x = np.zeros((n_samples, h, w, 3), np.float32)
    y = np.zeros((n_samples, h, w), np.int32)
    protos = rng.randn(n_classes, 3).astype(np.float32)
    for i in range(n_samples):
        # 2-4 random rectangles of random classes over a class-0 background
        for _ in range(rng.randint(2, 5)):
            c = rng.randint(1, n_classes)
            y0, x0 = rng.randint(0, h // 2), rng.randint(0, w // 2)
            y1, x1 = y0 + rng.randint(4, h // 2), x0 + rng.randint(4, w // 2)
            y[i, y0:y1, x0:x1] = c
        x[i] = protos[y[i]] + 0.3 * rng.randn(h, w, 3)
        void = rng.rand(h, w) < ignore_frac
        y[i][void] = ignore_index
    return x, y


def synthetic_alpha_beta(
    alpha: float = 1.0,
    beta: float = 1.0,
    n_clients: int = 30,
    n_features: int = 60,
    n_classes: int = 10,
    seed: int = 0,
    min_samples: int = 10,
    mean_samples: int = 50,
):
    """Returns ``(x, y, client_index_map)`` with power-law client sizes."""
    rng = np.random.RandomState(seed)
    sizes = (rng.lognormal(np.log(mean_samples), 1.0, n_clients)).astype(int) + min_samples
    sigma = np.diag(np.arange(1, n_features + 1, dtype=np.float64) ** -1.2)
    xs, ys, idx_map, pos = [], [], {}, 0
    for k in range(n_clients):
        u_k = rng.normal(0, alpha)
        b_k = rng.normal(0, beta)
        w_k = rng.normal(u_k, 1.0, (n_features, n_classes))
        bias_k = rng.normal(u_k, 1.0, (n_classes,))
        v_k = rng.normal(b_k, 1.0, (n_features,))
        x_k = rng.multivariate_normal(v_k, sigma, sizes[k]).astype(np.float32)
        y_k = np.argmax(x_k @ w_k + bias_k, axis=1).astype(np.int32)
        xs.append(x_k)
        ys.append(y_k)
        idx_map[k] = np.arange(pos, pos + sizes[k])
        pos += sizes[k]
    return np.concatenate(xs), np.concatenate(ys), idx_map


def make_stackoverflow_shard(
    n_clients: int,
    seq_len: int = 20,
    vocab: int = 10004,
    seed: int = 0,
    law: str = "uniform",
    kgroup: int = 8,
    active_tokens: int = 64,
    peak: float = 0.9,
    dialect_seed: int = 0,
    group_offset: int = 0,
    count_scale: int = 1,
):
    """ONE shard's worth of the StackOverflow-NWP law — ``(x, y,
    counts)`` with pareto per-client sentence counts and next-token
    targets over [1, vocab). The single source of the count/token
    distribution: :func:`make_stackoverflow_nwp` builds the flat
    federation from it, and ``bench.py``'s million-client
    ``synthetic_1m`` section feeds it per shard to
    ``ShardedFederatedStore.from_shard_builder`` — the 342k and 1M
    scale points can never drift apart in law.

    ``law`` picks the TOKEN law (the count law is shared, so the two
    laws emit identical per-client sizes at one ``seed``):

    - ``"uniform"`` (default, stream-identical to the pre-PR-15 code):
      i.i.d. tokens over [1, vocab) — the throughput/scale shape, no
      learnable signal.
    - ``"dialect"``: the LEARNABLE personalization law the adapter
      finetune measures against (transformer-consumable next-word
      prediction). All clients share one ``active_tokens``-sized
      vocabulary subset, but client ``c`` follows dialect ``(c +
      group_offset) % kgroup``'s OWN successor permutation over it
      (with prob ``peak``; else a uniform jump within the subset) — the
      same token has ``kgroup`` plausible successors, so a GLOBAL model
      is capped near ``peak/kgroup`` plus whatever in-context dialect
      inference it learns, while a client-personalized model can reach
      ``peak``. Dialect tables draw from ``dialect_seed`` (independent
      of ``seed``), so a held-out split (different ``seed``) shares the
      dialects; ``group_offset`` keeps per-shard builders' dialect
      assignment keyed on GLOBAL client ids.

    ``count_scale`` multiplies the pareto per-client sentence counts
    (same SHAPE, more mass — the personalization drills need enough
    per-client transitions to cover a dialect table); 1 (default) keeps
    the count stream bit-identical to the pre-PR-15 law."""
    rng = np.random.RandomState(seed)
    counts = 1 + (rng.pareto(1.5, n_clients) * 4).astype(np.int64).clip(0, 63)
    if count_scale != 1:
        counts = counts * int(count_scale)
    tot = int(counts.sum())
    if law == "uniform":
        x = rng.randint(1, vocab, (tot, seq_len)).astype(np.int32)
        y = np.roll(x, -1, axis=1)
        return x, y, counts
    if law != "dialect":
        raise ValueError(f"unknown token law {law!r}: expected "
                         "uniform | dialect")
    if not 1 <= active_tokens <= vocab - 1:
        raise ValueError(
            f"active_tokens={active_tokens} must fit in [1, vocab) "
            f"(vocab={vocab})")
    trng = np.random.RandomState((dialect_seed * 0x9E3779B1 + 0xD1A7)
                                 % (2 ** 31))
    subset = trng.choice(np.arange(1, vocab, dtype=np.int64),
                         size=active_tokens, replace=False)
    perms = np.stack([trng.permutation(active_tokens)
                      for _ in range(kgroup)])
    seq_group = np.repeat(
        (group_offset + np.arange(n_clients, dtype=np.int64)) % kgroup,
        counts)
    toks = np.empty((tot, seq_len + 1), np.int64)
    cur = rng.randint(0, active_tokens, tot)
    toks[:, 0] = cur
    for t in range(1, seq_len + 1):
        follow = rng.rand(tot) < peak
        jump = rng.randint(0, active_tokens, tot)
        cur = np.where(follow, perms[seq_group, cur], jump)
        toks[:, t] = cur
    seqs = subset[toks]
    x = seqs[:, :seq_len].astype(np.int32)
    y = seqs[:, 1:].astype(np.int32)
    return x, y, counts


def make_stackoverflow_nwp(
    n_clients: int,
    seq_len: int = 20,
    vocab: int = 10004,
    seed: int = 0,
    **law_kw,
):
    """StackOverflow-NWP-shaped synthetic federation at any client count
    (the real set enumerates 342,477 users — reference
    stackoverflow_nwp/data_loader.py): pareto per-client sentence counts,
    next-token targets, tokens drawn from [1, vocab) so pad_id=0 never
    collides. Returns ``(x, y, client_indices)`` for FederatedStore /
    build_federated_arrays. Shared by the full-scale store test and the
    bench submetric so the two can never drift. ``law_kw`` forwards the
    token-law knobs (``law="dialect"`` + friends) to
    :func:`make_stackoverflow_shard`."""
    x, y, counts = make_stackoverflow_shard(n_clients, seq_len, vocab, seed,
                                            **law_kw)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(n_clients)}
    return x, y, parts


def make_hetero_charlm(n_clients=256, seq_len=80, vocab=90, kgroup=16,
                       seqs_per_client=4, peak=0.98, seed=0):
    """Heterogeneity-boosted char-LM federation: ``kgroup`` DISJOINT
    order-1 Markov chains over the vocab (client c follows table
    c % kgroup), so sampled cohorts pull a shared model toward
    incompatible local optima — the drift regime FedProx's μ targets.

    Returns ``(x, y, parts)`` like the other builders here: [N, T]
    inputs, [N, T] shifted targets, per-client index dict. Single
    source for the FedProx reference-scale pin
    (tests/test_repro_convergence.py) and its calibration sweep
    (scripts/calibrate_prox_opt_pins.py) — the thresholds there are
    only valid for THIS generator at these defaults.
    """
    rng = np.random.RandomState(seed)
    succ = rng.randint(1, vocab, size=(kgroup, vocab))
    n_seq = n_clients * seqs_per_client
    group = (np.arange(n_seq) // seqs_per_client) % kgroup
    seqs = np.empty((n_seq, seq_len + 1), np.int32)
    state = rng.randint(1, vocab, size=n_seq)
    for t in range(seq_len + 1):
        seqs[:, t] = state
        follow = rng.rand(n_seq) < peak
        state = np.where(follow, succ[group, state],
                         rng.randint(1, vocab, size=n_seq))
    parts = {c: np.arange(c * seqs_per_client, (c + 1) * seqs_per_client)
             for c in range(n_clients)}
    return seqs[:, :seq_len], seqs[:, 1:], parts


def make_femnist_shaped(n_clients=200, n_classes=62, alpha=0.6, per=22,
                        maxper=None, n_test=2000, seed=0):
    """FEMNIST-shaped synthetic federation: 28x28x1 class-conditional
    Gaussian images with separation ``alpha``, lognormal power-law
    client sizes (optionally capped at ``maxper`` to bound the cohort
    step bucket — a bucket-4 round costs ~80 s on a 1-core CPU mesh).

    Returns ``(x_train, y_train, parts, x_test, y_test)``. Single
    source for the FedOpt reference-scale pin and its calibration
    sweep (see make_hetero_charlm).
    """
    rng = np.random.RandomState(seed)
    counts = np.maximum(4, rng.lognormal(np.log(per), 0.5,
                                         n_clients).astype(int))
    if maxper is not None:
        counts = np.minimum(counts, maxper)
    tot = int(counts.sum())
    y = rng.randint(0, n_classes, size=tot + n_test).astype(np.int32)
    protos = rng.randn(n_classes, 28, 28, 1).astype(np.float32)
    x = alpha * protos[y] + rng.randn(len(y), 28, 28, 1).astype(np.float32)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(n_clients)}
    return x[:tot], y[:tot], parts, x[tot:], y[tot:]
