"""Sharded client directory — the million-client storage tier.

``FederatedStore`` (data/store.py) keeps the WHOLE federation as one
in-RSS CSR array pair. That is the wall between the 342k-user
StackOverflow point and the millions-of-users north star: host memory is
O(dataset) even though every round touches only a ~50-client cohort.

This module splits the store into G shards behind the SAME gather
contract, bit-identically:

- ``ClientDirectory`` is the sampling/metadata service: the client→shard
  map, per-client sample counts, and per-shard client/row/sample tallies
  — O(num_clients) integers, never the sample arrays. Cohort sampling
  draws from these counts alone, so the sampled cohort is INVARIANT
  under re-sharding (same seed → same cohort for any G; tested) and the
  directory of a million clients is a few MB.
- ``ShardedFederatedStore`` subclasses ``FederatedStore``, overriding
  only the storage primitive (``_fill_rows``): every cohort slot maps to
  (shard, local row range) through the directory and is filled by a
  per-shard fancy-index gather. Bucketing, masks, staging buffers, the
  H2D put contract, ``gather_cohort``/``gather_window``, and the
  prefetchers are inherited unchanged — a sharded gather is
  byte-identical to the flat store's (tested: power-law partitions,
  empty clients, duplicates, non-dividing shard counts, forced buckets).
- Shards can be ``np.memmap``-backed (``spill_dir``): the sample arrays
  live in read-only ``.npy`` files and only the PAGES a gather touches
  become resident — host RSS is O(cohort + hot shard pages), not
  O(dataset). ``from_shard_builder`` constructs the store one shard at a
  time (generate → spill → drop), so even BUILD peak RSS is O(one
  shard). The existing ``CohortPrefetcher``/``WindowPrefetcher`` run the
  per-shard gathers on their worker thread, overlapping all shard page-in
  I/O with the current round's device compute.

The reduction-side counterpart (hierarchical sparse aggregation over
groups instead of a client-stacked ``all_gather``) lives in
``parallel/shard.py`` (``group_reduce``) and ``algos/hierarchical.py``;
``bench.py``'s ``synthetic_1m`` section drives both at 1M+ synthetic
clients with peak host RSS as a first-class submetric. See
docs/EXECUTION.md "Scale tiers".
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.core.sampling import sample_clients, sample_clients_weighted
from fedml_tpu.data.store import FederatedStore


class ClientDirectory:
    """Client→shard map + count metadata: the part of a federation a
    cohort SAMPLER needs, decoupled from the sample arrays.

    ``counts[c]`` is client c's sample count (already capped by any
    ``max_steps`` truncation), ``shard_of[c]`` its shard. Within a shard,
    clients are stored in ascending global-id order, so
    ``local_row_start[c]`` (the first row of client c inside its shard's
    arrays) is the exclusive cumsum of the shard's counts in id order.
    """

    def __init__(self, counts, shard_of, num_shards: Optional[int] = None):
        counts = np.asarray(counts, np.int64)
        shard_of = np.asarray(shard_of, np.int32)
        if counts.shape != shard_of.shape:
            raise ValueError(
                f"counts {counts.shape} and shard_of {shard_of.shape} must "
                "have one entry per client")
        n = len(counts)
        g = int(num_shards if num_shards is not None
                else (shard_of.max() + 1 if n else 0))
        if n and (shard_of.min() < 0 or shard_of.max() >= g):
            raise ValueError(
                f"shard ids must be in [0, {g}); got "
                f"[{shard_of.min()}, {shard_of.max()}]")
        self.counts = counts.astype(np.int32)
        self.shard_of = shard_of
        self.num_clients = n
        self.num_shards = g
        self.shard_clients = np.bincount(shard_of, minlength=g).astype(
            np.int64)
        self.shard_rows = (np.bincount(shard_of, weights=counts,
                                       minlength=g).astype(np.int64)
                           if n else np.zeros(g, np.int64))
        # local_row_start in ONE grouped pass (a per-shard boolean scan
        # would be O(G·N) — minutes at 1M clients with thousands of
        # shards): order clients by (shard, id), take the global
        # exclusive row cumsum in that order, and subtract each shard's
        # starting row.
        self.local_row_start = np.zeros(n, np.int64)
        if n:
            order = np.argsort(shard_of, kind="stable")  # id-sorted within
            excl = np.concatenate([[0], np.cumsum(counts[order])[:-1]])
            shard_row_start = np.concatenate(
                [[0], np.cumsum(self.shard_rows)[:-1]])
            self.local_row_start[order] = \
                excl - shard_row_start[shard_of[order]]

    # -- the sampling service -------------------------------------------
    # Both draws consume ONLY directory metadata (never sample arrays)
    # and delegate to core/sampling's reference-seeded streams, so the
    # cohort a round samples is a pure function of (seed, total, num) —
    # identical for the flat store and ANY sharding of it (the
    # re-sharding determinism invariant, pinned in tests/test_directory).

    def sample_cohort(self, round_idx: int, num: int) -> np.ndarray:
        """Seeded-uniform cohort draw (the reference's
        ``np.random.seed(round_idx)`` stream, ``core/sampling``)."""
        return sample_clients(round_idx, self.num_clients, num)

    def sample_cohort_weighted(self, round_idx: int, num: int) -> np.ndarray:
        """Data-fraction-proportional draw over the directory's counts
        (Power-of-Choice candidate sampling) — still no sample arrays."""
        return sample_clients_weighted(
            round_idx, self.num_clients, num, self.counts)

    def shard_histogram(self, indices) -> np.ndarray:
        """``[G]`` — how many of ``indices`` live on each shard (gather
        planning / hot-shard accounting)."""
        return np.bincount(self.shard_of[np.asarray(indices)],
                           minlength=self.num_shards)

    def agg_shard_of(self, indices, num_agg_shards: int):
        """Aggregator-shard assignment for the sharded aggregation plane
        (comm/shardplane.py): fold the ``G`` DATA shards onto ``M``
        aggregator shards by modulo, so clients that share a data shard
        share an aggregator shard whenever ``M`` divides ``G`` — upload
        locality follows storage locality. Scalar in → scalar out;
        array in → int32 array."""
        m = int(num_agg_shards)
        if m < 1:
            raise ValueError(f"num_agg_shards={num_agg_shards} must be >= 1")
        if np.isscalar(indices):
            return int(self.shard_of[int(indices)]) % m
        return (self.shard_of[np.asarray(indices)] % m).astype(np.int32)

    def nbytes(self) -> int:
        return (self.counts.nbytes + self.shard_of.nbytes
                + self.local_row_start.nbytes + self.shard_clients.nbytes
                + self.shard_rows.nbytes)


def _spill(arr: np.ndarray, path: str) -> np.ndarray:
    """Write ``arr`` to a ``.npy`` memmap and reopen READ-ONLY: the dirty
    build pages are unmapped on close (RSS drops back), and subsequent
    gathers fault in only the pages they touch."""
    mm = np.lib.format.open_memmap(path, mode="w+", dtype=arr.dtype,
                                   shape=arr.shape)
    mm[...] = arr
    mm.flush()
    del mm
    return np.load(path, mmap_mode="r")


class StoreShard:
    """One shard's sample storage: rows of its clients in ascending
    global-client-id order (``x [rows, ...]``, ``y [rows, ...]`` — plain
    ndarray or read-only memmap)."""

    __slots__ = ("x", "y")

    def __init__(self, x: np.ndarray, y: np.ndarray):
        if len(x) != len(y):
            raise ValueError(f"shard x/y row mismatch: {len(x)} vs {len(y)}")
        self.x = x
        self.y = y


class ShardedFederatedStore(FederatedStore):
    """G-sharded ``FederatedStore``: same gather contract, bit-identical
    output, host RSS O(cohort + hot shards). Construct via
    :meth:`from_flat` (split an in-memory federation; tests,
    medium scale) or :meth:`from_shard_builder` (per-shard generation +
    memmap spill; million-client scale)."""

    def __init__(self, shards: Sequence[StoreShard],
                 directory: ClientDirectory, batch_size: int,
                 max_steps: Optional[int] = None):
        if len(shards) != directory.num_shards:
            raise ValueError(
                f"{len(shards)} shards vs directory.num_shards="
                f"{directory.num_shards}")
        for s, sh in enumerate(shards):
            if len(sh.x) != directory.shard_rows[s]:
                raise ValueError(
                    f"shard {s} holds {len(sh.x)} rows; directory expects "
                    f"{int(directory.shard_rows[s])}")
        self._shards = list(shards)
        self.directory = directory
        ref = shards[0].x if shards else np.zeros((0, 1), np.float32)
        refy = shards[0].y if shards else np.zeros((0,), np.int32)
        self._init_meta(directory.counts, batch_size, max_steps,
                        ref.shape[1:], ref.dtype, refy.shape[1:], refy.dtype)

    # -- the storage primitive ------------------------------------------
    def _fill_rows(self, idx: np.ndarray, cap: int,
                   xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
        """Per-shard fancy-index gather: each cohort slot's rows come
        from ``local_row_start[client] + position`` inside its shard
        (positions past the count repeat the first row — the same pad
        rule as the flat CSR row map). Empty slots are left for the
        caller to zero, exactly the flat contract. On memmap shards the
        fancy index reads only the touched rows' pages."""
        d = self.directory
        flat = idx.reshape(-1)
        n = (self.offsets[flat + 1] - self.offsets[flat]).astype(np.int64)
        lo = d.local_row_start[flat]
        pos = np.arange(cap, dtype=np.int64)
        rows = lo[:, None] + np.where(pos < n[:, None], pos, 0)
        empty = n == 0
        sid = d.shard_of[flat]
        xf = xs.reshape((-1, cap) + self._sample_shape)
        yf = ys.reshape((-1, cap) + self._label_shape)
        for s in np.unique(sid):
            m = (sid == s) & ~empty
            if not m.any():
                continue
            sh = self._shards[s]
            xf[m] = sh.x[rows[m]]
            yf[m] = sh.y[rows[m]]
        return empty.reshape(idx.shape)

    def _gather_cohort_loop(self, indices, steps=None):
        raise NotImplementedError(
            "the scalar copy-loop reference lives on the flat "
            "FederatedStore; sharded gathers are pinned bit-equal to the "
            "flat store's instead (tests/test_directory.py)")

    def nbytes(self) -> int:
        """Total DATASET bytes across shards (memmap shards count their
        file size, not their resident pages — see ``bench.py``'s RSS
        submetrics for what is actually paged in)."""
        return sum(sh.x.nbytes + sh.y.nbytes for sh in self._shards)

    @property
    def memmapped(self) -> bool:
        return any(isinstance(sh.x, np.memmap) for sh in self._shards)

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_flat(cls, x: np.ndarray, y: np.ndarray,
                  client_indices: Dict[int, np.ndarray], batch_size: int,
                  num_shards: int = 1, shard_of=None,
                  max_steps: Optional[int] = None,
                  spill_dir: Optional[str] = None) -> "ShardedFederatedStore":
        """Split an in-memory federation (the ``FederatedStore``
        constructor signature plus sharding controls). ``shard_of``
        assigns clients to shards arbitrarily (per group / per host);
        default is ``num_shards`` contiguous client blocks. With
        ``spill_dir`` each shard is memmap-spilled."""
        n_clients = len(client_indices)
        counts = np.array(
            [len(client_indices[c]) for c in range(n_clients)], np.int64)
        if max_steps is not None:
            counts = np.minimum(counts, max_steps * batch_size)
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if shard_of is None:
            shard_of = ((np.arange(n_clients) * num_shards)
                        // max(n_clients, 1)).astype(np.int32)
        else:
            # An explicit num_shards larger than the map's max id keeps
            # its trailing EMPTY shards (mirroring a host layout where
            # some hosts currently hold no clients) instead of being
            # silently discarded.
            shard_of = np.asarray(shard_of, np.int32)
            num_shards = max(num_shards,
                             int(shard_of.max()) + 1 if n_clients else 0)
        directory = ClientDirectory(counts, shard_of, num_shards)
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        shards = []
        for s in range(num_shards):
            cl = np.flatnonzero(shard_of == s)  # ascending global id
            order = (np.concatenate(
                [np.asarray(client_indices[c])[: counts[c]] for c in cl])
                if cl.size and counts[cl].sum() else np.zeros((0,), np.int64))
            sx = np.ascontiguousarray(x[order])
            sy = np.ascontiguousarray(y[order])
            if spill_dir is not None:
                sx = _spill(sx, os.path.join(spill_dir, f"shard{s:05d}_x.npy"))
                sy = _spill(sy, os.path.join(spill_dir, f"shard{s:05d}_y.npy"))
            shards.append(StoreShard(sx, sy))
        return cls(shards, directory, batch_size, max_steps=max_steps)

    @classmethod
    def from_shard_builder(
            cls,
            builder: Callable[[int], Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]],
            num_shards: int, batch_size: int, spill_dir: str,
            progress: Optional[Callable[[int], None]] = None,
    ) -> "ShardedFederatedStore":
        """Build one shard at a time: ``builder(s) -> (x_s, y_s,
        counts_s)`` where ``counts_s`` are the per-client sample counts
        of shard s's clients and shard s owns the NEXT ``len(counts_s)``
        global client ids (contiguous blocks, in shard order). Each
        shard is generated, memmap-spilled, and DROPPED before the next
        is built, so construction peak RSS is O(one shard) — the path
        the million-client bench takes. ``progress(s)`` is called before
        each shard build (deadline checks / logging)."""
        os.makedirs(spill_dir, exist_ok=True)
        shards: List[StoreShard] = []
        count_parts: List[np.ndarray] = []
        for s in range(num_shards):
            if progress is not None:
                progress(s)
            sx, sy, scounts = builder(s)
            scounts = np.asarray(scounts, np.int64)
            if len(sx) != int(scounts.sum()):
                raise ValueError(
                    f"builder({s}) returned {len(sx)} rows but counts sum "
                    f"to {int(scounts.sum())}")
            shards.append(StoreShard(
                _spill(np.ascontiguousarray(sx),
                       os.path.join(spill_dir, f"shard{s:05d}_x.npy")),
                _spill(np.ascontiguousarray(sy),
                       os.path.join(spill_dir, f"shard{s:05d}_y.npy"))))
            count_parts.append(scounts)
            del sx, sy  # peak RSS stays O(one shard)
        counts = (np.concatenate(count_parts) if count_parts
                  else np.zeros((0,), np.int64))
        shard_of = (np.repeat(np.arange(num_shards, dtype=np.int32),
                              [len(p) for p in count_parts])
                    if count_parts else np.zeros((0,), np.int32))
        directory = ClientDirectory(counts, shard_of, num_shards)
        return cls(shards, directory, batch_size)
