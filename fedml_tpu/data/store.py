"""Host-resident federated dataset with per-round cohort streaming.

The resident ``FederatedArrays`` layout (batching.py) pads EVERY client to
the size of the largest one and keeps the whole dataset in device memory —
elegant at 128 clients, impossible at the reference's client scales
(FederatedEMNIST: 3,400 writers, ``FederatedEMNIST/data_loader.py:15``;
StackOverflow: 342,477 users, ``stackoverflow_nwp/data_loader.py``), and
on power-law partitions (LEAF MNIST, ``MNIST/data_loader.py:87``) one
giant client inflates every client's padded rows.

``FederatedStore`` keeps the dataset as host numpy in CSR form (one flat
sample array sorted by client + offsets) and materializes only the
sampled cohort per round:

  - device memory per round = cohort_size x cohort_max_steps x batch —
    independent of the total client count;
  - the cohort is padded to ITS OWN max count (bucketed to a power of two
    so XLA sees a handful of shapes, not one per round), so power-law
    tails no longer tax every round;
  - ``gather_cohort`` returns a regular ``FederatedArrays``, so the
    existing jitted rounds (vmap and shard_map) consume it unchanged;
  - ``CohortPrefetcher`` overlaps the next round's host gather + H2D
    transfer with the current round's compute (double buffering): JAX
    dispatch is async, so ``jnp.asarray`` from the worker thread starts
    the copy immediately;
  - ``gather_window`` stacks W precomputed cohorts into ONE
    ``[W, k, S, B, ...]`` superbatch (a single fancy-index gather into
    reused staging buffers + one H2D transfer per field) for the windowed
    execution tier (``FedAvgAPI.train_rounds_windowed``), with
    ``WindowPrefetcher`` double-buffering the next window's gather + H2D
    against the current window's scan.

Past the flat store's own wall (host RSS is O(dataset)), the
million-client tier shards this layout behind the SAME contract:
``data/directory.py``'s ``ShardedFederatedStore`` overrides only the
``_fill_rows`` storage primitive (per-shard, memmap-backed gathers,
bit-equal — see docs/EXECUTION.md "Scale tiers").
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.data.batching import FederatedArrays, WindowBatch
from fedml_tpu.obs.sanitizer import planned_transfer


def _bucket_steps(steps: int) -> int:
    """Round up to a power of two: bounds the number of distinct cohort
    shapes (→ jit retraces) at log2(max_steps)."""
    steps = max(int(steps), 1)
    return 1 << (steps - 1).bit_length()


def bucket_steps_for_counts(counts, batch_size: int) -> np.ndarray:
    """Vectorized :func:`_bucket_steps` of every client's step need —
    the ONE other place the bucket policy is computed (bench warmup must
    warm exactly the shapes the store will produce; a drifted copy would
    let jit recompiles land inside timed windows). Exact bit-twiddle
    round-up, no float log2; pinned equal to the scalar form in
    tests/test_store.py."""
    steps = np.maximum(
        -(-np.asarray(counts, np.int64) // int(batch_size)),
        1).astype(np.uint64)
    v = steps - 1
    for shift in (1, 2, 4, 8, 16, 32):
        v |= v >> np.uint64(shift)
    return (v + 1).astype(np.int64)


class FederatedStore:
    """CSR host store over a federated dataset.

    ``client_indices`` maps client id (0..C-1) to index arrays into
    ``(x, y)`` — the same contract as ``build_federated_arrays``. The
    store copies samples into client-sorted order once so each client's
    block is one contiguous slice at gather time.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        client_indices: Dict[int, np.ndarray],
        batch_size: int,
        max_steps: Optional[int] = None,
    ):
        n_clients = len(client_indices)
        counts = np.array(
            [len(client_indices[c]) for c in range(n_clients)], np.int64)
        if max_steps is not None:
            counts = np.minimum(counts, max_steps * batch_size)
        order = np.concatenate(
            [np.asarray(client_indices[c])[: counts[c]]
             for c in range(n_clients)]) if counts.sum() else \
            np.zeros((0,), np.int64)
        self._x = np.ascontiguousarray(x[order])
        self._y = np.ascontiguousarray(y[order])
        self._init_meta(counts, batch_size, max_steps,
                        x.shape[1:], x.dtype, y.shape[1:], y.dtype)

    def _init_meta(self, counts, batch_size, max_steps,
                   sample_shape, sample_dtype, label_shape, label_dtype):
        """Shared metadata/staging init — everything about the store that
        is NOT the backing sample storage. ``ShardedFederatedStore``
        (data/directory.py) reuses the whole gather contract through this
        plus the :meth:`_fill_rows` storage primitive."""
        counts = np.asarray(counts, np.int64)
        self.offsets = np.concatenate([[0], np.cumsum(counts)])
        self.counts = counts.astype(np.int32)
        self.batch_size = int(batch_size)
        self.max_steps = max_steps
        self.num_clients = len(counts)
        self._sample_shape = tuple(sample_shape)
        self._sample_dtype = np.dtype(sample_dtype)
        self._label_shape = tuple(label_shape)
        self._label_dtype = np.dtype(label_dtype)
        # Reused host staging buffers for window superbatches (one buffer
        # per (field, shape) — windows of the same span length and bucket
        # refill the same memory instead of re-faulting fresh pages every
        # window). Guarded by a lock: gather_window publishes its device
        # copies BEFORE releasing, so a concurrent gather can never
        # overwrite a buffer an in-flight H2D transfer still reads.
        self._staging: Dict[tuple, np.ndarray] = {}
        self._staging_lock = threading.Lock()

    def example_input(self) -> np.ndarray:
        """One zero batch with the store's sample shape/dtype — what model
        init needs (mirrors ``train_fed.x[0, 0]`` on the resident path)."""
        return np.zeros((self.batch_size,) + self._sample_shape,
                        self._sample_dtype)

    def nbytes(self) -> int:
        return self._x.nbytes + self._y.nbytes

    def cohort_steps(self, indices) -> int:
        """The power-of-two step bucket a cohort needs — the same number
        ``gather_cohort`` computes internally, exposed so window planning
        (``FedAvgAPI.train_rounds_windowed``) can group upcoming rounds by
        bucket WITHOUT gathering them."""
        ccounts = self.counts[np.asarray(indices)]
        return _bucket_steps(
            int(np.ceil(max(int(ccounts.max()), 1) / self.batch_size)))

    def _resolve_steps(self, ccounts: np.ndarray, steps: Optional[int]):
        """Validate/derive the step bucket for a gather over clients with
        per-client counts ``ccounts`` (any shape)."""
        bs = self.batch_size
        need = _bucket_steps(int(np.ceil(max(int(ccounts.max()), 1) / bs)))
        if steps is None:
            return need
        if steps < need:
            raise ValueError(
                f"forced steps {steps} < cohort need {need} "
                f"(max client count {int(ccounts.max())}, batch {bs})")
        return int(steps)

    def _rowmap(self, idx: np.ndarray, cap: int):
        """Precomputed row map for a fancy-index gather: for every cohort
        slot and sample position, the row of the flat CSR arrays to copy.
        Positions past a client's count repeat its FIRST row (the masked
        own-first-sample pad rule of ``build_federated_arrays``). Returns
        ``(rows [*idx.shape, cap] int64, empty [*idx.shape] bool)`` —
        rows of ``empty`` (zero-count) clients point at row 0 and must be
        zeroed after the gather (the loop reference leaves them zero)."""
        lo = self.offsets[idx].astype(np.int64)
        n = (self.offsets[idx + 1] - self.offsets[idx]).astype(np.int64)
        pos = np.arange(cap, dtype=np.int64)
        rows = lo[..., None] + np.where(pos < n[..., None], pos, 0)
        empty = n == 0
        if empty.any():
            rows = np.where(empty[..., None], 0, rows)
        return rows, empty

    def _fill_rows(self, idx: np.ndarray, cap: int,
                   xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
        """The STORAGE PRIMITIVE behind both gathers: fill the
        preallocated ``xs [*idx.shape, cap, ...]`` / ``ys`` with each
        cohort slot's rows (positions past a client's count repeat its
        first row — the masked own-first-sample pad rule) and return the
        ``[*idx.shape]`` bool mask of EMPTY (zero-count) slots, whose
        rows the caller zeroes (this method may leave them unwritten).
        The flat store gathers from its one CSR array pair;
        ``ShardedFederatedStore`` overrides this with per-shard gathers —
        everything above (bucketing, masks, staging, H2D, put contracts)
        is storage-agnostic and shared."""
        rows, empty = self._rowmap(idx, cap)
        np.take(self._x, rows, axis=0, out=xs)
        np.take(self._y, rows, axis=0, out=ys)
        return empty

    def gather_cohort(self, indices,
                      steps: Optional[int] = None) -> FederatedArrays:
        """Materialize the sampled clients as a device-resident
        ``FederatedArrays`` padded to the COHORT max count (power-of-two
        step bucket). Duplicate indices are fine (pad_to_multiple repeats
        index 0 with weight 0). One vectorized fancy-index gather per
        field (byte-identical to :meth:`_gather_cohort_loop`, the scalar
        reference the tests pin it against — the per-client Python copy
        loop cost O(k) interpreter trips per round at reference scale).

        ``steps`` forces the step bucket (must cover the cohort's own
        need): multi-host runs, where each host holds only its
        ``process_local_client_slice`` of the clients, pass the GLOBAL
        cohort bucket (allgather of the per-host maxima) so every host's
        shard of the client-sharded round has identical [S, B] shapes —
        see tests/multihost_worker.py:run_store_rounds."""
        idx = np.asarray(indices)
        k = len(idx)
        ccounts = self.counts[idx]
        steps = self._resolve_steps(ccounts, steps)
        cap = steps * self.batch_size

        xs = np.empty((k, cap) + self._sample_shape, self._sample_dtype)
        ys = np.empty((k, cap) + self._label_shape, self._label_dtype)
        empty = self._fill_rows(idx, cap, xs, ys)
        mask = (np.arange(cap) < ccounts[:, None]).astype(np.float32)
        if empty.any():
            xs[empty] = 0
            ys[empty] = 0

        def split(a):
            return a.reshape((k, steps, self.batch_size) + a.shape[2:])

        # planned_transfer: the cohort H2D is the streaming tier's ONE
        # deliberate staging copy per round — mark it so the whole round
        # loop can run under obs.sanitizer.sanitized()'s transfer guard.
        with planned_transfer():
            return FederatedArrays(
                x=jnp.asarray(split(xs)),
                y=jnp.asarray(split(ys)),
                mask=jnp.asarray(split(mask)),
                counts=jnp.asarray(ccounts, jnp.int32),
            )

    def _gather_cohort_loop(self, indices,
                            steps: Optional[int] = None) -> FederatedArrays:
        """The original per-client copy-loop gather, kept as the scalar
        REFERENCE implementation: tests assert ``gather_cohort``'s
        vectorized fancy-index path stays byte-identical to it. Not used
        on any hot path."""
        idx = np.asarray(indices)
        k = len(idx)
        ccounts = self.counts[idx]
        steps = self._resolve_steps(ccounts, steps)
        cap = steps * self.batch_size

        xs = np.zeros((k, cap) + self._x.shape[1:], self._x.dtype)
        ys = np.zeros((k, cap) + self._y.shape[1:], self._y.dtype)
        mask = np.zeros((k, cap), np.float32)
        for j, c in enumerate(idx):
            lo, hi = int(self.offsets[c]), int(self.offsets[c + 1])
            n = hi - lo
            if n == 0:
                continue
            xs[j, :n] = self._x[lo:hi]
            ys[j, :n] = self._y[lo:hi]
            mask[j, :n] = 1.0
            if n < cap:  # pad with the client's own first sample (masked)
                xs[j, n:] = self._x[lo]
                ys[j, n:] = self._y[lo]

        def split(a):
            return a.reshape((k, steps, self.batch_size) + a.shape[2:])

        return FederatedArrays(
            x=jnp.asarray(split(xs)),
            y=jnp.asarray(split(ys)),
            mask=jnp.asarray(split(mask)),
            counts=jnp.asarray(ccounts, jnp.int32),
        )

    def window_weights(self, window_indices, wmask) -> np.ndarray:
        """``[W, k]`` float32 aggregation weights for a window: per-slot
        sample counts gathered through the window's index map, zeroed at
        padded slots (``wmask``). The window-keyed companion of
        :meth:`gather_window` for count-derived per-round state — host
        math (one fancy-index gather over ``counts``), shared by the
        windowed executor's weights and the carry protocol's masks so
        they can never drift from the per-round host loop's
        ``sub.counts * wmask``."""
        idx = np.asarray(window_indices)
        return (self.counts[idx].astype(np.float32)
                * np.asarray(wmask, np.float32))

    def window_trained_mask(self, window_indices, wmask) -> np.ndarray:
        """``[W, k]`` float32 mask of slots that actually TRAIN in their
        round: active (un-padded) AND non-empty. Algorithms that carry
        per-client state through the window scan (SCAFFOLD's controls)
        gate their scatter-back on this — a sampled EMPTY client runs
        zero real steps and must not write its state slot (same rule as
        the per-round host loop)."""
        idx = np.asarray(window_indices)
        return (np.asarray(wmask, np.float32)
                * (self.counts[idx] > 0).astype(np.float32))

    def _staged(self, field: str, shape: tuple, dtype) -> np.ndarray:
        """Reused staging buffer, one per (field, shape, dtype) — keyed
        by the full shape so alternating window-max buckets (giant
        client in/out of the window) each keep their own buffer instead
        of thrashing a single slot with reallocations. Shape count is
        bounded by the power-of-two bucket count. Caller must hold
        ``_staging_lock``."""
        key = (field, shape, np.dtype(dtype).str)
        buf = self._staging.get(key)
        if buf is None:
            buf = np.empty(shape, dtype)
            self._staging[key] = buf
        return buf

    def gather_window(self, window_indices, steps: int,
                      put=None) -> WindowBatch:
        """Gather W rounds' cohorts into ONE ``[W, k, S, B, ...]``
        superbatch: a single fancy-index gather per field (precomputed row
        maps, reused staging buffers) and a single H2D transfer per field,
        instead of W per-round gather + transfer round-trips.

        ``window_indices`` is the ``[W, k]`` array of per-round padded
        cohort indices (known in advance under seeded-random selection).
        ``steps`` is the window's SHARED step bucket and must cover every
        round's own need; the windowed executor passes the window-max
        bucket, so a round whose natural bucket is smaller gets extra
        masked pad rows — its slice equals its own
        ``gather_cohort(idx, steps=steps)`` with the same forced bucket
        (tested), and training on it is an exact no-op relative to the
        natural bucket because the trainer's rng streams are
        prefix-stable in the step count (``trainer.local``).

        ``put`` maps each staged host array to the device (default
        ``jnp.array`` — an EXPLICIT copy: the CPU backend may otherwise
        alias numpy memory zero-copy, and the staging buffers are
        refilled next window); mesh runs pass a sharded ``device_put``.
        A custom ``put`` must either copy before putting and declare it
        (``put.copies = True``, as ``parallel.shard.window_put`` does)
        or accept the defensive ``np.array`` copy this method inserts —
        the PR-1 aliasing bug class (fedlint R2) is a put that zero-copy
        aliases a staging buffer the next window refills.
        The device arrays are blocked on before the staging lock is
        released, so buffer reuse can never race an in-flight transfer."""
        idx = np.asarray(window_indices)
        if idx.ndim != 2:
            raise ValueError(f"window_indices must be [W, k], got {idx.shape}")
        w, k = idx.shape
        ccounts = self.counts[idx]
        steps = self._resolve_steps(ccounts, steps)
        cap = steps * self.batch_size
        if put is None:
            put, put_copies = jnp.array, True  # jnp.array copies by default
        else:
            put_copies = bool(getattr(put, "copies", False))

        with self._staging_lock:
            xs = self._staged("x", (w, k, cap) + self._sample_shape,
                              self._sample_dtype)
            ys = self._staged("y", (w, k, cap) + self._label_shape,
                              self._label_dtype)
            empty = self._fill_rows(idx, cap, xs, ys)
            if empty.any():
                xs[empty] = 0
                ys[empty] = 0
            mask = (np.arange(cap) < ccounts[..., None]).astype(np.float32)

            def split(a):
                return a.reshape((w, k, steps, self.batch_size) + a.shape[3:])

            def staged_put(a):
                # R2 staging-alias guard: a put that has not declared
                # ``copies = True`` may alias the reused staging buffer
                # zero-copy (jax.device_put does, on the CPU backend) —
                # hand it a fresh copy, the same guard window_put carries
                # internally. ``mask`` is freshly allocated per call, so
                # only the staged x/y fields need it.
                return put(a if put_copies else np.array(a))

            # planned_transfer: the window superbatch H2D is THE
            # deliberate staging copy of the windowed tier (one per
            # window) — mark it for obs.sanitizer.sanitized() regions.
            with planned_transfer():
                batch = WindowBatch(
                    x=staged_put(split(xs)),
                    y=staged_put(split(ys)),
                    mask=put(split(mask)),
                    counts=jnp.asarray(ccounts, jnp.int32),
                )
                # Block INSIDE the lock: once we release, the next window
                # may refill xs/ys while these transfers still read them.
                jax.block_until_ready((batch.x, batch.y, batch.mask))
        return batch


class CohortPrefetcher:
    """Double buffer: prepare round r+1's cohort (host gather + async H2D)
    on a worker thread while round r computes. ``get`` blocks on the
    in-flight preparation only if it has not finished yet."""

    def __init__(self, store: FederatedStore):
        self.store = store
        self._pending: Dict[int, threading.Thread] = {}
        self._ready: Dict[int, tuple] = {}  # round -> (indices, cohort)
        self._lock = threading.Lock()

    def prefetch(self, round_idx: int, indices) -> None:
        indices = np.asarray(indices)

        def work():
            try:
                cohort = self.store.gather_cohort(indices)
                with self._lock:
                    self._ready[round_idx] = (indices, cohort)
            finally:
                # Always clear pending — a worker failure (host OOM, bad
                # index) must not permanently block future prefetches for
                # this round; get() then re-gathers synchronously and the
                # real exception surfaces in the caller's context.
                with self._lock:
                    self._pending.pop(round_idx, None)

        t = threading.Thread(target=work, daemon=True)
        with self._lock:
            # Membership check and registration under ONE acquisition:
            # check-then-act across two lock scopes would let concurrent
            # prefetch calls for the same round both spawn gather threads.
            if round_idx in self._pending or round_idx in self._ready:
                return
            self._pending[round_idx] = t
        t.start()

    def get(self, round_idx: int, indices) -> FederatedArrays:
        with self._lock:
            t = self._pending.get(round_idx)
        if t is not None:
            t.join()
        with self._lock:
            hit = self._ready.pop(round_idx, None)
            # Drop stale buffers (a user skipping rounds must not leak).
            for r in [r for r in self._ready if r < round_idx]:
                self._ready.pop(r)
        # The prefetched cohort is only valid for the EXACT index list the
        # caller now wants — sampling inputs may have changed between the
        # prefetch and the round (cfg mutation, subclass overrides).
        if hit is not None and np.array_equal(hit[0], np.asarray(indices)):
            return hit[1]
        return self.store.gather_cohort(indices)


class WindowPrefetcher:
    """Double buffer for window superbatches: gather + H2D of window w+1
    on a worker thread while window w's scan computes. A worker failure
    (host OOM, bad index) is CONTAINED: the exception is captured and
    re-raised in the caller's ``get`` — never a deadlock, never a
    silently-dropped window — and the prefetcher stays usable afterwards
    (subsequent gets fall through to a synchronous gather)."""

    def __init__(self, store: FederatedStore, put=None):
        self.store = store
        self.put = put
        self._pending: Dict[int, threading.Thread] = {}
        # key -> ("ok", (indices, steps, batch)) | ("err", exception)
        self._done: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    def prefetch(self, key: int, window_indices, steps: int) -> None:
        indices = np.asarray(window_indices)

        def work():
            try:
                res = ("ok", (indices, steps,
                              self.store.gather_window(
                                  indices, steps, put=self.put)))
            except BaseException as e:  # surfaces in get(), not the log
                res = ("err", e)
            with self._lock:
                self._done[key] = res
                self._pending.pop(key, None)

        t = threading.Thread(target=work, daemon=True)
        with self._lock:
            if key in self._pending or key in self._done:
                return
            self._pending[key] = t
        t.start()

    def get(self, key: int, window_indices, steps: int) -> WindowBatch:
        with self._lock:
            t = self._pending.get(key)
        if t is not None:
            t.join()
        with self._lock:
            hit = self._done.pop(key, None)
            for stale in [s for s in self._done if s < key]:
                self._done.pop(stale)  # skipped windows must not leak
        if hit is not None:
            tag, val = hit
            if tag == "err":
                raise val
            pidx, psteps, batch = val
            if psteps == steps and np.array_equal(
                    pidx, np.asarray(window_indices)):
                return batch
        return self.store.gather_window(window_indices, steps, put=self.put)
