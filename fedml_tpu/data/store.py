"""Host-resident federated dataset with per-round cohort streaming.

The resident ``FederatedArrays`` layout (batching.py) pads EVERY client to
the size of the largest one and keeps the whole dataset in device memory —
elegant at 128 clients, impossible at the reference's client scales
(FederatedEMNIST: 3,400 writers, ``FederatedEMNIST/data_loader.py:15``;
StackOverflow: 342,477 users, ``stackoverflow_nwp/data_loader.py``), and
on power-law partitions (LEAF MNIST, ``MNIST/data_loader.py:87``) one
giant client inflates every client's padded rows.

``FederatedStore`` keeps the dataset as host numpy in CSR form (one flat
sample array sorted by client + offsets) and materializes only the
sampled cohort per round:

  - device memory per round = cohort_size x cohort_max_steps x batch —
    independent of the total client count;
  - the cohort is padded to ITS OWN max count (bucketed to a power of two
    so XLA sees a handful of shapes, not one per round), so power-law
    tails no longer tax every round;
  - ``gather_cohort`` returns a regular ``FederatedArrays``, so the
    existing jitted rounds (vmap and shard_map) consume it unchanged;
  - ``CohortPrefetcher`` overlaps the next round's host gather + H2D
    transfer with the current round's compute (double buffering): JAX
    dispatch is async, so ``jnp.asarray`` from the worker thread starts
    the copy immediately.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from fedml_tpu.data.batching import FederatedArrays


def _bucket_steps(steps: int) -> int:
    """Round up to a power of two: bounds the number of distinct cohort
    shapes (→ jit retraces) at log2(max_steps)."""
    steps = max(int(steps), 1)
    return 1 << (steps - 1).bit_length()


class FederatedStore:
    """CSR host store over a federated dataset.

    ``client_indices`` maps client id (0..C-1) to index arrays into
    ``(x, y)`` — the same contract as ``build_federated_arrays``. The
    store copies samples into client-sorted order once so each client's
    block is one contiguous slice at gather time.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        client_indices: Dict[int, np.ndarray],
        batch_size: int,
        max_steps: Optional[int] = None,
    ):
        n_clients = len(client_indices)
        counts = np.array(
            [len(client_indices[c]) for c in range(n_clients)], np.int64)
        if max_steps is not None:
            counts = np.minimum(counts, max_steps * batch_size)
        order = np.concatenate(
            [np.asarray(client_indices[c])[: counts[c]]
             for c in range(n_clients)]) if counts.sum() else \
            np.zeros((0,), np.int64)
        self._x = np.ascontiguousarray(x[order])
        self._y = np.ascontiguousarray(y[order])
        self.offsets = np.concatenate([[0], np.cumsum(counts)])
        self.counts = counts.astype(np.int32)
        self.batch_size = int(batch_size)
        self.max_steps = max_steps
        self.num_clients = n_clients

    def example_input(self) -> np.ndarray:
        """One zero batch with the store's sample shape/dtype — what model
        init needs (mirrors ``train_fed.x[0, 0]`` on the resident path)."""
        return np.zeros((self.batch_size,) + self._x.shape[1:], self._x.dtype)

    def nbytes(self) -> int:
        return self._x.nbytes + self._y.nbytes

    def gather_cohort(self, indices,
                      steps: Optional[int] = None) -> FederatedArrays:
        """Materialize the sampled clients as a device-resident
        ``FederatedArrays`` padded to the COHORT max count (power-of-two
        step bucket). Duplicate indices are fine (pad_to_multiple repeats
        index 0 with weight 0).

        ``steps`` forces the step bucket (must cover the cohort's own
        need): multi-host runs, where each host holds only its
        ``process_local_client_slice`` of the clients, pass the GLOBAL
        cohort bucket (allgather of the per-host maxima) so every host's
        shard of the client-sharded round has identical [S, B] shapes —
        see tests/multihost_worker.py:run_store_rounds."""
        idx = np.asarray(indices)
        k = len(idx)
        ccounts = self.counts[idx]
        bs = self.batch_size
        need = _bucket_steps(int(np.ceil(max(int(ccounts.max()), 1) / bs)))
        if steps is None:
            steps = need
        elif steps < need:
            raise ValueError(
                f"forced steps {steps} < cohort need {need} "
                f"(max client count {int(ccounts.max())}, batch {bs})")
        cap = steps * bs

        xs = np.zeros((k, cap) + self._x.shape[1:], self._x.dtype)
        ys = np.zeros((k, cap) + self._y.shape[1:], self._y.dtype)
        mask = np.zeros((k, cap), np.float32)
        for j, c in enumerate(idx):
            lo, hi = int(self.offsets[c]), int(self.offsets[c + 1])
            n = hi - lo
            if n == 0:
                continue
            xs[j, :n] = self._x[lo:hi]
            ys[j, :n] = self._y[lo:hi]
            mask[j, :n] = 1.0
            if n < cap:  # pad with the client's own first sample (masked)
                xs[j, n:] = self._x[lo]
                ys[j, n:] = self._y[lo]

        def split(a):
            return a.reshape((k, steps, bs) + a.shape[2:])

        return FederatedArrays(
            x=jnp.asarray(split(xs)),
            y=jnp.asarray(split(ys)),
            mask=jnp.asarray(split(mask)),
            counts=jnp.asarray(ccounts, jnp.int32),
        )


class CohortPrefetcher:
    """Double buffer: prepare round r+1's cohort (host gather + async H2D)
    on a worker thread while round r computes. ``get`` blocks on the
    in-flight preparation only if it has not finished yet."""

    def __init__(self, store: FederatedStore):
        self.store = store
        self._pending: Dict[int, threading.Thread] = {}
        self._ready: Dict[int, tuple] = {}  # round -> (indices, cohort)
        self._lock = threading.Lock()

    def prefetch(self, round_idx: int, indices) -> None:
        indices = np.asarray(indices)

        def work():
            try:
                cohort = self.store.gather_cohort(indices)
                with self._lock:
                    self._ready[round_idx] = (indices, cohort)
            finally:
                # Always clear pending — a worker failure (host OOM, bad
                # index) must not permanently block future prefetches for
                # this round; get() then re-gathers synchronously and the
                # real exception surfaces in the caller's context.
                with self._lock:
                    self._pending.pop(round_idx, None)

        t = threading.Thread(target=work, daemon=True)
        with self._lock:
            # Membership check and registration under ONE acquisition:
            # check-then-act across two lock scopes would let concurrent
            # prefetch calls for the same round both spawn gather threads.
            if round_idx in self._pending or round_idx in self._ready:
                return
            self._pending[round_idx] = t
        t.start()

    def get(self, round_idx: int, indices) -> FederatedArrays:
        with self._lock:
            t = self._pending.get(round_idx)
        if t is not None:
            t.join()
        with self._lock:
            hit = self._ready.pop(round_idx, None)
            # Drop stale buffers (a user skipping rounds must not leak).
            for r in [r for r in self._ready if r < round_idx]:
                self._ready.pop(r)
        # The prefetched cohort is only valid for the EXACT index list the
        # caller now wants — sampling inputs may have changed between the
        # prefetch and the round (cfg mutation, subclass overrides).
        if hit is not None and np.array_equal(hit[0], np.asarray(indices)):
            return hit[1]
        return self.store.gather_cohort(indices)
