"""Rectangular client-batched array layout.

TPU/XLA wants static shapes; federated datasets are ragged (non-IID clients
have unequal sample counts — the reference handles this with per-client Python
DataLoaders, fedml_api/data_preprocessing/cifar10/data_loader.py:221-233).
Here every client's data is padded into one rectangular array

    x: [num_clients, steps_per_epoch, batch, ...]
    y: [num_clients, steps_per_epoch, batch]
    mask: [num_clients, steps_per_epoch, batch]   (1.0 = real sample)
    counts: [num_clients]                          (true local sample count)

so local training is a ``lax.scan`` over ``steps`` and client parallelism is a
``vmap``/``shard_map`` over the leading axis. Masks keep losses and the
sample-count-weighted FedAvg average exact despite padding.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class FederatedArrays:
    x: jax.Array  # [C, S, B, ...]
    y: jax.Array  # [C, S, B] (int labels) or [C, S, B, ...] (dense targets)
    mask: jax.Array  # [C, S, B] float32
    counts: jax.Array  # [C] int32 true sample counts

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]

    @property
    def steps_per_epoch(self) -> int:
        return self.x.shape[1]

    @property
    def batch_size(self) -> int:
        return self.x.shape[2]


@struct.dataclass
class WindowBatch:
    """W communication rounds' cohorts stacked on a leading round axis —
    the superbatch the windowed execution tier ships in ONE H2D transfer
    and consumes with one ``lax.scan`` dispatch (``data.store.
    gather_window`` builds it; ``parallel.shard.make_window_scan`` runs
    it). Round ``w``'s slice is exactly the ``FederatedArrays`` the
    per-round host loop would have gathered for that round."""

    x: jax.Array  # [W, C, S, B, ...]
    y: jax.Array  # [W, C, S, B] (int labels) or [W, C, S, B, ...]
    mask: jax.Array  # [W, C, S, B] float32
    counts: jax.Array  # [W, C] int32 true sample counts

    @property
    def num_rounds(self) -> int:
        return self.x.shape[0]

    @property
    def num_clients(self) -> int:
        return self.x.shape[1]

    def round_arrays(self, w: int) -> FederatedArrays:
        """One round's cohort as a regular ``FederatedArrays``."""
        return FederatedArrays(x=self.x[w], y=self.y[w],
                               mask=self.mask[w], counts=self.counts[w])


def build_federated_arrays(
    x: np.ndarray,
    y: np.ndarray,
    client_indices: Dict[int, np.ndarray],
    batch_size: int,
    max_steps: Optional[int] = None,
    dtype=None,
) -> FederatedArrays:
    """Pack per-client index lists over a global (x, y) store into the
    rectangular layout. Padding replicates sample 0 of each client (masked
    out, so it never contributes to loss or aggregation weights)."""
    n_clients = len(client_indices)
    counts = np.array([len(client_indices[c]) for c in range(n_clients)], np.int32)
    steps = int(np.ceil(max(int(counts.max()), 1) / batch_size))
    if max_steps is not None:
        steps = min(steps, max_steps)
    cap = steps * batch_size

    xs = np.zeros((n_clients, cap) + x.shape[1:], dtype or x.dtype)
    ys = np.zeros((n_clients, cap) + y.shape[1:], y.dtype)
    mask = np.zeros((n_clients, cap), np.float32)
    for c in range(n_clients):
        idx = np.asarray(client_indices[c])[:cap]
        k = len(idx)
        if k == 0:
            continue
        xs[c, :k] = x[idx]
        ys[c, :k] = y[idx]
        mask[c, :k] = 1.0
        if k < cap:  # pad with the client's own first sample (masked)
            xs[c, k:] = x[idx[0]]
            ys[c, k:] = y[idx[0]]
    counts = np.minimum(counts, cap)

    def split(a):
        return a.reshape((n_clients, steps, batch_size) + a.shape[2:])

    return FederatedArrays(
        x=jnp.asarray(split(xs)),
        y=jnp.asarray(split(ys)),
        mask=jnp.asarray(split(mask)),
        counts=jnp.asarray(counts),
    )


def gather_clients(fed: FederatedArrays, indices) -> FederatedArrays:
    """Device-side gather of a sampled client subset (replaces the reference's
    per-round ``update_local_dataset`` swap, standalone/fedavg/fedavg_api.py:57-66)."""
    idx = jnp.asarray(indices)
    return FederatedArrays(
        x=jnp.take(fed.x, idx, axis=0),
        y=jnp.take(fed.y, idx, axis=0),
        mask=jnp.take(fed.mask, idx, axis=0),
        counts=jnp.take(fed.counts, idx, axis=0),
    )


def batch_global(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Pad + reshape a flat (test) set into ``[steps, batch, ...]`` with a mask
    — used for on-device global eval."""
    n = len(x)
    steps = int(np.ceil(n / batch_size))
    cap = steps * batch_size
    pad = cap - n
    xs = np.concatenate([x, np.repeat(x[:1], pad, axis=0)]) if pad else x
    ys = np.concatenate([y, np.repeat(y[:1], pad, axis=0)]) if pad else y
    mask = np.concatenate([np.ones((n,), np.float32), np.zeros((pad,), np.float32)])
    return (
        jnp.asarray(xs.reshape((steps, batch_size) + x.shape[1:])),
        jnp.asarray(ys.reshape((steps, batch_size) + y.shape[1:])),
        jnp.asarray(mask.reshape(steps, batch_size)),
    )
