from fedml_tpu.data.partition import (
    partition_dirichlet,
    partition_homo,
    partition_power_law,
    record_data_stats,
)
from fedml_tpu.data.batching import (
    FederatedArrays,
    WindowBatch,
    build_federated_arrays,
    gather_clients,
)

__all__ = [
    "partition_dirichlet",
    "partition_homo",
    "partition_power_law",
    "record_data_stats",
    "FederatedArrays",
    "WindowBatch",
    "build_federated_arrays",
    "gather_clients",
]
