from fedml_tpu.data.partition import (
    partition_dirichlet,
    partition_homo,
    partition_power_law,
    record_data_stats,
)
from fedml_tpu.data.batching import (
    FederatedArrays,
    WindowBatch,
    build_federated_arrays,
    gather_clients,
)
from fedml_tpu.data.directory import (
    ClientDirectory,
    ShardedFederatedStore,
    StoreShard,
)

__all__ = [
    "ClientDirectory",
    "ShardedFederatedStore",
    "StoreShard",
    "partition_dirichlet",
    "partition_homo",
    "partition_power_law",
    "record_data_stats",
    "FederatedArrays",
    "WindowBatch",
    "build_federated_arrays",
    "gather_clients",
]
