"""Federated partitioners (host-side, numpy).

Re-implements the semantics of the reference's two partitioners:

- the shared Dirichlet/LDA partitioner with a min-size retry loop
  (fedml_core/non_iid_partition/noniid_partition.py:6-97 and the in-loader
  variant fedml_api/data_preprocessing/cifar10/data_loader.py:113-160);
- uniform ("homo") partitioning (cifar10/data_loader.py:118-121);
- power-law client sizes in the style of the LEAF MNIST split
  (fedml_api/data_preprocessing/MNIST/data_loader.py — pre-partitioned there;
  generated here since we build datasets locally).

All return ``{client_id: np.ndarray of sample indices}``.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def partition_homo(n_samples: int, n_clients: int, seed: int = 0) -> Dict[int, np.ndarray]:
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in enumerate(np.array_split(idxs, n_clients))}


def partition_dirichlet(
    labels: np.ndarray,
    n_clients: int,
    alpha: float,
    min_size: int = 10,
    seed: int = 0,
    max_retries: int = 1000,
) -> Dict[int, np.ndarray]:
    """Label-Dirichlet (LDA) partition with the reference's min-size retry loop.

    For each class, draw p ~ Dir(alpha) over clients and split that class's
    sample indices by the cumulative proportions, with the reference's
    balancing tweak: a client already holding >= n/n_clients samples gets
    probability 0 for further allocation this draw
    (noniid_partition.py:79-97). Retry the whole draw until every client has
    at least ``min_size`` samples.
    """
    labels = np.asarray(labels).ravel()
    n = len(labels)
    rng = np.random.RandomState(seed)
    classes = np.unique(labels)

    for _ in range(max_retries):
        idx_batch = [[] for _ in range(n_clients)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, n_clients))
            proportions = np.array(
                [
                    p * (len(idx_j) < n / n_clients)
                    for p, idx_j in zip(proportions, idx_batch)
                ]
            )
            s = proportions.sum()
            if s <= 0:
                proportions = np.ones(n_clients) / n_clients
            else:
                proportions = proportions / s
            cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            for j, part in enumerate(np.split(idx_k, cuts)):
                idx_batch[j].extend(part.tolist())
        if min(len(b) for b in idx_batch) >= min_size:
            break
    else:
        raise ValueError(
            f"partition_dirichlet: could not satisfy min_size={min_size} for "
            f"{n_clients} clients over {n} samples (alpha={alpha}) in "
            f"{max_retries} retries; lower min_size or n_clients"
        )

    out = {}
    for j in range(n_clients):
        arr = np.array(idx_batch[j], dtype=np.int64)
        rng.shuffle(arr)
        out[j] = arr
    return out


def partition_power_law(
    n_samples: int,
    n_clients: int,
    seed: int = 0,
    sigma: float = 2.0,
    min_size: int = 2,
) -> Dict[int, np.ndarray]:
    """Heavy-tailed client sizes drawn from a lognormal, normalised to cover
    the dataset once (LEAF-style power-law split)."""
    rng = np.random.RandomState(seed)
    # min_size must be feasible; otherwise relax it to an even split.
    min_size = min(min_size, n_samples // n_clients)
    raw = rng.lognormal(mean=0.0, sigma=sigma, size=n_clients) + 1e-9
    sizes = np.maximum((raw / raw.sum() * n_samples).astype(int), min_size)
    # Fix rounding drift so sizes sum exactly to n_samples. Increments go to
    # the largest clients first; decrements stop at min_size (always feasible
    # because n_clients * min_size <= n_samples).
    drift = n_samples - int(sizes.sum())
    order = np.argsort(-sizes)
    i = 0
    while drift != 0:
        j = order[i % n_clients]
        step = 1 if drift > 0 else -1
        if sizes[j] + step >= min_size:
            sizes[j] += step
            drift -= step
        i += 1
    idxs = rng.permutation(n_samples)
    out, pos = {}, 0
    for j in range(n_clients):
        out[j] = np.sort(idxs[pos : pos + sizes[j]])
        pos += sizes[j]
    return out


def record_data_stats(labels: np.ndarray, net_dataidx_map: Dict[int, np.ndarray]):
    """Per-client class histogram (noniid_partition.py:98-102)."""
    labels = np.asarray(labels).ravel()
    stats = {}
    for client, idxs in net_dataidx_map.items():
        unq, counts = np.unique(labels[idxs], return_counts=True)
        stats[client] = {int(u): int(c) for u, c in zip(unq, counts)}
    return stats
