"""TFF-packaged h5 loaders: FederatedEMNIST, fed_cifar100, fed_shakespeare,
StackOverflow (next-word prediction and tag logistic regression).

H5 layout (FederatedEMNIST/data_loader.py:22-24): group ``examples`` with one
subgroup per client id holding per-feature datasets (``pixels``/``label`` for
EMNIST, ``image``/``label`` for cifar100, ``snippets`` for shakespeare,
``tokens``/``title``/``tags`` for stackoverflow).

Every loader takes ``client_num`` (defaults to the dataset's full client
count — 3400 for FEMNIST, 500/100 for fed_cifar100, 342,477 for
stackoverflow) and falls back to a synthetic in-memory h5 when the data dir
is absent. ``write_synthetic_h5`` is exposed so tests can exercise the real
h5 read path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

from fedml_tpu.data.loaders.common import FederatedDataset, build_federated_dataset
from fedml_tpu.data import text

DEFAULT_TRAIN_CLIENTS_NUM_FEMNIST = 3400  # FederatedEMNIST/data_loader.py:15
DEFAULT_TRAIN_CLIENTS_NUM_CIFAR100 = 500  # fed_cifar100/data_loader.py:17
DEFAULT_TEST_CLIENTS_NUM_CIFAR100 = 100

_EXAMPLE = "examples"


def _h5_client_ids(h5file) -> List[str]:
    return sorted(h5file[_EXAMPLE].keys())


def _read_h5_clients(
    path: str, feature: str, label: str | None, limit: int | None
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    import h5py

    out = {}
    with h5py.File(path, "r") as f:
        ids = _h5_client_ids(f)
        if limit is not None:
            ids = ids[:limit]
        for i, cid in enumerate(ids):
            g = f[_EXAMPLE][cid]
            x = np.asarray(g[feature][()])
            y = (
                np.asarray(g[label][()]).squeeze()
                if label is not None
                else np.zeros(len(x), np.int32)
            )
            out[i] = (x, np.atleast_1d(y))
    return out


def write_synthetic_h5(
    path: str,
    n_clients: int,
    samples_per_client: int,
    feature: str,
    feature_shape: Tuple[int, ...],
    label: str | None = "label",
    n_classes: int = 10,
    seed: int = 0,
    text_feature: bool = False,
):
    """Produce a tiny TFF-layout h5 file (tests / zero-egress stand-in)."""
    import h5py

    rng = np.random.RandomState(seed)
    with h5py.File(path, "w") as f:
        ex = f.create_group(_EXAMPLE)
        for c in range(n_clients):
            g = ex.create_group(f"client_{c:05d}")
            if text_feature:
                chars = np.array(list(text.ALL_LETTERS))
                lines = [
                    "".join(chars[rng.randint(0, len(chars), feature_shape[0])])
                    for _ in range(samples_per_client)
                ]
                g.create_dataset(feature, data=np.array(lines, dtype="S"))
            else:
                g.create_dataset(
                    feature,
                    data=rng.randn(samples_per_client, *feature_shape).astype(np.float32),
                )
            if label is not None:
                g.create_dataset(
                    label, data=rng.randint(0, n_classes, (samples_per_client, 1))
                )


def _maybe_synthetic(
    data_dir: str,
    train_file: str,
    test_file: str,
    feature: str,
    feature_shape,
    n_classes: int,
    synthetic_clients: int,
    text_feature: bool = False,
    label: str | None = "label",
):
    """Return (train_path, test_path), generating tmp synthetic h5 if absent."""
    tp = os.path.join(data_dir, train_file)
    sp = os.path.join(data_dir, test_file)
    if os.path.isfile(tp) and os.path.isfile(sp):
        return tp, sp
    import tempfile

    tmp = tempfile.mkdtemp(prefix="fedml_tpu_h5_")
    tp = os.path.join(tmp, train_file)
    sp = os.path.join(tmp, test_file)
    write_synthetic_h5(tp, synthetic_clients, 24, feature, feature_shape, label, n_classes, 0, text_feature)
    write_synthetic_h5(sp, synthetic_clients, 8, feature, feature_shape, label, n_classes, 1, text_feature)
    return tp, sp


def load_partition_data_federated_emnist(
    batch_size: int,
    data_dir: str = "./data/FederatedEMNIST/datasets",
    client_num: int | None = None,
    synthetic_clients: int = 12,
) -> FederatedDataset:
    """3400-writer FEMNIST, 28x28 pixels, 62 classes
    (FederatedEMNIST/data_loader.py:103-160)."""
    tp, sp = _maybe_synthetic(
        data_dir, "fed_emnist_train.h5", "fed_emnist_test.h5", "pixels", (28, 28), 62, synthetic_clients
    )
    train = _read_h5_clients(tp, "pixels", "label", client_num)
    test = _read_h5_clients(sp, "pixels", "label", client_num)
    # Model input is NHWC with one channel.
    train = {c: (x[..., None].astype(np.float32), y.astype(np.int32)) for c, (x, y) in train.items()}
    test = {c: (x[..., None].astype(np.float32), y.astype(np.int32)) for c, (x, y) in test.items()}
    return build_federated_dataset(train, test, batch_size, class_num=62)


def load_partition_data_federated_cifar100(
    batch_size: int,
    data_dir: str = "./data/fed_cifar100/datasets",
    client_num: int | None = None,
    synthetic_clients: int = 10,
) -> FederatedDataset:
    """TFF Pachinko-partitioned CIFAR-100: 500 train / 100 test clients
    (fed_cifar100/data_loader.py:105-160)."""
    tp, sp = _maybe_synthetic(
        data_dir, "fed_cifar100_train.h5", "fed_cifar100_test.h5", "image", (32, 32, 3), 100, synthetic_clients
    )
    train = _read_h5_clients(tp, "image", "label", client_num)
    test = _read_h5_clients(sp, "image", "label", client_num)
    train = {c: (x.astype(np.float32) / 255.0 if x.max() > 2 else x, y.astype(np.int32)) for c, (x, y) in train.items()}
    test = {c: (x.astype(np.float32) / 255.0 if x.max() > 2 else x, y.astype(np.int32)) for c, (x, y) in test.items()}
    return build_federated_dataset(train, test, batch_size, class_num=100)


def load_partition_data_federated_shakespeare(
    batch_size: int,
    data_dir: str = "./data/fed_shakespeare/datasets",
    client_num: int | None = None,
    synthetic_clients: int = 8,
) -> FederatedDataset:
    """TFF Shakespeare: per-role snippet strings → bos/eos/pad id sequences;
    x = ids[:-1], y = ids[1:] (fed_shakespeare/data_loader.py +
    utils.py:52-77). class_num = 90-slot vocab."""
    tp, sp = _maybe_synthetic(
        data_dir,
        "shakespeare_train.h5",
        "shakespeare_test.h5",
        "snippets",
        (text.SHAKESPEARE_SEQ_LEN + 10,),
        0,
        synthetic_clients,
        text_feature=True,
        label=None,
    )

    def read_text(path):
        import h5py

        out = {}
        with h5py.File(path, "r") as f:
            ids = _h5_client_ids(f)
            if client_num is not None:
                ids = ids[:client_num]
            for i, cid in enumerate(ids):
                raw = f[_EXAMPLE][cid]["snippets"][()]
                sents = [s.decode("utf-8", "ignore") if isinstance(s, bytes) else str(s) for s in raw]
                seq = text.shakespeare_preprocess(sents)
                out[i] = (seq[:, :-1], seq[:, 1:])
        return out

    train, test = read_text(tp), read_text(sp)
    return build_federated_dataset(
        train, test, batch_size, class_num=len(text.shakespeare_word_dict()) + 1
    )


def _synthetic_word_list(n: int = 50) -> List[str]:
    return [f"word{i}" for i in range(n)]


def load_partition_data_federated_stackoverflow_nwp(
    batch_size: int,
    data_dir: str = "./data/stackoverflow/datasets",
    client_num: int | None = None,
    vocab_size: int = 10000,
    max_seq_len: int = 20,
    synthetic_clients: int = 8,
) -> FederatedDataset:
    """StackOverflow next-word prediction: 342,477 clients in the real data
    (stackoverflow_nwp/data_loader.py); tokens from the top-``vocab_size``
    word-count file; class_num = vocab_size + pad/bos/eos + oov = 10004."""
    wc = os.path.join(data_dir, "stackoverflow.word_count")
    if os.path.isfile(wc):
        with open(wc) as f:
            words = [next(f).split()[0] for _ in range(vocab_size)]
    else:
        words = _synthetic_word_list(min(vocab_size, 50))
    vocab = text.StackOverflowVocab(words)

    tp = os.path.join(data_dir, "stackoverflow_train.h5")
    sp = os.path.join(data_dir, "stackoverflow_test.h5")
    if os.path.isfile(tp) and os.path.isfile(sp):
        def read(path):
            import h5py

            out = {}
            with h5py.File(path, "r") as f:
                ids = _h5_client_ids(f)
                if client_num is not None:
                    ids = ids[:client_num]
                for i, cid in enumerate(ids):
                    raw = f[_EXAMPLE][cid]["tokens"][()]
                    sents = [s.decode("utf-8", "ignore") if isinstance(s, bytes) else str(s) for s in raw]
                    out[i] = vocab.encode_nwp(sents, max_seq_len)
            return out

        train, test = read(tp), read(sp)
    else:
        rng = np.random.RandomState(11)
        def synth(n_clients, n_sent, seed_off):
            out = {}
            for c in range(n_clients):
                sents = [
                    " ".join(rng.choice(words, rng.randint(3, max_seq_len + 4)))
                    for _ in range(n_sent)
                ]
                out[c] = vocab.encode_nwp(sents, max_seq_len)
            return out

        train = synth(synthetic_clients, 16, 0)
        test = synth(synthetic_clients, 5, 1)
    return build_federated_dataset(train, test, batch_size, class_num=vocab.vocab_size)


def load_partition_data_federated_stackoverflow_lr(
    batch_size: int,
    data_dir: str = "./data/stackoverflow/datasets",
    client_num: int | None = None,
    vocab_size: int = 10000,
    tag_size: int = 500,
    synthetic_clients: int = 8,
) -> FederatedDataset:
    """StackOverflow tag prediction: bag-of-words inputs (vocab+oov), multi-hot
    tag targets (stackoverflow_lr/data_loader.py + utils.py)."""
    import json

    wc = os.path.join(data_dir, "stackoverflow.word_count")
    tc = os.path.join(data_dir, "stackoverflow.tag_count")
    if os.path.isfile(wc) and os.path.isfile(tc):
        with open(wc) as f:
            words = [next(f).split()[0] for _ in range(vocab_size)]
        with open(tc) as f:
            tags = list(json.load(f).keys())[:tag_size]
    else:
        words = _synthetic_word_list(min(vocab_size, 50))
        tags = [f"tag{i}" for i in range(min(tag_size, 10))]
    word_dict = {w: i for i, w in enumerate(words)}
    tag_dict = {t: i for i, t in enumerate(tags)}

    tp = os.path.join(data_dir, "stackoverflow_train.h5")
    sp = os.path.join(data_dir, "stackoverflow_test.h5")
    if os.path.isfile(tp) and os.path.isfile(sp):
        def read(path):
            import h5py

            out = {}
            with h5py.File(path, "r") as f:
                ids = _h5_client_ids(f)
                if client_num is not None:
                    ids = ids[:client_num]
                for i, cid in enumerate(ids):
                    g = f[_EXAMPLE][cid]
                    sents = [
                        s.decode("utf-8", "ignore") if isinstance(s, bytes) else str(s)
                        for s in g["tokens"][()]
                    ]
                    raw_tags = [
                        s.decode("utf-8", "ignore") if isinstance(s, bytes) else str(s)
                        for s in g["tags"][()]
                    ]
                    x = text.bag_of_words(sents, word_dict)
                    y = text.bag_of_tags([t.split("|") for t in raw_tags], tag_dict)
                    out[i] = (x, y)
            return out

        train, test = read(tp), read(sp)
    else:
        rng = np.random.RandomState(13)

        def synth(n_clients, n_sent):
            out = {}
            for c in range(n_clients):
                sents = [" ".join(rng.choice(words, 6)) for _ in range(n_sent)]
                tag_lists = [rng.choice(tags, 2).tolist() for _ in range(n_sent)]
                out[c] = (
                    text.bag_of_words(sents, word_dict),
                    text.bag_of_tags(tag_lists, tag_dict),
                )
            return out

        train = synth(synthetic_clients, 14)
        test = synth(synthetic_clients, 4)
    return build_federated_dataset(train, test, batch_size, class_num=len(tag_dict))
