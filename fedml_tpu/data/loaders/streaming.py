"""Streaming datasets for decentralized online learning (UCI SUSY /
Room-Occupancy), reference ``fedml_api/data_preprocessing/UCI/
data_loader_for_susy_and_ro.py:7-126``.

The reference's DataLoader reads a CSV, optionally clusters features with
k-means to create heterogeneous client streams ("adversarial" mode) or
shuffles uniformly ("stochastic"), then deals samples round-robin to
clients as an online stream. Same semantics here, numpy-only.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np


def _kmeans(x: np.ndarray, k: int, iters: int = 20, seed: int = 0) -> np.ndarray:
    """Tiny k-means (scipy-free) for the adversarial stream ordering."""
    rng = np.random.RandomState(seed)
    centers = x[rng.choice(len(x), k, replace=False)]
    assign = np.zeros(len(x), np.int64)
    for _ in range(iters):
        d = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        new_assign = d.argmin(1)
        if (new_assign == assign).all():
            break
        assign = new_assign
        for j in range(k):
            m = assign == j
            if m.any():
                centers[j] = x[m].mean(0)
    return assign


class StreamingDataLoader:
    """``load_datastream()`` → per-client list of (x, y) sample streams.

    mode="stochastic": uniform shuffle then round-robin deal;
    mode="adversarial": sort by k-means cluster so each client sees a
    drifting distribution (reference read_csv_file_for_cluster:92-120).
    """

    def __init__(
        self,
        data_name: str = "SUSY",
        data_path: str | None = None,
        client_list: List[int] | None = None,
        sample_num_in_total: int = 2000,
        beta: float = 0.5,
        mode: str = "stochastic",
        n_features: int = 18,
        seed: int = 0,
    ):
        self.data_name = data_name
        self.client_list = client_list or list(range(8))
        self.n = sample_num_in_total
        self.beta = beta
        self.mode = mode
        rng = np.random.RandomState(seed)
        if data_path and os.path.isfile(data_path):
            raw = np.genfromtxt(data_path, delimiter=",", max_rows=self.n)
            self.y = raw[:, 0].astype(np.float32)
            self.x = raw[:, 1:].astype(np.float32)
        else:
            w = rng.randn(n_features)
            self.x = rng.randn(self.n, n_features).astype(np.float32)
            self.y = (self.x @ w > 0).astype(np.float32)
        self.x = (self.x - self.x.mean(0)) / (self.x.std(0) + 1e-6)

    def load_datastream(self) -> Dict[int, List[Tuple[np.ndarray, np.ndarray]]]:
        k = len(self.client_list)
        rng = np.random.RandomState(1)
        if self.mode == "adversarial":
            order = np.argsort(_kmeans(self.x, k, seed=2), kind="stable")
        else:
            order = rng.permutation(len(self.x))
        streams: Dict[int, List] = {c: [] for c in self.client_list}
        for i, idx in enumerate(order):
            c = self.client_list[i % k]
            streams[c].append((self.x[idx], self.y[idx]))
        return streams

    def stream_arrays(self):
        """Rectangular [clients, T, d] / [clients, T] arrays for the
        on-device gossip simulator (truncated to the min stream length)."""
        streams = self.load_datastream()
        t = min(len(v) for v in streams.values())
        xs = np.stack([np.stack([s[0] for s in streams[c][:t]]) for c in self.client_list])
        ys = np.stack([np.stack([s[1] for s in streams[c][:t]]) for c in self.client_list])
        return xs, ys
