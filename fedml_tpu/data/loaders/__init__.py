"""Dataset loader registry — the L3 layer.

``load_data(dataset, ...)`` reproduces the dispatch in the reference's
experiment mains (fedml_experiments/distributed/fedavg/main_fedavg.py:133-351)
and returns a ``FederatedDataset`` (the 8-tuple contract as a dataclass).
All loaders read the real on-disk formats when present and degrade to
synthetic same-shape data in this zero-egress environment.
"""

from __future__ import annotations

from fedml_tpu.data.loaders.common import (
    FederatedDataset,
    batch_data,
    build_federated_dataset,
    clients_from_partition,
    contiguous_shard,
    to_federated_arrays,
)
from fedml_tpu.data.loaders.leaf import (
    load_partition_data_mnist,
    load_partition_data_mnist_by_device_id,
    load_partition_data_shakespeare,
    read_leaf_dir,
)
from fedml_tpu.data.loaders.tff_h5 import (
    load_partition_data_federated_cifar100,
    load_partition_data_federated_emnist,
    load_partition_data_federated_shakespeare,
    load_partition_data_federated_stackoverflow_lr,
    load_partition_data_federated_stackoverflow_nwp,
    write_synthetic_h5,
)
from fedml_tpu.data.loaders.cifar import (
    load_partition_data_cifar10,
    load_partition_data_cifar100,
    load_partition_data_cinic10,
    partition_data,
)
from fedml_tpu.data.loaders.imagenet import (
    load_partition_data_imagenet,
    load_partition_data_landmarks,
)
from fedml_tpu.data.loaders.edge_case import load_poisoned_dataset
from fedml_tpu.data.loaders.vertical import (
    load_lending_club,
    load_three_party_nus_wide,
    load_two_party_nus_wide,
    vertical_split,
)
from fedml_tpu.data.loaders.streaming import StreamingDataLoader


def load_synthetic_seg(
    batch_size: int,
    n_clients: int = 8,
    samples_per_client: int = 24,
    hw=(16, 16),
    n_classes: int = 4,
    seed: int = 0,
) -> FederatedDataset:
    """Synthetic segmentation dataset (blob masks + void pixels) for the
    FedSeg pipeline — the reference's fedseg has no in-repo dataset either
    (it points at external Pascal/ADE setups)."""
    from fedml_tpu.data.synthetic import make_segmentation

    train, test = {}, {}
    for c in range(n_clients):
        x, y = make_segmentation(samples_per_client, hw=hw, n_classes=n_classes,
                                 seed=seed + c)
        train[c] = (x, y)
        xt, yt = make_segmentation(max(4, samples_per_client // 4), hw=hw,
                                   n_classes=n_classes, seed=seed + 100 + c)
        test[c] = (xt, yt)
    return build_federated_dataset(train, test, batch_size, class_num=n_classes)


def load_synthetic_1_1(batch_size: int, n_clients: int = 30, seed: int = 0) -> FederatedDataset:
    """LEAF synthetic(α=1, β=1) LR task (data_preprocessing/synthetic_1_1/)."""
    from fedml_tpu.data.synthetic import synthetic_alpha_beta

    x, y, idx_map = synthetic_alpha_beta(1.0, 1.0, n_clients=n_clients, seed=seed)
    clients = clients_from_partition(x, y, idx_map)
    # 80/20 train/test split inside each client.
    train, test = {}, {}
    for c, (cx, cy) in clients.items():
        k = max(1, int(0.8 * len(cx)))
        train[c] = (cx[:k], cy[:k])
        test[c] = (cx[k:], cy[k:]) if len(cx) > k else (cx[:1], cy[:1])
    return build_federated_dataset(train, test, batch_size, class_num=10)


_CIFAR_FAMILY = {
    "cifar10": load_partition_data_cifar10,
    "cifar100": load_partition_data_cifar100,
    "cinic10": load_partition_data_cinic10,
}


def load_data(
    dataset: str,
    data_dir: str | None = None,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    client_num_in_total: int = 10,
    batch_size: int = 32,
    **kw,
) -> FederatedDataset:
    """The main_fedavg.py:133 dispatch, one entry per supported dataset."""
    if dataset == "mnist":
        return load_partition_data_mnist(batch_size, **_paths(data_dir, "train", "test"), **kw)
    if dataset == "shakespeare":
        return load_partition_data_shakespeare(batch_size, **_paths(data_dir, "train", "test"), **kw)
    if dataset == "femnist":
        return load_partition_data_federated_emnist(batch_size, data_dir or "./data/FederatedEMNIST/datasets", **kw)
    if dataset == "fed_cifar100":
        return load_partition_data_federated_cifar100(batch_size, data_dir or "./data/fed_cifar100/datasets", **kw)
    if dataset == "fed_shakespeare":
        return load_partition_data_federated_shakespeare(batch_size, data_dir or "./data/fed_shakespeare/datasets", **kw)
    if dataset == "stackoverflow_lr":
        return load_partition_data_federated_stackoverflow_lr(batch_size, data_dir or "./data/stackoverflow/datasets", **kw)
    if dataset == "stackoverflow_nwp":
        return load_partition_data_federated_stackoverflow_nwp(batch_size, data_dir or "./data/stackoverflow/datasets", **kw)
    if dataset in _CIFAR_FAMILY:
        return _CIFAR_FAMILY[dataset](
            data_dir, partition_method, client_num_in_total, partition_alpha, batch_size, **kw
        )
    if dataset in ("ILSVRC2012", "imagenet"):
        return load_partition_data_imagenet(data_dir, client_num_in_total, batch_size, **kw)
    if dataset in ("gld23k", "gld160k"):
        return load_partition_data_landmarks(data_dir, kw.pop("fed_train_map_file", None), kw.pop("fed_test_map_file", None), batch_size, **kw)
    if dataset == "synthetic_1_1":
        return load_synthetic_1_1(batch_size, n_clients=client_num_in_total, **kw)
    if dataset == "synthetic_seg":
        return load_synthetic_seg(batch_size, n_clients=client_num_in_total, **kw)
    raise ValueError(f"unknown dataset {dataset!r}")


def _paths(data_dir, train_sub, test_sub):
    import os

    if data_dir:
        return {
            "train_path": os.path.join(data_dir, train_sub),
            "test_path": os.path.join(data_dir, test_sub),
        }
    return {}


__all__ = [
    "FederatedDataset",
    "batch_data",
    "build_federated_dataset",
    "clients_from_partition",
    "contiguous_shard",
    "to_federated_arrays",
    "load_data",
    "load_partition_data_mnist",
    "load_partition_data_mnist_by_device_id",
    "load_partition_data_shakespeare",
    "load_partition_data_federated_emnist",
    "load_partition_data_federated_cifar100",
    "load_partition_data_federated_shakespeare",
    "load_partition_data_federated_stackoverflow_lr",
    "load_partition_data_federated_stackoverflow_nwp",
    "load_partition_data_cifar10",
    "load_partition_data_cifar100",
    "load_partition_data_cinic10",
    "load_partition_data_imagenet",
    "load_partition_data_landmarks",
    "load_poisoned_dataset",
    "load_synthetic_1_1",
    "load_synthetic_seg",
    "load_two_party_nus_wide",
    "load_three_party_nus_wide",
    "load_lending_club",
    "vertical_split",
    "StreamingDataLoader",
    "write_synthetic_h5",
    "partition_data",
    "read_leaf_dir",
]
