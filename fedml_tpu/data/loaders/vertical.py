"""Vertically-partitioned (feature-split) datasets for VFL.

Reference: NUS-WIDE two/three-party split (NUS_WIDE/nus_wide_dataset.py:73 —
party A gets 634 low-level image features, party B the 1000-d bag-of-tags;
binary one-vs-rest label from the top-5 concepts) and lending_club loan
(lending_club_loan/lending_club_dataset.py:100 — pandas featurisation, the
loan-status binary label, features split across two parties).

Real CSVs are download-gated; ``vertical_split`` turns ANY (x, y) into an
n-party feature split, and the two loaders below read the real files when
present or synthesize matching shapes otherwise.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np


def vertical_split(
    x: np.ndarray, splits: Sequence[int]
) -> List[np.ndarray]:
    """Split features [n, d] into parties of widths ``splits`` (sum ≤ d;
    remainder goes to the last party)."""
    parts, pos = [], 0
    for i, w in enumerate(splits):
        end = x.shape[1] if i == len(splits) - 1 and sum(splits) >= x.shape[1] else pos + w
        parts.append(x[:, pos:end])
        pos = end
    return parts


def _synth_binary(n: int, d: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    w = rng.randn(d)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n) > 0).astype(np.float32)
    return x, y


def load_two_party_nus_wide(
    data_dir: str | None = None,
    selected_label: str = "sky",
    n_samples: int = 2000,
    seed: int = 0,
):
    """Two-party NUS-WIDE: returns (Xa_train, Xb_train, y_train),
    (Xa_test, Xb_test, y_test). Party A: 634 image features; party B: 1000
    tag features (NUS_WIDE_load_two_party_data, nus_wide_dataset.py:73-120)."""
    d_a, d_b = 634, 1000
    if data_dir and os.path.isdir(data_dir):
        # Real layout: Low_Level_Features/*.dat + NUS_WID_Tags/*.dat + labels.
        # Parsing mirrors get_labeled_data_with_2_party semantics via pandas.
        import pandas as pd

        feat_dir = os.path.join(data_dir, "Low_Level_Features")
        dfs = [
            pd.read_csv(os.path.join(feat_dir, f), sep=" ", header=None)
            for f in sorted(os.listdir(feat_dir))
            if f.startswith("Train")
        ]
        xa = pd.concat(dfs, axis=1).dropna(axis=1).values.astype(np.float32)
        tags = pd.read_csv(
            os.path.join(data_dir, "NUS_WID_Tags", "Train_Tags1k.dat"),
            sep="\t",
            header=None,
        ).values.astype(np.float32)
        lab = pd.read_csv(
            os.path.join(
                data_dir, "Groundtruth", "TrainTestLabels",
                f"Labels_{selected_label}_Train.txt",
            ),
            header=None,
        ).values.ravel()
        n = min(len(xa), len(tags), len(lab), n_samples if n_samples > 0 else len(xa))
        xa, xb, y = xa[:n], tags[:n], (lab[:n] > 0).astype(np.float32)
    else:
        x, y = _synth_binary(n_samples, d_a + d_b, seed)
        xa, xb = vertical_split(x, [d_a, d_b])
    k = int(0.8 * len(y))
    return (xa[:k], xb[:k], y[:k]), (xa[k:], xb[k:], y[k:])


def load_three_party_nus_wide(
    data_dir: str | None = None, n_samples: int = 2000, seed: int = 0
):
    """Three-party variant: B's tag features are themselves split in half
    (NUS_WIDE_load_three_party_data, nus_wide_dataset.py:122-164)."""
    (xa, xb, y), (xa_t, xb_t, y_t) = load_two_party_nus_wide(
        data_dir, n_samples=n_samples, seed=seed
    )
    half = xb.shape[1] // 2
    return (
        (xa, xb[:, :half], xb[:, half:], y),
        (xa_t, xb_t[:, :half], xb_t[:, half:], y_t),
    )


LOAN_FEATURE_SPLITS = (20, 18)  # guest/host widths after featurisation


def load_lending_club(
    data_path: str | None = None, n_samples: int = 2000, seed: int = 1
):
    """lending_club loan: binary good/bad-loan label, numeric features split
    between two parties (lending_club_dataset.py:100-140 prepare_data/
    process_data — digitize categorical cols, normalize, split)."""
    d = sum(LOAN_FEATURE_SPLITS)
    if data_path and os.path.isfile(data_path):
        import pandas as pd

        df = pd.read_csv(data_path, low_memory=False)
        num = df.select_dtypes(include=[np.number]).fillna(0)
        y = (
            df["loan_status"].astype(str).str.contains("Fully Paid").astype(np.float32).values
            if "loan_status" in df
            else (num.iloc[:, 0] > num.iloc[:, 0].median()).astype(np.float32).values
        )
        x = num.values.astype(np.float32)[:, :d]
        x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    else:
        x, y = _synth_binary(n_samples, d, seed)
    xa, xb = vertical_split(x, list(LOAN_FEATURE_SPLITS))
    k = int(0.8 * len(y))
    return (xa[:k], xb[:k], y[:k]), (xa[k:], xb[k:], y[k:])
