"""Backdoor / poisoned datasets for the robust-FL harness.

The reference's ``load_poisoned_dataset``
(edge_case_examples/data_loader.py:283) loads pre-baked poisoned torch
datasets (southwest-airplane CIFAR backdoor, ARDIS digit-7 MNIST backdoor,
green-car edge cases) plus the clean set and a *targeted* test loader that
measures attack success rate. The artifacts aren't downloadable here, so
this module generates the same *structure* synthetically:

- ``make_backdoor_dataset`` stamps a trigger patch onto a fraction of
  samples and flips their label to the attack target — the classic pattern
  backdoor (Gu et al., BadNets);
- ``make_edge_case_dataset`` draws inputs from a rare tail distribution
  labelled with the target class (edge-case attack of the reference's
  southwest set);
- returns (poisoned_train, clean_test, targeted_test) with the targeted set
  containing ONLY triggered inputs whose ground truth is the target label,
  so accuracy on it == attack success rate, matching
  FedAvgRobustAggregator.test_target_accuracy (fedavg_robust/
  FedAvgRobustAggregator.py:270).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def stamp_trigger(x: np.ndarray, patch: int = 3, value: float | None = None) -> np.ndarray:
    """Set a bottom-right patch to the max intensity (NHWC or N,features)."""
    x = x.copy()
    if x.ndim == 2:  # flat features: poison the last `patch` dims
        x[:, -patch:] = value if value is not None else x.max()
    else:
        x[:, -patch:, -patch:, :] = value if value is not None else x.max()
    return x


def make_backdoor_dataset(
    x: np.ndarray,
    y: np.ndarray,
    target_label: int,
    fraction: float = 0.2,
    patch: int = 3,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Poison ``fraction`` of (x, y): stamp trigger, relabel to target.
    Returns (x_poisoned, y_poisoned, poison_mask)."""
    rng = np.random.RandomState(seed)
    n = len(x)
    k = int(round(fraction * n))
    idx = rng.choice(n, k, replace=False)
    xp, yp = x.copy(), y.copy()
    xp[idx] = stamp_trigger(x[idx], patch)
    yp[idx] = target_label
    mask = np.zeros(n, bool)
    mask[idx] = True
    return xp, yp, mask


def make_targeted_test_set(
    x_test: np.ndarray,
    y_test: np.ndarray,
    target_label: int,
    patch: int = 3,
    max_samples: int = 512,
) -> Tuple[np.ndarray, np.ndarray]:
    """Triggered inputs drawn from NON-target classes, labelled target:
    model accuracy on this set == attack success rate."""
    keep = np.where(y_test != target_label)[0][:max_samples]
    return stamp_trigger(x_test[keep], patch), np.full(len(keep), target_label, y_test.dtype)


def make_edge_case_dataset(
    n_samples: int,
    hwc=(32, 32, 3),
    target_label: int = 9,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Tail-distribution inputs (shifted far mode) all labelled target —
    the southwest-airplane style edge-case poison."""
    rng = np.random.RandomState(seed)
    x = 3.0 + 0.25 * rng.randn(n_samples, *hwc).astype(np.float32)
    y = np.full(n_samples, target_label, np.int32)
    return x, y


def load_poisoned_dataset(
    dataset: str = "cifar10",
    fraction: float = 0.2,
    target_label: int = 2,
    n_samples: int = 1024,
    batch_size: int = 32,
    seed: int = 0,
):
    """Structured equivalent of edge_case_examples/data_loader.py:283 —
    returns (poisoned_train_batches, clean_test_batches, targeted_test_batches,
    num_poisoned)."""
    from fedml_tpu.data.loaders.common import batch_data
    from fedml_tpu.data.synthetic import make_image_classification

    hwc = (784,) if dataset in ("mnist", "emnist") else (32, 32, 3)
    x, y = make_image_classification(n_samples, hwc=hwc, n_classes=10, seed=seed)
    xt, yt = make_image_classification(n_samples // 4, hwc=hwc, n_classes=10, seed=seed + 1)
    xp, yp, mask = make_backdoor_dataset(x, y, target_label, fraction, seed=seed)
    tx, ty = make_targeted_test_set(xt, yt, target_label)
    return (
        batch_data(xp, yp, batch_size),
        batch_data(xt, yt, batch_size),
        batch_data(tx, ty, batch_size),
        int(mask.sum()),
    )
