"""The universal dataset contract.

The reference's L3→L1/L4 interface is the 8-tuple returned by every
``load_partition_data_<dataset>`` (SURVEY.md §1; e.g.
fedml_api/data_preprocessing/cifar10/data_loader.py:235,
MNIST/data_loader.py:87). Here it is a dataclass with ``.as_tuple()`` for
positional compatibility, and "dataloaders" are lists of ``(x, y)`` numpy
batch pairs — host-side, JAX-ready, no torch DataLoader machinery.

``to_federated_arrays`` converts a FederatedDataset into the rectangular
on-device layout (``fedml_tpu.data.batching.FederatedArrays``) that the
vmapped/shard_mapped round functions consume.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]


def batch_data(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    seed: int | None = 100,
    drop_last: bool = False,
) -> List[Batch]:
    """Shuffle-once-then-chunk batching, reproducing LEAF ``batch_data``
    (MNIST/data_loader.py:52-76 — note its fixed ``np.random.seed(100)``)."""
    x = np.asarray(x)
    y = np.asarray(y)
    n = len(x)
    if seed is not None:
        perm = np.random.RandomState(seed).permutation(n)
        x, y = x[perm], y[perm]
    out = []
    end = n - (n % batch_size) if drop_last else n
    for i in range(0, end, batch_size):
        out.append((x[i : i + batch_size], y[i : i + batch_size]))
    return out


@dataclasses.dataclass
class FederatedDataset:
    """The 8-tuple contract (+ explicit client_num) as a structure."""

    client_num: int
    train_data_num: int
    test_data_num: int
    train_data_global: List[Batch]
    test_data_global: List[Batch]
    train_data_local_num_dict: Dict[int, int]
    train_data_local_dict: Dict[int, List[Batch]]
    test_data_local_dict: Dict[int, List[Batch]]
    class_num: int
    # Extra (not in the reference tuple): raw per-client arrays, kept so the
    # TPU path can build rectangular stacked layouts without re-concatenating
    # batches. Optional.
    train_arrays: Dict[int, Batch] | None = None
    test_arrays: Dict[int, Batch] | None = None

    def as_tuple(self):
        """Positional form matching main_fedavg.py:341-351 dataset list."""
        return (
            self.client_num,
            self.train_data_num,
            self.test_data_num,
            self.train_data_global,
            self.test_data_global,
            self.train_data_local_num_dict,
            self.train_data_local_dict,
            self.test_data_local_dict,
            self.class_num,
        )


def build_federated_dataset(
    train_clients: Dict[int, Batch],
    test_clients: Dict[int, Batch],
    batch_size: int,
    class_num: int,
    shuffle_seed: int | None = 100,
) -> FederatedDataset:
    """Assemble the contract from per-client ``(x, y)`` arrays.

    ``test_clients`` may be a subset of train clients (some datasets have no
    per-client test split); the global test set is the concatenation of all
    provided test arrays.
    """
    train_local, test_local, num_dict = {}, {}, {}
    train_global: List[Batch] = []
    test_global: List[Batch] = []
    train_num = test_num = 0
    for cid in sorted(train_clients):
        x, y = train_clients[cid]
        num_dict[cid] = len(x)
        train_num += len(x)
        b = batch_data(x, y, batch_size, seed=shuffle_seed)
        train_local[cid] = b
        train_global += b
    for cid in sorted(test_clients):
        x, y = test_clients[cid]
        test_num += len(x)
        b = batch_data(x, y, batch_size, seed=shuffle_seed)
        test_local[cid] = b
        test_global += b
    return FederatedDataset(
        client_num=len(train_clients),
        train_data_num=train_num,
        test_data_num=test_num,
        train_data_global=train_global,
        test_data_global=test_global,
        train_data_local_num_dict=num_dict,
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=class_num,
        train_arrays={c: (np.asarray(v[0]), np.asarray(v[1])) for c, v in train_clients.items()},
        test_arrays={c: (np.asarray(v[0]), np.asarray(v[1])) for c, v in test_clients.items()},
    )


def clients_from_partition(
    x: np.ndarray, y: np.ndarray, index_map: Dict[int, np.ndarray]
) -> Dict[int, Batch]:
    return {cid: (x[idx], y[idx]) for cid, idx in index_map.items()}


def to_federated_arrays(fed: FederatedDataset, batch_size: int,
                        split: str = "train"):
    """Rectangular stacked layout for the on-device round functions.

    ``split="test"`` builds the layout from the per-client TEST shards
    (the reference's ``test_data_local_dict`` leg of the 8-tuple) for
    on-device per-client test evaluation; clients with no local test data
    get an empty (all-masked) row so indices stay aligned with the train
    layout. Returns None if the loader kept no test arrays at all."""
    from fedml_tpu.data.batching import build_federated_arrays

    assert fed.train_arrays is not None, "loader did not keep raw arrays"
    if split == "train":
        arrays = fed.train_arrays
    elif split == "test":
        if not fed.test_arrays:
            return None
        extra = set(fed.test_arrays) - set(fed.train_arrays)
        if extra:
            raise ValueError(
                "test_arrays contain client ids with no train shard "
                f"({sorted(extra)[:5]}...): the test layout is indexed by "
                "train client id, so these shards would be silently "
                "dropped — use a separate FederatedDataset for held-out "
                "clients")
        # Keep the client-index space identical to the train layout.
        sample = next(iter(fed.test_arrays.values()))
        empty = (sample[0][:0], sample[1][:0])
        arrays = {c: fed.test_arrays.get(c, empty) for c in fed.train_arrays}
    else:
        raise ValueError(f"split must be 'train' or 'test', got {split!r}")
    cids = sorted(arrays)
    xs = np.concatenate([arrays[c][0] for c in cids])
    ys = np.concatenate([arrays[c][1] for c in cids])
    index_map, pos = {}, 0
    for c in cids:
        n = len(arrays[c][0])
        index_map[c] = np.arange(pos, pos + n)
        pos += n
    return build_federated_arrays(xs, ys, index_map, batch_size)


def contiguous_shard(n_samples: int, n_clients: int) -> Dict[int, np.ndarray]:
    """ImageNet/Landmarks-style contiguous per-client shard
    (ImageNet/data_loader.py:300 splits sample ranges by client_number)."""
    return {
        i: part
        for i, part in enumerate(np.array_split(np.arange(n_samples), n_clients))
    }
