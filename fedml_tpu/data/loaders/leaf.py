"""LEAF-format loaders (per-user JSON): MNIST and Shakespeare.

File format (MNIST/data_loader.py:9-49): ``*.json`` files with keys
``users`` (list), optional ``hierarchies``, and ``user_data``:
``{user: {"x": [...], "y": [...]}}``. Train/test dirs hold the same users.

When the data directory is absent (zero-egress environment), loaders fall
back to synthetic generators with the same shapes/stats so every pipeline is
still exercisable end-to-end; pass ``synthetic_clients`` to control size.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

import numpy as np

from fedml_tpu.data.loaders.common import FederatedDataset, build_federated_dataset
from fedml_tpu.data.partition import partition_power_law
from fedml_tpu.data.synthetic import make_image_classification
from fedml_tpu.data import text


def read_leaf_dir(data_dir: str) -> Tuple[List[str], List, Dict, Dict]:
    """Parse one split directory of LEAF json files
    (MNIST/data_loader.py:9-49)."""
    users: List[str] = []
    groups: List = []
    data: Dict = {}
    for f in sorted(os.listdir(data_dir)):
        if not f.endswith(".json"):
            continue
        with open(os.path.join(data_dir, f)) as inf:
            cdata = json.load(inf)
        users.extend(cdata["users"])
        groups.extend(cdata.get("hierarchies", []))
        data.update(cdata["user_data"])
    return sorted(users), groups, data


def _leaf_to_clients(users, data, xdtype, ydtype) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    return {
        i: (
            np.asarray(data[u]["x"], dtype=xdtype),
            np.asarray(data[u]["y"], dtype=ydtype),
        )
        for i, u in enumerate(users)
    }


def load_partition_data_mnist(
    batch_size: int,
    train_path: str = "./data/MNIST/train",
    test_path: str = "./data/MNIST/test",
    synthetic_clients: int = 20,
    synthetic_samples_per_client: int = 30,
) -> FederatedDataset:
    """LEAF MNIST: 1000 power-law clients, flat 784 features, 10 classes
    (MNIST/data_loader.py:87-130). Synthetic fallback mirrors the power-law
    client-size skew."""
    if os.path.isdir(train_path) and os.path.isdir(test_path):
        users, _, train = read_leaf_dir(train_path)
        _, _, test = read_leaf_dir(test_path)
        train_clients = _leaf_to_clients(users, train, np.float32, np.int32)
        test_clients = _leaf_to_clients(users, test, np.float32, np.int32)
    else:
        n = synthetic_clients * synthetic_samples_per_client
        x, y = make_image_classification(n, hwc=(784,), n_classes=10)
        idx = partition_power_law(n, synthetic_clients, seed=1)
        train_clients = {c: (x[i], y[i]) for c, i in idx.items()}
        xt, yt = make_image_classification(n // 4 + synthetic_clients, hwc=(784,), n_classes=10, seed=7)
        idx_t = partition_power_law(len(xt), synthetic_clients, seed=2, min_size=1)
        test_clients = {c: (xt[i], yt[i]) for c, i in idx_t.items()}
    return build_federated_dataset(train_clients, test_clients, batch_size, class_num=10)


def load_partition_data_mnist_by_device_id(
    batch_size: int, device_id: str, train_path: str = "MNIST_mobile", test_path: str = "MNIST_mobile"
) -> FederatedDataset:
    """Mobile split variant (MNIST/data_loader.py:78-85)."""
    return load_partition_data_mnist(
        batch_size,
        os.path.join(train_path, device_id, "train"),
        os.path.join(test_path, device_id, "test"),
    )


def _shakespeare_clients(users, data) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    out = {}
    for i, u in enumerate(users):
        x, y = text.leaf_shakespeare_encode(data[u]["x"], data[u]["y"])
        out[i] = (x, y)
    return out


def _synthetic_play(rng, n_lines: int, line_len: int = 90) -> List[str]:
    chars = np.array(list(text.ALL_LETTERS))
    return ["".join(chars[rng.randint(0, len(chars), line_len)]) for _ in range(n_lines)]


def load_partition_data_shakespeare(
    batch_size: int,
    train_path: str = "./data/shakespeare/train",
    test_path: str = "./data/shakespeare/test",
    synthetic_clients: int = 8,
    synthetic_lines_per_client: int = 12,
) -> FederatedDataset:
    """LEAF Shakespeare char-LM: x = 80-char snippet indices, y = next char
    (shakespeare/data_loader.py + language_utils.py:27-53). class_num is the
    90-slot vocab."""
    if os.path.isdir(train_path) and os.path.isdir(test_path):
        users, _, train = read_leaf_dir(train_path)
        _, _, test = read_leaf_dir(test_path)
        train_clients = _shakespeare_clients(users, train)
        test_clients = _shakespeare_clients(users, test)
    else:
        rng = np.random.RandomState(3)
        train_clients, test_clients = {}, {}
        L = text.SHAKESPEARE_SEQ_LEN
        for c in range(synthetic_clients):
            lines = _synthetic_play(rng, synthetic_lines_per_client, L + 1)
            snip = [l[:L] for l in lines]
            nxt = [l[L] for l in lines]
            train_clients[c] = text.leaf_shakespeare_encode(snip, nxt)
            lines_t = _synthetic_play(rng, max(2, synthetic_lines_per_client // 4), L + 1)
            test_clients[c] = text.leaf_shakespeare_encode(
                [l[:L] for l in lines_t], [l[L] for l in lines_t]
            )
    return build_federated_dataset(
        train_clients, test_clients, batch_size, class_num=text.VOCAB_SIZE
    )
