"""ImageNet (ILSVRC2012) and Google Landmarks (gld23k/gld160k) federated
loaders.

Reference: ImageNet/data_loader.py:300 shards the sample range contiguously
across ``client_number`` clients; Landmarks/data_loader.py maps images to
authors via the federated train csv (233 clients for gld23k, 1262 for
gld160k). Real data is download-gated; when absent we synthesize matching
shapes at reduced resolution so pipelines remain runnable.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, Tuple

import numpy as np

from fedml_tpu.data.loaders.common import (
    FederatedDataset,
    build_federated_dataset,
    clients_from_partition,
    contiguous_shard,
)
from fedml_tpu.data.synthetic import make_image_classification


def _read_folder_dataset(root: str, image_size: int, max_per_class: int | None):
    from fedml_tpu.data.loaders.cifar import read_image_folder
    from PIL import Image

    x, y, classes = read_image_folder(root, max_per_class)
    if x.shape[1] != image_size:
        x = np.stack(
            [
                np.asarray(
                    Image.fromarray(im).resize((image_size, image_size)), np.uint8
                )
                for im in x
            ]
        )
    return x.astype(np.float32) / 255.0, y, len(classes)


def load_partition_data_imagenet(
    data_dir: str | None,
    client_number: int,
    batch_size: int,
    image_size: int = 64,
    synthetic_samples: int = 512,
    synthetic_classes: int = 20,
) -> FederatedDataset:
    """Contiguous-shard ImageNet (ImageNet/data_loader.py:300)."""
    if data_dir and os.path.isdir(os.path.join(data_dir, "train")):
        x, y, ncls = _read_folder_dataset(os.path.join(data_dir, "train"), image_size, None)
        xt, yt, _ = _read_folder_dataset(os.path.join(data_dir, "val"), image_size, None)
    else:
        ncls = synthetic_classes
        x, y = make_image_classification(synthetic_samples, (image_size, image_size, 3), ncls)
        xt, yt = make_image_classification(synthetic_samples // 4, (image_size, image_size, 3), ncls, seed=5)
    train = clients_from_partition(x, y, contiguous_shard(len(x), client_number))
    test = clients_from_partition(xt, yt, contiguous_shard(len(xt), client_number))
    return build_federated_dataset(train, test, batch_size, class_num=ncls)


def read_landmarks_csv(csv_path: str) -> Dict[str, list]:
    """``user_id,image_id,class`` federated-split csv → {user: [(img, cls)]}."""
    out: Dict[str, list] = {}
    with open(csv_path) as f:
        for row in csv.DictReader(f):
            out.setdefault(row["user_id"], []).append(
                (row["image_id"], int(row["class"]))
            )
    return out


def load_partition_data_landmarks(
    data_dir: str | None,
    fed_train_map_file: str | None,
    fed_test_map_file: str | None,
    batch_size: int,
    image_size: int = 64,
    synthetic_clients: int = 16,
    synthetic_classes: int = 30,
) -> FederatedDataset:
    """Author-partitioned Landmarks (Landmarks/data_loader.py; gld23k = 233
    clients / 203 classes, gld160k = 1262 clients / 2028 classes)."""
    if data_dir and fed_train_map_file and os.path.isfile(fed_train_map_file):
        from PIL import Image

        users = read_landmarks_csv(fed_train_map_file)
        train: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        all_cls = set()
        for i, (u, items) in enumerate(sorted(users.items())):
            imgs, lbls = [], []
            for img_id, cls in items:
                p = os.path.join(data_dir, "images", f"{img_id}.jpg")
                if not os.path.isfile(p):
                    continue
                with Image.open(p) as im:
                    imgs.append(
                        np.asarray(im.convert("RGB").resize((image_size, image_size)), np.float32) / 255.0
                    )
                lbls.append(cls)
                all_cls.add(cls)
            if imgs:
                train[i] = (np.stack(imgs), np.asarray(lbls, np.int32))
        test = train  # reference evaluates on the test csv; same structure
        if fed_test_map_file and os.path.isfile(fed_test_map_file):
            users_t = read_landmarks_csv(fed_test_map_file)
            # test csv is not author-partitioned in gld; shard contiguously
        ncls = max(all_cls) + 1 if all_cls else 1
    else:
        ncls = synthetic_classes
        train, test = {}, {}
        for c in range(synthetic_clients):
            x, y = make_image_classification(20, (image_size, image_size, 3), ncls, seed=c)
            train[c] = (x, y)
            xt, yt = make_image_classification(6, (image_size, image_size, 3), ncls, seed=100 + c)
            test[c] = (xt, yt)
    return build_federated_dataset(train, test, batch_size, class_num=ncls)
