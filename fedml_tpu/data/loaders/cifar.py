"""CIFAR-10 / CIFAR-100 / CINIC-10 loaders with in-loader federated
partitioning (homo / hetero-LDA), the reference's
``load_partition_data_cifar10`` family (cifar10/data_loader.py:235,
cifar100, cinic10 — identical structure, different normalisation constants).

Raw formats are read directly (no torchvision): CIFAR python pickle batches,
CINIC-10 class-folder PNGs via PIL. Augmentation (random crop + flip +
cutout, cifar10/data_loader.py:58-76) is NOT baked into host arrays — it is
an on-device jax transform (``fedml_tpu.data.augment``) applied per batch
inside the jitted local-training step, which keeps host arrays static and
the MXU fed.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Tuple

import numpy as np

from fedml_tpu.data.loaders.common import (
    FederatedDataset,
    build_federated_dataset,
    clients_from_partition,
)
from fedml_tpu.data.partition import partition_dirichlet, partition_homo, record_data_stats
from fedml_tpu.data.synthetic import make_image_classification

CIFAR10_MEAN = np.array([0.49139968, 0.48215827, 0.44653124], np.float32)
CIFAR10_STD = np.array([0.24703233, 0.24348505, 0.26158768], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)
CINIC10_MEAN = np.array([0.47889522, 0.47227842, 0.43047404], np.float32)
CINIC10_STD = np.array([0.24205776, 0.23828046, 0.25874835], np.float32)


def _unpickle(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f, encoding="bytes")


def read_cifar10_dir(data_dir: str):
    """cifar-10-batches-py: 5 train batches + test_batch, CHW uint8 rows."""
    xs, ys = [], []
    for i in range(1, 6):
        d = _unpickle(os.path.join(data_dir, f"data_batch_{i}"))
        xs.append(d[b"data"])
        ys.extend(d[b"labels"])
    x_train = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_train = np.asarray(ys, np.int32)
    d = _unpickle(os.path.join(data_dir, "test_batch"))
    x_test = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_test = np.asarray(d[b"labels"], np.int32)
    return x_train, y_train, x_test, y_test


def read_cifar100_dir(data_dir: str):
    d = _unpickle(os.path.join(data_dir, "train"))
    x_train = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_train = np.asarray(d[b"fine_labels"], np.int32)
    d = _unpickle(os.path.join(data_dir, "test"))
    x_test = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_test = np.asarray(d[b"fine_labels"], np.int32)
    return x_train, y_train, x_test, y_test


def read_image_folder(root: str, max_per_class: int | None = None):
    """CINIC-10 style ``root/<class>/*.png`` tree via PIL."""
    from PIL import Image

    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    xs, ys = [], []
    for ci, cname in enumerate(classes):
        files = sorted(os.listdir(os.path.join(root, cname)))
        if max_per_class:
            files = files[:max_per_class]
        for fn in files:
            with Image.open(os.path.join(root, cname, fn)) as im:
                xs.append(np.asarray(im.convert("RGB"), np.uint8))
            ys.append(ci)
    return np.stack(xs), np.asarray(ys, np.int32), classes


def _normalize(x: np.ndarray, mean, std) -> np.ndarray:
    return ((x.astype(np.float32) / 255.0) - mean) / std


def partition_data(
    y_train: np.ndarray, partition: str, n_nets: int, alpha: float, seed: int = 0
) -> Dict[int, np.ndarray]:
    """The reference's partition switch (cifar10/data_loader.py:113-160):
    ``homo`` uniform permutation split; ``hetero`` Dirichlet-LDA with
    min-size retry."""
    if partition == "homo":
        return partition_homo(len(y_train), n_nets, seed=seed)
    if partition == "hetero":
        return partition_dirichlet(y_train, n_nets, alpha, min_size=10, seed=seed)
    raise ValueError(f"unknown partition {partition!r} (homo|hetero)")


def _load_cifar_family(
    reader,
    data_dir: str,
    partition: str,
    client_number: int,
    alpha: float,
    batch_size: int,
    mean,
    std,
    class_num: int,
    synthetic_samples: int,
    seed: int = 0,
) -> FederatedDataset:
    if data_dir and os.path.isdir(data_dir):
        x_train, y_train, x_test, y_test = reader(data_dir)
        x_train = _normalize(x_train, mean, std)
        x_test = _normalize(x_test, mean, std)
    else:
        x_train, y_train = make_image_classification(
            synthetic_samples, hwc=(32, 32, 3), n_classes=class_num, seed=seed
        )
        x_test, y_test = make_image_classification(
            max(synthetic_samples // 5, client_number * 4),
            hwc=(32, 32, 3),
            n_classes=class_num,
            seed=seed + 1,
        )
    index_map = partition_data(y_train, partition, client_number, alpha, seed=seed)
    train_clients = clients_from_partition(x_train, y_train, index_map)
    # The reference gives every client the same global test loader
    # (cifar10/data_loader.py get_dataloader test side); we shard the test
    # set homogeneously so per-client eval exists, and the global test set
    # is the concatenation.
    test_map = partition_homo(len(y_test), client_number, seed=seed + 2)
    test_clients = clients_from_partition(x_test, y_test, test_map)
    fed = build_federated_dataset(train_clients, test_clients, batch_size, class_num)
    fed.traindata_cls_counts = record_data_stats(y_train, index_map)  # type: ignore[attr-defined]
    return fed


def load_partition_data_cifar10(
    data_dir: str | None,
    partition: str,
    client_number: int,
    alpha: float,
    batch_size: int,
    synthetic_samples: int = 2000,
    seed: int = 0,
) -> FederatedDataset:
    return _load_cifar_family(
        read_cifar10_dir, data_dir or "", partition, client_number, alpha,
        batch_size, CIFAR10_MEAN, CIFAR10_STD, 10, synthetic_samples, seed,
    )


def load_partition_data_cifar100(
    data_dir: str | None,
    partition: str,
    client_number: int,
    alpha: float,
    batch_size: int,
    synthetic_samples: int = 2000,
    seed: int = 0,
) -> FederatedDataset:
    return _load_cifar_family(
        read_cifar100_dir, data_dir or "", partition, client_number, alpha,
        batch_size, CIFAR100_MEAN, CIFAR100_STD, 100, synthetic_samples, seed,
    )


def load_partition_data_cinic10(
    data_dir: str | None,
    partition: str,
    client_number: int,
    alpha: float,
    batch_size: int,
    synthetic_samples: int = 2000,
    seed: int = 0,
) -> FederatedDataset:
    def reader(d):
        x_train, y_train, _ = read_image_folder(os.path.join(d, "train"))
        x_test, y_test, _ = read_image_folder(os.path.join(d, "test"))
        return x_train, y_train, x_test, y_test

    return _load_cifar_family(
        reader, data_dir or "", partition, client_number, alpha,
        batch_size, CINIC10_MEAN, CINIC10_STD, 10, synthetic_samples, seed,
    )
