"""Text preprocessing for the federated language-modelling datasets.

Mirrors the semantics of the reference's three vocabularies:

- LEAF shakespeare char vocab (90 = 86 chars + pad/oov/bos/eos slots),
  fedml_api/data_preprocessing/shakespeare/language_utils.py:11-53;
- TFF fed_shakespeare word_dict ([pad] + chars + [bos] + [eos]),
  fedml_api/data_preprocessing/fed_shakespeare/utils.py:23-77;
- TFF stackoverflow next-word-prediction tokenizer (10k words + pad/bos/eos
  + oov bucket => vocab 10004) and the tag-prediction bag-of-words encoder,
  fedml_api/data_preprocessing/stackoverflow_nwp/utils.py:56-90 and
  stackoverflow_lr/utils.py:66-101.

Everything returns numpy int32 arrays (JAX-ready); no one-hot on the host —
embedding lookup happens on device.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

# TFF text-generation tutorial vocabulary (86 chars), identical ordering.
CHAR_VOCAB = list(
    'dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#\'/37;?bfjnrvzBFJNRVZ"&*.26:\naeimquyAEIMQUY]!%)-159\r'
)
ALL_LETTERS = "".join(CHAR_VOCAB)
# pad=0 ... + oov, bos, eos slots → 90, matching RNN_OriginalFedAvg's vocab
# (model/nlp/rnn.py:4 embedding size 90).
VOCAB_SIZE = len(ALL_LETTERS) + 4

SHAKESPEARE_SEQ_LEN = 80  # McMahan et al. AISTATS'17 window

PAD, BOS, EOS = "<pad>", "<bos>", "<eos>"


def letter_to_index(letter: str) -> int:
    """LEAF-style: position in ALL_LETTERS, -1 for unknown."""
    return ALL_LETTERS.find(letter)


def word_to_indices(word: str) -> List[int]:
    return [ALL_LETTERS.find(c) for c in word]


def shakespeare_word_dict() -> Dict[str, int]:
    """TFF fed_shakespeare dict: [pad] + CHAR_VOCAB + [bos] + [eos]."""
    words = [PAD] + CHAR_VOCAB + [BOS] + [EOS]
    return {w: i for i, w in enumerate(words)}


def shakespeare_char_to_id(char: str, word_dict: Dict[str, int] | None = None) -> int:
    wd = word_dict or shakespeare_word_dict()
    return wd.get(char, len(wd))  # oov bucket = len(dict)


def shakespeare_preprocess(
    sentences: Sequence[str], max_seq_len: int = SHAKESPEARE_SEQ_LEN
) -> np.ndarray:
    """TFF-style: bos + char ids (+ eos if short) padded to max_seq_len+1.

    Returns [n, max_seq_len+1] int32; split x = [:, :-1], y = [:, 1:] for
    next-char prediction (fed_shakespeare/utils.py:52-77).
    """
    wd = shakespeare_word_dict()
    bos, eos, pad = wd[BOS], wd[EOS], wd[PAD]
    out = []
    for s in sentences:
        ids = [shakespeare_char_to_id(c, wd) for c in s[:max_seq_len]]
        if len(ids) < max_seq_len:
            ids = ids + [eos]
        ids = [bos] + ids
        ids += [pad] * (max_seq_len + 1 - len(ids))
        out.append(ids[: max_seq_len + 1])
    return np.asarray(out, dtype=np.int32)


def leaf_shakespeare_encode(snippets: Sequence[str], targets: Sequence[str]) -> tuple:
    """LEAF shakespeare: 80-char snippet → indices; targets are the FULL
    shifted sequence (next char at every position — x[1:] + the LEAF next
    char), training the LSTM on all 80 positions instead of only the last.
    Unknown chars index to -1, which the seq loss masks out (pad_id=-1)."""
    x = np.asarray([word_to_indices(s) for s in snippets], dtype=np.int32)
    nxt = np.asarray(
        [letter_to_index(t[0]) if t else -1 for t in targets], dtype=np.int32
    )
    y = np.concatenate([x[:, 1:], nxt[:, None]], axis=1)
    return x, y


class StackOverflowVocab:
    """NWP tokenizer: [pad] + top-k words + [bos] + [eos], 1 oov bucket.

    ``words`` is the frequency-sorted word list (stackoverflow.word_count in
    the reference's data dir; any list in tests).
    """

    def __init__(self, words: Sequence[str], num_oov_buckets: int = 1):
        self.word_dict = {PAD: 0}
        for w in words:
            self.word_dict[w] = len(self.word_dict)
        self.word_dict[BOS] = len(self.word_dict)
        self.word_dict[EOS] = len(self.word_dict)
        self.num_oov_buckets = num_oov_buckets

    @property
    def vocab_size(self) -> int:  # e.g. 10000 + 3 + 1 = 10004
        return len(self.word_dict) + self.num_oov_buckets

    def word_to_id(self, word: str) -> int:
        if word in self.word_dict:
            return self.word_dict[word]
        return hash(word) % self.num_oov_buckets + len(self.word_dict)

    def tokenize(self, sentence: str, max_seq_len: int = 20) -> List[int]:
        tokens = [self.word_to_id(t) for t in sentence.split(" ")[:max_seq_len]]
        if len(tokens) < max_seq_len:
            tokens = tokens + [self.word_dict[EOS]]
        tokens = [self.word_dict[BOS]] + tokens
        tokens += [self.word_dict[PAD]] * (max_seq_len + 1 - len(tokens))
        return tokens[: max_seq_len + 1]

    def encode_nwp(self, sentences: Sequence[str], max_seq_len: int = 20):
        """[n, L] inputs, [n, L] next-word targets (nwp/utils.py:85-90 splits
        last column only; we keep the full shifted sequence for the TPU LSTM
        and the caller may slice)."""
        ids = np.asarray([self.tokenize(s, max_seq_len) for s in sentences], np.int32)
        return ids[:, :-1], ids[:, 1:]


def bag_of_words(
    sentences: Sequence[str], word_dict: Dict[str, int], normalize: bool = True
) -> np.ndarray:
    """stackoverflow_lr input encoding: mean one-hot over tokens incl. one
    oov slot (stackoverflow_lr/utils.py:66-101)."""
    v = len(word_dict)
    out = np.zeros((len(sentences), v + 1), dtype=np.float32)
    for i, s in enumerate(sentences):
        toks = [word_dict.get(t, v) for t in s.split(" ")]
        for t in toks:
            out[i, t] += 1.0
        if normalize and toks:
            out[i] /= len(toks)
    return out


def bag_of_tags(tag_lists: Sequence[Sequence[str]], tag_dict: Dict[str, int]) -> np.ndarray:
    """Multi-hot tag targets (stackoverflow_lr/utils.py preprocess_targets)."""
    out = np.zeros((len(tag_lists), len(tag_dict)), dtype=np.float32)
    for i, tags in enumerate(tag_lists):
        for t in tags:
            if t in tag_dict:
                out[i, tag_dict[t]] = 1.0
    return out
