#!/bin/bash
# CI smoke script — parity with the reference's CI-script-*.sh family
# (pyflakes gate + tiny-config end-to-end runs, CI-script-fedavg.sh:6-56).
# The pytest suite (python -m pytest tests/ -x -q) is the primary gate; this
# script is the fast end-to-end sanity layer.
#
# Suite cost structure (r6 re-audit on the 2-core box, where the tier-1
# verify runs under a hard `timeout 870`; r5 numbers were from a 1-core
# box):
#   fast lane   python -m pytest tests/ -m "not slow" -x -q   ~12 min
#               (must FIT the 870 s tier-1 budget with margin: every
#               test >20 s on the 2-core box was slow-marked in r6 —
#               --durations=40 audit — including the 342k-client store
#               instantiation, remat/bf16/fedgkt/fednas exact-match
#               runs, and the fedseg/fedgan/sequence CLI e2e tests)
#   slow lane   python -m pytest tests/ -m slow -q            ~2.5-3 h
#               (FEMNIST-CNN 3400c/60r convergence ~70 min is the long
#               pole; plus everything moved down in the r6 audit)
#   this script                                               ~10 min
# The fast lane keeps full algorithmic coverage (every algorithm still
# trains 2-4 tiny rounds there) and the windowed/streaming bit-equality
# pins; reference-scale loops and >20 s exact-match runs live slow.
set -euo pipefail

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
export XLA_FLAGS=--xla_force_host_platform_device_count=8

echo "== static check (compileall + fedlint; the reference ran pyflakes) =="
python -m compileall -q fedml_tpu
# fedlint: the repo's own AST analyzer, both rule families — the JAX
# pitfalls PR 1 shipped (carried rng chains, staging aliasing, host
# syncs in hot paths, recompile hazards, donation misuse) and the
# protocol/concurrency family (P1 thread-shared state, P2 drop-without-
# reply, P3 flag-refusal coverage, P4 copy-divergence — docs/LINT.md).
# Exits nonzero on any finding not covered by fedlint.baseline.json
# (kept empty: clean); U1 dead suppressions gate here too (strict).
# The JSON finding list lands beside the smoke logs as a CI artifact.
lint_dir="${CI_RUN_DIR:-$(mktemp -d "${TMPDIR:-/tmp}/fedlint-ci.XXXXXX")}"
mkdir -p "$lint_dir"
lint_t0=$SECONDS
python scripts/fedlint.py fedml_tpu --no-unused-suppressions \
    --format=json > "$lint_dir/fedlint.json" \
    || { cat "$lint_dir/fedlint.json"; exit 1; }
echo "fedlint: clean in $((SECONDS - lint_t0))s" \
     "(artifact: $lint_dir/fedlint.json)"

common="--client_num_in_total 4 --client_num_per_round 4 --batch_size 8 \
        --comm_round 2 --epochs 1 --ci 1"

echo "== standalone FedAvg on LEAF-shaped mnist =="
python -m fedml_tpu.exp.main_fedavg --model lr --dataset mnist $common

echo "== FedOpt (server adam) on synthetic =="
python -m fedml_tpu.exp.run --algorithm FedOpt --server_optimizer adam \
    --model lr --dataset synthetic_1_1 $common

echo "== FedAvg sharded over 4 devices =="
python -m fedml_tpu.exp.main_fedavg --model lr --dataset synthetic_1_1 \
    --num_devices 4 $common

echo "== SCAFFOLD / q-FedAvg / Ditto (drift, fairness, personalization) =="
python -m fedml_tpu.exp.run --algorithm Scaffold \
    --model lr --dataset synthetic_1_1 $common
python -m fedml_tpu.exp.run --algorithm QFedAvg --qffl_q 2.0 \
    --model lr --dataset synthetic_1_1 $common
python -m fedml_tpu.exp.run --algorithm Ditto --ditto_lam 0.1 \
    --model lr --dataset synthetic_1_1 $common

echo "== centralized baseline (mesh data parallelism) =="
python -m fedml_tpu.exp.main_centralized --model lr --dataset synthetic_1_1 \
    --num_devices 8 $common

echo "== reproduce-baselines wiring (synthetic sanity, one config) =="
CI_LITE=1 bash scripts/reproduce_baselines.sh synthetic_lr > /dev/null

echo "== fed_cifar100 ResNet-GN wiring row (CI_LITE_DEPTH compile proxy) =="
# resnet10_gn: same flags/loader as the published resnet18_gn config at a
# CPU-compilable depth (~100 s here) — the row is exercised, not skipped.
CI_LITE=1 CI_LITE_DEPTH=10 bash scripts/reproduce_baselines.sh \
  fed_cifar100_resnet18 > /dev/null

echo "== DP-SGD clients (example-level privacy) =="
python -m fedml_tpu.exp.main_fedavg --model lr --dataset synthetic_1_1 \
    --dp_clip 1.0 --dp_noise_multiplier 0.5 $common

echo "== sharded client directory (million-client tier, small-G smoke) =="
python - <<'PYEOF'
import tempfile, numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.data.directory import ShardedFederatedStore
from fedml_tpu.data.store import FederatedStore
from fedml_tpu.models.lr import LogisticRegression

def builder(s):
    rng = np.random.RandomState(100 + s)
    counts = 1 + rng.randint(0, 6, 16).astype(np.int64)
    tot = int(counts.sum())
    return (rng.randn(tot, 6).astype(np.float32),
            (rng.rand(tot) > 0.5).astype(np.int32), counts)

with tempfile.TemporaryDirectory() as td:
    store = ShardedFederatedStore.from_shard_builder(
        builder, 4, batch_size=8, spill_dir=td)
    assert store.memmapped and store.num_clients == 64
    # flat-store twin over the same generated data: one cohort bit-equal
    xs, ys, cs = zip(*(builder(s) for s in range(4)))
    counts = np.concatenate(cs)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(64)}
    flat = FederatedStore(np.concatenate(xs), np.concatenate(ys), parts,
                          batch_size=8)
    idx = np.array([0, 17, 33, 63, 5])
    a, b = flat.gather_cohort(idx), store.gather_cohort(idx)
    for l, r in zip((a.x, a.y, a.mask, a.counts), (b.x, b.y, b.mask, b.counts)):
        np.testing.assert_array_equal(np.asarray(l), np.asarray(r))
    cfg = FedConfig(client_num_in_total=64, client_num_per_round=6,
                    comm_round=2, epochs=1, batch_size=8, lr=0.3)
    api = FedAvgAPI(LogisticRegression(num_classes=2), store, None, cfg)
    for r in range(2):
        assert np.isfinite(api.train_one_round(r)["train_loss"])
    # directory sampling is re-sharding-invariant (G=4 vs flat G=1)
    from fedml_tpu.data.directory import ClientDirectory
    ref = ClientDirectory(store.counts, np.zeros(64, int), 1)
    assert np.array_equal(store.directory.sample_cohort(1, 6),
                          ref.sample_cohort(1, 6))
print("sharded directory smoke OK")
PYEOF

echo "== pod compute plane: host-grouped reduce on a forced 2x4 DCN mesh =="
python - <<'PYEOF'
import numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.data.batching import build_federated_arrays
from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.parallel.multihost import simulated_dcn_mesh

# 16 learnable clients over a SIMULATED 2x4 DCN x ICI mesh (single
# process, forced factorization): real training, mean bit-equality
# group_reduce=True vs False (the hierarchical partial-sum program is
# the mean path either way), median-of-host-medians in the clean
# ballpark, and the O(G) traffic gauges live.
rng = np.random.RandomState(0)
n, per, d = 16, 32, 6
w_true = rng.randn(d)
x = rng.randn(n * per, d).astype(np.float32)
y = (x @ w_true > 0).astype(np.int32)
parts = {c: np.arange(c * per, (c + 1) * per) for c in range(n)}
fed = build_federated_arrays(x, y, parts, batch_size=16)
test = (x.reshape(-1, 16, d), y.reshape(-1, 16),
        np.ones((n * per // 16, 16), np.float32))
mesh = simulated_dcn_mesh(2, 4)
mk = lambda **kw: FedAvgAPI(
    LogisticRegression(num_classes=2), fed, test,
    FedConfig(client_num_in_total=n, client_num_per_round=8,
              comm_round=6, epochs=1, batch_size=16, lr=0.3,
              frequency_of_the_test=1000, **kw), mesh=mesh)
flat, grp = mk(), mk(group_reduce=True)
for r in range(6):
    flat.train_one_round(r)
    grp.train_one_round(r)
import jax
for a, b in zip(jax.tree.leaves(flat.net.params),
                jax.tree.leaves(grp.net.params)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
acc = float(np.asarray(grp.evaluate()["accuracy"]))
med = mk(group_reduce=True, aggregator="coord_median")
for r in range(6):
    med.train_one_round(r)
macc = float(np.asarray(med.evaluate()["accuracy"]))
assert acc > 0.8, acc
assert macc > acc - 0.15, (macc, acc)  # median-of-medians clean ballpark
prof = grp.reduce_profile()
assert prof["dcn_partials"] == 2  # G = hosts, not the 8-client cohort
assert prof["dcn_rounds"] == 6
print(f"pod reduce smoke OK: mean bit-equal, acc {acc:.2f}, "
      f"median-of-host-medians {macc:.2f}, DCN partials/round "
      f"{int(prof['dcn_partials'])} (G) vs flat "
      f"{int(prof['dcn_flat_bytes_per_round'] // (prof['dcn_bytes_per_round'] // 2))} (C)")
PYEOF

echo "== fused donated round step + lane-fill compute layout =="
python - <<'PYEOF'
import jax, numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.data.batching import build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.models.cnn import CNNOriginalFedAvg
from fedml_tpu.obs.sanitizer import donation_audit, sanitized

rng = np.random.RandomState(0)
x = rng.rand(8 * 16, 28, 28, 1).astype(np.float32)
y = rng.randint(0, 10, len(x)).astype(np.int32)
fed = build_federated_arrays(x, y, partition_homo(len(x), 8), 8)
cfg = FedConfig(client_num_in_total=8, client_num_per_round=4,
                comm_round=100, epochs=1, batch_size=8, lr=0.05,
                compute_layout="auto")
# Deliberately misaligned conv widths: the layout policy pads them, and
# the logical shapes must still be what everything above the step sees.
api = FedAvgAPI(CNNOriginalFedAvg(num_classes=10, widths=(12, 20)),
                fed, None, cfg)
assert api._layout is not None and not api._layout.is_identity
assert api._fused_round_step() is not None
logical = [tuple(l.shape) for l in jax.tree.leaves(api.net)]
api.train_one_round(0)  # compile once
old = api.net
with sanitized(transfer="allow") as rep:  # strict: zero recompiles
    with donation_audit(api.net) as audit:
        base = audit.sample()
        for r in range(1, 3):
            m = api.train_one_round(r)
            assert np.isfinite(m["train_loss"])
            audit.sample()
assert all(l.is_deleted() for l in jax.tree.leaves(old))  # donated
assert audit.peak <= base + 0.25, (audit.peak, base)
assert [tuple(l.shape) for l in jax.tree.leaves(api.net)] == logical
print("fused+padded smoke OK: zero recompiles, donated carry, "
      f"logical shapes held ({api._layout.describe()})")
PYEOF

echo "== whole-zoo carry records: FedDyn windowed bit-equal to host loop =="
python - <<'PYEOF'
import jax, numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.feddyn import FedDynAPI
from fedml_tpu.data.store import FederatedStore
from fedml_tpu.models.lr import LogisticRegression

# Power-law counts so the window-max bucket forcing path actually runs.
rng = np.random.RandomState(0)
counts = np.concatenate([[120], rng.randint(10, 40, 7)])
edges = np.concatenate([[0], np.cumsum(counts)])
x = rng.randn(int(counts.sum()), 6).astype(np.float32)
y = (x @ rng.randn(6) > 0).astype(np.int32)
parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(8)}

def mk():
    cfg = FedConfig(client_num_in_total=8, client_num_per_round=3,
                    comm_round=5, epochs=1, batch_size=8, lr=0.1)
    return FedDynAPI(LogisticRegression(num_classes=2),
                     FederatedStore(x, y, parts, batch_size=8), None,
                     cfg, alpha=0.05)

host, win = mk(), mk()
la = [host.train_one_round(r)["train_loss"] for r in range(5)]
lb = win.train_rounds_windowed(5, window=2)  # non-dividing: 2+2+1
np.testing.assert_array_equal(la, lb)
for a, b in zip(jax.tree.leaves((host.net.params, host.server_h,
                                 host.client_grads)),
                jax.tree.leaves((win.net.params, win.server_h,
                                 win.client_grads))):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
rec = win.capability()
assert rec.fused and rec.windowed and rec.pipelined
print("zoo carry-record smoke OK: FedDyn windowed == host "
      f"(5 rounds, W=2, losses[-1]={lb[-1]:.4f})")
PYEOF

echo "== compressed distributed smoke (int8+top-k wire codec over loopback) =="
python - <<'PYEOF'
import numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import FedML_FedAvg_distributed
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression

x, y = make_classification(240, n_features=16, n_classes=4, seed=1)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)
cfg = FedConfig(client_num_in_total=4, client_num_per_round=4, comm_round=2,
                epochs=2, batch_size=16, lr=0.3, frequency_of_the_test=1)
agg = FedML_FedAvg_distributed(
    LogisticRegression(num_classes=4), fed, test, cfg,
    wire_codec="topk0.25+int8", loopback_wire="tensor")
accs = [h["accuracy"] for h in agg.test_history]
assert accs and accs[-1] > 0.5, accs       # accuracy sanity, 2 rounds
h = agg.final_health
assert h["bytes_rx"] > 0 and h["bytes_tx"] > 0, h  # bytes counted
print(f"compressed smoke OK: acc={accs[-1]:.2f}, "
      f"rx={h['bytes_rx']}B tx={h['bytes_tx']}B")
PYEOF

echo "== adapter finetune smoke (frozen base + topk0.1+int8 adapter deltas) =="
python - <<'PYEOF'
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedbuff import FedML_FedBuff_distributed
from fedml_tpu.data.batching import build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.models.adapter import adapter_model_fns
from fedml_tpu.models.registry import create_model
from fedml_tpu.trainer.local import seq_softmax_ce

V, T, B = 64, 16, 4
rng = np.random.RandomState(0)
seqs = rng.randint(1, V, size=(32, T + 1))
fed = build_federated_arrays(seqs[:, :T].astype(np.int32),
                             seqs[:, 1:].astype(np.int32),
                             partition_homo(32, 4), B)
loss = partial(seq_softmax_ce, pad_id=0)


def mk(rank):
    return create_model("transformer_lm", vocab_size=V, d_model=32,
                        n_heads=2, n_layers=2, max_len=T,
                        adapter_rank=rank)


def drill(rank):
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=2,
                    comm_round=2, epochs=1, batch_size=B, lr=0.1, seed=0,
                    adapter_rank=rank)
    srv = FedML_FedBuff_distributed(mk(rank), fed, None, cfg,
                                    wire_codec="topk0.1+int8",
                                    loopback_wire="tensor", buffer_k=2,
                                    loss_fn=loss)
    h = srv.final_health
    assert h["codec_refusals"] == 0, h
    return srv, h["bytes_rx"] / max(len(srv.arrival_log), 1)


dense_srv, dense_bpu = drill(0)     # the dense-delta codec point
srv, adapter_bpu = drill(8)         # adapter-only deltas, same codec
assert adapter_bpu < 0.5 * dense_bpu, (adapter_bpu, dense_bpu)
# Frozen base: bitwise-identical to the deterministic init.
ref = adapter_model_fns(mk(8))
ref.init(jax.random.PRNGKey(0), jnp.zeros((1, T), jnp.int32))
for a, b in zip(jax.tree.leaves(ref.holder["base"]),
                jax.tree.leaves(srv.adapter_holder["base"])):
    assert np.array_equal(np.asarray(a), np.asarray(b))
print(f"adapter smoke OK: {adapter_bpu:.0f}B/upload vs dense-delta "
      f"{dense_bpu:.0f}B, base frozen, codec_refusals=0")
PYEOF

echo "== serve smoke (requests during a FedBuff run; rank-0 row == dense) =="
python - <<'PYEOF'
import math
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedbuff import FedML_FedBuff_distributed
from fedml_tpu.data.batching import build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.models.adapter import PersonalAdapterStore, adapter_model_fns
from fedml_tpu.models.registry import create_model
from fedml_tpu.serve import ServeForward, ServeManager
from fedml_tpu.trainer.local import NetState, model_fns, seq_softmax_ce

V, T, B = 64, 16, 4
rng = np.random.RandomState(0)
seqs = rng.randint(1, V, size=(32, T + 1))
fed = build_federated_arrays(seqs[:, :T].astype(np.int32),
                             seqs[:, 1:].astype(np.int32),
                             partition_homo(32, 4), B)


def mk(rank):
    return create_model("transformer_lm", vocab_size=V, d_model=32,
                        n_heads=2, n_layers=2, max_len=T,
                        adapter_rank=rank)


# The serve plane over the SAME deterministic frozen base the trainer
# uses (seed 0 — base bitwise identity is pinned by the adapter smoke).
fns = adapter_model_fns(mk(4))
glob0 = fns.init(jax.random.PRNGKey(0), jnp.zeros((1, T), jnp.int32)).params
fwd = ServeForward(fns, glob0)
store = PersonalAdapterStore(32, glob0)
mgr = ServeManager(fwd, store, glob0, seq_len=T, max_batch=8,
                   deadline_s=0.005, queue_cap=64).start()
probe = rng.randint(1, V, T).astype(np.int32)
mgr.request(0, probe)  # warm the one compiled [8, T] shape

# 2-aggregation FedBuff run in the background; requests ride DURING it.
result = {}
cfg = FedConfig(client_num_in_total=4, client_num_per_round=2,
                comm_round=2, epochs=1, batch_size=B, lr=0.1, seed=0,
                adapter_rank=4)
trainer = threading.Thread(target=lambda: result.update(
    srv=FedML_FedBuff_distributed(mk(4), fed, None, cfg,
                                  loopback_wire="tensor", buffer_k=2,
                                  loss_fn=partial(seq_softmax_ce,
                                                  pad_id=0))))
trainer.start()
during = 0
while trainer.is_alive() and during < 48:
    mgr.request(int(during % 32), probe)
    during += 1
trainer.join()

# Publish the trained globals to the plane, then pin the identity
# invariant on the read path: a client with a ZERO (rank-0) adapter row
# serves logits byte-identical to the DENSE model over the same frozen
# base, at the plane's own [8, T] batch shape.
mgr.set_live(1, result["srv"].net.params)
store.scatter([7], np.zeros((1, fwd.dim), np.float32))
logits, _ = mgr.request(7, probe)
dense_fns = model_fns(mk(0))
base = fns.holder["base"]


def dense_row(tok):
    out, _ = dense_fns.apply(NetState(base, {}), tok[None], train=False)
    return out[0]


padded = np.zeros((8, T), np.int32)
padded[0] = probe
dense = np.asarray(jax.jit(jax.vmap(dense_row))(jnp.asarray(padded)))[0]
assert np.array_equal(np.asarray(logits), dense), "rank-0 row != dense"

stats = mgr.stats()
mgr.close()
p95 = stats.get("serve/latency_ms_p95")
assert p95 is not None and math.isfinite(p95), stats
assert stats.get("serve/refused", 0) == 0, stats
assert stats.get("serve/shed", 0) == 0, stats
assert stats.get("serve/served", 0) >= during + 2, stats
print(f"serve smoke OK: {during} requests during training, "
      f"p95={p95:.1f}ms, refused=0 shed=0, rank-0 row == dense model")
PYEOF

echo "== parallel ingest pool: workers=2 bit-equal to workers=1 + pool spans =="
python - <<'PYEOF'
import json, os, tempfile
import numpy as np, jax
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import FedML_FedAvg_distributed
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression

x, y = make_classification(240, n_features=16, n_classes=4, seed=1)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)

def run(workers, trace_dir=None):
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=2, epochs=2, batch_size=16, lr=0.3,
                    frequency_of_the_test=1, ingest_workers=workers)
    return FedML_FedAvg_distributed(
        LogisticRegression(num_classes=4), fed, test, cfg,
        wire_codec="topk0.25+int8", loopback_wire="tensor",
        trace_dir=trace_dir)

with tempfile.TemporaryDirectory() as td:
    a1 = run(1)
    a2 = run(2, trace_dir=td)
    # The pooled fixed-point fold is associative-exact: any worker count
    # lands the bit-identical final net regardless of loopback's
    # thread-scheduled arrival order.
    for l1, l2 in zip(jax.tree.leaves(a1.net), jax.tree.leaves(a2.net)):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    prof = a2.ingest_profile
    assert prof["ingest_pool"]["workers"] == 2, prof
    # Every pool worker traced its tasks: nonzero per-worker span count.
    chrome = json.load(open(os.path.join(td, "trace.chrome.json")))
    per_worker = {}
    for e in chrome["traceEvents"]:
        if e["name"] == "ingest.pool":
            per_worker[e["args"]["worker"]] = \
                per_worker.get(e["args"]["worker"], 0) + 1
    assert per_worker and all(n > 0 for n in per_worker.values()), per_worker
    assert sum(per_worker.values()) == 8  # 2 rounds x 4 uploads
print(f"ingest pool smoke OK: bit-equal nets, pool spans {per_worker}")
PYEOF

echo "== sharded aggregation plane: M=2 bit-equal to M=1 + forced eviction =="
python - <<'PYEOF'
import json, os, tempfile
import numpy as np, jax
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (FedAVGAggregator,
                                                FedML_FedAvg_distributed)
from fedml_tpu.comm.loopback import LoopbackNetwork
from fedml_tpu.comm.shardplane import (AggregatorShardManager,
                                       ShardedFedAVGServerManager)
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression

x, y = make_classification(240, n_features=16, n_classes=4, seed=1)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)

def run(m):
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=2, epochs=2, batch_size=16, lr=0.3,
                    frequency_of_the_test=1)
    return FedML_FedAvg_distributed(
        LogisticRegression(num_classes=4), fed, test, cfg,
        wire_codec="topk0.25+int8", loopback_wire="tensor", agg_shards=m)

a1, a2 = run(1), run(2)
# The coordinator wire-merges the shards' int64 partials through the
# same division site as the in-process pool: any M lands the
# bit-identical net for the same arrivals.
for l1, l2 in zip(jax.tree.leaves(a1.net), jax.tree.leaves(a2.net)):
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
h = a2.final_health
assert h["shards"] == 2 and h["shard_evictions"] == 0, h
assert h["bytes_rx"] > 0, h  # per-shard ByteLedger totals rolled up

# Forced shard eviction (fake-clock protocol drive): shard 2 goes
# silent past the heartbeat deadline — the coordinator evicts it and
# the flight recorder persists the postmortem event.
with tempfile.TemporaryDirectory() as td:
    t = [0.0]
    class A: pass
    a = A(); a.network = LoopbackNetwork(7)
    scfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                     comm_round=2, frequency_of_the_test=1000)
    sagg = FedAVGAggregator({"w": np.zeros(8, np.float32)}, 4, scfg)
    srv = ShardedFedAVGServerManager(a, sagg, scfg, 7, 2,
                                     round_timeout_s=10.0,
                                     clock=lambda: t[0], flight_dir=td)
    shards = {r: AggregatorShardManager(a, r, 7, scfg,
                                        {"w": np.zeros(8, np.float32)},
                                        beat_interval_s=0.0,
                                        clock=lambda: t[0])
              for r in (1, 2)}
    for mgr in [srv, *shards.values()]:
        mgr.register_message_receive_handlers()
    srv.send_init_msg()
    t[0] = 99.0
    srv.shard_heartbeat.beat(1)
    srv._post_shard_tick([2])
    for rank, mgr in [(0, srv), (1, shards[1]), (2, shards[2])]:
        q = a.network.inbox(rank)
        while not q.empty():
            msg = q.get()
            if hasattr(msg, "get_type"):
                mgr.receive_message(msg.get_type(), msg)
    assert srv.shard_evictions == 1 and srv.health()["shards"] == 1
    fr = [json.loads(l)
          for l in open(os.path.join(td, "flight_recorder.jsonl"))]
    assert any(e["kind"] == "shard_eviction" for e in fr)
print(f"shard plane smoke OK: M=2 bit-equal to M=1 "
      f"(rx={h['bytes_rx']}B over {h['shards']} shards), forced "
      "eviction flight-recorded")
PYEOF

echo "== obs smoke: flight recorder + span trace + ingest histograms =="
python - <<'PYEOF'
import json, os, tempfile
import numpy as np
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, FedAVGAggregator,
    FedAVGServerManager, FedML_FedAvg_distributed)
from fedml_tpu.comm.codec import CODEC_KEY, make_wire_codec
from fedml_tpu.comm.loopback import LoopbackNetwork
from fedml_tpu.comm.message import Message
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.obs import MetricsLogger

x, y = make_classification(240, n_features=16, n_classes=4, seed=1)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)
cfg = FedConfig(client_num_in_total=4, client_num_per_round=4, comm_round=2,
                epochs=1, batch_size=16, lr=0.3, frequency_of_the_test=1)
with tempfile.TemporaryDirectory() as td:
    # 2-round loopback codec drill with --trace semantics on
    metrics = MetricsLogger.for_run(run_dir=td, stdout=False)
    agg = FedML_FedAvg_distributed(
        LogisticRegression(num_classes=4), fed, test, cfg,
        wire_codec="topk0.25+int8", loopback_wire="tensor",
        metrics=metrics, trace_dir=td)
    metrics.close()
    # the Chrome trace-event JSON parses and holds the upload lifecycle
    chrome = json.load(open(os.path.join(td, "trace.chrome.json")))
    names = {e["name"] for e in chrome["traceEvents"]}
    assert {"client.train", "client.serialize", "ingest.decode",
            "ingest.fold", "round.commit"} <= names, names
    # metrics.jsonl carries the per-round ctrl/ ingest histograms
    rows = [json.loads(l) for l in open(os.path.join(td, "metrics.jsonl"))]
    ctrl = [r for r in rows if "ctrl/decode_ms_p50" in r]
    assert ctrl and all("ts" in r for r in rows), rows[:1]
    prof = agg.ingest_profile
    assert prof["uploads"] == 8 and prof["ingest_occupancy"] is not None
    # forced eviction (fake-clock protocol drive, corrupt codec frame):
    # the flight-recorder file must appear with the refusal + eviction
    class A: pass
    a = A(); a.network = LoopbackNetwork(3)
    scfg = FedConfig(client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, frequency_of_the_test=1000)
    sagg = FedAVGAggregator({"w": np.zeros(8, np.float32)}, 2, scfg)
    srv = FedAVGServerManager(a, sagg, scfg, 3, flight_dir=td)
    good, _ = make_wire_codec("int8").encode({"w": np.ones(8, np.float32)},
                                             None, 1)
    bad = dict(good); bad["q"] = bad["q"][:2]
    m = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
    m.add(Message.MSG_ARG_KEY_MODEL_PARAMS, bad)
    m.add(Message.MSG_ARG_KEY_NUM_SAMPLES, 10)
    m.add("round", 0); m.add(CODEC_KEY, "int8")
    srv.handle_message_receive_model_from_client(m)
    fr = [json.loads(l)
          for l in open(os.path.join(td, "flight_recorder.jsonl"))]
    kinds = {e["kind"] for e in fr}
    assert {"codec_refusal", "eviction"} <= kinds, kinds
print("obs smoke OK: trace parsed, ctrl/ histograms live, "
      "flight recorder dumped on forced eviction")
PYEOF

echo "== secure aggregation: masked M=2 bit-equal to unmasked + seed reveal =="
python - <<'PYEOF'
import json, os, tempfile, time
import numpy as np, jax
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (FedAVGAggregator,
                                                FedAVGClientManager,
                                                FedAVGServerManager,
                                                FedML_FedAvg_distributed,
                                                build_federation_setup)
from fedml_tpu.comm.loopback import run_workers
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.trainer.local import softmax_ce

x, y = make_classification(240, n_features=16, n_classes=4, seed=1)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)

def run(masked):
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=2, epochs=2, batch_size=16, lr=0.3,
                    frequency_of_the_test=1, secagg=masked)
    return FedML_FedAvg_distributed(
        LogisticRegression(num_classes=4), fed, test, cfg,
        wire_codec="topk0.25+int8", loopback_wire="tensor", agg_shards=2)

plain, masked = run(False), run(True)
# Pairwise seed-expanded masks live in the SAME fixed-point int64
# domain the shards fold, so they cancel exactly in the wire-merged
# sum: the masked federation lands the bit-identical net.
for l1, l2 in zip(jax.tree.leaves(plain.net), jax.tree.leaves(masked.net)):
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
h = masked.final_health
assert h["shards"] == 2 and h.get("seed_reveals", 0) == 0, h

# Forced mid-round dropout: rank 1's local step outlasts the round
# deadline and its beats stop — the watchdog evicts it, >=t survivors
# return Shamir shares of its seeds, the orphaned masks are subtracted
# and the round commits over survivors; the reveal is flight-recorded.
with tempfile.TemporaryDirectory() as td:
    cfgd = FedConfig(client_num_in_total=4, client_num_per_round=4,
                     comm_round=3, epochs=1, batch_size=16, lr=0.3,
                     frequency_of_the_test=10 ** 6, ingest_workers=1,
                     heartbeat_interval_s=0.05, secagg=True)
    size, net0, local_train, eval_fn, args = build_federation_setup(
        LogisticRegression(num_classes=4), fed, None, cfgd, "LOOPBACK",
        softmax_ce)
    srv = FedAVGServerManager(args, FedAVGAggregator(net0, size - 1, cfgd),
                              cfgd, size, round_timeout_s=1.5,
                              heartbeat_timeout_s=0.4, flight_dir=td)

    def victim_train(*a, **kw):
        if srv.round_idx >= 1:
            time.sleep(3.5)  # outlast the 1.5s round deadline
        return local_train(*a, **kw)

    clients = [FedAVGClientManager(args, r, size, fed,
                                   (victim_train if r == 1
                                    else local_train), cfgd)
               for r in range(1, size)]

    def killer():
        deadline = time.monotonic() + 20.0
        while srv.round_idx < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        clients[0].finish()  # beats stop: the watchdog owns it now

    run_workers([srv.run] + [c.run for c in clients] + [killer])
    assert not srv.aborted and srv.seed_reveals >= 1, \
        (srv.aborted, srv.seed_reveals)
    assert srv.health()["evictions"] >= 1
    fr = [json.loads(l)
          for l in open(os.path.join(td, "flight_recorder.jsonl"))]
    kinds = {e["kind"] for e in fr}
    assert "seed_reveal" in kinds, kinds
print(f"secagg smoke OK: masked M=2 bit-equal to unmasked, dropout "
      f"recovered via {srv.seed_reveals} seed reveal(s), flight-recorded")
PYEOF

echo "== async FL (no-barrier staleness-weighted) =="
python -m fedml_tpu.exp.main_extra --algorithm FedAsync \
    --model lr --dataset synthetic_1_1 $common

echo "== buffered semi-sync FL (aggregate every k arrivals, controller on) =="
python -m fedml_tpu.exp.main_extra --algorithm FedBuff --buffer_k 2 \
    --controller adaptive --model lr --dataset synthetic_1_1 $common

echo "== adaptive controller: spiked sim actuates; off-twin digest pinned =="
python - <<'PYEOF'
import hashlib, json, os, tempfile
from fedml_tpu.algos.config import FedConfig
from fedml_tpu.ctrl import (FederationController, StalenessAdmissionPolicy,
                            WindowSchedulePolicy)
from fedml_tpu.data.batching import batch_global, build_federated_arrays
from fedml_tpu.data.partition import partition_homo
from fedml_tpu.data.synthetic import make_classification
from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.sim import FleetSimulator, FleetSpec, make_fleet_trace

# Controller-off twin: the seeded fedbuff drill stays bit-identical to
# the pre-controller tree (tests/test_ctrl.py pins all three modes; this
# digest is the fedbuff one).
x, y = make_classification(160, n_features=8, n_classes=2, seed=3)
fed = build_federated_arrays(x, y, partition_homo(len(x), 4), batch_size=16)
test = batch_global(x[:64], y[:64], 16)
cfg = FedConfig(client_num_in_total=4, client_num_per_round=4, comm_round=12,
                epochs=1, batch_size=16, lr=0.3, frequency_of_the_test=4)
spec = FleetSpec(n_devices=4, seed=5, horizon_s=4000.0, mean_online=0.8,
                 base_round_s=25.0, slot_s=150.0)
res = FleetSimulator(LogisticRegression(num_classes=2), fed, test, cfg,
                     make_fleet_trace(spec), mode="fedbuff",
                     buffer_k=2).run()
digest = hashlib.sha256(repr(
    (res.arrival_log, res.staleness, res.updates, round(res.virtual_s, 3),
     [round(t, 3) for t in res.completion_times])).encode()).hexdigest()
GOLDEN = "e2b90d4c28ed5e1e0efd6ccf5c79088535fd77ef6781a46b1bbbdeadd8dd433b"
assert digest == GOLDEN, f"controller-off drift: {digest}"

# Forced load spike: the guard-band admission policy must actuate
# through the seam, and the actuation must land in the on-disk flight
# dump (the postmortem artifact an operator reads after a bad night).
sx, sy = make_classification(320, n_features=10, n_classes=4, seed=1)
sfed = build_federated_arrays(sx, sy, partition_homo(len(sx), 8),
                              batch_size=16)
stest = batch_global(sx[:96], sy[:96], 16)
scfg = FedConfig(client_num_in_total=8, client_num_per_round=8,
                 comm_round=12, epochs=1, batch_size=16, lr=0.3,
                 frequency_of_the_test=4)
sspec = FleetSpec(n_devices=8, seed=11, horizon_s=20000.0, mean_online=0.92,
                  base_round_s=20.0, slot_s=400.0, arrival_spread_s=30.0,
                  spike_t0=250.0, spike_t1=700.0, spike_factor=6.0)
ctl = FederationController(
    [WindowSchedulePolicy(w_min=1, w_max=4),
     StalenessAdmissionPolicy(band_lo=2.0, band_hi=4.0, k_max=4,
                              cap_slack=0, cooldown=2)], interval=1)
with tempfile.TemporaryDirectory() as td:
    sim = FleetSimulator(LogisticRegression(num_classes=4), sfed, stest,
                         scfg, make_fleet_trace(sspec), mode="fedbuff",
                         buffer_k=2, controller=ctl)
    sim.server.flight.path = os.path.join(td, "flight_recorder.jsonl")
    sim.run()
    applied = [e for e in ctl.actuation_log if e["outcome"] == "applied"
               and e["policy"] == "staleness_admission"]
    assert applied, ctl.actuation_log
    snap = sim.server.registry.snapshot()
    assert snap.get("actuation_applied", 0) >= 1, snap
    fr = [json.loads(l) for l in open(sim.server.flight.path)]
    assert any(e["kind"] == "actuation" for e in fr), {e["kind"] for e in fr}
print(f"controller smoke OK: off-twin digest pinned, spike drew "
      f"{len(applied)} admission actuation(s), flight-recorded on disk")
PYEOF

echo "== message-passing framework templates =="
python -m fedml_tpu.exp.main_extra --algorithm BaseFramework $common

echo "== vertical FL (synthetic NUS-WIDE-shaped two-party data) =="
python -m fedml_tpu.exp.main_extra --algorithm VFL $common

echo "CI OK"
