"""North-star benchmark: FedAvg local samples/sec/chip on CIFAR10-ResNet56.

Config follows BASELINE.json: 128 simulated clients, CIFAR10-shaped data
(synthetic — zero-egress environment), ResNet-56, batch 32, 1 local epoch.
Sampled clients train back-to-back on the chip via vmapped lax.scan local
SGD and a weighted-average aggregation — a full FedAvg round.

``vs_baseline`` compares against a single-GPU PyTorch simulator reference of
~1500 samples/sec (RTX2080Ti-class ResNet-56/CIFAR training throughput; the
reference repo's hardware per BASELINE.md — it publishes no direct
throughput number, so this is the stated assumption).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 1500.0  # single-GPU torch simulator assumption


def main():
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.resnet import resnet56

    n_clients, per_client, batch = 128, 256, 32
    clients_per_round = 8

    rng = np.random.RandomState(0)
    x = rng.randn(n_clients * per_client, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=len(x)).astype(np.int32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), n_clients), batch)

    cfg = FedConfig(
        client_num_in_total=n_clients,
        client_num_per_round=clients_per_round,
        comm_round=1,
        epochs=1,
        batch_size=batch,
        lr=0.1,
    )
    # Mixed precision (bf16 compute, fp32 params/grads) — the standard TPU
    # training configuration; MXU runs bf16 natively (~1.6x over fp32 here).
    api = FedAvgAPI(resnet56(num_classes=10, dtype="bf16"), fed, None, cfg)

    rounds = 3
    # Whole-federation-in-one-jit: lax.scan over rounds with on-device
    # sampling (train_rounds_on_device) — no host dispatch between rounds.
    # Every client holds the same sample count (homo partition), so
    # samples/round is constant regardless of which clients are drawn.
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)

    t0 = time.perf_counter()
    api.train_rounds_on_device(rounds)
    jax.block_until_ready(api.net.params)
    dt = time.perf_counter() - t0

    samples_per_round = clients_per_round * per_client
    sps = samples_per_round * rounds / dt
    print(
        json.dumps(
            {
                "metric": "fedavg_cifar10_resnet56_samples_per_sec_per_chip",
                "value": round(sps, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(sps / BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
