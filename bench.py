"""North-star benchmark + secondary configs, with honest accounting.

Primary metric (BASELINE.json): FedAvg local samples/sec/chip AND
rounds/sec on CIFAR10-ResNet56, 128 simulated clients (batch 32, 1 local
epoch, 8 clients/round) — synthetic CIFAR-shaped data (zero-egress).
Whole-federation-in-one-jit via ``train_rounds_on_device`` (lax.scan over
rounds, on-device sampling).

Accounting:
- median + IQR over ``TRIALS`` timed trials (the axon tunnel shows ~±25%
  run-to-run variance; a single sample cannot separate a regression from
  noise);
- MFU = delivered FLOP/s ÷ the chip's advertised bf16 peak, with
  delivered = 3 x forward-pass FLOPs (XLA cost analysis of the compiled
  forward, ``obs/flops.model_cost``) x samples/sec — the standard
  fwd+bwd≈3x-fwd estimate, stated as such;
- one XLA profile (``obs/timing.trace``) captured per bench run under
  ``runs/bench_profile`` (TensorBoard-loadable), best-effort;
- kernel A/B sections enforce a 0.4 s device-work floor per timed call
  (``_calibrated_side`` / ``_lm_scan_bench(min_call_s=...)``): chain
  lengths are sized from a measured warm-call rate with the tunnel's
  dispatch RTT cancelled by a two-point fit, and the floor is asserted
  — r3's fixed schedules left fast sides inside the RTT noise band,
  deflating them 3-4x (r3 VERDICT #1);
- MFU is a FIRST-CLASS headline target (ROADMAP item 4): every training
  section reports ``mfu`` + ``delivered_tflops`` against the LOGICAL
  model's FLOPs (``_mfu_fields``), and the headline carries
  ``resnet56_mfu`` (the untouched primary) plus ``best_cnn_mfu`` (the
  best honest CNN-family utilization with the measured lane-fill levers
  applied) so the trajectory files track utilization round-over-round,
  not just samples/s;
- secondary configs as sub-metrics in the SAME JSON object: the
  3400-client FEMNIST-CNN federation (BASELINE.md north-star scale, on
  the host-resident FederatedStore), the store_windowed A/B (windowed
  superbatch execution vs the synced per-round loop on that same
  config), a ViT federation, the lane-fill story on one section
  (s2d stem at batch 32 and 128 — the measured levers; the redundant
  reference-stem batch-128 row rides only under BENCH_HEAVY=1), the
  compute-layout + fused-round-step section (pad A/B, fused-vs-separate
  dispatch A/B, donation audit), the shard_map
  round on a 1-device mesh (the multi-chip code path's single-chip
  throughput), the pallas flash-attention vs dense T-sweep (crossover +
  memory evidence + a labelled memory-cliff datum), and two federated-
  transformer sections (the high-MFU proof at d_model=512; the
  flash-in-training A/B curve at T ∈ {2048, 4096, 8192}).

Prints the full JSON blob (also written to ``docs/bench_local.json``)
followed by a compact (<1 KB) headline JSON as the FINAL stdout line —
{"metric", "value", "unit", "vs_baseline", "mfu", "tuned_best", one
scalar per submetric} — so the driver's bounded tail capture always
keeps a parseable record of the primary number (r4 VERDICT #1: the full
line outgrew the tail window and BENCH_r0{3,4}.json lost the metric).
``vs_baseline`` keeps the round-1 convention — a ~1500 samples/sec
single-GPU PyTorch simulator assumption (RTX2080Ti-class ResNet-56/CIFAR;
the reference publishes no throughput number, BASELINE.md) — while the
absolute numbers + MFU above are the honest figures of merit.
``tuned_best`` carries the best honest number for the same task with the
measured tuning levers applied (s2d stem, batch 128), next to the
untouched comparable primary.

See docs/ROOFLINE.md for why the ResNet-56 number sits where it does
(16/32-channel stages under-fill the 128-lane MXU).
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 1500.0  # single-GPU torch simulator assumption
TRIALS = 5


class _SectionTimeout(Exception):
    """A bench section overran its per-section wall-clock cap."""


# Per-section deadline (absolute perf_counter value), set by main()
# around each section. The r5 postmortem: the BUDGET check runs BEFORE a
# section starts, so one long section (transformer_flash_e2e) still blew
# past the driver's kill timer — rc 124, headline never printed. The cap
# is enforced subprocess-free: every A/B repeat/calibration loop calls
# _check_section_deadline() between timed units and bails with
# _SectionTimeout, which main() records as {"timeout": ...} and moves on.
_SECTION_DEADLINE = None


def _check_section_deadline():
    if _SECTION_DEADLINE is not None \
            and time.perf_counter() > _SECTION_DEADLINE:
        raise _SectionTimeout(
            f"per-section cap exceeded "
            f"(+{time.perf_counter() - _SECTION_DEADLINE:.0f}s past "
            "deadline)")


def _rss_mb():
    """CURRENT host RSS in MB — single-sourced in
    :func:`fedml_tpu.utils.rss_mb` since PR 12 (sim.FleetResult.summary()
    reports the same memory axis without this harness). Sampled once per
    timed block by the section machinery, so every section's record
    carries its memory trajectory for free."""
    from fedml_tpu.utils import rss_mb

    return rss_mb()


# Cross-section scale-comparison state (the 342k flat-store point vs the
# 1M sharded-directory point must report RATIOS measured in the SAME
# process): section fns record {"rps": ..., "rss_peak_mb": ...} here.
_scale_state = {}

# Advertised peak bf16 TFLOP/s per chip (public spec sheets), keyed by
# device_kind substring. Unknown kinds → MFU omitted.
CHIP_PEAK_BF16_TFLOPS = {
    "v6": 918.0,
    "v5p": 459.0,
    "v5e": 197.0,
    "v5 lite": 197.0,
    "v4": 275.0,
    "v3": 123.0,
}


def _chip_peak(device_kind: str):
    kind = device_kind.lower()
    for key, peak in CHIP_PEAK_BF16_TFLOPS.items():
        if key in kind:
            return peak
    return None


_mfu_cost_cache = {}


def _mfu_fields(model, sample_x, sps, batch, prefix=""):
    """{"delivered_tflops", "mfu"} for a section's measured samples/sec:
    3x forward FLOPs per sample (fwd+bwd estimate, XLA cost analysis of
    the compiled forward — ``obs/flops.model_cost``) at the measured
    rate, against the chip's advertised bf16 peak. ALWAYS the LOGICAL
    model's FLOPs: lane-fill padding (parallel/layout.py) does extra
    multiplies on zeros that must never inflate the numerator. None/None
    on unknown chips or when the section produced no rate. The cost
    analysis is memoized per (model config, input shape) — three
    sections share the FEMNIST CNN, and each lower+compile would
    otherwise eat seconds of the section budget."""
    import jax

    from fedml_tpu.obs.flops import model_cost

    if not sps:
        return {f"{prefix}delivered_tflops": None, f"{prefix}mfu": None}
    key = (repr(model), np.shape(sample_x), str(np.asarray(sample_x).dtype))
    flops = _mfu_cost_cache.get(key)
    if flops is None:
        flops = _mfu_cost_cache[key] = model_cost(
            model, sample_x, train=False)["flops"]
    delivered = 3.0 * flops / batch * sps / 1e12
    peak = _chip_peak(jax.devices()[0].device_kind)
    return {f"{prefix}delivered_tflops": round(delivered, 3),
            f"{prefix}mfu": (round(delivered / peak, 4) if peak else None)}


def _med_iqr(vals):
    med = statistics.median(vals)
    if len(vals) >= 4:
        q = statistics.quantiles(vals, n=4)
        return med, [round(q[0], 4), round(q[2], 4)]
    return med, [round(min(vals), 4), round(max(vals), 4)]


def _synthetic_cifar_fed(n_clients, per_client, batch):
    """CIFAR-shaped synthetic federated data (zero-egress environment),
    shared by every image-model bench section."""
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo

    rng = np.random.RandomState(0)
    x = rng.randn(n_clients * per_client, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=len(x)).astype(np.int32)
    return build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                  batch)


def _timed_scan_trials(api, rounds, samples_per_round, n_trials=3):
    """samples/sec per trial of the whole-run scan, synced by a host
    scalar fetch (block_until_ready does not reliably wait through the
    axon tunnel). Caller warms up first."""
    vals = []
    for _ in range(n_trials):
        _check_section_deadline()
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())
        vals.append(samples_per_round * rounds / (time.perf_counter() - t0))
    return vals


def _scan_bench(model, n_clients, per_client, batch, cpr, lr,
                rounds=3, mesh=None, with_iqr=False, min_call_s=0.5):
    """Median samples/sec of the whole-run scan for one (model, config):
    the shared scaffold behind every secondary image-model section.
    ``with_iqr=True`` → (median, [q1, q3]) so trend-sensitive submetrics
    carry their spread in the artifact (r3 VERDICT #7).

    The scan length is grown until a warm call exceeds ``min_call_s``
    (the r3 VERDICT #1 device-work floor, applied here in r4): through
    the tunnel each call carries ~0.1 s of fixed dispatch cost, so a
    3-round window on a fast config under-reports steady-state
    throughput by up to ~45% (measured on the s2d variant: 23k
    samples/s at 3 rounds vs 42.7k by two-point fit,
    scripts/sweep_s2d_attrib.py `bench_path`)."""
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI

    fed = _synthetic_cifar_fed(n_clients, per_client, batch)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=1, epochs=1, batch_size=batch, lr=lr)
    api = FedAvgAPI(model, fed, None, cfg, mesh=mesh)
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)
    for _ in range(4):
        _check_section_deadline()
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())
        dt = time.perf_counter() - t0
        if dt >= min_call_s:
            break
        rounds = max(rounds + 1,
                     int(np.ceil(rounds * min_call_s * 1.3 / dt)))
        api.train_rounds_on_device(rounds)  # recompile + warm new length
        jax.block_until_ready(api.net.params)
    trials = _timed_scan_trials(api, rounds, cpr * per_client)
    # The floor is asserted, matching _lm_scan_bench (r4 ADVICE: the
    # silent give-up here contradicted the module docstring).
    call_s = cpr * per_client * rounds / statistics.median(trials)
    assert call_s >= FLOOR_S, (
        f"timed call {call_s:.3f}s below the {FLOOR_S}s floor")
    if with_iqr:
        return _med_iqr(trials)
    return statistics.median(trials)


def bench_cifar_resnet56(profile_dir=None):
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.models.resnet import resnet56
    from fedml_tpu.obs.flops import model_cost

    n_clients, per_client, batch = 128, 256, 32
    clients_per_round, rounds = 8, 3

    fed = _synthetic_cifar_fed(n_clients, per_client, batch)
    cfg = FedConfig(
        client_num_in_total=n_clients, client_num_per_round=clients_per_round,
        comm_round=1, epochs=1, batch_size=batch, lr=0.1,
    )
    # Mixed precision (bf16 compute, fp32 params/grads) — the standard TPU
    # training configuration; MXU runs bf16 natively (~1.6x over fp32 here).
    model = resnet56(num_classes=10, dtype="bf16")
    api = FedAvgAPI(model, fed, None, cfg)
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)
    # Device-work floor (currently a no-op at ~0.6 s/call; guards the
    # metric's honesty if this config ever speeds past the tunnel RTT).
    for _ in range(4):
        _check_section_deadline()
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())
        if time.perf_counter() - t0 >= 0.5:
            break
        rounds *= 2
        api.train_rounds_on_device(rounds)
        jax.block_until_ready(api.net.params)

    sps_trials, rps_trials = [], []
    for trial in range(TRIALS):
        if sps_trials:
            # Primary cap (BENCH_PRIMARY_S): keep the trials already
            # timed — a 3-trial median beats a {"timeout": ...} hole in
            # the headline; raise only while there is nothing to report.
            try:
                _check_section_deadline()
            except _SectionTimeout:
                break
        else:
            _check_section_deadline()
        ctx = None
        if profile_dir is not None and trial == TRIALS - 1:
            try:  # best-effort: profiling through the tunnel may not work
                from fedml_tpu.obs.timing import trace

                ctx = trace(profile_dir)
                ctx.__enter__()
            except Exception:
                ctx, profile_dir = None, None
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())  # host fetch = reliable sync
        dt = time.perf_counter() - t0
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception:
                profile_dir = None
        sps_trials.append(clients_per_round * per_client * rounds / dt)
        rps_trials.append(rounds / dt)

    sps, sps_iqr = _med_iqr(sps_trials)
    rps, rps_iqr = _med_iqr(rps_trials)

    # MFU: 3x forward FLOPs per sample (fwd+bwd estimate) at the measured
    # samples/sec, against the chip's advertised bf16 peak.
    fwd = model_cost(model, np.zeros((batch, 32, 32, 3), np.float32),
                     train=False)
    flops_per_sample = 3.0 * fwd["flops"] / batch
    delivered_tflops = sps * flops_per_sample / 1e12
    kind = jax.devices()[0].device_kind
    peak = _chip_peak(kind)
    return {
        "samples_per_sec": round(sps, 2),
        "samples_per_sec_iqr": sps_iqr,
        "rounds_per_sec": round(rps, 3),
        "rounds_per_sec_iqr": rps_iqr,
        "trials": len(sps_trials),
        "chip": kind,
        "delivered_tflops": round(delivered_tflops, 3),
        "flops_model": "3x forward (XLA cost analysis), bf16 compute",
        "mfu": (round(delivered_tflops / peak, 4) if peak else None),
        "profile_dir": profile_dir,
    }


def _warm_store_buckets(api, store, counts, cpr, batch):
    """Warm EVERY cohort-shape bucket a FederatedStore can produce (a
    cohort's step count is the power-of-two bucket of its max client) so
    no XLA compile lands inside the timed window — sampled warmup rounds
    do not reliably cover all buckets. Shared by every store-backed
    bench section."""
    import jax

    from fedml_tpu.data.store import bucket_steps_for_counts

    # Vectorized (a per-client Python loop costs seconds of the section
    # cap at the 1M-client scale); single-sourced with the store's
    # bucket policy so warmed shapes can never drift from gathered ones.
    buckets = bucket_steps_for_counts(counts, batch)
    # The program the streaming host loop actually dispatches is the
    # FUSED donated step (capability record), a SEPARATE XLA executable
    # from round_fn — warm THAT per bucket, or its per-bucket compiles
    # land inside the timed windows. Custom-protocol records (FedDyn's
    # stateful carry) only ever run fused; "round" records with a fused
    # step also warm round_fn (the windowed scan inlines it, and the
    # run_round fallback paths dispatch it directly).
    fused = (api._fused_round_step()
             if hasattr(api, "_fused_round_step") else None)
    wmask1 = np.ones(cpr, np.float32)
    for bkt in sorted(set(buckets)):
        c = int(np.argmax(buckets == bkt))
        idx = np.full(cpr, c)
        sub = store.gather_cohort(idx)
        w = np.asarray(sub.counts, np.float32)
        if fused is not None:
            pre, _gather = fused
            extra = api._window_carry_init()
            aux = api._fused_round_extras(0, idx, wmask1)
            (api.net, extra), _ = pre(api.net, extra, sub.x, sub.y,
                                      sub.mask, w, jax.random.PRNGKey(0),
                                      *aux)
            api._window_carry_commit(extra)
        if getattr(api, "window_protocol", "round") == "round":
            # Rounds with per-round aux operands (FedNova's τ-weights +
            # γ) take them as trailing arguments — the capability-record
            # _round_aux hook supplies exactly what run_round would.
            aux = api._round_aux(0, idx, wmask1)
            api.round_fn(api.net, sub.x, sub.y, sub.mask, w, w,
                         jax.random.PRNGKey(0), *aux)
    api.train_one_round(0)
    jax.block_until_ready(api.net.params)


def _timed_store_windows(api, store, windows=5, window=10,
                         count_samples=False, min_window_s=6.0):
    """Median rounds/sec (and samples/sec) over ``windows`` timed windows
    of store-backed rounds, each window floor-calibrated to carry
    ``min_window_s`` seconds of work. Synced per-round loop BY DEFAULT:
    through the axon tunnel a flood of unsynced dispatches costs more
    than the per-round float(loss) sync saves (A/B'd 2026-07-30, ~8.8 vs
    ~5.5 rounds/sec — the prefetch worker already overlaps the next
    gather with the wait). That floor is a TUNNEL property: on a
    directly-attached chip set BENCH_ATTACHED=1 to time the pipelined
    loop instead (docs/PLATFORMS.md).

    Window calibration (r4 VERDICT #2): the scan sections got the
    device-work floor in r4 but these per-round loops kept fixed 10-round
    windows (~3 s for femnist, inside the tunnel's RTT band once divided
    per-round), so the submetric's IQR spanned 2.5x and round-over-round
    trends were unreadable. Now the window length is grown from a probe
    window until one window ≥ ``min_window_s``, then median-of-5 windows
    with IQR. Like FLOOR_S vs TARGET_S elsewhere in this file, the
    calibration aims at ``min_window_s`` but the post-measurement assert
    allows 2/3 of it — headroom so ordinary tunnel variance cannot crash
    a section after its measurement succeeded."""
    import os

    attached = os.environ.get("BENCH_ATTACHED") == "1"
    window_floor_s = min_window_s * 2.0 / 3.0

    def run_window(r, window):
        _check_section_deadline()
        samples = 0
        if count_samples:
            for rr in range(r, r + window):
                idx, _ = api._sample_round_uncached(rr)
                samples += int(
                    np.asarray(store.counts)[np.asarray(idx)].sum())
        t0 = time.perf_counter()
        if attached:
            losses = api.train_rounds_pipelined(window, start_round=r)
            assert np.isfinite(losses).all()
        else:
            for rr in range(r, r + window):
                m = api.train_one_round(rr)
            assert np.isfinite(m["train_loss"])
        return time.perf_counter() - t0, samples

    # Calibrate: grow the window until a single window carries
    # min_window_s of wall work, then VERIFY on a second window before
    # accepting (r5 ADVICE: the old loop could exit on an unprobed
    # growth, or on a first crossing inflated by one-time warmup — a
    # compile tail or allocator growth — leaving the steady-state
    # windows under the floor the timed runs are asserted against).
    r = 1
    for _ in range(5):
        dt, _ = run_window(r, window)
        r += window
        if dt >= min_window_s:
            dt2, _ = run_window(r, window)
            r += window
            if dt2 >= window_floor_s:
                break
            dt = dt2  # steady-state is faster than the first crossing
        window = max(window + 5,
                     int(np.ceil(window * min_window_s * 1.2 / dt)))
    else:
        raise AssertionError(
            f"window calibration could not reach the {min_window_s:.1f}s "
            f"target (last window {window} rounds, {dt:.2f}s)")

    rps_w, sps_w, window_s, rss_w = [], [], [], []
    for _ in range(windows):
        dt, samples = run_window(r, window)
        rps_w.append(window / dt)
        sps_w.append(samples / dt)
        window_s.append(dt)
        rss_w.append(_rss_mb())  # one RSS sample per timed block
        r += window
    # EVERY timed window must clear the floor, not just the median — with
    # median-only, 2 of 5 windows could sit inside the RTT noise band
    # unflagged (r5 ADVICE; the committed r5 femnist median was 5.99s vs
    # a 6.0s target, so the margin is real).
    assert min(window_s) >= window_floor_s, window_s
    rps_med, rps_iqr = _med_iqr(rps_w)
    out = {"loop": "pipelined" if attached else "synced",
           "rounds_per_sec": round(rps_med, 3),
           "rounds_per_sec_iqr": rps_iqr, "windows": windows,
           "window_rounds": window,
           "window_s_floor": min_window_s,
           "window_s_median": round(statistics.median(window_s), 2),
           "rss_peak_mb": round(max(rss_w), 1)}
    if count_samples:
        sps_med, sps_iqr = _med_iqr(sps_w)
        out["samples_per_sec"] = round(sps_med, 2)
        out["samples_per_sec_iqr"] = sps_iqr
    return out


# Shared between the femnist submetric and the store_windowed A/B (they
# run back-to-back over the SAME federation): one store/api build + bucket
# warmup + synced measurement instead of two — duplicated minutes here are
# exactly what would push later sections past the wall-clock budget.
_femnist_state = {}


def _synthetic_femnist_store(n_clients, batch, seed=0):
    """FEMNIST-shaped synthetic streaming federation (28x28x1, 62
    classes, lognormal power-law-ish counts ≈140 samples/writer) —
    the SHARED builder for every store-backed FEMNIST section, so the
    windowed-FedOpt A/B can never silently drift from the federation
    shape its FedAvg comparison sections measure."""
    from fedml_tpu.data.store import FederatedStore

    rng = np.random.RandomState(seed)
    counts = np.maximum(1, rng.lognormal(3.6, 0.7, n_clients).astype(int))
    tot = int(counts.sum())
    x = rng.rand(tot, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 62, tot).astype(np.int32)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(n_clients)}
    return FederatedStore(x, y, parts, batch_size=batch), counts


def _femnist_3400_setup():
    """The FEMNIST-3400 streaming configuration (BASELINE.md shallow-NN
    row at its TRUE client count: 3400 writers, 10/round, batch 20,
    Reddi'20 CNN, power-law-ish counts) — built once, cached in
    ``_femnist_state`` for the store_windowed section."""
    if "api" in _femnist_state:
        return (_femnist_state["api"], _femnist_state["store"],
                _femnist_state["counts"], _femnist_state["cpr"],
                _femnist_state["batch"])
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.models.cnn import CNNDropOut

    n_clients, batch, cpr = 3400, 20, 10
    store, counts = _synthetic_femnist_store(n_clients, batch)
    # comm_round bounds prefetch (fedavg.py _stream_cohort only prefetches
    # while round_idx+1 < comm_round): the floor-calibrated windows run
    # well past 40 rounds, so keep the horizon above any window schedule
    # or the timed loop silently degrades to synchronous gathers mid-run.
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=100_000, epochs=1, batch_size=batch, lr=0.1)
    api = FedAvgAPI(CNNDropOut(num_classes=62), store, None, cfg)
    _warm_store_buckets(api, store, counts, cpr, batch)
    _femnist_state.update(api=api, store=store, counts=counts, cpr=cpr,
                          batch=batch)
    return api, store, counts, cpr, batch


def bench_femnist_cnn_3400():
    """FEMNIST-3400 streaming throughput (the configuration VERDICT r1
    flagged as never actually executed), synced per-round loop."""
    from fedml_tpu.models.cnn import CNNDropOut

    api, store, counts, cpr, batch = _femnist_3400_setup()
    timed = _timed_store_windows(api, store, count_samples=True)
    _femnist_state["synced"] = timed  # store_windowed's A/B denominator
    return {"clients": 3400, **timed,
            **_mfu_fields(CNNDropOut(num_classes=62),
                          np.zeros((batch, 28, 28, 1), np.float32),
                          timed.get("samples_per_sec"), batch),
            "host_dataset_mb": round(store.nbytes() / 1e6, 1)}


def _timed_windowed_blocks(api, window, blocks=3, min_block_s=4.0,
                           start_round=1, count_samples=False, store=None):
    """Median rounds/sec over ``blocks`` timed blocks of
    ``train_rounds_windowed`` calls, block length floor-calibrated like
    every other timed section (the block's trailing loss fetch is the
    windowed tier's natural sync cadence, so it belongs on the clock).
    ``count_samples`` (with ``store``) also reports samples/sec —
    cohorts re-derived from the seeded sampler exactly as
    ``_timed_store_windows`` does — so windowed sections can carry MFU
    submetrics."""
    floor_s = min_block_s * 2.0 / 3.0
    rounds, r = 4 * window, start_round

    def block_samples(r, rounds):
        if not count_samples:
            return 0
        counts = np.asarray(store.counts)
        return int(sum(
            counts[np.asarray(api._sample_round_uncached(rr)[0])].sum()
            for rr in range(r, r + rounds)))

    def run_block(r, rounds):
        _check_section_deadline()
        samples = block_samples(r, rounds)
        t0 = time.perf_counter()
        losses = api.train_rounds_windowed(rounds, start_round=r,
                                           window=window)
        dt = time.perf_counter() - t0
        assert np.isfinite(losses).all()
        return dt, samples

    # Same grow-then-verify calibration discipline as
    # _timed_store_windows: the first crossing can ride one-time warmup
    # (the window-scan compile lands in the first probe).
    for _ in range(5):
        dt, _ = run_block(r, rounds)
        r += rounds
        if dt >= min_block_s:
            dt2, _ = run_block(r, rounds)
            r += rounds
            if dt2 >= floor_s:
                break
            dt = dt2
        # Grow to a MULTIPLE of window: a remainder would run per-round
        # through the host loop inside every timed block, silently
        # diluting the windowed throughput this section exists to report.
        rounds = max(rounds + window,
                     int(np.ceil(rounds * min_block_s * 1.2 / dt)))
        rounds = -(-rounds // window) * window
    else:
        raise AssertionError(
            f"block calibration could not reach the {min_block_s:.1f}s "
            f"target (last block {rounds} rounds, {dt:.2f}s)")

    # Timed blocks run SANITIZED (obs.sanitizer): the transfer guard
    # makes any unplanned host<->device copy raise mid-block (the store's
    # staging H2D and the trailing loss fetch are marked planned), and
    # the compile counter reports whether the steady state re-traced.
    # Non-strict: on the power-law federation a late window can
    # legitimately surface a not-yet-seen window-max bucket (one fresh
    # scan executable) — that is a number to REPORT here, and a hard
    # zero to assert in tests/test_fedlint.py's uniform-bucket pin.
    from fedml_tpu.obs.sanitizer import sanitized

    rps, sps, block_s, rss_b = [], [], [], []
    with sanitized(strict=False) as san:
        for _ in range(blocks):
            dt, samples = run_block(r, rounds)
            rps.append(rounds / dt)
            sps.append(samples / dt)
            block_s.append(dt)
            rss_b.append(_rss_mb())  # one RSS sample per timed block
            r += rounds
    assert min(block_s) >= floor_s, block_s
    med, iqr = _med_iqr(rps)
    # Block lengths are window multiples, so every timed round rides a
    # scan by construction (api._window_stats would report coverage 1.0
    # tautologically — not a measurement, so not a metric).
    out = {"rounds_per_sec": round(med, 3), "rounds_per_sec_iqr": iqr,
           "block_rounds": rounds, "blocks": blocks,
           "steady_state_compiles": san.compiles,
           "rss_peak_mb": round(max(rss_b), 1)}
    if count_samples:
        sps_med, sps_iqr = _med_iqr(sps)
        out["samples_per_sec"] = round(sps_med, 2)
        out["samples_per_sec_iqr"] = sps_iqr
    return out


def bench_store_windowed():
    """Windowed vs synced streaming A/B on the FEMNIST-3400 config — the
    windowed execution tier's headline evidence. Synced: per-round host
    loop (one dispatch + one loss sync per round, prefetcher overlapping
    the next gather). Windowed: ``train_rounds_windowed`` — the next W
    same-bucket rounds' cohorts gathered as ONE superbatch, one H2D
    transfer, one lax.scan dispatch, host syncs amortized 1/W. Both sides
    measure the SAME store/model/config — the api/store build, bucket
    warmup, and the synced measurement are REUSED from the femnist
    section when it ran (one federation, one baseline; duplicating them
    is what would push later sections past the wall-clock budget). The
    timed blocks are window multiples, so every timed round rides a
    scan."""
    from fedml_tpu.models.cnn import CNNDropOut

    try:
        api, store, counts, cpr, batch = _femnist_3400_setup()
        window = 16
        synced = _femnist_state.get("synced")
        if synced is None:  # femnist section skipped/errored: own baseline
            synced = _timed_store_windows(api, store, windows=3,
                                          min_window_s=4.0)
        windowed = _timed_windowed_blocks(api, window, blocks=3,
                                          min_block_s=4.0,
                                          count_samples=True, store=store)
        return {"clients": 3400, "window": window,
                "synced_rounds_per_sec": synced["rounds_per_sec"],
                "synced_rounds_per_sec_iqr": synced["rounds_per_sec_iqr"],
                "windowed_rounds_per_sec": windowed["rounds_per_sec"],
                "windowed_rounds_per_sec_iqr":
                    windowed["rounds_per_sec_iqr"],
                "windowed_samples_per_sec": windowed.get("samples_per_sec"),
                **_mfu_fields(CNNDropOut(num_classes=62),
                              np.zeros((batch, 28, 28, 1), np.float32),
                              windowed.get("samples_per_sec"), batch),
                "block_rounds": windowed["block_rounds"],
                "steady_state_compiles": windowed["steady_state_compiles"],
                "speedup": round(windowed["rounds_per_sec"]
                                 / synced["rounds_per_sec"], 3)}
    finally:
        # Free the GB-scale host store before the later sections run.
        _femnist_state.clear()


def bench_store_windowed_fedopt():
    """Windowed FedOpt (server adam) A/B — the carry-protocol tier's
    headline evidence: W rounds per dispatch WITH the server optimizer
    state threaded through the scan carry, vs the same federation's
    per-round host loop. Before this tier, every adaptive-server run
    floored at dispatch RTT (the windowed guard rejected any
    _server_update override). Its own moderate federation (the 3400-
    client store is freed after its section; this one is sized to fit
    the per-section cap): 600 power-law writers, FEMNIST-shaped CNN,
    10 clients/round."""
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedopt import FedOptAPI
    from fedml_tpu.models.cnn import CNNDropOut

    n_clients, batch, cpr, window = 600, 20, 10, 16
    store, counts = _synthetic_femnist_store(n_clients, batch, seed=1)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=100_000,  # > any window schedule (prefetch)
                    epochs=1, batch_size=batch, lr=0.1,
                    server_optimizer="adam", server_lr=0.01)
    api = FedOptAPI(CNNDropOut(num_classes=62), store, None, cfg)
    _warm_store_buckets(api, store, counts, cpr, batch)
    synced = _timed_store_windows(api, store, windows=3, min_window_s=3.0)
    windowed = _timed_windowed_blocks(api, window, blocks=3, min_block_s=3.0,
                                      count_samples=True, store=store)
    return {"clients": n_clients, "window": window,
            "server_optimizer": "adam",
            "synced_rounds_per_sec": synced["rounds_per_sec"],
            "synced_rounds_per_sec_iqr": synced["rounds_per_sec_iqr"],
            "windowed_rounds_per_sec": windowed["rounds_per_sec"],
            "windowed_rounds_per_sec_iqr": windowed["rounds_per_sec_iqr"],
            **_mfu_fields(CNNDropOut(num_classes=62),
                          np.zeros((batch, 28, 28, 1), np.float32),
                          windowed.get("samples_per_sec"), batch),
            "block_rounds": windowed["block_rounds"],
            "steady_state_compiles": windowed["steady_state_compiles"],
            "speedup": round(windowed["rounds_per_sec"]
                             / synced["rounds_per_sec"], 3)}


def bench_zoo_windowed():
    """Whole-zoo carry capability records (docs/EXECUTION.md generated
    matrix): the algorithms the windowed tier used to refuse now scan W
    rounds per dispatch. Two A/B arms measure the payoff on newly
    converted records — FedNova ("round" protocol, τ-normalized weights
    + γ riding the scanned aux slot) and FedDyn ("custom" protocol,
    server h + the client correction stack as the donated carry) — each
    windowed-vs-synced on a FEMNIST-shaped store federation, plus the
    accuracy-per-round arm: FedAc (arXiv:2006.08950) vs FedAvg on a
    LEARNABLE FEMNIST-shaped task at the same round budget, both running
    windowed (the acceleration is a pure carry, so better
    accuracy-per-round costs no throughput). Headline scalars:
    ``zoo_windowed_speedup`` (median windowed/synced across the
    converted arms) and ``fedac_acc_delta`` (FedAc − FedAvg held-out
    accuracy at the final shared eval round)."""
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedac import FedAcAPI
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.algos.feddyn import FedDynAPI
    from fedml_tpu.algos.fednova import FedNovaAPI
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.models.lr import LogisticRegression

    out = {}
    speedups = []

    # All arms run the FEMNIST-shaped LINEAR model: the windowed win is
    # host-sync amortization (most visible when the round's device work
    # is small — exactly the regime the converted zoo's tiny-model
    # members live in), and a conv model would spend the section cap
    # compiling per-bucket executables instead of measuring.
    def _ab_arm(api, store, counts, cpr, batch, window):
        _warm_store_buckets(api, store, counts, cpr, batch)
        synced = _timed_store_windows(api, store, windows=3,
                                      min_window_s=2.0)
        windowed = _timed_windowed_blocks(api, window, blocks=2,
                                          min_block_s=2.0)
        sp = round(windowed["rounds_per_sec"] / synced["rounds_per_sec"],
                   3)
        return synced, windowed, sp

    # --- arm 1: FedNova windowed vs synced ("round" + scanned aux) -----
    n_clients, batch, cpr, window = 300, 20, 10, 16
    store, counts = _synthetic_femnist_store(n_clients, batch, seed=2)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=100_000,  # > any window schedule (prefetch)
                    epochs=1, batch_size=batch, lr=0.1)
    api = FedNovaAPI(LogisticRegression(num_classes=62), store, None, cfg)
    synced, windowed, sp = _ab_arm(api, store, counts, cpr, batch, window)
    speedups.append(sp)
    out.update(fednova_synced_rps=synced["rounds_per_sec"],
               fednova_windowed_rps=windowed["rounds_per_sec"],
               fednova_speedup=sp,
               fednova_steady_state_compiles=windowed[
                   "steady_state_compiles"])
    del api, store

    # --- arm 2: FedDyn windowed vs synced ("custom" carry stack) -------
    # The correction stack is O(total clients x model) device state —
    # the carry the scan donates round-to-round.
    _check_section_deadline()
    n_clients = 64
    store, counts = _synthetic_femnist_store(n_clients, batch, seed=3)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=100_000, epochs=1, batch_size=batch, lr=0.05)
    api = FedDynAPI(LogisticRegression(num_classes=62), store, None, cfg,
                    alpha=0.05)
    synced, windowed, sp = _ab_arm(api, store, counts, cpr, batch, window)
    speedups.append(sp)
    out.update(feddyn_synced_rps=synced["rounds_per_sec"],
               feddyn_windowed_rps=windowed["rounds_per_sec"],
               feddyn_speedup=sp,
               feddyn_steady_state_compiles=windowed[
                   "steady_state_compiles"])
    del api, store
    out["zoo_windowed_speedup"] = round(float(np.median(speedups)), 3)

    # --- arm 3: FedAc vs FedAvg accuracy-per-round ---------------------
    # Learnable FEMNIST-shaped task (8 classes encoded as quadrant
    # offsets, weak enough signal that accuracy MOVES over the budget);
    # both arms run windowed with identical seeds/cohorts — the only
    # difference is the server carry. Measured on this config: FedAc
    # γ=2 reaches ~0.95 when FedAvg is at ~0.89 (delta ≈ +0.06 at the
    # final shared eval round, and the win holds POINTWISE along the
    # curve).
    _check_section_deadline()
    rng = np.random.RandomState(7)
    n_clients, per, classes = 64, 40, 8
    tot = n_clients * per
    y = rng.randint(0, classes, tot).astype(np.int32)
    x = (rng.rand(tot, 28, 28, 1) * 0.3).astype(np.float32)
    bits = np.stack([(y >> b) & 1 for b in range(3)], axis=1)
    x[:, :14, :14, 0] += 0.35 * bits[:, 0, None, None]
    x[:, 14:, :14, 0] += 0.35 * bits[:, 1, None, None]
    x[:, :14, 14:, 0] += 0.35 * bits[:, 2, None, None]
    parts = {c: np.arange(c * per, (c + 1) * per)
             for c in range(n_clients)}
    test_n = 256
    xt, yt = x[:test_n], y[:test_n]  # held-in probe (synthetic task)
    from fedml_tpu.data.batching import batch_global

    test_global = batch_global(xt, yt, 32)
    rounds, eval_every, win = 32, 8, 8

    def acc_curve(cls, **kw):
        cfg = FedConfig(client_num_in_total=n_clients,
                        client_num_per_round=8, comm_round=rounds + 1,
                        epochs=1, batch_size=20, lr=0.02,
                        frequency_of_the_test=1000)
        api = cls(LogisticRegression(num_classes=classes),
                  FederatedStore(x, y, parts, batch_size=20),
                  test_global, cfg, **kw)
        curve, r = [], 0
        while r < rounds:
            _check_section_deadline()
            api.train_rounds_windowed(eval_every, start_round=r,
                                      window=win)
            r += eval_every
            curve.append(round(api.evaluate()["accuracy"], 4))
        return curve

    fedavg_curve = acc_curve(FedAvgAPI)
    fedac_curve = acc_curve(FedAcAPI, gamma=2.0)
    out.update(fedavg_acc_curve=fedavg_curve, fedac_acc_curve=fedac_curve,
               acc_eval_every=eval_every, acc_rounds=rounds,
               fedac_final_acc=fedac_curve[-1],
               fedavg_final_acc=fedavg_curve[-1],
               fedac_acc_delta=round(fedac_curve[-1] - fedavg_curve[-1],
                                     4))
    return out


def bench_robust_agg():
    """Byzantine-robust aggregation cost (docs/ROBUSTNESS.md): windowed
    streaming rounds with aggregator ∈ {mean, coord_median, krum} on ONE
    moderate federation (300 power-law writers, FEMNIST-shaped CNN,
    10/round, window 8) — same store, same seeded cohorts, only the
    server reduction changes, so the RPS deltas are the aggregators'
    price. Sized to fit the per-section cap (three sides, each with its
    own warmup + floor-calibrated blocks). Headline scalar
    ``robust_agg_overhead`` = mean_rps / krum_rps — krum is the
    expensive end of the zoo (pairwise distances over the cohort), so
    this bounds what turning the defense on can cost."""
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.models.cnn import CNNDropOut

    n_clients, batch, cpr, window = 300, 20, 10, 8
    out = {"clients": n_clients, "window": window}
    rps = {}
    for agg in ("mean", "coord_median", "krum"):
        _check_section_deadline()
        store, counts = _synthetic_femnist_store(n_clients, batch, seed=2)
        cfg = FedConfig(client_num_in_total=n_clients,
                        client_num_per_round=cpr,
                        comm_round=100_000,  # > any window schedule
                        epochs=1, batch_size=batch, lr=0.1, aggregator=agg)
        api = FedAvgAPI(CNNDropOut(num_classes=62), store, None, cfg)
        _warm_store_buckets(api, store, counts, cpr, batch)
        timed = _timed_windowed_blocks(api, window, blocks=3,
                                       min_block_s=2.0)
        rps[agg] = timed["rounds_per_sec"]
        out[agg] = timed
    out["robust_agg_overhead"] = round(rps["mean"] / rps["krum"], 3)
    out["coord_median_overhead"] = round(rps["mean"] / rps["coord_median"],
                                         3)
    return out


def bench_chaos():
    """Control-plane resilience price (docs/ROBUSTNESS.md "Control
    plane"): every backend's ``send_message`` now runs through the
    unified RetryPolicy — this section measures what that wrapper costs
    on the CLEAN path (no faults, no retries), where it is pure
    overhead. A/B over the native TCP transport with a model-sized-ish
    64 KB payload: policy path = the production ``send_message``
    (serialize + RetryPolicy.run + one transport attempt); raw path =
    the same serialize + the same single attempt with the policy
    machinery bypassed. Headline scalar ``chaos_clean_overhead`` =
    policy_time / raw_time (1.0 = free). Also reports the
    ChaosTransport pass-through ratio with an all-zeros spec — the cost
    of LEAVING the drill wrapper installed in production."""
    import threading

    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.resilience import ChaosSpec, ChaosTransport
    from fedml_tpu.comm.tcp import TcpCommManager
    from fedml_tpu.comm.wire import serialize_message

    n_msgs, repeats = 400, 5
    table = {0: ("127.0.0.1", 0), 1: ("127.0.0.1", 0)}
    m0 = TcpCommManager(table, 0)
    m1 = TcpCommManager(table, 1)
    got = []

    class Obs:
        def receive_message(self, t, msg):
            got.append(t)

    m1.add_observer(Obs())
    rx = threading.Thread(target=m1.handle_receive_message, daemon=True)
    rx.start()
    msg = Message(type=3, sender_id=0, receiver_id=1)
    msg.add("round", 0)
    msg.add(Message.MSG_ARG_KEY_MODEL_PARAMS,
            {"w": np.zeros(16384, np.float32)})
    chaos_clean = ChaosTransport(m0, ChaosSpec(seed=0), rank=0)

    def _wait_drained(target):
        deadline = time.perf_counter() + 30
        while len(got) < target and time.perf_counter() < deadline:
            time.sleep(0.002)

    sent = [0]

    def timed(send_one):
        _check_section_deadline()
        t0 = time.perf_counter()
        for _ in range(n_msgs):
            send_one()
        dt = time.perf_counter() - t0  # sender-side cost only
        sent[0] += n_msgs
        _wait_drained(sent[0])  # isolate trials from each other (untimed)
        return dt

    def raw_send():
        blob = serialize_message(msg, m0._serializer)
        m0._send_once(1, *m0.ip_config[1], blob)

    try:
        raw_send()  # connect + warm both paths
        m0.send_message(msg)
        sent[0] = 2
        raw_t, policy_t, wrapped_t = [], [], []
        for _ in range(repeats):
            raw_t.append(timed(raw_send))
            policy_t.append(timed(lambda: m0.send_message(msg)))
            wrapped_t.append(timed(lambda: chaos_clean.send_message(msg)))
        raw_med, raw_iqr = _med_iqr(raw_t)
        pol_med, pol_iqr = _med_iqr(policy_t)
        wrap_med, _ = _med_iqr(wrapped_t)
    finally:
        m1.stop_receive_message()
        m0.close()
        m1.close()
    return {
        "messages_per_trial": n_msgs,
        "payload_bytes": 16384 * 4,
        "raw_send_s": round(raw_med, 4),
        "raw_send_s_iqr": raw_iqr,
        "policy_send_s": round(pol_med, 4),
        "policy_send_s_iqr": pol_iqr,
        "chaos_wrapped_send_s": round(wrap_med, 4),
        "delivered": len(got),
        "chaos_clean_overhead": round(pol_med / raw_med, 3),
        "chaos_wrapper_overhead": round(wrap_med / raw_med, 3),
        "send_retries_on_clean_path": m0.retry_count,
    }


def bench_wire_codec():
    """Compressed wire codec A/B (comm/codec.py + streaming ingest):
    bytes/upload and uploads/s for uncompressed vs bf16 vs int8 vs
    top-k+error-feedback on the loopback drill with the TENSOR wire
    round-trip live (bytes actually serialized, ByteLedger counted) and
    a ChaosTransport composed in (duplication + delay), so compression
    and fault injection are proven together — a duplicated compressed
    upload must stay idempotent at the server's streaming accumulator.

    Headline scalars: ``wire_bytes_ratio`` (uncompressed bytes/upload ÷
    top-k+EF bytes/upload — the bytes-on-wire reduction, acceptance
    floor 4x) and ``codec_acc_delta`` (top-k arm final accuracy −
    uncompressed arm; ~0 = compression is accuracy-free on this drill).
    """
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg_distributed import FedML_FedAvg_distributed
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.lr import LogisticRegression

    # 784-d LR (MNIST-shaped): big enough that frame headers don't mask
    # the codec's ratio, small enough to jit+run 4 arms in seconds.
    C, D, K, rounds = 8, 784, 10, 8
    rng = np.random.RandomState(0)
    y = rng.randint(0, K, size=C * 64).astype(np.int32)
    protos = rng.randn(K, D).astype(np.float32)
    x = 0.8 * protos[y] + rng.randn(len(y), D).astype(np.float32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), C),
                                 batch_size=16)
    test = batch_global(x[:256], y[:256], 64)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=4,
                    comm_round=rounds, epochs=1, batch_size=16, lr=0.2,
                    frequency_of_the_test=1000)

    arms = [("uncompressed", "none"), ("bf16", "bf16"), ("int8", "int8"),
            ("topk_ef", "topk0.05+int8")]
    out = {"rounds": rounds, "workers": cfg.client_num_per_round,
           "model_params": D * K + K, "wire": "tensor",
           "chaos": "dup_p=0.1 delay_p=0.1"}
    per_upload = {}
    for label, spec in arms:
        _check_section_deadline()
        t0 = time.perf_counter()
        # idle_timeout_s bounds the drill: a DELAYED terminal done whose
        # chaos timer dies with the server's transport close would
        # otherwise strand that worker's receive loop forever (and with
        # it this section, past any cap).
        agg = FedML_FedAvg_distributed(
            LogisticRegression(num_classes=K), fed, test, cfg,
            wire_codec=spec, loopback_wire="tensor",
            chaos=ChaosSpec(seed=11, dup_p=0.1, delay_p=0.1),
            idle_timeout_s=15.0)
        dt = time.perf_counter() - t0
        h = agg.test_history[-1] if agg.test_history else {}
        uploads = rounds * cfg.client_num_per_round
        # Uplink bytes: the server's ByteLedger rx total (heartbeats are
        # off here, so rx ≈ uploads — including chaos duplicates, which
        # honestly cross the wire twice), from the final health snapshot
        # the runner stamps on the aggregator.
        rx = agg.final_health["bytes_rx"]
        per_upload[label] = rx / max(uploads, 1)
        out[label] = {
            "bytes_rx_total": int(rx),
            "bytes_per_upload": round(per_upload[label], 1),
            "uploads_per_sec": round(uploads / dt, 2),
            "final_accuracy": round(float(h.get("accuracy", 0.0)), 4),
            "duplicate_drops": agg.final_health["duplicate_drops"],
        }
    out["wire_bytes_ratio"] = round(
        per_upload["uncompressed"] / max(per_upload["topk_ef"], 1e-9), 2)
    out["codec_acc_delta"] = round(
        out["topk_ef"]["final_accuracy"]
        - out["uncompressed"]["final_accuracy"], 4)
    return out


def bench_ingest_profile(C=8, D=4096, K=10, rounds=6):
    """The measured ruler for the server-ingest wall (ROADMAP item 1;
    arXiv:2307.06561 frames server ingest as *the* FL bottleneck): every
    upload funnels through ONE single-threaded dispatch loop doing
    decode + fold. This section runs the loopback ``topk+int8`` chaos
    drill with the ingest registry live (obs/registry.py; always on —
    the span tracer stays off, so this is the production-cost path) and
    reports WHERE an upload's server time goes:

    - ``ingest_occupancy`` (headline): dispatch-thread busy seconds over
      the first→last-message span — measured 0.78 in r11, the baseline
      the parallel ingest pool must drive DOWN at the same offered load;
    - decode/fold p50/p95 milliseconds + bytes/upload from the
      per-upload histograms (log-bucketed, ≤~9% quantile error);
    - a ``pooled`` arm (r12): the IDENTICAL drill with
      ``cfg.ingest_workers=2`` — decode+fold move to the pool
      (comm/ingest.py), so the before/after of the dispatch-thread
      occupancy is visible in one ruler. The serving-scale saturation
      curve lives in the ``serving_1m`` section.

    The model is deliberately bigger than the wire_codec section's
    (D=4096: ~41k params) so decode/fold cost is measurable above
    header noise while the section stays seconds-scale."""
    import dataclasses

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg_distributed import FedML_FedAvg_distributed
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.lr import LogisticRegression

    rng = np.random.RandomState(0)
    y = rng.randint(0, K, size=C * 32).astype(np.int32)
    protos = rng.randn(K, D).astype(np.float32)
    x = 0.8 * protos[y] + rng.randn(len(y), D).astype(np.float32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), C),
                                 batch_size=16)
    test = batch_global(x[:128], y[:128], 64)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=4,
                    comm_round=rounds, epochs=1, batch_size=16, lr=0.2,
                    frequency_of_the_test=1000)

    def drill(cfg):
        _check_section_deadline()
        t0 = time.perf_counter()
        # Same drill shape as wire_codec: tensor wire round-trip + chaos
        # (dup+delay), idle_timeout_s bounding chaos-stranded workers.
        agg = FedML_FedAvg_distributed(
            LogisticRegression(num_classes=K), fed, test, cfg,
            wire_codec="topk0.05+int8", loopback_wire="tensor",
            chaos=ChaosSpec(seed=11, dup_p=0.1, delay_p=0.1),
            idle_timeout_s=15.0)
        dt = time.perf_counter() - t0
        prof = dict(agg.ingest_profile)
        uploads = int(prof.get("uploads") or 0)
        return {
            "uploads_per_sec": round(uploads / dt, 2) if dt > 0 else None,
            "final_accuracy": round(float(
                (agg.test_history[-1] if agg.test_history else {}).get(
                    "accuracy", 0.0)), 4),
            **prof,
        }

    out = {
        "rounds": rounds, "workers": cfg.client_num_per_round,
        "model_params": D * K + K, "wire": "tensor",
        "codec": "topk0.05+int8", "chaos": "dup_p=0.1 delay_p=0.1",
        **drill(cfg),
        "pooled": drill(dataclasses.replace(cfg, ingest_workers=2)),
    }
    base, pooled = out.get("ingest_occupancy"), \
        out["pooled"].get("ingest_occupancy")
    out["pooled_occupancy_delta"] = (round(pooled - base, 4)
                                     if base is not None
                                     and pooled is not None else None)
    return out


def bench_serving_1m(C=1_048_576, G=64, n_devices=32, features=32,
                     classes=32_768, horizon_s=900.0, buffer_k=32,
                     saturation_uploads=480, workers_arms=(0, 1, 2, 4)):
    """The COMPOSED 1M-device serving drill (ROADMAP item 1): the three
    subsystems built since the last re-anchor run as ONE system, then
    the server-ingest wall they expose is broken with the parallel
    ingest pool (comm/ingest.py) and the break is measured.

    **Composition** — a diurnal-churn fleet of ``n_devices`` active
    device ranks serving a 2^20-client population: ``ClientDirectory``
    (PR 7) owns the million-client count metadata and samples every
    assignment; ``ShardedFederatedStore`` (PR 7) holds the population's
    data in G memmap-spilled shards (gathers page in only assigned
    clients); devices ship ``topk0.05+int8`` error-feedback deltas
    (PR 10's codec) over the SIM tensor wire (bytes counted per rank)
    into the FedBuff buffered server (PR 6) under ChaosTransport
    dup+delay — replayed on the virtual clock, so the same seed is
    event-for-event reproducible. Reported: uploads/s (virtual),
    bytes/s, staleness tails, evictions, churn-killed uploads, and host
    RSS (the memory axis). The drill runs twice — ``ingest_workers`` 1
    and 2 — and pins the pooled mean's interleaving-invariance at this
    scale: ``sim_nets_bitequal`` is the bit-comparison of the two final
    nets.

    **Ingest saturation** — the SIM replays client work on one event
    thread, so wall-clock uploads/s there measures the GIL, not the
    server. The saturation curve instead drives the SERVER ALONE at
    offered load (the fake-clock protocol-test pattern: pre-encoded
    topk+int8 frames of the same 1M-param model fed straight into the
    real ``FedBuffServerManager`` handler): ``uploads_per_sec`` vs
    ``ingest_workers`` ∈ {0, 1, 2, 4}, where workers=0 is the inline
    r11 baseline (``ingest_occupancy`` ≈ 1: the dispatch thread IS the
    wall) and the pool arms move decode+fold off the dispatch thread.
    Headline scalars: ``uploads_per_sec`` (the 4-worker arm) and
    ``ingest_speedup_4v1``."""
    import dataclasses
    import shutil
    import tempfile

    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedbuff import FedBuffServerManager
    from fedml_tpu.algos.fedasync import (MSG_ARG_KEY_MODEL_VERSION,
                                          MSG_ARG_KEY_TASK_SEQ)
    from fedml_tpu.algos.fedavg_distributed import (
        MSG_TYPE_C2S_SEND_MODEL_TO_SERVER)
    from fedml_tpu.comm.codec import CODEC_KEY, make_wire_codec, tree_spec
    from fedml_tpu.comm.loopback import LoopbackNetwork
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.directory import ShardedFederatedStore
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.sim import (FleetSimulator, FleetSpec, StoreFleetData,
                               make_fleet_trace)

    codec_spec = "topk0.05+int8"
    model = LogisticRegression(num_classes=classes)
    n_params = features * classes + classes
    out = {"clients": C, "shards": G, "devices": n_devices,
           "model_params": n_params, "codec": codec_spec, "wire": "tensor",
           "buffer_k": buffer_k, "chaos": "dup_p=0.05 delay_p=0.05",
           "virtual_horizon_s": horizon_s}

    # -- the 2^20-client population: directory + memmap-sharded store ----
    sizes = [C // G + (1 if s < C % G else 0) for s in range(G)]

    def builder(s):
        rng = np.random.RandomState(77_000 + s)
        n = sizes[s]
        counts = np.full(n, 2, np.int64)  # 2 samples per client
        tot = 2 * n
        return (rng.randn(tot, features).astype(np.float32),
                rng.randint(0, classes, tot).astype(np.int32), counts)

    spill = tempfile.mkdtemp(prefix="bench_serving1m_")
    try:
        t0 = time.perf_counter()
        store = ShardedFederatedStore.from_shard_builder(
            builder, G, batch_size=2, spill_dir=spill,
            progress=lambda s: _check_section_deadline())
        out["store_build_s"] = round(time.perf_counter() - t0, 1)
        out["dataset_disk_mb"] = round(store.nbytes() / 1e6, 1)
        out["directory_mb"] = round(store.directory.nbytes() / 1e6, 2)
        data = StoreFleetData(store)

        # -- composed SIM drill: churn × codec × chaos × pool ------------
        spec = FleetSpec(n_devices=n_devices, seed=11, horizon_s=horizon_s,
                         mean_online=0.8, base_round_s=30.0, slot_s=120.0,
                         speed_alpha=1.5, diurnal_amplitude=0.4,
                         diurnal_period_s=2400.0, arrival_spread_s=60.0)
        trace = make_fleet_trace(spec)
        cfg0 = FedConfig(client_num_in_total=C,
                         client_num_per_round=n_devices,
                         comm_round=10 ** 9, epochs=1, batch_size=2,
                         lr=0.05, frequency_of_the_test=10 ** 9)
        sim_nets = []
        for w in (1, 2):
            _check_section_deadline()
            sim = FleetSimulator(
                model, data, None,
                dataclasses.replace(cfg0, ingest_workers=w), trace,
                mode="fedbuff", buffer_k=buffer_k, wire_codec=codec_spec,
                sim_wire="tensor",
                chaos=ChaosSpec(seed=11, dup_p=0.05, delay_p=0.05),
                directory=store.directory)
            # Warm the shared jit cache outside the timed window.
            c0 = int(store.directory.sample_cohort(0, 1)[0])
            jax.block_until_ready(sim.local_train(
                sim.net0, data.x[c0], data.y[c0], data.mask[c0],
                jax.random.PRNGKey(0))[0])
            t0 = time.perf_counter()
            res = sim.run()
            dt = time.perf_counter() - t0
            uploads = len(res.arrival_log)
            h = sim.server.health()
            s = res.summary()
            virt = max(res.virtual_s, 1e-9)
            sim_nets.append(sim.server.net)
            out[f"sim_workers_{w}"] = {
                "uploads": uploads, "wall_s": round(dt, 2),
                "updates": res.updates,
                "uploads_per_vmin": round(60.0 * uploads / virt, 2),
                "bytes_rx_total": h["bytes_rx"],
                "bytes_per_upload": round(h["bytes_rx"] / max(uploads, 1),
                                          1),
                "bytes_per_vsec": round(h["bytes_rx"] / virt, 1),
                "staleness_p50": s.get("staleness_p50"),
                "staleness_p95": s.get("staleness_p95"),
                "staleness_max": s.get("staleness_max"),
                "evictions": s["evictions"],
                "churn_killed_uploads": s["churn_killed_uploads"],
                "host_rss_mb": s["host_rss_mb"],
            }
        out["sim_nets_bitequal"] = bool(all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(sim_nets[0]),
                            jax.tree.leaves(sim_nets[1]))))

        # -- ingest-saturation curve: the server alone at offered load --
        rng = np.random.RandomState(5)
        # The servers start from the composed drill's final net (host
        # numpy copy) — same shapes as the frames, zero extra init cost.
        net0 = jax.tree.map(np.asarray, sim_nets[0])
        spec_tree = tree_spec(net0)
        codec = make_wire_codec(codec_spec)
        frames = []
        for r in range(min(n_devices, 8)):
            delta = jax.tree.map(
                lambda l: (0.01 * rng.randn(*np.shape(l))).astype(
                    np.float32), net0)
            frames.append(codec.encode(delta, None, 1000 + r)[0])

        def saturation_arm(workers):
            _check_section_deadline()
            class A:  # the fake-clock protocol-test shim
                pass

            a = A()
            a.chaos = None
            a.network = LoopbackNetwork(n_devices + 1)
            # Full participation here (client_num_in_total = the device
            # count): the saturation sub-drill isolates the INGEST path,
            # and the per-version 2^20-population cohort draw is ~19 ms
            # of unrelated dispatch-thread work per flush that would
            # blur the curve. The composed SIM arms above keep the full
            # 1M directory sampling in the loop.
            cfg = dataclasses.replace(cfg0, ingest_workers=workers,
                                      client_num_in_total=n_devices)
            srv = FedBuffServerManager(a, net0, cfg, n_devices + 1,
                                       buffer_k=buffer_k)
            srv.register_message_receive_handlers()
            seqs = {}
            t0 = time.perf_counter()
            for i in range(saturation_uploads):
                worker = 1 + (i % n_devices)
                m = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, worker, 0)
                m.add(Message.MSG_ARG_KEY_MODEL_PARAMS,
                      frames[i % len(frames)])
                m.add(CODEC_KEY, codec_spec)
                m.add(MSG_ARG_KEY_MODEL_VERSION, srv.version)
                m.add(MSG_ARG_KEY_TASK_SEQ, seqs.get(worker, 0))
                seqs[worker] = seqs.get(worker, 0) + 1
                # Through receive_message, not the bare handler: the
                # dispatch-thread occupancy clock lives there.
                srv.receive_message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, m)
            if srv._pool is not None:
                srv._pool.drain()
            dt = time.perf_counter() - t0
            prof = srv.ingest_profile()
            pool = prof.get("ingest_pool") or {}
            occ = pool.get("occupancy_per_worker")
            arm = {
                "uploads": saturation_uploads, "wall_s": round(dt, 2),
                "uploads_per_sec": round(saturation_uploads / dt, 1),
                "versions": srv.version,
                "ingest_occupancy": prof.get("ingest_occupancy"),
                "pool_occupancy_mean": (round(float(np.mean(occ)), 4)
                                        if occ else None),
                "pool_task_ms_p50": prof.get("pool_task_ms_p50"),
            }
            if srv._pool is not None:
                srv._pool.close()
            return arm

        sat = {f"workers_{w}": saturation_arm(w) for w in workers_arms}
        out["saturation"] = sat
        u1 = sat.get("workers_1", {}).get("uploads_per_sec")
        u4 = sat.get("workers_4", {}).get("uploads_per_sec")
        out["uploads_per_sec"] = u4
        out["ingest_speedup_4v1"] = (round(u4 / u1, 2)
                                     if u1 and u4 else None)
        u0 = sat.get("workers_0", {}).get("uploads_per_sec")
        out["ingest_speedup_4v0"] = (round(u4 / u0, 2)
                                     if u0 and u4 else None)
        # -- adapter arm (PR 15): the same churn × codec × pool × chaos
        # composition shipping ADAPTER-only topk+int8 EF deltas from a
        # frozen-base transformer. Degraded to an error record instead
        # of discarding the measured scalars above (the PR 7
        # gather_probe_error discipline).
        try:
            out["adapter_arm"] = _serving_adapter_arm()
        except Exception as e:
            out["adapter_arm"] = {"error": f"{type(e).__name__}: {e}"[:200]}
        return out
    finally:
        shutil.rmtree(spill, ignore_errors=True)


def _serving_adapter_arm(n_devices=8, horizon_s=600.0, rank=8,
                         d_model=64, vocab=2004, seq_len=20):
    """serving_1m's adapter arm: a diurnal-churn FedBuff fleet of
    frozen-base transformers shipping adapter-only ``topk0.05+int8`` EF
    deltas through the 2-worker ingest pool over the SIM tensor wire
    under ChaosTransport — the million-client drill's composition with
    the upload shrunk by the rank ratio BEFORE the codec runs."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.synthetic import make_stackoverflow_nwp
    from fedml_tpu.models import create_model
    from fedml_tpu.models.adapter import param_count
    from fedml_tpu.sim import FleetSimulator, FleetSpec, make_fleet_trace
    from fedml_tpu.trainer.local import model_fns, seq_softmax_ce

    _check_section_deadline()
    model = create_model("transformer_lm", vocab_size=vocab,
                         d_model=d_model, n_heads=4, n_layers=2,
                         max_len=seq_len, adapter_rank=rank)
    x, y, parts = make_stackoverflow_nwp(64, seq_len=seq_len, vocab=vocab,
                                         seed=3)
    fed = build_federated_arrays(x, y, parts, 2)
    cfg = FedConfig(client_num_in_total=64, client_num_per_round=n_devices,
                    comm_round=10 ** 9, epochs=1, batch_size=2, lr=0.05,
                    frequency_of_the_test=10 ** 9, adapter_rank=rank,
                    ingest_workers=2)
    spec = FleetSpec(n_devices=n_devices, seed=11, horizon_s=horizon_s,
                     mean_online=0.8, base_round_s=30.0, slot_s=120.0,
                     speed_alpha=1.5, diurnal_amplitude=0.4,
                     diurnal_period_s=2400.0, arrival_spread_s=60.0)
    sim = FleetSimulator(model, fed, None, cfg, make_fleet_trace(spec),
                         mode="fedbuff", buffer_k=4,
                         wire_codec="topk0.05+int8", sim_wire="tensor",
                         chaos=ChaosSpec(seed=11, dup_p=0.05, delay_p=0.05),
                         loss_fn=partial(seq_softmax_ce, pad_id=0))
    jax.block_until_ready(sim.local_train(
        sim.net0, fed.x[0], fed.y[0], fed.mask[0],
        jax.random.PRNGKey(0))[0])  # jit warm, outside the timed window
    t0 = time.perf_counter()
    res = sim.run()
    dt = time.perf_counter() - t0
    uploads = len(res.arrival_log)
    h = sim.server.health()
    s = res.summary()
    adapter_params = param_count(sim.net0.params)
    dense_params = param_count(model_fns(
        create_model("transformer_lm", vocab_size=vocab, d_model=d_model,
                     n_heads=4, n_layers=2, max_len=seq_len)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)).params)
    bpu = h["bytes_rx"] / max(uploads, 1)
    return {
        "devices": n_devices, "rank": rank,
        "adapter_params": adapter_params, "dense_params": dense_params,
        "codec": "topk0.05+int8", "ingest_workers": 2,
        "uploads": uploads, "wall_s": round(dt, 2),
        "updates": res.updates,
        "bytes_per_upload": round(bpu, 1),
        "bytes_vs_dense_wire": round(4.0 * dense_params / max(bpu, 1e-9),
                                     1),
        "staleness_p95": s.get("staleness_p95"),
        "evictions": s["evictions"],
        "churn_killed_uploads": s["churn_killed_uploads"],
        "codec_refusals": h["codec_refusals"],
        "host_rss_mb": s["host_rss_mb"],
    }


def bench_agg_shards(n_workers=32, rounds=3, features=32, classes=8192,
                     shard_arms=(1, 2, 4)):
    """The r16 sharded aggregation plane (comm/shardplane.py): M
    ``AggregatorShardManager`` ranks each decode+fold their client
    partition and ship ONE int64 fixed-point partial per flush; the
    rank-0 coordinator wire-merges the M partials through the same
    ``finalize_partial_mean`` division site as the in-process pool
    (bit-equality by construction — pinned in tests/test_shardplane.py).

    Each arm runs the REAL loopback federation control plane — live
    receive loops for the coordinator and the M shards — at offered
    load: driver threads play the workers, posting pre-encoded
    ``topk0.05+int8`` DELTA frames of a ~270k-param model straight into
    the routed shard's inbox the instant the new round's anchor lands
    (no local training in the loop, so uploads/s measures the
    aggregation plane alone). Reported per arm: uploads/s, the
    coordinator's dispatch-thread occupancy (the scale-out claim: the
    coordinator folds NOTHING — its per-upload cost is one ACCEPT
    notice, so occupancy stays low while the shards carry decode+fold),
    per-shard pool occupancy, and the health rollups. Headline pair:
    ``speedup_4v1`` (target ≥ 1.5 — thread-parallel shard folds, so the
    measured value is bounded by ``cpu_count``, recorded alongside) and
    ``coord_occupancy_m4`` (target < 0.5)."""
    import os

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg_distributed import (
        MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, FedAVGAggregator)
    from fedml_tpu.comm.codec import CODEC_KEY, make_wire_codec
    from fedml_tpu.comm.loopback import (LoopbackCommManager,
                                         LoopbackNetwork, run_workers)
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.shardplane import (AggregatorShardManager,
                                           ShardedFedAVGServerManager)

    codec_spec = "topk0.05+int8"
    n_params = features * classes + classes
    rng = np.random.RandomState(3)
    net0 = {"b": np.zeros(classes, np.float32),
            "w": np.zeros((features, classes), np.float32)}
    codec = make_wire_codec(codec_spec)
    frames = [codec.encode(
        {"b": (0.01 * rng.randn(classes)).astype(np.float32),
         "w": (0.01 * rng.randn(features, classes)).astype(np.float32)},
        None, 300 + s)[0] for s in range(min(n_workers, 8))]
    cfg = FedConfig(client_num_in_total=n_workers,
                    client_num_per_round=n_workers, comm_round=rounds,
                    epochs=1, batch_size=2, lr=0.05,
                    frequency_of_the_test=10 ** 9, ingest_workers=1)

    def arm(m):
        _check_section_deadline()

        class A:  # the protocol-shim args surface
            pass

        a = A()
        a.chaos = None
        size = n_workers + m + 1
        a.network = LoopbackNetwork(size)
        agg = FedAVGAggregator(net0, n_workers, cfg)
        srv = ShardedFedAVGServerManager(a, agg, cfg, size, m)
        shards = [AggregatorShardManager(a, r, size, cfg, net0)
                  for r in range(1, m + 1)]

        def driver(worker):
            com = LoopbackCommManager(a.network, worker)
            slot = worker - m - 1
            for r in range(rounds):
                # The anchor-before-upload fence, driver-side: post only
                # once the ROUTED shard adopted round r (in the real
                # federation local training provides this slack).
                sh = shards[slot % m]
                while (sh.round_idx < r or srv.round_idx < r) \
                        and not srv._stopped:
                    time.sleep(0.0005)
                if srv._stopped:
                    return
                msg = Message(MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, worker,
                              sh.rank)
                msg.add(Message.MSG_ARG_KEY_MODEL_PARAMS,
                        frames[slot % len(frames)])
                msg.add(CODEC_KEY, codec_spec)
                msg.add(Message.MSG_ARG_KEY_NUM_SAMPLES, 2)
                msg.add("round", r)
                msg.add("epoch", 0)
                com.send_message(msg)

        t0 = time.perf_counter()
        run_workers([srv.run] + [sh.run for sh in shards]
                    + [lambda w=w: driver(w)
                       for w in range(m + 1, size)])
        dt = time.perf_counter() - t0
        uploads = rounds * n_workers
        h = srv.health()
        prof = srv.ingest_profile()
        shard_occ = [sh.ingest_profile().get("ingest_occupancy")
                     for sh in shards]
        shard_occ = [o for o in shard_occ if o is not None]
        return {
            "uploads": uploads, "wall_s": round(dt, 2),
            "uploads_per_sec": round(uploads / dt, 1),
            "rounds": srv.round_idx,
            "coord_occupancy": prof.get("ingest_occupancy"),
            "shard_occupancy_mean": (round(float(np.mean(shard_occ)), 4)
                                     if shard_occ else None),
            "shard_evictions": h["shard_evictions"],
            "bytes_rx_total": h["bytes_rx"],
        }

    out = {"workers": n_workers, "rounds": rounds,
           "model_params": n_params, "codec": codec_spec,
           "cpu_count": os.cpu_count(),
           **{f"shards_{m}": arm(m) for m in shard_arms}}
    u1 = out.get("shards_1", {}).get("uploads_per_sec")
    u4 = out.get("shards_4", {}).get("uploads_per_sec")
    out["speedup_4v1"] = round(u4 / u1, 2) if u1 and u4 else None
    out["coord_occupancy_m4"] = out.get("shards_4", {}).get(
        "coord_occupancy")
    return out


def bench_secagg(C=8, D=784, K=10, rounds=6):
    """Dropout-robust secure aggregation (comm/secagg.py, r19): the
    masked arm runs the SAME ``topk0.05+int8`` delta federation under
    ChaosTransport as the plain arm — pairwise seed-expanded masks over
    the fixed-point int64 contributions, cancelled exactly in the
    pooled fold — so the uploads/s ratio IS the masking cost (the
    DH/Shamir handshake round, per-upload self-decode + mask expansion,
    and the masked frames' dense int64 wire payload; the bytes ruler is
    honest about that last part — masking trades the sparsifier's wire
    ratio for the privacy bound, and only the adapter scope shrinks the
    MASKED payload). Headline scalar ``secagg_overhead`` = plain ÷
    masked uploads/s, target ≤ 1.3x. A third mini-drill kills one
    roster client mid-federation: heartbeat eviction triggers the
    t-of-n Shamir seed reveal, the round commits over survivors, and
    the server's ``secagg_reveal_ms`` histogram supplies the
    reveal-latency submetric."""
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg_distributed import (
        FedAVGAggregator, FedAVGClientManager, FedAVGServerManager,
        FedML_FedAvg_distributed, build_federation_setup)
    from fedml_tpu.comm.loopback import run_workers
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.trainer.local import softmax_ce

    rng = np.random.RandomState(0)
    y = rng.randint(0, K, size=C * 64).astype(np.int32)
    protos = rng.randn(K, D).astype(np.float32)
    x = 0.8 * protos[y] + rng.randn(len(y), D).astype(np.float32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), C),
                                 batch_size=16)
    test = batch_global(x[:256], y[:256], 64)

    out = {"rounds": rounds, "workers": 4, "model_params": D * K + K,
           "codec": "topk0.05+int8", "chaos": "dup_p=0.1 delay_p=0.1"}
    per_ups = {}
    for label, masked in (("plain", False), ("masked", True)):
        _check_section_deadline()
        cfg = FedConfig(client_num_in_total=C, client_num_per_round=4,
                        comm_round=rounds, epochs=1, batch_size=16,
                        lr=0.2, frequency_of_the_test=1000,
                        ingest_workers=1, secagg=masked)
        t0 = time.perf_counter()
        agg = FedML_FedAvg_distributed(
            LogisticRegression(num_classes=K), fed, test, cfg,
            wire_codec="topk0.05+int8", loopback_wire="tensor",
            chaos=ChaosSpec(seed=11, dup_p=0.1, delay_p=0.1),
            idle_timeout_s=15.0)
        dt = time.perf_counter() - t0
        uploads = rounds * cfg.client_num_per_round
        per_ups[label] = uploads / dt
        h = agg.final_health
        out[label] = {
            "uploads_per_sec": round(per_ups[label], 2),
            "bytes_per_upload": round(
                h["bytes_rx"] / max(uploads, 1), 1),
            "duplicate_drops": h["duplicate_drops"],
            "seed_reveals": h.get("seed_reveals", 0),
            "final_accuracy": round(float(
                (agg.test_history[-1] if agg.test_history
                 else {}).get("accuracy", 0.0)), 4),
        }
    out["secagg_overhead"] = round(
        per_ups["plain"] / max(per_ups["masked"], 1e-9), 2)

    # The seed-reveal drill: 4 roster workers, one goes silent inside
    # round 1 (its local step outlasts the round deadline and its beats
    # stop) — the watchdog evicts it, >=t survivors return Shamir
    # shares, the orphaned masks are subtracted, the round commits.
    _check_section_deadline()
    cfgd = FedConfig(client_num_in_total=4, client_num_per_round=4,
                     comm_round=3, epochs=1, batch_size=16, lr=0.2,
                     frequency_of_the_test=10 ** 6, ingest_workers=1,
                     heartbeat_interval_s=0.05, secagg=True)
    size, net0, local_train, eval_fn, args = build_federation_setup(
        LogisticRegression(num_classes=K),
        build_federated_arrays(x[:256], y[:256],
                               partition_homo(256, 4), batch_size=16),
        None, cfgd, "LOOPBACK", softmax_ce)
    srv = FedAVGServerManager(args, FedAVGAggregator(net0, size - 1, cfgd),
                              cfgd, size, round_timeout_s=1.5,
                              heartbeat_timeout_s=0.4)

    def victim_train(*a, **kw):
        if srv.round_idx >= 1:
            time.sleep(3.5)  # outlast the 1.5s round deadline
        return local_train(*a, **kw)

    fed4 = build_federated_arrays(x[:256], y[:256], partition_homo(256, 4),
                                  batch_size=16)
    clients = [FedAVGClientManager(args, r, size, fed4,
                                   (victim_train if r == 1
                                    else local_train), cfgd)
               for r in range(1, size)]

    def killer():
        deadline = time.monotonic() + 20.0
        while srv.round_idx < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        clients[0].finish()  # beats stop: the watchdog owns it now

    run_workers([srv.run] + [c.run for c in clients] + [killer])
    snap = srv._h_reveal.snapshot()
    out["reveal_drill"] = {
        "rounds": srv.round_idx, "aborted": srv.aborted,
        "evictions": srv.health()["evictions"],
        "seed_reveals": srv.seed_reveals,
        "reveal_ms_p50": snap.get("p50"),
        "reveal_ms_max": snap.get("max"),
    }
    return out


def bench_serving_10m(C=2 ** 23, G=128, M=4, features=4, classes=64,
                      cohorts=32, cohort_size=1024):
    """The 10M-client serving drill (r16): the 2^23-client population
    lives in a ``ShardedFederatedStore`` (memmap spill — host RSS stays
    O(active cohort), not O(population)), its ``ClientDirectory`` owns
    the counts/shard metadata, and every cohort draw is routed onto the
    M=4 aggregator shards by ``directory.agg_shard_of`` (data-shard
    locality: clients of one store shard land on one aggregator shard).
    Measured: store build + disk/directory footprint at 8.4M clients,
    cohort-draw and shard-routing microseconds per client, the routing
    balance across shards, gather page-in for one cohort, and a
    directory-routed M-shard fold round — cohort uploads folded into
    per-shard int64 partials, wire-encoded, merged, finalized (the
    shardplane commit path) — as uploads/s. The full federation fabric
    at this population rides ``agg_shards``/``serving_1m``; this section
    pins the POPULATION axis: 8x serving_1m's 2^20."""
    import shutil
    import tempfile

    from fedml_tpu.comm.ingest import (PartialAccumulator,
                                       finalize_partial_mean)
    from fedml_tpu.comm.shardplane import decode_partial, encode_partial
    from fedml_tpu.data.directory import ShardedFederatedStore
    from fedml_tpu.sim import StoreFleetData

    sizes = [C // G + (1 if s < C % G else 0) for s in range(G)]

    def builder(s):
        rng = np.random.RandomState(88_000 + s)
        n = sizes[s]
        counts = np.ones(n, np.int64)  # 1 sample per client
        return (rng.randn(n, features).astype(np.float32),
                rng.randint(0, classes, n).astype(np.int32), counts)

    out = {"clients": C, "store_shards": G, "agg_shards": M,
           "features": features}
    spill = tempfile.mkdtemp(prefix="bench_serving10m_")
    try:
        t0 = time.perf_counter()
        store = ShardedFederatedStore.from_shard_builder(
            builder, G, batch_size=1, spill_dir=spill,
            progress=lambda s: _check_section_deadline())
        out["store_build_s"] = round(time.perf_counter() - t0, 1)
        out["dataset_disk_mb"] = round(store.nbytes() / 1e6, 1)
        out["directory_mb"] = round(store.directory.nbytes() / 1e6, 2)
        d = store.directory

        # -- the assignment plane: draw + route, per-shard balance ------
        _check_section_deadline()
        tally = np.zeros(M, np.int64)
        t0 = time.perf_counter()
        for k in range(cohorts):
            cohort = d.sample_cohort(k, cohort_size)
            route = d.agg_shard_of(cohort, M)
            tally += np.bincount(route, minlength=M)
        dt = time.perf_counter() - t0
        n_routed = cohorts * cohort_size
        out["route_us_per_client"] = round(1e6 * dt / n_routed, 3)
        out["shard_balance_max_over_mean"] = round(
            float(tally.max() / max(tally.mean(), 1e-9)), 3)

        # -- page-in: gather ONE cohort out of the 8.4M-client memmap ---
        _check_section_deadline()
        data = StoreFleetData(store)
        cohort = d.sample_cohort(0, cohort_size)
        t0 = time.perf_counter()
        for c in cohort[:64]:
            np.asarray(data.x[int(c)])
        out["gather_ms_per_client"] = round(
            1e3 * (time.perf_counter() - t0) / 64, 3)

        # -- directory-routed M-shard fold + wire merge (the shardplane
        # commit path at this population: route → per-shard int64 fold →
        # encode/decode partials → merge → ONE finalize) ----------------
        _check_section_deadline()
        rng = np.random.RandomState(9)
        net_ref = {"b": np.zeros(classes, np.float32),
                   "w": np.zeros((features, classes), np.float32)}
        deltas = [[(0.01 * rng.randn(classes)).astype(np.float32),
                   (0.01 * rng.randn(features, classes)).astype(np.float32)]
                  for _ in range(8)]
        route = d.agg_shard_of(cohort, M)
        accs = [PartialAccumulator() for _ in range(M)]
        t0 = time.perf_counter()
        for i, c in enumerate(cohort):
            accs[int(route[i])].add(deltas[i % len(deltas)], 1.0)
        total = PartialAccumulator()
        for acc in accs:
            decode_partial(encode_partial(acc)).merge_into(total)
        mean, count = finalize_partial_mean(total, net_ref)
        dt = time.perf_counter() - t0
        assert count == len(cohort)
        out["fold_uploads"] = int(count)
        out["uploads_per_sec"] = round(count / dt, 1)
        out["host_rss_mb"] = round(_rss_mb(), 1)
        return out
    finally:
        shutil.rmtree(spill, ignore_errors=True)


def bench_fleet_sim():
    """Serving under churn on the REAL control plane (fedml_tpu.sim):
    one fixed seeded fleet trace — staggered arrivals, diurnal
    availability windows, power-law device speeds, mid-round churn —
    replayed against sync first-k (fedavg_distributed), buffered
    semi-sync (fedbuff, aggregate every k arrivals with polynomial
    staleness discounting), and pure async (fedasync). Virtual clock:
    a four-virtual-hour diurnal scenario replays in wall seconds, the
    training math is exact (final_accuracy is real), and the whole
    interleaving is pinned by the seed (tests/test_fleet_sim.py diffs
    two runs' full arrival logs). The serving story the headline
    carries: buffered(k) beats first-k(k) round-throughput (no barrier,
    no discarded straggler work) while holding a lower staleness tail
    than pure async (docs/ROBUSTNESS.md "Serving under churn")."""
    import dataclasses

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.data.synthetic import make_classification
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.sim import FleetSimulator, FleetSpec, make_fleet_trace

    x, y = make_classification(320, n_features=10, n_classes=4, seed=1)
    fed = build_federated_arrays(x, y, partition_homo(len(x), 8),
                                 batch_size=16)
    test = batch_global(x[:96], y[:96], 16)
    cfg = FedConfig(client_num_in_total=8, client_num_per_round=8,
                    comm_round=12, epochs=1, batch_size=16, lr=0.3,
                    frequency_of_the_test=4)
    spec = FleetSpec(n_devices=8, seed=11, horizon_s=14400.0,
                     mean_online=0.75, base_round_s=30.0, slot_s=180.0,
                     speed_alpha=1.3, diurnal_amplitude=0.3,
                     arrival_spread_s=120.0)
    k = 4

    def go(mode, spec=spec, **kw):
        sim = FleetSimulator(LogisticRegression(num_classes=4), fed, test,
                             cfg, make_fleet_trace(spec), mode=mode, **kw)
        return sim.run()

    out = {"k": k, "trace": make_fleet_trace(spec).describe()}
    # Accuracy yardstick: the same federation on an always-on fleet.
    _check_section_deadline()
    clean = go("sync", spec=dataclasses.replace(spec, mean_online=1.0,
                                                diurnal_amplitude=0.0),
               aggregate_k=0)
    out["clean_accuracy"] = clean.final_accuracy
    runs = {}
    for label, mode, kw in (("sync_firstk", "sync", {"aggregate_k": k}),
                            ("buffered", "fedbuff", {"buffer_k": k}),
                            ("async", "fedasync", {})):
        _check_section_deadline()
        runs[label] = go(mode, **kw)
        out[label] = runs[label].summary()
    sync_tp = runs["sync_firstk"].updates_per_vmin
    buf_tp = runs["buffered"].updates_per_vmin
    out["buffered_vs_firstk_throughput"] = (round(buf_tp / sync_tp, 3)
                                            if sync_tp else None)
    bp = out["buffered"].get("staleness_p95")
    ap = out["async"].get("staleness_p95")
    out["buffered_vs_async_stale_p95"] = (round(bp / ap, 3)
                                          if bp is not None and ap else None)
    return out


def bench_adaptive_control(comm_round=24, static_ks=(2, 6)):
    """Self-tuning federation control under a load spike (fedml_tpu.ctrl,
    docs/ROBUSTNESS.md "Adaptive control"): one seeded fleet trace with a
    6x compute-slowdown window early in the run, replayed against static
    buffered arms (each ``buffer_k`` fixed for the whole run) and the
    adaptive controller (1807.06629-style window schedule + guard-band
    staleness admission) actuating the SAME fedbuff manager through its
    seam. The static arms frame the tradeoff the controller escapes: a
    small k is fast but its staleness tail blows through the spike, a
    large k holds the tail down but pays for it in virtual time all run
    long. Headline ``adaptive_ctrl_gain``: controller accuracy per
    virtual minute over the best static arm's — >= 1.0 means the closed
    loop beats every static configuration while (also asserted by
    tests/test_ctrl.py on this exact config) holding a lower accepted-
    staleness p95 than the best arm. Deterministic: the drill test pins
    two-run-identical actuation logs on this seed."""
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.ctrl import (FederationController,
                                StalenessAdmissionPolicy,
                                WindowSchedulePolicy)
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.data.synthetic import make_classification
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.sim import FleetSimulator, FleetSpec, make_fleet_trace

    x, y = make_classification(320, n_features=10, n_classes=4, seed=1)
    fed = build_federated_arrays(x, y, partition_homo(len(x), 8),
                                 batch_size=16)
    test = batch_global(x[:96], y[:96], 16)
    cfg = FedConfig(client_num_in_total=8, client_num_per_round=8,
                    comm_round=comm_round, epochs=1, batch_size=16, lr=0.3,
                    frequency_of_the_test=4)
    spec = FleetSpec(n_devices=8, seed=11, horizon_s=20000.0,
                     mean_online=0.92, base_round_s=20.0, slot_s=400.0,
                     arrival_spread_s=30.0, spike_t0=250.0, spike_t1=700.0,
                     spike_factor=6.0)

    def go(controller=None, buffer_k=2):
        _check_section_deadline()
        sim = FleetSimulator(LogisticRegression(num_classes=4), fed, test,
                             cfg, make_fleet_trace(spec), mode="fedbuff",
                             buffer_k=buffer_k, controller=controller)
        res = sim.run()
        acc_vmin = ((res.final_accuracy or 0.0) * 60.0
                    / max(res.virtual_s, 1e-9))
        return res, sim, {**res.summary(),
                          "acc_per_vmin": round(acc_vmin, 5)}

    out = {"trace": make_fleet_trace(spec).describe(),
           "spike": {"t0": spec.spike_t0, "t1": spec.spike_t1,
                     "factor": spec.spike_factor}}
    best_static = None
    for k in static_ks:
        _, _, rec = go(buffer_k=k)
        out[f"static_k{k}"] = rec
        if best_static is None \
                or rec["acc_per_vmin"] > best_static["acc_per_vmin"]:
            best_static = rec
    ctl = FederationController(
        [WindowSchedulePolicy(w_min=1, w_max=4),
         StalenessAdmissionPolicy(band_lo=2.0, band_hi=4.0, k_max=4,
                                  cap_slack=0, cooldown=2)],
        interval=1)
    _, sim, rec = go(controller=ctl)
    applied = [e for e in ctl.actuation_log if e["outcome"] == "applied"]
    snap = sim.server.registry.snapshot()
    out["controller"] = {
        **rec,
        "actuations_applied": len(applied),
        "actuations_refused": int(snap.get("actuation_refused", 0)),
        "admission_drops": int(snap.get("admission_drops", 0)),
        "final_knobs": sim.server.ctrl.values(),
        # The full decision trail (the reproducibility artifact the
        # drill test diffs across two runs) — blob-only, never headline.
        "actuation_log": ctl.actuation_log,
    }
    out["adaptive_ctrl_gain"] = (
        round(rec["acc_per_vmin"] / best_static["acc_per_vmin"], 3)
        if best_static and best_static["acc_per_vmin"] else None)
    out["ctrl_vs_best_static_stale_p95"] = (
        round(rec.get("staleness_p95", 0.0)
              / best_static["staleness_p95"], 3)
        if best_static and best_static.get("staleness_p95") else None)
    return out


def _gather_overlap_probe(api, store, probe_rounds=10, start=90_001):
    """Median SYNCHRONOUS cohort gather+H2D seconds per round, measured
    on rounds the timed windows never visit (fresh seeds, warm shapes).
    Divided by the measured round wall-clock this yields the
    prefetch-overlap ratio: the fraction of a round the prefetcher must
    hide (<1 = the host gather fits entirely under the device compute —
    the store's stated design point, now measured; >1 = gather-bound).
    Checks the section deadline per round (cold memmap page-ins at 1M
    clients are IO-bound); both callers catch the resulting
    _SectionTimeout as a probe error so an overrun never discards the
    primary measurement already taken."""
    import jax

    ts = []
    for r in range(start, start + probe_rounds):
        _check_section_deadline()
        idx, _ = api._sample_round_uncached(r)
        t0 = time.perf_counter()
        sub = store.gather_cohort(np.asarray(idx))
        jax.block_until_ready((sub.x, sub.y, sub.mask))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def bench_stackoverflow_342k():
    """BASELINE.md's largest row at its TRUE scale: 342,477 clients
    (the reference enumerates exactly that many stackoverflow_nwp
    users), reference model dims (embed 96, LSTM 670, vocab 10004),
    50 clients/round, batch 16. Host-resident CSR store (~360 MB for
    ~2.25M synthetic sentences); each round's device cohort is a few MB
    regardless of the client count. Reports samples/sec and the
    measured host-gather vs round-time split (VERDICT r6 #8) so this
    point and the 1M sharded-directory point (``synthetic_1m``) carry
    comparable units."""
    from functools import partial

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.models.rnn import RNNStackOverflow
    from fedml_tpu.trainer.local import seq_softmax_ce

    from fedml_tpu.data.synthetic import make_stackoverflow_nwp

    C, T, V, cpr, batch = 342_477, 20, 10004, 50, 16
    x, y, parts = make_stackoverflow_nwp(C, seq_len=T, vocab=V)
    counts = np.array([len(parts[c]) for c in range(C)])
    store = FederatedStore(x, y, parts, batch_size=batch)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=cpr,
                    comm_round=100_000,  # > any window schedule: keeps
                    # the cohort prefetcher live for every timed round
                    epochs=1, batch_size=batch,
                    lr=10 ** -0.5)  # BASELINE.md row lr
    api = FedAvgAPI(RNNStackOverflow(vocab_size=V), store, None, cfg,
                    loss_fn=partial(seq_softmax_ce, pad_id=0), pad_id=0)
    _warm_store_buckets(api, store, counts, cpr, batch)
    timed = _timed_store_windows(api, store, count_samples=True)
    # Record the scale point and assemble the result BEFORE the
    # auxiliary probe: a probe failure must not discard the primary
    # throughput/RSS measurement already taken.
    _scale_state["342k"] = {"rps": timed["rounds_per_sec"],
                            "rss_peak_mb": timed["rss_peak_mb"]}
    out = {"clients": C, **timed,
           "host_dataset_mb": round(store.nbytes() / 1e6, 1)}
    try:
        gather_s = _gather_overlap_probe(api, store)
        out["host_gather_ms_per_round"] = round(gather_s * 1e3, 1)
        out["prefetch_overlap_ratio"] = round(
            gather_s * timed["rounds_per_sec"], 3)
    except Exception as e:  # incl. _SectionTimeout: the probe is
        # auxiliary and deadline-checked per round — degrade to an
        # explicit hole, keep the timed measurement.
        out["gather_probe_error"] = f"{type(e).__name__}: {e}"[:120]
    return out


def bench_synthetic_1m(C=1_048_576, G=64, cpr=50, model_kw=None,
                       min_window_s=6.0):
    """The MILLION-CLIENT tier (ROADMAP open item 1): 2^20 = 1,048,576
    synthetic StackOverflow-NWP clients through the SHARDED client
    directory (``data/directory.py`` — G memmap-spilled shards built one
    at a time, directory metadata O(clients), gathers page in only the
    cohort's rows) on the same model/round config as
    ``stackoverflow_342k``, so the two points differ ONLY in client
    count and storage tier. The claims this section records, as
    measured ratios against the 342k flat-store point (same process,
    same units): host RSS stays FLAT as the client count grows 3x past
    the flat store's scale (``peak_rss_ratio`` — sampled current RSS
    per timed block, the flat-RSS story of the sharded tier), and
    rounds/sec stays within 2x (``rps_vs_342k`` — cohort cost is
    independent of the client count; the extra price is directory
    sampling at 1M and memmap page-ins). The parameters exist for the
    machinery test (tests/test_bench_headline.py) — the section always
    runs the defaults."""
    import shutil
    import tempfile
    from functools import partial

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.directory import ShardedFederatedStore
    from fedml_tpu.models.rnn import RNNStackOverflow
    from fedml_tpu.trainer.local import seq_softmax_ce

    from fedml_tpu.data.synthetic import make_stackoverflow_shard

    T, V, batch = 20, 10004, 16
    # Remainder-aware shard sizes: sum(sizes) == C exactly, so the
    # directory's client count always matches cfg.client_num_in_total
    # (the sampler-delegation guard) even for non-dividing C/G.
    sizes = [C // G + (1 if s < C % G else 0) for s in range(G)]

    def builder(s):
        # THE make_stackoverflow_nwp law (single source — data/
        # synthetic.py), seeded per shard so build peak RSS is O(one
        # shard).
        return make_stackoverflow_shard(sizes[s], seq_len=T, vocab=V,
                                        seed=10_000 + s)

    spill = tempfile.mkdtemp(prefix="bench_synth1m_")
    try:
        store = ShardedFederatedStore.from_shard_builder(
            builder, G, batch_size=batch, spill_dir=spill,
            progress=lambda s: _check_section_deadline())
        build_rss = _rss_mb()
        cfg = FedConfig(client_num_in_total=C, client_num_per_round=cpr,
                        comm_round=100_000, epochs=1, batch_size=batch,
                        lr=10 ** -0.5)
        api = FedAvgAPI(RNNStackOverflow(vocab_size=V, **(model_kw or {})),
                        store, None, cfg,
                        loss_fn=partial(seq_softmax_ce, pad_id=0), pad_id=0)
        _warm_store_buckets(api, store, np.asarray(store.counts), cpr,
                            batch)
        timed = _timed_store_windows(api, store, count_samples=True,
                                     min_window_s=min_window_s)
        ref = _scale_state.get("342k")
        out = {"clients": C, "shards": G, "memmap_spill": True, **timed,
               "dataset_disk_mb": round(store.nbytes() / 1e6, 1),
               "directory_mb": round(store.directory.nbytes() / 1e6, 2),
               "build_rss_mb": round(build_rss, 1),
               # Ratios vs the flat-store 342k point (None if its
               # section was skipped/errored this run):
               "rps_vs_342k": (round(timed["rounds_per_sec"] / ref["rps"],
                                     3) if ref else None),
               "peak_rss_ratio": (round(timed["rss_peak_mb"]
                                        / ref["rss_peak_mb"], 3)
                                  if ref else None)}
        try:  # auxiliary (incl. _SectionTimeout — deadline-checked per
            # round): must not discard the measurements above
            gather_s = _gather_overlap_probe(api, store)
            out["host_gather_ms_per_round"] = round(gather_s * 1e3, 1)
            out["prefetch_overlap_ratio"] = round(
                gather_s * timed["rounds_per_sec"], 3)
        except Exception as e:
            out["gather_probe_error"] = f"{type(e).__name__}: {e}"[:120]
        return out
    finally:
        shutil.rmtree(spill, ignore_errors=True)


def bench_vit():
    """ViT federation (new capability beyond reference parity): CIFAR-
    shaped inputs, patch 4, d=128, 4 heads x 4 layers."""
    from fedml_tpu.models import create_model

    model = create_model("vit", num_classes=10, patch=4, d_model=128,
                         n_heads=4, n_layers=4)
    sps = _scan_bench(model, n_clients=64, per_client=256, batch=32,
                      cpr=8, lr=0.01)
    return {"samples_per_sec": round(sps, 2),
            **_mfu_fields(model, np.zeros((32, 32, 32, 3), np.float32),
                          sps, 32)}


def bench_resnet56_b128():
    """The primary config with the per-client batch raised 32 → 128 (the
    measured MXU tiling sweet spot, docs/ROOFLINE.md): same model, same
    federation semantics, ~1.6x the samples/sec. BENCH_HEAVY=1 only
    since r9: it measures the same lane-fill story as the
    ``resnet56_s2d_stem`` section, whose b128 row (now with its own MFU
    submetrics) keeps the coverage inside the fast-bench budget — the
    two levers compose there, and ``tuned_best`` still picks the best
    honest number across whatever ran."""
    from fedml_tpu.models.resnet import resnet56

    model = resnet56(num_classes=10, dtype="bf16")
    sps = _scan_bench(model, n_clients=128, per_client=256, batch=128,
                      cpr=8, lr=0.1)
    return {"samples_per_sec": round(sps, 2),
            **_mfu_fields(model, np.zeros((128, 32, 32, 3), np.float32),
                          sps, 128)}


def bench_resnet56_s2d():
    """The space-to-depth stem variant (docs/ROOFLINE.md's first named
    lane-fill lever, first-class in the model registry as
    ``resnet56_s2d``): 2x2 s2d input + doubled stage widths (32/64/128)
    at half spatial — per-conv FLOPs ~equal to the reference model
    (0.170 vs 0.186 GFLOP/sample) with 2x the MXU lane fill per stage.
    Same federation config as the primary; reported as a VARIANT row
    because the model differs (4x params) — the primary stays on the
    reference stem for comparability. The b128 row composes the two
    measured lane-fill levers and carries its own MFU submetrics — the
    ``best_cnn_mfu`` headline scalar typically comes from here."""
    from fedml_tpu.models.resnet import resnet56

    model = resnet56(num_classes=10, dtype="bf16", stem="s2d")
    sps = _scan_bench(model, n_clients=128, per_client=256, batch=32,
                      cpr=8, lr=0.1)
    # s2d + batch 128: the two levers composed — the repo's best honest
    # CIFAR-ResNet56 number, feeding the top-level ``tuned_best`` field
    # (r3 VERDICT #8). Measured fresh every round, not quoted from docs.
    sps_b128 = _scan_bench(resnet56(num_classes=10, dtype="bf16",
                                    stem="s2d"),
                           n_clients=128, per_client=256, batch=128,
                           cpr=8, lr=0.1)
    return {"samples_per_sec": round(sps, 2),
            **_mfu_fields(model, np.zeros((32, 32, 32, 3), np.float32),
                          sps, 32),
            "s2d_b128_samples_per_sec": round(sps_b128, 2),
            **_mfu_fields(model, np.zeros((128, 32, 32, 3), np.float32),
                          sps_b128, 128, prefix="s2d_b128_")}


def bench_sharded_path():
    """The shard_map round (the multi-chip code path) on a 1-device mesh:
    full-participation whole-run scan with client shards pinned — the
    dryrun validates N>1 correctness on a virtual mesh; this measures the
    sharded machinery's throughput on the real chip vs the vmap path
    (primary metric). Same model/data scale as the primary config."""
    from fedml_tpu.models.resnet import resnet56
    from fedml_tpu.parallel.mesh import client_mesh

    n_clients = 8  # full participation: cpr == total
    sps, iqr = _scan_bench(resnet56(num_classes=10, dtype="bf16"),
                           n_clients=n_clients, per_client=256, batch=32,
                           cpr=n_clients, lr=0.1, mesh=client_mesh(1),
                           with_iqr=True)
    return {"samples_per_sec": round(sps, 2),
            "samples_per_sec_iqr": iqr,
            "rounds_per_sec": round(sps / (n_clients * 256), 3)}


def _timed_host_rounds(round_fn, r0, rounds, min_s, reps,
                       units_per_round=1.0):
    """Grow-then-verify floor calibration at the per-round grain: grow
    the window of host-loop ``round_fn`` calls until one carries
    ``min_s`` of work, then report ``_med_iqr`` of units/sec over
    ``reps`` windows (``units_per_round=1`` → rounds/s; pass
    samples-per-round for samples/s). The ONE copy of the discipline
    shared by the per-round sections (the scan sections calibrate whole
    windows in ``_timed_store_windows``)."""
    r = r0

    def window(r, rounds):
        _check_section_deadline()
        t0 = time.perf_counter()
        for rr in range(r, r + rounds):
            round_fn(rr)
        return time.perf_counter() - t0

    for _ in range(5):  # grow-then-verify floor calibration
        dt = window(r, rounds)
        r += rounds
        if dt >= min_s:
            break
        rounds = max(rounds + 1,
                     int(np.ceil(rounds * min_s * 1.2 / dt)))
    vals = []
    for _ in range(reps):
        dt = window(r, rounds)
        vals.append(rounds * units_per_round / dt)
        r += rounds
    return _med_iqr(vals), r


def bench_pod_reduce(n_clients=16, per_client=64, batch=16, cpr=8,
                     d=32, min_s=1.0, reps=3):
    """Pod-scale compute plane (r14): the host-grouped hierarchical
    reduction on a SIMULATED 2×4 DCN×ICI mesh (single process, forced
    factorization — the compiled program is the pod one, the DCN hop
    isn't physically here). Three arms, same federation:

    - ``mean`` — the partial-sum fast path, hierarchically associated
      (ICI stage 1, one host partial across DCN);
    - ``flat`` — coord_median with ``group_reduce=False``: the exact
      flat statistic, full client-stack ``all_gather`` across the DCN
      axis (O(C·model) inter-host bytes);
    - ``grouped`` — coord_median with ``group_reduce=True``:
      median-of-host-medians, stage-1 ICI-only, G=2 partials across DCN
      (O(G·model)).

    ``dcn_bytes_ratio`` (flat/grouped = C/G) is the STRUCTURAL claim,
    read from the live ``FedAvgAPI.reduce_profile`` gauges — on real DCN
    it is the wire-bytes win; the rounds/s A/B here measures the
    single-host cost of the reshaped collective (the gather shrinks
    C→G models, so grouped should never be slower)."""
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.parallel.multihost import simulated_dcn_mesh

    rng = np.random.RandomState(7)
    x = rng.randn(n_clients * per_client, d).astype(np.float32)
    y = (x @ rng.randn(d) > 0).astype(np.int32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                 batch)
    mesh = simulated_dcn_mesh(2, 4)

    def make_api(**kw):
        cfg = FedConfig(client_num_in_total=n_clients,
                        client_num_per_round=cpr, comm_round=100_000,
                        epochs=1, batch_size=batch, lr=0.1, **kw)
        return FedAvgAPI(LogisticRegression(num_classes=2), fed, None,
                         cfg, mesh=mesh)

    def timed_rps(api, r0):
        return _timed_host_rounds(api.train_one_round, r0, 8, min_s, reps)

    out = {"mesh": "2x4 DCN x ICI (simulated)", "clients": n_clients,
           "clients_per_round": cpr}
    arms = (("mean", {}),
            ("flat", {"aggregator": "coord_median"}),
            ("grouped", {"aggregator": "coord_median",
                         "group_reduce": True}))
    profs = {}
    for name, kw in arms:
        api = make_api(**kw)
        api.train_one_round(0)  # warm the executable
        jax.block_until_ready(api.net.params)
        (rps, iqr), _ = timed_rps(api, 1)
        out[f"{name}_rounds_per_sec"] = round(rps, 3)
        out[f"{name}_rounds_per_sec_iqr"] = iqr
        profs[name] = api.reduce_profile()
        del api
    out.update({
        "dcn_partials_grouped": profs["grouped"]["dcn_partials"],
        "dcn_partials_flat": profs["flat"]["dcn_partials"],
        "dcn_bytes_grouped": profs["grouped"]["dcn_bytes_per_round"],
        "dcn_bytes_flat": profs["flat"]["dcn_bytes_per_round"],
        "dcn_bytes_ratio": round(
            profs["flat"]["dcn_bytes_per_round"]
            / profs["grouped"]["dcn_bytes_per_round"], 3),
        "grouped_vs_flat_rps": round(
            out["grouped_rounds_per_sec"] / out["flat_rounds_per_sec"],
            3),
    })
    return out


def bench_cnn_mfu_levers(n_clients=16, per_client=64, batch=16, cpr=8,
                         acc_rounds=10, min_s=2.0, reps=3):
    """The MFU playbook's two remaining levers, measured (r14):

    - **bf16 client step** (``cfg.client_step_dtype="bf16"``): layer
      compute in bfloat16 inside the jitted client step, fp32 params/
      gradients/aggregation/eval — A/B'd against the fp32 arm for
      samples/s, ``mfu``/``delivered_tflops`` (always the LOGICAL fp32
      model's FLOPs), and held-out ACCURACY DELTA at the same round
      budget (eval always runs fp32, so the delta is the training
      effect). On CPU bf16 is emulated and usually SLOWER — the honest
      expectation here is the accuracy-delta measurement plus the TPU
      projection stated in docs/EXECUTION.md, not a CPU speedup.
    - **im2col conv lane shaping** (``cfg.compute_layout="im2col"``):
      the 5x5 stem conv rephrased as patches + a 1x1 GEMM
      (contraction dim 25 vs 1 input channel) — samples/s and MFU vs
      the same fp32 baseline.
    """
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    rng = np.random.RandomState(11)
    n = n_clients * per_client
    # Learnable image task (held-out accuracy must move): label = which
    # half of the image carries the brighter blob.
    x = rng.rand(n, 28, 28, 1).astype(np.float32) * 0.1
    y = rng.randint(0, 2, n).astype(np.int32)
    for i in range(n):
        r0 = 4 if y[i] == 0 else 18
        x[i, r0:r0 + 6, 8:20, 0] += 1.0
    fed = build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                 batch)
    xt = rng.rand(256, 28, 28, 1).astype(np.float32) * 0.1
    yt = rng.randint(0, 2, 256).astype(np.int32)
    for i in range(256):
        r0 = 4 if yt[i] == 0 else 18
        xt[i, r0:r0 + 6, 8:20, 0] += 1.0
    test = (xt.reshape(-1, batch, 28, 28, 1), yt.reshape(-1, batch),
            np.ones((256 // batch, batch), np.float32))
    model = CNNOriginalFedAvg(num_classes=2)
    samples_per_round = cpr * per_client

    def make_api(**kw):
        cfg = FedConfig(client_num_in_total=n_clients,
                        client_num_per_round=cpr, comm_round=100_000,
                        epochs=1, batch_size=batch, lr=0.1,
                        frequency_of_the_test=1000, **kw)
        return FedAvgAPI(model, fed, test, cfg)

    def timed_sps(api, r0):
        return _timed_host_rounds(api.train_one_round, r0, 2, min_s,
                                  reps, samples_per_round)

    sample = np.zeros((batch, 28, 28, 1), np.float32)
    out = {"clients": n_clients, "acc_rounds": acc_rounds}
    accs, losses = {}, {}
    arms = (("fp32", {}),
            ("bf16", {"client_step_dtype": "bf16"}),
            ("im2col", {"compute_layout": "im2col"}))
    for name, kw in arms:
        api = make_api(**kw)
        # Accuracy at a fixed round budget FIRST (fresh model), then the
        # throughput windows continue on the warm executable. The task
        # converges inside the budget by design: a STABLE accuracy
        # delta (0.0 = "no accuracy cost measured") beats a mid-descent
        # operating point that flips between 0.2 and 1.0 across seeds
        # (measured — the transition is cliff-like); the train-loss
        # delta below is the finer-grained sensitivity observable.
        for rr in range(acc_rounds):
            loss = api.train_one_round(rr)["train_loss"]
        accs[name] = float(np.asarray(api.evaluate()["accuracy"]))
        losses[name] = float(loss)
        jax.block_until_ready(api.net.params)
        (sps, iqr), _ = timed_sps(api, acc_rounds)
        prefix = "" if name == "fp32" else f"{name}_"
        out.update({f"{prefix}samples_per_sec": round(sps, 2),
                    f"{prefix}samples_per_sec_iqr": iqr,
                    f"{prefix}accuracy": round(accs[name], 4),
                    f"{prefix}final_train_loss": round(losses[name], 5),
                    **_mfu_fields(model, sample, sps, batch,
                                  prefix=prefix)})
        del api
    out["bf16_speedup"] = round(
        out["bf16_samples_per_sec"] / out["samples_per_sec"], 3)
    out["bf16_acc_delta"] = round(accs["bf16"] - accs["fp32"], 4)
    out["bf16_loss_delta"] = round(losses["bf16"] - losses["fp32"], 5)
    out["im2col_speedup"] = round(
        out["im2col_samples_per_sec"] / out["samples_per_sec"], 3)
    out["im2col_acc_delta"] = round(accs["im2col"] - accs["fp32"], 4)
    out["im2col_loss_delta"] = round(losses["im2col"] - losses["fp32"], 5)
    return out


def bench_layout_fused_round(n_clients=64, per_client=128, batch=20,
                             cpr=10, widths=(120, 120), min_s=2.0,
                             reps=5):
    """The r9 tentpole pair measured together on a CNN hot path:

    - **fused donated round step** (``parallel/shard.make_fused_round_
      step``): one dispatch per host-loop round (train + aggregate +
      server update, ``(net, extra)`` donated) vs the pre-r9 separate
      ``run_round`` + ``_server_update`` procedure — same federation,
      same per-round loss sync, so ``fused_speedup`` is the dispatch +
      undonated-intermediate cost. The donation audit
      (``obs.sanitizer.donation_audit``) and the compile counter pin the
      steady state: ``live_model_copies`` ≈ 1 and
      ``steady_state_compiles`` == 0.
    - **lane-fill compute layout** (``parallel/layout.py``): the SAME
      model with deliberately just-under-lane conv widths (120 → padded
      128) trained through ``cfg.compute_layout="auto"`` vs the logical
      layout — ``layout_pad_ratio`` is what squaring up to the lane
      width buys (docs/EXECUTION.md "MFU playbook": padding pays just
      under a lane multiple, hurts far below one). MFU for both sides
      uses the LOGICAL FLOPs, so padding can never inflate it.

    The parameters exist for the machinery test
    (tests/test_bench_headline.py); the section always runs the
    defaults."""
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.cnn import CNNOriginalFedAvg
    from fedml_tpu.obs.sanitizer import donation_audit, sanitized

    rng = np.random.RandomState(3)
    x = rng.rand(n_clients * per_client, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 62, len(x)).astype(np.int32)
    fed = build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                 batch)
    model = CNNOriginalFedAvg(num_classes=62, widths=tuple(widths))
    samples_per_round = cpr * per_client  # homo partition: equal counts

    def make_api(layout):
        cfg = FedConfig(client_num_in_total=n_clients,
                        client_num_per_round=cpr, comm_round=100_000,
                        epochs=1, batch_size=batch, lr=0.05,
                        compute_layout=layout)
        return FedAvgAPI(model, fed, None, cfg)

    def timed_sps(round_fn, r0, rounds=4):
        """Median samples/sec over ``reps`` floor-calibrated windows of
        per-round host-loop rounds (each round pays its loss sync, both
        sides identically)."""
        r = r0

        def window(r, rounds):
            _check_section_deadline()
            t0 = time.perf_counter()
            for rr in range(r, r + rounds):
                round_fn(rr)
            return time.perf_counter() - t0

        for _ in range(5):  # grow-then-verify, like every timed section
            dt = window(r, rounds)
            r += rounds
            if dt >= min_s:
                dt2 = window(r, rounds)
                r += rounds
                if dt2 >= min_s * 2.0 / 3.0:
                    break
                dt = dt2
            rounds = max(rounds + 1,
                         int(np.ceil(rounds * min_s * 1.2 / dt)))
        vals = []
        for _ in range(reps):
            dt = window(r, rounds)
            vals.append(rounds * samples_per_round / dt)
            r += rounds
        return _med_iqr(vals), r

    out = {"clients": n_clients, "widths": list(widths)}

    # --- fused vs separate dispatch, logical layout ------------------
    api = make_api("none")

    def separate_round(rr):
        avg, loss = api.run_round(rr)
        api.net = api._server_update(api.net, avg)
        assert np.isfinite(float(loss))

    def fused_round(rr):
        assert np.isfinite(api.train_one_round(rr)["train_loss"])

    fused_round(0)  # warm both executables
    separate_round(1)
    jax.block_until_ready(api.net.params)
    (fused_sps, fused_iqr), r = timed_sps(fused_round, 2)
    (sep_sps, sep_iqr), r = timed_sps(separate_round, r)
    out.update({"fused_samples_per_sec": round(fused_sps, 2),
                "fused_samples_per_sec_iqr": fused_iqr,
                "separate_samples_per_sec": round(sep_sps, 2),
                "separate_samples_per_sec_iqr": sep_iqr,
                "fused_speedup": round(fused_sps / sep_sps, 3),
                **_mfu_fields(model, np.zeros((batch, 28, 28, 1),
                                              np.float32),
                              fused_sps, batch)})

    # Donation + recompile audit on the fused steady state: the model-
    # sized live-buffer count must hold at ~one copy (the donated carry
    # is reused in place) and nothing may re-trace. Sampled OUTSIDE any
    # other live API's lifetime — signature matching counts every live
    # net in the process.
    with sanitized(transfer="allow", strict=False) as san:
        with donation_audit(api.net) as audit:
            for rr in range(r, r + 5):
                fused_round(rr)
                audit.sample()
            r += 5
    out["live_model_copies"] = round(audit.peak, 2)
    out["steady_state_compiles"] = san.compiles
    del api  # free its net before the padded twin's audit window

    # --- lane-fill layout A/B (padded physical twin, same model) -----
    api = make_api("auto")
    layout = api._layout
    out["layout"] = (None if layout is None else layout.describe())
    fused_round(0)
    jax.block_until_ready(api.net.params)
    (pad_sps, pad_iqr), _ = timed_sps(fused_round, 2)
    out.update({"layout_samples_per_sec": round(pad_sps, 2),
                "layout_samples_per_sec_iqr": pad_iqr,
                "layout_pad_ratio": round(pad_sps / fused_sps, 3),
                **_mfu_fields(model, np.zeros((batch, 28, 28, 1),
                                              np.float32),
                              pad_sps, batch, prefix="layout_")})
    return out


FLOOR_S = 0.4   # required device work per timed call (asserted, not assumed)
TARGET_S = 0.6  # calibration aims a margin above the floor


def _calibrated_side(f, q, k, v, tokens_per_iter, n_timed=5):
    """Median tokens/sec for one side of a kernel A/B, with the iteration
    count CALIBRATED from a measured warm-call rate so every timed call
    carries ≥ FLOOR_S seconds of device work — enforced, not assumed (r3
    VERDICT: the fixed iters schedule left the fast side at ~0.15 s/call,
    inside the tunnel's ±30 ms RTT noise band).

    ``f(q, k, v, iters)`` must accept the chain length as a DYNAMIC
    operand (no recompile across iters). Per-iteration device time is fit
    two-point — (t(n2) − t(n1)) / (n2 − n1) — which cancels the constant
    dispatch RTT the tunnel adds to every call; the RTT estimate itself
    is kept to refine the fit from the timed calls, and the floor is
    re-checked against the refined rate (retry with more iters if a noisy
    first fit under-sized the chain)."""
    def call(iters):
        _check_section_deadline()
        t0 = time.perf_counter()
        float(f(q, k, v, iters))
        return time.perf_counter() - t0

    call(1)  # warm + compile (host fetch = the only reliable tunnel sync)
    n1, n2 = 1, 5
    t1 = min(call(n1) for _ in range(2))
    t2 = min(call(n2) for _ in range(2))
    per_iter = max((t2 - t1) / (n2 - n1), 1e-4)
    rtt = max(t1 - per_iter * n1, 0.0)
    for _attempt in range(4):
        iters = max(1, min(4096, int(np.ceil(TARGET_S / per_iter))))
        calls = sorted(call(iters) for _ in range(n_timed))
        med = calls[n_timed // 2]
        refined = max((med - rtt) / iters, 1e-4)
        if refined * iters >= FLOOR_S:
            return {"tokens_per_sec": round(tokens_per_iter * iters / med),
                    "iters": iters, "call_s": round(med, 3),
                    "device_s_per_call_est": round(refined * iters, 3)}
        per_iter = refined  # noisy first fit under-sized the chain: retry
    raise RuntimeError(
        f"could not reach the {FLOOR_S}s device-work floor "
        f"(per_iter≈{per_iter:.4f}s, iters≈{iters})")


def bench_flash_attention_sweep():
    """Pallas fused attention vs XLA dense attention across sequence
    lengths, in the TRAINING configuration (bf16 activations, causal).
    Each point chains data-dependent iterations inside one jit (output
    feeds the next query) with a single device sync — per-call timing
    through the axon tunnel measures dispatch RTT, not the kernel. The
    chain length is calibrated per side (``_calibrated_side``) so every
    timed call clears the 0.4 s device-work floor.

    Reports tokens/sec for both, the per-T speedup, the crossover T, and
    each side's compiled temp-memory (the O(T) vs O(T²) claim, measured
    rather than asserted — r2 VERDICT). Dense is EXPECTED to fail at the
    longest T (its [B, H, T, T] scores exceed HBM); that failure is
    recorded as a data point, not an error. All comparable points run
    batch 1 at T≥8192; the r3-era T=8192 batch-2 configuration — where
    dense's 8.6 GB compiled temp sits against the HBM boundary and its
    throughput collapses ~9x — is kept as an explicitly-labelled
    memory-cliff datum (r3 VERDICT #1: a memory effect must not be
    presented as an O(T²) kernel property)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.flash_attention import flash_attention

    h, d = 8, 64

    def chained(attn):
        def run(q, k, v, iters):
            out = jax.lax.fori_loop(
                0, iters, lambda i, acc: attn(acc, k, v), q)
            return jnp.sum(out)  # scalar → float() forces a real sync
        return jax.jit(run)

    def temp_mb(f, q, k, v):
        try:
            ma = f.lower(q, k, v, 1).compile().memory_analysis()
            return round(ma.temp_size_in_bytes / 1e6, 1)
        except Exception:
            return None

    def measure(t, b):
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)
                   for _ in range(3))

        def naive(q, k, v, t=t):
            logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k)
                      .astype(jnp.float32) / np.sqrt(d))
            mask = jnp.tril(jnp.ones((t, t), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
            p = jax.nn.softmax(logits, -1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        f_flash = chained(lambda q, k, v: flash_attention(
            q, k, v, causal=True))
        f_naive = chained(naive)
        fl = _calibrated_side(f_flash, q, k, v, b * t)
        pt = {"batch": b,
              "flash_tokens_per_sec": fl["tokens_per_sec"],
              "flash_iters": fl["iters"], "flash_call_s": fl["call_s"],
              "flash_temp_mb": temp_mb(f_flash, q, k, v)}
        try:
            de = _calibrated_side(f_naive, q, k, v, b * t)
            pt.update({"dense_tokens_per_sec": de["tokens_per_sec"],
                       "dense_iters": de["iters"],
                       "dense_call_s": de["call_s"],
                       "dense_temp_mb": temp_mb(f_naive, q, k, v),
                       "speedup": round(fl["tokens_per_sec"]
                                        / de["tokens_per_sec"], 3)})
        except _SectionTimeout:  # the per-section cap must abort the
            raise                # section, not masquerade as a dense OOM
        except Exception as e:  # the T² wall: dense cannot allocate
            pt["dense_tokens_per_sec"] = None
            pt["dense_failed"] = f"{type(e).__name__}: {e}"[:120]
        return pt

    points, crossover = {}, None
    for t, b in [(2048, 4), (8192, 1), (16384, 1), (32768, 1), (65536, 1)]:
        _check_section_deadline()
        pt = measure(t, b)
        if (crossover is None and pt.get("speedup")
                and pt["speedup"] > 1.0):
            crossover = t
        points[f"t{t}"] = pt
    cliff = measure(8192, 2)
    cliff["note"] = ("memory-cliff datum, NOT comparable: dense's b=2 "
                     "compiled temp (~8.6 GB) sits against the HBM "
                     "boundary, so its collapse here is memory pressure, "
                     "not an O(T^2) kernel property — compare the b=1 row")
    points["t8192_b2_memcliff"] = cliff
    return {"points": points, "crossover_T": crossover,
            "floor_s": FLOOR_S,
            "config": "bf16, causal, h8 d64, tuned blocks"}


def _token_fed(n_clients, per_client, batch, t, vocab, seed=0):
    """Synthetic next-token federated data: [N, t] inputs, [N, t] shifted
    targets, tokens in [1, vocab) so pad_id=0 never collides."""
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo

    rng = np.random.RandomState(seed)
    seqs = rng.randint(1, vocab, size=(n_clients * per_client, t + 1))
    x = seqs[:, :t].astype(np.int32)
    y = seqs[:, 1:].astype(np.int32)
    return build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                  batch)


def _lm_scan_bench(model, n_clients, per_client, batch, cpr, t, vocab,
                   lr=0.1, rounds=3, min_call_s=None, api_cls=None,
                   api_kw=None):
    """Median seqs/sec of the whole-run scan for a token LM federation.

    With ``min_call_s`` set, the scan length is grown until a measured
    warm call exceeds it (the 0.4 s device-work floor of r3 VERDICT #1,
    with headroom for the tunnel's ~0.1 s dispatch RTT) — each growth
    recompiles once (scan length is static), so the loop converges in
    one or two steps. Returns (seqs/sec, rounds, call_s) then.

    ``api_cls``/``api_kw`` swap the algorithm (default FedAvgAPI) —
    the fed_adapter section measures FedAdapterAPI on the identical
    harness so the adapter-vs-dense tokens/s A/B shares every knob."""
    from functools import partial

    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.trainer.local import seq_softmax_ce

    fed = _token_fed(n_clients, per_client, batch, t, vocab)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=1, epochs=1, batch_size=batch, lr=lr)
    api = (api_cls or FedAvgAPI)(model, fed, None, cfg,
                                 loss_fn=partial(seq_softmax_ce, pad_id=0),
                                 **(api_kw or {}))
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)
    if min_call_s is None:
        return statistics.median(
            _timed_scan_trials(api, rounds, cpr * per_client))
    for _ in range(4):
        _check_section_deadline()
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())
        dt = time.perf_counter() - t0
        if dt >= min_call_s:
            break
        rounds = max(rounds + 1,
                     int(np.ceil(rounds * min_call_s * 1.3 / dt)))
        api.train_rounds_on_device(rounds)  # recompile + warm new length
        jax.block_until_ready(api.net.params)
    trials = _timed_scan_trials(api, rounds, cpr * per_client)
    med = statistics.median(trials)
    call_s = cpr * per_client * rounds / med
    assert call_s >= FLOOR_S, (
        f"timed call {call_s:.3f}s below the {FLOOR_S}s floor")
    return med, rounds, round(call_s, 3)


def bench_transformer_fed_mfu():
    """The high-MFU proof point (r2 VERDICT #3): a federated
    transformer_lm round at d_model=512 — lane-filling by construction —
    with MFU reported. Separates "the framework adds overhead" from
    "ResNet-56 is lane-starved": if the scan/vmap/aggregation scaffolding
    were the bottleneck, this config could not reach a healthy MFU
    either."""
    import jax

    from fedml_tpu.models import create_model
    from fedml_tpu.obs.flops import model_cost

    t, vocab, batch = 512, 10004, 8
    model = create_model("transformer_lm", vocab_size=vocab, d_model=512,
                         n_heads=8, n_layers=4, max_len=t, dtype="bf16")
    sps = _lm_scan_bench(model, n_clients=16, per_client=32, batch=batch,
                         cpr=8, t=t, vocab=vocab)
    fwd = model_cost(model, np.ones((batch, t), np.int32), train=False)
    delivered = 3.0 * fwd["flops"] / batch * sps / 1e12
    peak = _chip_peak(jax.devices()[0].device_kind)
    return {"seqs_per_sec": round(sps, 2),
            "tokens_per_sec": round(sps * t, 0),
            "d_model": 512, "seq_len": t,
            "delivered_tflops": round(delivered, 3),
            "mfu": (round(delivered / peak, 4) if peak else None)}


def _pretrain_dense_lm(x, y, vocab, seq_len, d_model, n_heads, n_layers,
                       steps=500, batch=32, lr=3e-3, seed=0):
    """Adam-pretrain a dense transformer_lm on the pooled token set —
    the 'shared pretrained LM' every fed_adapter arm finetunes FROM
    (LoRA is a finetuning method; a random frozen base has nothing for
    rank-r adapters to steer). Returns the host param tree."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.models import create_model
    from fedml_tpu.trainer.local import NetState, model_fns, seq_softmax_ce

    fns = model_fns(create_model("transformer_lm", vocab_size=vocab,
                                 d_model=d_model, n_heads=n_heads,
                                 n_layers=n_layers, max_len=seq_len))
    net = fns.init(jax.random.PRNGKey(seed),
                   jnp.zeros((1, seq_len), jnp.int32))
    opt = optax.adam(lr)
    loss_fn = partial(seq_softmax_ce, pad_id=0)

    def loss(params, xb, yb):
        logits, _ = fns.apply(NetState(params, net.model_state), xb)
        return loss_fn(logits, yb).mean()

    @jax.jit
    def step(params, ost, xb, yb):
        l, g = jax.value_and_grad(loss)(params, xb, yb)
        u, ost = opt.update(g, ost)
        return optax.apply_updates(params, u), ost, l

    params, ost = net.params, opt.init(net.params)
    rng = np.random.RandomState(seed)
    xs, ys = jnp.asarray(x), jnp.asarray(y)
    for it in range(steps):
        if it % 50 == 0:
            _check_section_deadline()
        idx = rng.randint(0, len(x), batch)
        params, ost, l = step(params, ost, xs[idx], ys[idx])
    return jax.tree.map(np.asarray, params), float(l)


def bench_fed_adapter(n_clients=24, seq_len=8, vocab=1004, d_model=64,
                      n_heads=2, n_layers=2, rank=8, kgroup=8,
                      active_tokens=32, count_scale=8, pretrain_steps=500,
                      agg_rounds=12, buffer_k=2, batch=8, fed_rounds=8,
                      personal_passes=4, codec="topk0.1+int8",
                      mfu_rank=16):
    """Parameter-efficient federated finetuning, measured end to end
    (ROADMAP item 3; FedNLP arXiv:2104.08815, low-rank updates
    arXiv:2108.06098).

    **Wire story** — three FedBuff arms on the loopback tensor wire
    under ChaosTransport (dup+delay), all finetuning the SAME adam-
    pretrained dense base on the StackOverflow-NWP dialect law
    (data/synthetic.make_stackoverflow_shard ``law="dialect"``):
    ``dense_wire`` ships uncompressed dense deltas (the wire ruler),
    ``dense_codec`` ships topk+int8 EF dense deltas (the PR 10 codec
    point), ``adapter_codec`` ships topk+int8 EF ADAPTER-only deltas
    (cfg.adapter_rank — the upload shrinks by the rank ratio BEFORE the
    codec runs). ``adapter_bytes_ratio`` = dense_codec / adapter_codec
    bytes-per-upload (the ≥8x acceptance); ``adapter_vs_dense_wire`` the
    ≥~100x ruler; ``adapter_acc_delta`` the held-out NWP accuracy gap
    between the codec arms (≈0 = the bytes win is free).

    **Personalization story** — FedAdapterAPI on the same law: federated
    adapter rounds, then ditto-style per-client personalization passes
    into the PersonalAdapterStore; ``personalized_delta`` is the
    held-out personalized-vs-global accuracy gap (positive = the
    per-client adapter stacks beat one global adapter set).

    **Throughput story** — tokens/s + MFU (vs LOGICAL FLOPs of the
    injected model) for the federated ADAPTER round at the
    transformer_fed_mfu scale (d_model=512), A/B'd against the dense
    round on the identical ``_lm_scan_bench`` harness; guarded so a
    compile-bound box records an honest hole without discarding the
    wire/personalization numbers."""
    import dataclasses
    from functools import partial

    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedadapter import FedAdapterAPI
    from fedml_tpu.algos.fedbuff import FedML_FedBuff_distributed
    from fedml_tpu.comm.resilience import ChaosSpec
    from fedml_tpu.data.batching import batch_global, build_federated_arrays
    from fedml_tpu.data.synthetic import make_stackoverflow_nwp
    from fedml_tpu.models import create_model
    from fedml_tpu.models.adapter import param_count
    from fedml_tpu.obs.flops import model_cost
    from fedml_tpu.trainer.local import seq_softmax_ce

    loss_fn = partial(seq_softmax_ce, pad_id=0)
    law = dict(seq_len=seq_len, vocab=vocab, law="dialect", kgroup=kgroup,
               active_tokens=active_tokens, count_scale=count_scale)
    x, y, parts = make_stackoverflow_nwp(n_clients, seed=0, **law)
    xh, yh, parts_h = make_stackoverflow_nwp(n_clients, seed=1, **law)
    fed = build_federated_arrays(x, y, parts, batch)
    test = batch_global(xh, yh, batch)

    _check_section_deadline()
    base, pre_loss = _pretrain_dense_lm(x, y, vocab, seq_len, d_model,
                                        n_heads, n_layers,
                                        steps=pretrain_steps)

    def mk_model(r, scope="attn"):
        # Wire arms: "attn" scope — the steepest rank ratio (the MLP
        # pair dominates adapter bytes at small d_model). The
        # personalization arm uses "all" (more steering capacity; its
        # own profile is reported).
        return create_model("transformer_lm", vocab_size=vocab,
                            d_model=d_model, n_heads=n_heads,
                            n_layers=n_layers, max_len=seq_len,
                            adapter_rank=r, adapter_scope=scope)

    cfg0 = FedConfig(client_num_in_total=n_clients, client_num_per_round=8,
                     comm_round=agg_rounds, epochs=2, batch_size=batch,
                     lr=0.1, seed=0, frequency_of_the_test=10 ** 9)
    chaos = ChaosSpec(seed=11, dup_p=0.1, delay_p=0.1)

    def arm(wire_codec, adapter):
        _check_section_deadline()
        cfg = (dataclasses.replace(cfg0, adapter_rank=rank) if adapter
               else cfg0)
        srv = FedML_FedBuff_distributed(
            mk_model(rank if adapter else 0), fed, test, cfg,
            wire_codec=wire_codec, loopback_wire="tensor",
            buffer_k=buffer_k, chaos=chaos, idle_timeout_s=15.0,
            loss_fn=loss_fn, pretrained_params=base)
        h = srv.final_health
        uploads = len(srv.arrival_log)
        acc = ((srv.test_history[-1] if srv.test_history else {})
               .get("accuracy"))
        return {"codec": wire_codec, "uploads": uploads,
                "bytes_per_upload": round(h["bytes_rx"] / max(uploads, 1),
                                          1),
                "codec_refusals": h["codec_refusals"],
                "heldout_accuracy": (round(float(acc), 4)
                                     if acc is not None else None)}

    arms = {"dense_wire": arm("none", False),
            "dense_codec": arm(codec, False),
            "adapter_codec": arm(codec, True)}
    dense_params = param_count(base)
    out = {
        "law": {k: v for k, v in law.items()},
        "pretrain": {"steps": pretrain_steps, "final_loss":
                     round(pre_loss, 4)},
        "dense_params": dense_params,
        "chaos": "dup_p=0.1 delay_p=0.1", "wire": "tensor",
        "buffer_k": buffer_k, "rank": rank,
        "arms": arms,
    }
    d, a = (arms["dense_codec"]["bytes_per_upload"],
            arms["adapter_codec"]["bytes_per_upload"])
    w = arms["dense_wire"]["bytes_per_upload"]
    out["adapter_bytes_ratio"] = round(d / a, 2) if a else None
    out["adapter_vs_dense_wire"] = round(w / a, 2) if a else None
    acc_d = arms["dense_codec"]["heldout_accuracy"]
    acc_a = arms["adapter_codec"]["heldout_accuracy"]
    out["adapter_acc_delta"] = (round(acc_a - acc_d, 4)
                                if None not in (acc_a, acc_d) else None)

    # -- personalization: per-client adapter stacks vs the global set --
    _check_section_deadline()
    papi = FedAdapterAPI(mk_model(rank, "all"), fed, None,
                         dataclasses.replace(cfg0, lr=0.3,
                                             comm_round=fed_rounds),
                         loss_fn=loss_fn, base_params=base,
                         personal_interp=1.0)
    papi.train()
    fedh = build_federated_arrays(xh, yh, parts_h, batch)
    # personal_interp=1.0 restarts every pass from the GLOBAL adapters,
    # so only the last pass's state survives the store scatter — run
    # that pass directly (bit-identical to looping personal_passes
    # times, at 1/personal_passes the compute).
    _check_section_deadline()
    papi.personalize_cohort(np.arange(n_clients), seed=personal_passes - 1)
    pm = papi.evaluate_personalized(fedh)
    out["personalization"] = {k: round(float(v), 4) for k, v in pm.items()}
    out["personalized_delta"] = round(float(pm["personalized_delta"]), 4)
    out["adapter_profile"] = {k: (round(v, 5) if isinstance(v, float)
                                  else v)
                              for k, v in papi.adapter_profile().items()}

    # -- tokens/s + MFU at the transformer_fed_mfu scale (guarded) -----
    try:
        _check_section_deadline()
        t, mv, mb = 512, 10004, 8
        mk_big = lambda r: create_model(
            "transformer_lm", vocab_size=mv, d_model=512, n_heads=8,
            n_layers=4, max_len=t, dtype="bf16", adapter_rank=r,
            adapter_scope="attn")
        kw = dict(n_clients=16, per_client=32, batch=mb, cpr=8, t=t,
                  vocab=mv)
        a_sps = _lm_scan_bench(mk_big(mfu_rank), api_cls=FedAdapterAPI,
                               **kw)
        d_sps = _lm_scan_bench(mk_big(0), **kw)
        fwd = model_cost(mk_big(mfu_rank), np.ones((mb, t), np.int32),
                         train=False)
        delivered = 3.0 * fwd["flops"] / mb * a_sps / 1e12
        peak = _chip_peak(jax.devices()[0].device_kind)
        out["throughput"] = {
            "adapter_seqs_per_sec": round(a_sps, 2),
            "adapter_tokens_per_sec": round(a_sps * t, 0),
            "dense_seqs_per_sec": round(d_sps, 2),
            "adapter_vs_dense_step": round(a_sps / d_sps, 3),
            "d_model": 512, "seq_len": t, "adapter_rank": mfu_rank,
            "delivered_tflops": round(delivered, 3),
            "mfu": (round(delivered / peak, 4) if peak else None)}
        out["adapter_tokens_per_sec"] = out["throughput"][
            "adapter_tokens_per_sec"]
    except _SectionTimeout as e:
        # Keep the measured wire/personalization numbers — the MFU A/B
        # is the TPU round's axis; a compile-bound box records the hole.
        out["throughput"] = {"timeout": str(e)}
        out["adapter_tokens_per_sec"] = None
    return out


def bench_serving_plane(N=1_048_576, d_model=64, n_heads=2, n_layers=2,
                        vocab=256, seq_len=16, rank=4, max_batch=32,
                        decode_tokens=8, personalized=1024,
                        min_window_s=1.5, max_requests=1024,
                        max_seq_requests=256, deadline_s=0.01):
    """The r18 multi-tenant serving plane (ROADMAP item 2's "heavy
    traffic" half): requests/s + tokens/s through ``ServeManager``'s
    micro-batcher at N=2^20 STORED adapters, A/B'd against
    one-adapter-at-a-time serving, while a training-fleet writer keeps
    scattering personalization updates into the same store.

    **Store** — a ``PersonalAdapterStore`` over the full 2^20-client id
    space, memmap-spilled (``open_memmap`` w+ creates the [N, D] file
    sparse, so only TOUCHED rows cost pages — ``store_nominal_gb`` is
    the addressable size, not RSS); ``personalized`` rows are scattered
    with per-client perturbations, and request traffic draws half from
    those rows and half from never-personalized ids (the
    fallback-to-global gather path). Request ids come from an
    ACTIVE-USER working set whose pages are pre-faulted during setup:
    on this box a FIRST touch of a sparse-spill row costs ~100-500 ms
    of synchronous fault I/O (measured; virtio-backed ext4), which
    would make both arms a disk-fault bench — serving traffic
    concentrates on a working set anyway, and the cold-row cost is an
    environment property, not a plane property. ``personalized`` is
    sized by the same constraint: WRITE faults on fresh sparse rows run
    ~80 ms/row here, so materializing the personalized set is the
    section's dominant setup cost (deadline-checked per chunk).

    **Batched arm** — the real plane: requests submitted through the
    started ``ServeManager`` (bounded queue → deadline-or-batch-full
    micro-batches padded to ONE compiled [max_batch, seq_len] shape →
    locked store gather → vmapped frozen-base prefill → KV-cached
    greedy decode of ``decode_tokens``), p50/p95 from the plane's own
    latency histogram. **Sequential arm** — the same work one request
    at a time (single-row gather → jitted per-row prefill → B=1
    decode): per-request dispatch is exactly the overhead the batched
    plane amortizes ``max_batch``-fold, which is the serving story at
    this model size (the per-request LoRA FLOPs are tiny; dispatch
    dominates). ``serve_batch_speedup`` = batched rps / sequential rps
    (the ≥4x acceptance). Both arms run under the SAME concurrent
    fleet-writer load (copy-on-read lock discipline, tests/test_serve's
    torn-row drill at bench scale); both windows are floor-calibrated
    (``min_window_s``) so neither side sits in timer noise."""
    import shutil
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp

    from fedml_tpu.models import create_model
    from fedml_tpu.models.adapter import (PersonalAdapterStore,
                                          adapter_model_fns)
    from fedml_tpu.serve import AdapterDecoder, ServeForward, ServeManager

    model = create_model("transformer_lm", vocab_size=vocab,
                         d_model=d_model, n_heads=n_heads,
                         n_layers=n_layers, max_len=seq_len + decode_tokens,
                         adapter_rank=rank, adapter_scope="all")
    fns = adapter_model_fns(model)
    net = fns.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, seq_len), jnp.int32))
    glob = net.params

    spill = tempfile.mkdtemp(prefix="bench_serveplane_")
    mgr = None
    stop = threading.Event()
    try:
        store = PersonalAdapterStore(N, glob, spill_dir=spill)
        glob_vec = store.vec_of(glob)
        rng = np.random.RandomState(17)
        ids_p = rng.choice(N, personalized, replace=False).astype(np.int64)
        for lo in range(0, personalized, 512):
            _check_section_deadline()
            chunk = ids_p[lo:lo + 512]
            store.scatter(chunk, glob_vec[None]
                          + 0.02 * rng.randn(len(chunk),
                                             store.dim).astype(np.float32))

        fwd = ServeForward(fns, glob)
        dec = AdapterDecoder(model, fns, glob)
        mgr = ServeManager(fwd, store, glob, seq_len=seq_len,
                           max_batch=max_batch, deadline_s=deadline_s,
                           queue_cap=4 * max_batch, decoder=dec).start()

        req_rng = np.random.RandomState(3)
        # Active-user working set: half personalized rows, half
        # never-personalized (fallback-path) ids — page-warmed below so
        # the timed windows measure serving, not first-touch faults.
        pool = np.concatenate([
            ids_p[:personalized // 2],
            req_rng.choice(N, personalized // 2, replace=False)])
        for lo in range(0, len(pool), 256):
            _check_section_deadline()
            store.gather(pool[lo:lo + 256], glob)

        def make_request(i):
            cid = int(pool[(7 * i) % len(pool)])
            return cid, req_rng.randint(0, vocab, seq_len).astype(np.int32)

        def drive_wave(n):
            pend = [mgr.submit(*make_request(i),
                               max_new_tokens=decode_tokens)
                    for i in range(n)]
            for r in pend:
                r.result(timeout=300.0)
            return n

        # Warm every compiled program OUTSIDE the timed windows: the
        # padded [max_batch, T] prefill + decode (batched arm) and the
        # per-row prefill + B=1 decode (sequential arm).
        drive_wave(max_batch)
        # Fresh meters after the warm wave: its compile-bound waiters
        # would otherwise own the latency histogram's p95 tail.
        from fedml_tpu.obs.registry import MetricsRegistry

        mgr.registry = MetricsRegistry()
        one_vec = store.gather(ids_p[:1], glob)
        one_tok = req_rng.randint(0, vocab, (1, seq_len)).astype(np.int32)
        jax.block_until_ready(fwd.prefill_sequential(one_vec, one_tok))
        dec.generate(fwd.stacked_tree(one_vec), jnp.asarray(one_tok),
                     decode_tokens)

        # -- the training-fleet writer (runs under BOTH arms) ----------
        wrote = [0]

        def fleet_writer():
            wr = np.random.RandomState(5)
            while not stop.is_set():
                idx = ids_p[wr.randint(0, personalized, 8)]
                store.scatter(idx, glob_vec[None]
                              + 0.02 * wr.randn(8, store.dim)
                              .astype(np.float32))
                wrote[0] += 8
                time.sleep(0.001)  # a fleet cadence, not a spin loop

        writer = threading.Thread(target=fleet_writer, daemon=True,
                                  name="bench-fleet-writer")
        writer.start()

        # -- batched arm ------------------------------------------------
        served = 0
        t0 = time.perf_counter()
        while (time.perf_counter() - t0 < min_window_s
               and served < max_requests):
            served += drive_wave(4 * max_batch)
            _check_section_deadline()
        batched_s = time.perf_counter() - t0
        serve_rps = served / batched_s
        stats = mgr.stats()

        # -- sequential arm (one adapter at a time) ---------------------
        seq_done = 0
        t0 = time.perf_counter()
        while (time.perf_counter() - t0 < min_window_s
               and seq_done < max_seq_requests):
            cid, toks = make_request(seq_done)
            vec = store.gather([cid], glob)
            logits = fwd.prefill_sequential(vec, toks[None])
            dec.generate(fwd.stacked_tree(vec), jnp.asarray(toks[None]),
                         decode_tokens)
            jax.block_until_ready(logits)
            seq_done += 1
            if seq_done % 16 == 0:
                _check_section_deadline()
        seq_s = time.perf_counter() - t0
        seq_rps = seq_done / seq_s
        stop.set()
        writer.join(timeout=5.0)

        tokens_per_req = seq_len + decode_tokens
        return {
            "stored_adapters": N, "adapter_dim": store.dim,
            "store_nominal_gb": round(store.nbytes() / 1e9, 2),
            "memmap_spill": True, "personalized_rows": personalized,
            "model": {"d_model": d_model, "n_layers": n_layers,
                      "vocab": vocab, "rank": rank, "scope": "all"},
            "seq_len": seq_len, "decode_tokens": decode_tokens,
            "max_batch": max_batch, "deadline_ms": deadline_s * 1e3,
            "requests_served": served,
            "serve_rps": round(serve_rps, 1),
            "serve_tokens_per_sec": round(serve_rps * tokens_per_req, 0),
            "latency_ms_p50": stats.get("serve/latency_ms_p50"),
            "latency_ms_p95": stats.get("serve/latency_ms_p95"),
            "batch_fill_mean": stats.get("serve/batch_fill_mean"),
            "shed": stats.get("serve/shed", 0),
            "refused": stats.get("serve/refused", 0),
            "sequential_requests": seq_done,
            "sequential_rps": round(seq_rps, 2),
            "serve_batch_speedup": round(serve_rps / seq_rps, 2),
            "fleet_scatters_during_drill": wrote[0],
        }
    finally:
        stop.set()
        if mgr is not None:
            mgr.close()
        shutil.rmtree(spill, ignore_errors=True)


def bench_transformer_flash_e2e():
    """Flash attention inside REAL federated training rounds (not a
    kernel microbench): transformer_lm federations at T ∈ {2048, 4096,
    8192} with attn="flash" vs attn="dense" — fwd+bwd through the
    training loss, so the three backward kernels are on the clock too.
    The full training A/B curve lives HERE, in the driver-captured
    artifact, rather than in offline script runs quoted by the docs
    (r3 VERDICT #1c); each side's scan length is floor-calibrated
    (``_lm_scan_bench(min_call_s=...)``) so no point sits inside the
    tunnel's RTT noise band."""
    from fedml_tpu.models import create_model

    vocab, out = 1004, {"points": {}}
    for t, per_client in [(2048, 8), (4096, 4), (8192, 2)]:
        mk = lambda attn: create_model(
            "transformer_lm", vocab_size=vocab, d_model=256, n_heads=4,
            n_layers=2, max_len=t, dtype="bf16", attn=attn)
        kw = dict(n_clients=8, per_client=per_client, batch=1, cpr=8,
                  t=t, vocab=vocab, min_call_s=0.5)
        flash_sps, fr, fcs = _lm_scan_bench(mk("flash"), **kw)
        dense_sps, dr, dcs = _lm_scan_bench(mk("dense"), **kw)
        out["points"][f"t{t}"] = {
            "flash_seqs_per_sec": round(flash_sps, 2),
            "dense_seqs_per_sec": round(dense_sps, 2),
            "flash_rounds_timed": fr, "dense_rounds_timed": dr,
            "flash_call_s": fcs, "dense_call_s": dcs,
            "speedup": round(flash_sps / dense_sps, 3)}
    return out


def main():
    import sys

    def _log(msg):
        print(f"[bench +{time.perf_counter() - _t0:.0f}s] {msg}",
              file=sys.stderr, flush=True)

    import os

    # XLA profile capture is env-gated: jax.profiler hangs against the
    # axon remote-compile tunnel (observed 2026-07-30 — the trace starts,
    # then blocks the program indefinitely). On directly-attached chips
    # set BENCH_PROFILE=1 (or BENCH_ATTACHED=1, which also switches the
    # store-backed sections to the pipelined round loop) to get the
    # TensorBoard trace — docs/PLATFORMS.md "Attached vs tunneled".
    attached = os.environ.get("BENCH_ATTACHED") == "1"
    profile_dir = ("runs/bench_profile"
                   if (os.environ.get("BENCH_PROFILE") == "1" or attached)
                   else None)
    # Wall-clock budget re-fit (r7; the r5-era scheme stopped bounding
    # the REAL wall clock and BENCH_r05 exited rc=124 with no headline):
    # 1. the PRIMARY now runs under its own cap (BENCH_PRIMARY_S — its
    #    calibration/trial loops check the section deadline, keeping
    #    whatever trials completed), so an uncapped primary can no
    #    longer eat the whole driver window before the budget loop even
    #    starts;
    # 2. a section is started only if its WORST CASE fits — elapsed +
    #    BENCH_SECTION_S <= BENCH_BUDGET_S — instead of merely starting
    #    before the budget line and overrunning it by a full section cap;
    # 3. the chronically compile-bound transformer_flash_e2e section
    #    (single uninterruptible XLA compiles at T=8192 that no
    #    between-units deadline check can preempt — what actually blew
    #    r05) is rotated out of the default list; BENCH_HEAVY=1 restores
    #    it, and flash/MFU coverage stays via flash_attention_sweep +
    #    transformer_fed_mfu.
    # Worst case is now BENCH_PRIMARY_S-bounded primary, sections ending
    # AT the budget line, + the JSON dump. Sections the budget skips are
    # recorded as {"skipped": ...}, capped sections as {"timeout": ...}
    # — explicit holes, not silent ones — and the headline ALWAYS lands
    # as the final line.
    global _SECTION_DEADLINE
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "900"))
    section_s = float(os.environ.get("BENCH_SECTION_S", "240"))
    primary_s = float(os.environ.get("BENCH_PRIMARY_S", "420"))
    _t0 = time.perf_counter()
    _SECTION_DEADLINE = time.perf_counter() + primary_s
    try:
        primary = bench_cifar_resnet56(profile_dir=profile_dir)
    except _SectionTimeout as e:
        # Not even one timed trial inside the cap: an honest hole beats
        # a headline that never prints.
        primary = {"samples_per_sec": None,
                   "timeout": f"primary cap {primary_s:.0f}s: {e}"}
    finally:
        _SECTION_DEADLINE = None
    _log("primary done")
    sections = [("femnist_cnn_3400clients", bench_femnist_cnn_3400),
                ("store_windowed", bench_store_windowed),
                ("store_windowed_fedopt", bench_store_windowed_fedopt),
                ("zoo_windowed", bench_zoo_windowed),
                ("robust_agg", bench_robust_agg),
                ("chaos", bench_chaos),
                ("wire_codec", bench_wire_codec),
                ("fed_adapter", bench_fed_adapter),
                ("serving_plane", bench_serving_plane),
                ("ingest_profile", bench_ingest_profile),
                ("serving_1m", bench_serving_1m),
                ("agg_shards", bench_agg_shards),
                ("secagg", bench_secagg),
                ("fleet_sim", bench_fleet_sim),
                ("adaptive_control", bench_adaptive_control),
                ("stackoverflow_342k", bench_stackoverflow_342k),
                ("synthetic_1m", bench_synthetic_1m),
                ("serving_10m", bench_serving_10m),
                ("vit_cifar_shaped", bench_vit),
                ("layout_fused_round", bench_layout_fused_round),
                ("pod_reduce", bench_pod_reduce),
                ("cnn_mfu_levers", bench_cnn_mfu_levers),
                ("resnet56_s2d_stem", bench_resnet56_s2d),
                ("sharded_path_mesh1", bench_sharded_path),
                ("flash_attention_sweep", bench_flash_attention_sweep),
                ("transformer_fed_mfu", bench_transformer_fed_mfu)]
    if os.environ.get("BENCH_HEAVY") == "1":
        # Rotated out of the fast bench (budget hygiene, ROADMAP item
        # 4): resnet56_batch128_tuned measures the same lane-fill story
        # the s2d section's b128 row now carries with MFU submetrics;
        # transformer_flash_e2e is the compile-bound section that blew
        # the r05 wall clock.
        sections.append(("resnet56_batch128_tuned", bench_resnet56_b128))
        sections.append(("transformer_flash_e2e", bench_transformer_flash_e2e))
    sub = {}
    for name, fn in sections:
        elapsed = time.perf_counter() - _t0
        if elapsed + section_s > budget_s:
            sub[name] = {"skipped": (f"wall-clock budget {budget_s:.0f}s "
                                     f"cannot fit a {section_s:.0f}s "
                                     f"section cap at +{elapsed:.0f}s")}
            _log(f"{name} SKIPPED (budget)")
            continue
        _SECTION_DEADLINE = time.perf_counter() + section_s
        try:
            sub[name] = fn()
        except _SectionTimeout as e:
            sub[name] = {"timeout": (f"section cap {section_s:.0f}s: {e}")}
            _log(f"{name} TIMED OUT (section cap)")
        except Exception as e:  # one broken submetric must not kill the line
            sub[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
        finally:
            _SECTION_DEADLINE = None
        if isinstance(sub[name], dict):
            # Memory trajectory for free: every section's record carries
            # the process RSS right after it ran (current, not the
            # monotone ru_maxrss peak — see _rss_mb).
            sub[name]["rss_after_mb"] = round(_rss_mb(), 1)
        _log(f"{name} done")

    sps = primary.pop("samples_per_sec")
    # The best honest number for the SAME task (CIFAR10 ResNet-56 FedAvg)
    # with the measured tuning levers applied — machine-readable next to
    # the untouched comparable primary (r3 VERDICT #8). The primary keeps
    # the reference stem + batch 32 for round-over-round comparability.
    tuned = None
    s2d = sub.get("resnet56_s2d_stem", {})
    candidates = [
        (s2d.get("s2d_b128_samples_per_sec"),
         "resnet56 stem=s2d + per-client batch 128"),
        (s2d.get("samples_per_sec"), "resnet56 stem=s2d, batch 32"),
        (sub.get("resnet56_batch128_tuned", {}).get("samples_per_sec"),
         "resnet56 reference stem, per-client batch 128"),
    ]
    candidates = [(v, c) for v, c in candidates if v]
    if candidates:
        best, config = max(candidates)
        tuned = {"samples_per_sec": best, "config": config,
                 "vs_baseline": round(best / BASELINE_SAMPLES_PER_SEC, 3)}
    # MFU as a first-class headline pair (ROADMAP item 4):
    # ``resnet56_mfu`` is the untouched comparable primary;
    # ``best_cnn_mfu`` is the best honest utilization for the same task
    # family with the measured lane-fill levers applied (s2d stem, b128,
    # compute layout) — always against LOGICAL FLOPs.
    cnn_mfus = [primary.get("mfu")] + [
        sub.get(sec, {}).get(key)
        for sec, key in (("resnet56_s2d_stem", "mfu"),
                         ("resnet56_s2d_stem", "s2d_b128_mfu"),
                         ("resnet56_batch128_tuned", "mfu"),
                         ("femnist_cnn_3400clients", "mfu"),
                         ("store_windowed", "mfu"),
                         ("layout_fused_round", "mfu"),
                         ("layout_fused_round", "layout_mfu"),
                         ("cnn_mfu_levers", "mfu"),
                         ("cnn_mfu_levers", "bf16_mfu"),
                         ("cnn_mfu_levers", "im2col_mfu"))]
    cnn_mfus = [m for m in cnn_mfus if isinstance(m, (int, float))]
    out = {
        "metric": "fedavg_cifar10_resnet56_samples_per_sec_per_chip",
        "value": sps,
        "unit": "samples/sec/chip",
        "vs_baseline": (round(sps / BASELINE_SAMPLES_PER_SEC, 3)
                        if sps else None),
        **primary,
        "resnet56_mfu": primary.get("mfu"),
        "best_cnn_mfu": max(cnn_mfus) if cnn_mfus else None,
        "tuned_best": tuned,
        "submetrics": sub,
    }
    # Full blob → a file the repo keeps (round-over-round comparison
    # material), plus stdout for anyone reading the whole log. The local
    # open() is anchored to THIS file's directory so it lands in the repo
    # wherever bench.py is launched from, but the HEADLINE records the
    # stable repo-relative pointer, not a machine-specific absolute path
    # (r5 ADVICE: the final stdout line is an artifact other machines
    # read).
    # Round-agnostic default blob name (r9 satellite: the hardcoded
    # docs/bench_r<N>_local.json default went stale every round and
    # misled readers about which round produced it). BENCH_BLOB still
    # overrides for archival copies.
    blob_rel = os.environ.get("BENCH_BLOB", "docs/bench_local.json")
    blob_path = (blob_rel if os.path.isabs(blob_rel)
                 else os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   *blob_rel.split("/")))
    try:
        with open(blob_path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        print(f"[bench] could not write {blob_path}: {e}", file=sys.stderr)
        blob_rel = None
    print(json.dumps(out))
    sys.stdout.flush()
    print(json.dumps(build_headline(out, full_path=blob_rel)))


def build_headline(out, full_path="docs/bench_local.json"):
    """Compact headline emitted as the FINAL stdout line (r4 VERDICT #1):
    the driver records a bounded TAIL of stdout, and by r3/r4 the full
    line had outgrown it — BENCH_r0{3,4}.json carried neither the primary
    metric nor tuned_best (parsed: null). One scalar per submetric, <1 KB
    total (pinned by tests/test_bench_headline.py), so any tail window
    keeps the number that matters and the driver's JSON parse works."""
    sub = out.get("submetrics", {})
    tuned = out.get("tuned_best")

    def _scalar(name, *path):
        node = sub.get(name, {})
        for p in path:
            node = node.get(p, {}) if isinstance(node, dict) else {}
        return node if isinstance(node, (int, float)) else None

    return {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
        "samples_per_sec_iqr": out.get("samples_per_sec_iqr"),
        "rounds_per_sec": out.get("rounds_per_sec"),
        "mfu": out.get("mfu"),
        "delivered_tflops": out.get("delivered_tflops"),
        # Utilization as a first-class trajectory pair (ROADMAP item 4):
        # the untouched primary's MFU under its canonical name, and the
        # best honest CNN-family MFU with the lane-fill levers applied
        # (every per-section mfu/delivered_tflops lives in the full
        # blob; the <1KB tail budget carries the two that define the
        # trajectory).
        "resnet56_mfu": out.get("resnet56_mfu", out.get("mfu")),
        "best_cnn_mfu": out.get("best_cnn_mfu"),
        "tuned_best": ({"samples_per_sec": tuned["samples_per_sec"],
                        "vs_baseline": tuned["vs_baseline"]}
                       if tuned else None),
        "sub": {
            "femnist_3400_rps": _scalar("femnist_cnn_3400clients",
                                        "rounds_per_sec"),
            # store_windowed_rps rotated out in r13 (the speedup carries
            # the windowed story; the rps lives in the full blob) to
            # fund the whole-zoo carry-record scalars under <1KB.
            "store_windowed_speedup": _scalar("store_windowed", "speedup"),
            # fedopt_windowed_speedup rotated out in r14 (the carry-
            # protocol story is carried by zoo_windowed_speedup since
            # r13, and store_windowed_speedup pins the windowed tier;
            # the blob keeps both fedopt scalars) to fund the pod-plane
            # scalars under the <1KB tail budget.
            # The whole-zoo carry capability records (r13): median
            # windowed/synced speedup across the newly converted
            # algorithms, and FedAc's accuracy-per-round win over FedAvg
            # at the same round budget (curves live in the full blob).
            "zoo_windowed_speedup": _scalar("zoo_windowed",
                                            "zoo_windowed_speedup"),
            # fedac_acc_delta rotated out in r18 (stable since r13;
            # zoo_windowed_speedup carries the whole-zoo carry story and
            # the blob keeps the accuracy delta) to fund the
            # serving-plane scalars under the <1KB tail budget.
            # robust_agg_overhead rotated out in r14 (stable since r4;
            # the blob keeps it) to fund the pod-plane scalars.
            # The r14 pod compute plane: the bf16 client-step A/B
            # (CPU-measured speedup + held-out accuracy delta at a
            # fixed round budget; per-arm MFU in the blob).
            # pod_dcn_bytes_ratio rotated out in r20 (structural —
            # measured exactly 4.0 since r14, the dcn_partials ratio is
            # C(padded)/G by construction; the blob keeps it) to fund
            # adaptive_ctrl_gain under the <1KB tail budget.
            "bf16_step_speedup": _scalar("cnn_mfu_levers",
                                         "bf16_speedup"),
            # The r20 adaptive control loop: controller accuracy per
            # virtual minute over the best static buffer_k arm on the
            # seeded load-spike drill — >= 1.0 means the closed loop
            # beats every static configuration (the staleness-p95 ratio
            # it holds while doing so lives in the blob).
            "adaptive_ctrl_gain": _scalar("adaptive_control",
                                          "adaptive_ctrl_gain"),
            # bf16_acc_delta rotated out in r16 (measured ~0 since r14 —
            # the speedup scalar carries the lever story and the blob
            # keeps the accuracy delta) to fund the sharded-aggregation-
            # plane scalars under the <1KB tail budget.
            # chaos_clean_overhead rotated out in r11 (stable ~1.08
            # since r5, and the wire_codec + ingest_profile arms both
            # run UNDER chaos now; the full blob keeps it) to fund
            # ingest_occupancy under the <1KB tail budget.
            "wire_bytes_ratio": _scalar("wire_codec", "wire_bytes_ratio"),
            # codec_acc_delta rotated out in r15 (measured 0.0 since
            # r10, and the fed_adapter section re-measures the
            # accuracy-under-codec story as adapter_acc_delta in the
            # blob); ingest_occupancy rotated out in r15 too (the r12
            # serving pair uploads_per_sec/ingest_speedup_4v1 carries
            # the ingest story; the blob keeps both) — funding the
            # adapter scalars under the <1KB tail budget.
            # The r15 adapter finetune: bytes-per-upload ratio of
            # adapter-only topk+int8 EF deltas over the dense-delta
            # codec point (both under ChaosTransport; the ~100x
            # vs-uncompressed ruler + held-out accuracy deltas +
            # personalized-vs-global live in the blob), and tokens/s of
            # the federated adapter round at the transformer_fed_mfu
            # scale.
            "adapter_bytes_ratio": _scalar("fed_adapter",
                                           "adapter_bytes_ratio"),
            "adapter_tokens_per_sec": _scalar("fed_adapter",
                                              "adapter_tokens_per_sec"),
            # The r18 serving plane: requests/s + tokens/s through the
            # micro-batched multi-adapter forward at 2^20 stored
            # adapters, and its speedup over one-adapter-at-a-time
            # serving under the same fleet-writer load (p50/p95 + arm
            # records live in the full blob).
            "serve_rps": _scalar("serving_plane", "serve_rps"),
            "serve_tokens_per_sec": _scalar("serving_plane",
                                            "serve_tokens_per_sec"),
            "serve_batch_speedup": _scalar("serving_plane",
                                           "serve_batch_speedup"),
            # uploads_per_sec rotated out in r18 (ingest_speedup_4v1
            # carries the ingest-wall story and serving_10m pins the
            # absolute uploads/s at 8x the population; the blob keeps
            # it) to fund the serving-plane scalars under <1KB.
            "ingest_speedup_4v1": _scalar("serving_1m",
                                          "ingest_speedup_4v1"),
            # The r16 sharded aggregation plane: uploads/s ratio of the
            # M=4 shard scale-out over M=1 on the live loopback control
            # plane (core-bounded; the per-arm records + cpu_count live
            # in the blob), the coordinator's dispatch occupancy at M=4
            # (the scale-out claim: the coordinator folds nothing), and
            # the 2^23-client drill's directory-routed fold rate.
            "agg_shard_speedup_4v1": _scalar("agg_shards", "speedup_4v1"),
            # agg_shard_coord_occupancy rotated out in r19 (structural,
            # not trajectory — measured ~0.13-0.16 << 0.5 since r16 and
            # speedup_4v1 carries the scale-out section; the blob keeps
            # the occupancy) to fund the secagg scalar under <1KB.
            # The r19 secure-aggregation plane: uploads/s cost of the
            # masked arm over the plain topk+int8 chaos drill (target
            # <= 1.3x; bytes/upload per arm + the seed-reveal drill's
            # latency live in the full blob).
            "secagg_overhead": _scalar("secagg", "secagg_overhead"),
            "serving_10m_uploads_per_sec": _scalar("serving_10m",
                                                   "uploads_per_sec"),
            "fleet_buffered_vs_firstk": _scalar(
                "fleet_sim", "buffered_vs_firstk_throughput"),
            # fleet_buffered_stale_p95_vs_async rotated out in r16
            # (stable since r6; buffered_vs_firstk carries the serving-
            # tier story and the blob keeps the staleness ratio) to fund
            # the sharded-plane scalars under the <1KB tail budget.
            # fleet_buffered_acc rotated out in r13 (stable 0.896 since
            # r6; the throughput/staleness pair carries the serving
            # story and the blob keeps the accuracy) to fund the
            # whole-zoo carry-record scalars under the <1KB tail budget.
            "stackoverflow_342k_rps": _scalar("stackoverflow_342k",
                                              "rounds_per_sec"),
            "synthetic_1m_rps": _scalar("synthetic_1m", "rounds_per_sec"),
            # synthetic_1m_peak_rss_ratio rotated out in r16 (stable
            # sublinear since r8; the serving_10m section now pins the
            # memory axis at 8x the population, host_rss_mb in the blob)
            # to fund the sharded-plane scalars under <1KB.
            # b128_sps / s2d_b128_sps rotated out in r9, s2d_sps in r10
            # (tuned_best and the s2d section's MFU pair carry the s2d
            # story), vit_sps + sharded_sps in r12 (stable since r4; the
            # full blob keeps them) to fund the layout/fused/MFU,
            # wire_codec and serving_1m scalars under the <1KB budget.
            "fused_speedup": _scalar("layout_fused_round",
                                     "fused_speedup"),
            # layout_pad_ratio rotated out in r18 (stable since r9 —
            # the pad A/B is structural, not trajectory; fused_speedup
            # carries the section and the blob keeps the ratio) to fund
            # the serving-plane scalars under the <1KB tail budget.
            "flash_speedup_t16384": _scalar("flash_attention_sweep",
                                            "points", "t16384", "speedup"),
            "transformer_mfu": _scalar("transformer_fed_mfu", "mfu"),
            # transformer_flash_e2e rides only under BENCH_HEAVY=1 (it
            # is what blew the r05 wall clock); its scalar stays out of
            # the default headline so the <1KB tail budget funds the
            # fleet_sim serving story instead.
        },
        "full": full_path,
    }


if __name__ == "__main__":
    main()
