"""North-star benchmark + secondary configs, with honest accounting.

Primary metric (BASELINE.json): FedAvg local samples/sec/chip AND
rounds/sec on CIFAR10-ResNet56, 128 simulated clients (batch 32, 1 local
epoch, 8 clients/round) — synthetic CIFAR-shaped data (zero-egress).
Whole-federation-in-one-jit via ``train_rounds_on_device`` (lax.scan over
rounds, on-device sampling).

Accounting:
- median + IQR over ``TRIALS`` timed trials (the axon tunnel shows ~±25%
  run-to-run variance; a single sample cannot separate a regression from
  noise);
- MFU = delivered FLOP/s ÷ the chip's advertised bf16 peak, with
  delivered = 3 x forward-pass FLOPs (XLA cost analysis of the compiled
  forward, ``obs/flops.model_cost``) x samples/sec — the standard
  fwd+bwd≈3x-fwd estimate, stated as such;
- one XLA profile (``obs/timing.trace``) captured per bench run under
  ``runs/bench_profile`` (TensorBoard-loadable), best-effort;
- secondary configs as sub-metrics in the SAME JSON object: the
  3400-client FEMNIST-CNN federation (BASELINE.md north-star scale, on
  the host-resident FederatedStore), a ViT federation, the primary
  config at the per-client-batch-128 tiling sweet spot, the shard_map
  round on a 1-device mesh (the multi-chip code path's single-chip
  throughput), the pallas flash-attention vs dense T-sweep (crossover +
  memory evidence), and two federated-transformer sections (the
  high-MFU proof at d_model=512; the flash-in-training A/B at T=2048).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` keeps the round-1 convention — a ~1500 samples/sec
single-GPU PyTorch simulator assumption (RTX2080Ti-class ResNet-56/CIFAR;
the reference publishes no throughput number, BASELINE.md) — while the
absolute numbers + MFU above are the honest figures of merit.

See docs/ROOFLINE.md for why the ResNet-56 number sits where it does
(16/32-channel stages under-fill the 128-lane MXU).
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 1500.0  # single-GPU torch simulator assumption
TRIALS = 5

# Advertised peak bf16 TFLOP/s per chip (public spec sheets), keyed by
# device_kind substring. Unknown kinds → MFU omitted.
CHIP_PEAK_BF16_TFLOPS = {
    "v6": 918.0,
    "v5p": 459.0,
    "v5e": 197.0,
    "v5 lite": 197.0,
    "v4": 275.0,
    "v3": 123.0,
}


def _chip_peak(device_kind: str):
    kind = device_kind.lower()
    for key, peak in CHIP_PEAK_BF16_TFLOPS.items():
        if key in kind:
            return peak
    return None


def _med_iqr(vals):
    med = statistics.median(vals)
    if len(vals) >= 4:
        q = statistics.quantiles(vals, n=4)
        return med, [round(q[0], 4), round(q[2], 4)]
    return med, [round(min(vals), 4), round(max(vals), 4)]


def _synthetic_cifar_fed(n_clients, per_client, batch):
    """CIFAR-shaped synthetic federated data (zero-egress environment),
    shared by every image-model bench section."""
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo

    rng = np.random.RandomState(0)
    x = rng.randn(n_clients * per_client, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=len(x)).astype(np.int32)
    return build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                  batch)


def _timed_scan_trials(api, rounds, samples_per_round, n_trials=3):
    """samples/sec per trial of the whole-run scan, synced by a host
    scalar fetch (block_until_ready does not reliably wait through the
    axon tunnel). Caller warms up first."""
    vals = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())
        vals.append(samples_per_round * rounds / (time.perf_counter() - t0))
    return vals


def _scan_bench(model, n_clients, per_client, batch, cpr, lr,
                rounds=3, mesh=None):
    """Median samples/sec of the whole-run scan for one (model, config):
    the shared scaffold behind every secondary image-model section."""
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI

    fed = _synthetic_cifar_fed(n_clients, per_client, batch)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=1, epochs=1, batch_size=batch, lr=lr)
    api = FedAvgAPI(model, fed, None, cfg, mesh=mesh)
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)
    return statistics.median(_timed_scan_trials(api, rounds, cpr * per_client))


def bench_cifar_resnet56(profile_dir=None):
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.models.resnet import resnet56
    from fedml_tpu.obs.flops import model_cost

    n_clients, per_client, batch = 128, 256, 32
    clients_per_round, rounds = 8, 3

    fed = _synthetic_cifar_fed(n_clients, per_client, batch)
    cfg = FedConfig(
        client_num_in_total=n_clients, client_num_per_round=clients_per_round,
        comm_round=1, epochs=1, batch_size=batch, lr=0.1,
    )
    # Mixed precision (bf16 compute, fp32 params/grads) — the standard TPU
    # training configuration; MXU runs bf16 natively (~1.6x over fp32 here).
    model = resnet56(num_classes=10, dtype="bf16")
    api = FedAvgAPI(model, fed, None, cfg)
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)

    sps_trials, rps_trials = [], []
    for trial in range(TRIALS):
        ctx = None
        if profile_dir is not None and trial == TRIALS - 1:
            try:  # best-effort: profiling through the tunnel may not work
                from fedml_tpu.obs.timing import trace

                ctx = trace(profile_dir)
                ctx.__enter__()
            except Exception:
                ctx, profile_dir = None, None
        t0 = time.perf_counter()
        losses = api.train_rounds_on_device(rounds)
        float(np.asarray(losses).sum())  # host fetch = reliable sync
        dt = time.perf_counter() - t0
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception:
                profile_dir = None
        sps_trials.append(clients_per_round * per_client * rounds / dt)
        rps_trials.append(rounds / dt)

    sps, sps_iqr = _med_iqr(sps_trials)
    rps, rps_iqr = _med_iqr(rps_trials)

    # MFU: 3x forward FLOPs per sample (fwd+bwd estimate) at the measured
    # samples/sec, against the chip's advertised bf16 peak.
    fwd = model_cost(model, np.zeros((batch, 32, 32, 3), np.float32),
                     train=False)
    flops_per_sample = 3.0 * fwd["flops"] / batch
    delivered_tflops = sps * flops_per_sample / 1e12
    kind = jax.devices()[0].device_kind
    peak = _chip_peak(kind)
    return {
        "samples_per_sec": round(sps, 2),
        "samples_per_sec_iqr": sps_iqr,
        "rounds_per_sec": round(rps, 3),
        "rounds_per_sec_iqr": rps_iqr,
        "trials": TRIALS,
        "chip": kind,
        "delivered_tflops": round(delivered_tflops, 3),
        "flops_model": "3x forward (XLA cost analysis), bf16 compute",
        "mfu": (round(delivered_tflops / peak, 4) if peak else None),
        "profile_dir": profile_dir,
    }


def _warm_store_buckets(api, store, counts, cpr, batch):
    """Warm EVERY cohort-shape bucket a FederatedStore can produce (a
    cohort's step count is the power-of-two bucket of its max client) so
    no XLA compile lands inside the timed window — sampled warmup rounds
    do not reliably cover all buckets. Shared by every store-backed
    bench section."""
    import jax

    from fedml_tpu.data.store import _bucket_steps

    buckets = np.array([_bucket_steps(int(np.ceil(c / batch)))
                        for c in counts])
    for bkt in sorted(set(buckets)):
        c = int(np.argmax(buckets == bkt))
        sub = store.gather_cohort(np.full(cpr, c))
        w = np.asarray(sub.counts, np.float32)
        api.round_fn(api.net, sub.x, sub.y, sub.mask, w, w,
                     jax.random.PRNGKey(0))
    api.train_one_round(0)
    jax.block_until_ready(api.net.params)


def _timed_store_windows(api, store, windows=3, window=10,
                         count_samples=False):
    """Median rounds/sec (and samples/sec) over ``windows`` timed windows
    of ``window`` store-backed rounds. Synced per-round loop BY DEFAULT:
    through the axon tunnel a flood of unsynced dispatches costs more
    than the per-round float(loss) sync saves (A/B'd 2026-07-30, ~8.8 vs
    ~5.5 rounds/sec — the prefetch worker already overlaps the next
    gather with the wait). That floor is a TUNNEL property: on a
    directly-attached chip set BENCH_ATTACHED=1 to time the pipelined
    loop instead (docs/PLATFORMS.md). Windowed medians because these
    sections are dispatch-RTT-heavy and single windows swing with tunnel
    variance."""
    import os

    attached = os.environ.get("BENCH_ATTACHED") == "1"
    rps_w, sps_w, r = [], [], 1
    for _ in range(windows):
        samples = 0
        if count_samples:
            for rr in range(r, r + window):
                idx, _ = api._sample_round_uncached(rr)
                samples += int(
                    np.asarray(store.counts)[np.asarray(idx)].sum())
        t0 = time.perf_counter()
        if attached:
            losses = api.train_rounds_pipelined(window, start_round=r)
            assert np.isfinite(losses).all()
        else:
            for rr in range(r, r + window):
                m = api.train_one_round(rr)
            assert np.isfinite(m["train_loss"])
        dt = time.perf_counter() - t0
        rps_w.append(window / dt)
        sps_w.append(samples / dt)
        r += window
    out = {"loop": "pipelined" if attached else "synced",
           "rounds_per_sec": round(statistics.median(rps_w), 3)}
    if count_samples:
        out["samples_per_sec"] = round(statistics.median(sps_w), 2)
    return out


def bench_femnist_cnn_3400():
    """BASELINE.md shallow-NN row at its TRUE client count: 3400 writers,
    10/round, batch 20, Reddi'20 CNN — host-resident FederatedStore
    streaming each round's cohort (the configuration VERDICT r1 flagged as
    never actually executed)."""
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.models.cnn import CNNDropOut

    n_clients, batch, cpr = 3400, 20, 10
    rng = np.random.RandomState(0)
    counts = np.maximum(1, rng.lognormal(3.6, 0.7, n_clients).astype(int))
    tot = int(counts.sum())  # ~140 samples/writer, power-law-ish
    x = rng.rand(tot, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 62, tot).astype(np.int32)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(n_clients)}
    store = FederatedStore(x, y, parts, batch_size=batch)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=40, epochs=1, batch_size=batch, lr=0.1)
    api = FedAvgAPI(CNNDropOut(num_classes=62), store, None, cfg)
    _warm_store_buckets(api, store, counts, cpr, batch)
    timed = _timed_store_windows(api, store, count_samples=True)
    return {"clients": n_clients, **timed,
            "host_dataset_mb": round(store.nbytes() / 1e6, 1)}


def bench_stackoverflow_342k():
    """BASELINE.md's largest row at its TRUE scale: 342,477 clients
    (the reference enumerates exactly that many stackoverflow_nwp
    users), reference model dims (embed 96, LSTM 670, vocab 10004),
    50 clients/round, batch 16. Host-resident CSR store (~360 MB for
    ~2.25M synthetic sentences); each round's device cohort is a few MB
    regardless of the client count."""
    import resource
    from functools import partial

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.models.rnn import RNNStackOverflow
    from fedml_tpu.trainer.local import seq_softmax_ce

    from fedml_tpu.data.synthetic import make_stackoverflow_nwp

    C, T, V, cpr, batch = 342_477, 20, 10004, 50, 16
    x, y, parts = make_stackoverflow_nwp(C, seq_len=T, vocab=V)
    counts = np.array([len(parts[c]) for c in range(C)])
    store = FederatedStore(x, y, parts, batch_size=batch)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=cpr,
                    comm_round=40, epochs=1, batch_size=batch,
                    lr=10 ** -0.5)  # BASELINE.md row lr
    api = FedAvgAPI(RNNStackOverflow(vocab_size=V), store, None, cfg,
                    loss_fn=partial(seq_softmax_ce, pad_id=0), pad_id=0)
    _warm_store_buckets(api, store, counts, cpr, batch)
    timed = _timed_store_windows(api, store)
    return {"clients": C, **timed,
            "host_dataset_mb": round(store.nbytes() / 1e6, 1),
            "host_rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
                0)}


def bench_vit():
    """ViT federation (new capability beyond reference parity): CIFAR-
    shaped inputs, patch 4, d=128, 4 heads x 4 layers."""
    from fedml_tpu.models import create_model

    sps = _scan_bench(
        create_model("vit", num_classes=10, patch=4, d_model=128,
                     n_heads=4, n_layers=4),
        n_clients=64, per_client=256, batch=32, cpr=8, lr=0.01)
    return {"samples_per_sec": round(sps, 2)}


def bench_resnet56_b128():
    """The primary config with the per-client batch raised 32 → 128 (the
    measured MXU tiling sweet spot, docs/ROOFLINE.md): same model, same
    federation semantics, ~1.6x the samples/sec. Quantifies what batch
    tuning buys when a user's config allows it — the primary metric keeps
    batch 32 for round-over-round comparability."""
    from fedml_tpu.models.resnet import resnet56

    sps = _scan_bench(resnet56(num_classes=10, dtype="bf16"),
                      n_clients=128, per_client=256, batch=128, cpr=8,
                      lr=0.1)
    return {"samples_per_sec": round(sps, 2)}


def bench_resnet56_s2d():
    """The space-to-depth stem variant (docs/ROOFLINE.md's first named
    lane-fill lever): 2x2 s2d input + doubled stage widths (32/64/128)
    at half spatial — per-conv FLOPs ~equal to the reference model
    (0.170 vs 0.186 GFLOP/sample) with 2x the MXU lane fill per stage.
    Same federation config as the primary; reported as a VARIANT row
    because the model differs (4x params) — the primary stays on the
    reference stem for comparability."""
    import jax

    from fedml_tpu.models.resnet import resnet56
    from fedml_tpu.obs.flops import model_cost

    model = resnet56(num_classes=10, dtype="bf16", stem="s2d")
    sps = _scan_bench(model, n_clients=128, per_client=256, batch=32,
                      cpr=8, lr=0.1)
    fwd = model_cost(model, np.zeros((32, 32, 32, 3), np.float32))
    delivered = 3.0 * fwd["flops"] / 32 * sps / 1e12
    peak = _chip_peak(jax.devices()[0].device_kind)
    return {"samples_per_sec": round(sps, 2),
            "delivered_tflops": round(delivered, 3),
            "mfu": (round(delivered / peak, 4) if peak else None)}


def bench_sharded_path():
    """The shard_map round (the multi-chip code path) on a 1-device mesh:
    full-participation whole-run scan with client shards pinned — the
    dryrun validates N>1 correctness on a virtual mesh; this measures the
    sharded machinery's throughput on the real chip vs the vmap path
    (primary metric). Same model/data scale as the primary config."""
    from fedml_tpu.models.resnet import resnet56
    from fedml_tpu.parallel.mesh import client_mesh

    n_clients = 8  # full participation: cpr == total
    sps = _scan_bench(resnet56(num_classes=10, dtype="bf16"),
                      n_clients=n_clients, per_client=256, batch=32,
                      cpr=n_clients, lr=0.1, mesh=client_mesh(1))
    return {"samples_per_sec": round(sps, 2),
            "rounds_per_sec": round(sps / (n_clients * 256), 3)}


def bench_flash_attention_sweep():
    """Pallas fused attention vs XLA dense attention across sequence
    lengths, in the TRAINING configuration (bf16 activations, causal).
    Each point chains ITERS data-dependent iterations inside one jit
    (output feeds the next query) with a single device sync — per-call
    timing through the axon tunnel measures dispatch RTT, not the kernel.

    Reports tokens/sec for both, the per-T speedup, the crossover T, and
    each side's compiled temp-memory (the O(T) vs O(T²) claim, measured
    rather than asserted — r2 VERDICT). Dense is EXPECTED to fail at the
    longest T (its [B, H, T, T] scores exceed HBM); that failure is
    recorded as a data point, not an error."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.flash_attention import flash_attention

    h, d = 8, 64

    def chained(attn, iters):
        def run(q, k, v):
            out = jax.lax.fori_loop(
                0, iters, lambda i, acc: attn(acc, k, v), q)
            return jnp.sum(out)  # scalar → float() forces a real sync
        return jax.jit(run)

    def timed(f, q, k, v, tokens):
        float(f(q, k, v))  # warm + sync (block_until_ready does not
        # reliably wait through the axon tunnel; a host transfer does)
        vals = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(f(q, k, v))
            vals.append(tokens / (time.perf_counter() - t0))
        return statistics.median(vals)

    def temp_mb(f, q, k, v):
        try:
            ma = f.lower(q, k, v).compile().memory_analysis()
            return round(ma.temp_size_in_bytes / 1e6, 1)
        except Exception:
            return None

    # iters sized so each timed call is ≥~0.4s of device work: at 16
    # iters the T=2048 point was ~0.13s/call and the tunnel's ±30ms RTT
    # swung the ratio ±25% run-to-run (observed 0.77x-1.15x); 48 iters
    # cuts that to <10%.
    points, crossover = {}, None
    for t, b, iters in [(2048, 4, 48), (8192, 2, 8), (16384, 1, 4),
                        (32768, 1, 2), (65536, 1, 2)]:
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)
                   for _ in range(3))
        tokens = b * t * iters

        def naive(q, k, v, t=t):
            logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k)
                      .astype(jnp.float32) / np.sqrt(d))
            mask = jnp.tril(jnp.ones((t, t), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
            p = jax.nn.softmax(logits, -1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        f_flash = chained(lambda q, k, v: flash_attention(
            q, k, v, causal=True), iters)
        f_naive = chained(naive, iters)
        pt = {"batch": b,
              "flash_tokens_per_sec": round(timed(f_flash, q, k, v, tokens)),
              "flash_temp_mb": temp_mb(f_flash, q, k, v)}
        try:
            pt["dense_tokens_per_sec"] = round(timed(f_naive, q, k, v,
                                                     tokens))
            pt["dense_temp_mb"] = temp_mb(f_naive, q, k, v)
            pt["speedup"] = round(pt["flash_tokens_per_sec"]
                                  / pt["dense_tokens_per_sec"], 3)
            if crossover is None and pt["speedup"] > 1.0:
                crossover = t
        except Exception as e:  # the T² wall: dense cannot allocate
            pt["dense_tokens_per_sec"] = None
            pt["dense_failed"] = f"{type(e).__name__}: {e}"[:120]
        points[f"t{t}"] = pt
    return {"points": points, "crossover_T": crossover,
            "config": "bf16, causal, h8 d64, tuned blocks"}


def _token_fed(n_clients, per_client, batch, t, vocab, seed=0):
    """Synthetic next-token federated data: [N, t] inputs, [N, t] shifted
    targets, tokens in [1, vocab) so pad_id=0 never collides."""
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo

    rng = np.random.RandomState(seed)
    seqs = rng.randint(1, vocab, size=(n_clients * per_client, t + 1))
    x = seqs[:, :t].astype(np.int32)
    y = seqs[:, 1:].astype(np.int32)
    return build_federated_arrays(x, y, partition_homo(len(x), n_clients),
                                  batch)


def _lm_scan_bench(model, n_clients, per_client, batch, cpr, t, vocab,
                   lr=0.1, rounds=3):
    """Median seqs/sec of the whole-run scan for a token LM federation."""
    from functools import partial

    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.trainer.local import seq_softmax_ce

    fed = _token_fed(n_clients, per_client, batch, t, vocab)
    cfg = FedConfig(client_num_in_total=n_clients, client_num_per_round=cpr,
                    comm_round=1, epochs=1, batch_size=batch, lr=lr)
    api = FedAvgAPI(model, fed, None, cfg,
                    loss_fn=partial(seq_softmax_ce, pad_id=0))
    api.train_rounds_on_device(rounds)  # warmup/compile
    jax.block_until_ready(api.net.params)
    return statistics.median(
        _timed_scan_trials(api, rounds, cpr * per_client))


def bench_transformer_fed_mfu():
    """The high-MFU proof point (r2 VERDICT #3): a federated
    transformer_lm round at d_model=512 — lane-filling by construction —
    with MFU reported. Separates "the framework adds overhead" from
    "ResNet-56 is lane-starved": if the scan/vmap/aggregation scaffolding
    were the bottleneck, this config could not reach a healthy MFU
    either."""
    import jax

    from fedml_tpu.models import create_model
    from fedml_tpu.obs.flops import model_cost

    t, vocab, batch = 512, 10004, 8
    model = create_model("transformer_lm", vocab_size=vocab, d_model=512,
                         n_heads=8, n_layers=4, max_len=t, dtype="bf16")
    sps = _lm_scan_bench(model, n_clients=16, per_client=32, batch=batch,
                         cpr=8, t=t, vocab=vocab)
    fwd = model_cost(model, np.ones((batch, t), np.int32), train=False)
    delivered = 3.0 * fwd["flops"] / batch * sps / 1e12
    peak = _chip_peak(jax.devices()[0].device_kind)
    return {"seqs_per_sec": round(sps, 2),
            "tokens_per_sec": round(sps * t, 0),
            "d_model": 512, "seq_len": t,
            "delivered_tflops": round(delivered, 3),
            "mfu": (round(delivered / peak, 4) if peak else None)}


def bench_transformer_flash_e2e():
    """Flash attention inside a REAL federated training round (not a
    kernel microbench): a transformer_lm federation at T=4096 with
    attn="flash" vs attn="dense" — the end-to-end win the r2 VERDICT
    asked for ("wire flash into the training path and show one federated
    round where it helps"). T=4096 is past the measured END-TO-END
    crossover: fwd+bwd through the training loss, flash/dense =
    0.97x @ T=2048, 1.38x @ 4096, 2.02x @ 8192 (v5e, 2026-07-31 —
    the backward kernels give back some of the forward's T=2k win, so
    the e2e crossover sits later than the fwd-only one)."""
    from fedml_tpu.models import create_model

    t, vocab = 4096, 1004
    mk = lambda attn: create_model(
        "transformer_lm", vocab_size=vocab, d_model=256, n_heads=4,
        n_layers=2, max_len=t, dtype="bf16", attn=attn)
    kw = dict(n_clients=8, per_client=4, batch=1, cpr=8, t=t, vocab=vocab)
    flash_sps = _lm_scan_bench(mk("flash"), **kw)
    dense_sps = _lm_scan_bench(mk("dense"), **kw)
    return {"seq_len": t,
            "flash_seqs_per_sec": round(flash_sps, 2),
            "dense_seqs_per_sec": round(dense_sps, 2),
            "speedup": round(flash_sps / dense_sps, 3)}


def main():
    import sys

    def _log(msg):
        print(f"[bench +{time.perf_counter() - _t0:.0f}s] {msg}",
              file=sys.stderr, flush=True)

    import os

    # XLA profile capture is env-gated: jax.profiler hangs against the
    # axon remote-compile tunnel (observed 2026-07-30 — the trace starts,
    # then blocks the program indefinitely). On directly-attached chips
    # set BENCH_PROFILE=1 (or BENCH_ATTACHED=1, which also switches the
    # store-backed sections to the pipelined round loop) to get the
    # TensorBoard trace — docs/PLATFORMS.md "Attached vs tunneled".
    attached = os.environ.get("BENCH_ATTACHED") == "1"
    profile_dir = ("runs/bench_profile"
                   if (os.environ.get("BENCH_PROFILE") == "1" or attached)
                   else None)
    _t0 = time.perf_counter()
    primary = bench_cifar_resnet56(profile_dir=profile_dir)
    _log("primary done")
    sub = {}
    for name, fn in (("femnist_cnn_3400clients", bench_femnist_cnn_3400),
                     ("stackoverflow_342k", bench_stackoverflow_342k),
                     ("vit_cifar_shaped", bench_vit),
                     ("resnet56_batch128_tuned", bench_resnet56_b128),
                     ("resnet56_s2d_stem", bench_resnet56_s2d),
                     ("sharded_path_mesh1", bench_sharded_path),
                     ("flash_attention_sweep", bench_flash_attention_sweep),
                     ("transformer_fed_mfu", bench_transformer_fed_mfu),
                     ("transformer_flash_e2e", bench_transformer_flash_e2e)):
        try:
            sub[name] = fn()
        except Exception as e:  # one broken submetric must not kill the line
            sub[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
        _log(f"{name} done")

    sps = primary.pop("samples_per_sec")
    out = {
        "metric": "fedavg_cifar10_resnet56_samples_per_sec_per_chip",
        "value": sps,
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / BASELINE_SAMPLES_PER_SEC, 3),
        **primary,
        "submetrics": sub,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
