#!/usr/bin/env python
"""Generate the EXECUTION.md algorithm × tier support matrix from the
carry capability records (fedml_tpu/algos/capability.py).

The matrix lives between marker comments in docs/EXECUTION.md; this
script regenerates that region. The drift test
(tests/test_zoo_windowed.py::test_execution_matrix_matches_records)
fails whenever the committed table differs from the records — the docs
CANNOT silently diverge from the guards again.

Usage:
    python scripts/gen_support_matrix.py           # print the block
    python scripts/gen_support_matrix.py --write   # rewrite EXECUTION.md
    python scripts/gen_support_matrix.py --check   # exit 1 on drift
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

DOC = os.path.join(os.path.dirname(__file__), os.pardir, "docs",
                   "EXECUTION.md")


def _split(text):
    from fedml_tpu.algos.capability import MATRIX_BEGIN, MATRIX_END

    try:
        head, rest = text.split(MATRIX_BEGIN, 1)
        _, tail = rest.split(MATRIX_END, 1)
    except ValueError:
        raise SystemExit(
            f"docs/EXECUTION.md is missing the generated-matrix markers "
            f"({MATRIX_BEGIN!r} ... {MATRIX_END!r})")
    return head, tail


def main(argv):
    from fedml_tpu.algos.capability import matrix_block

    block = matrix_block()
    if "--write" in argv:
        with open(DOC) as f:
            head, tail = _split(f.read())
        with open(DOC, "w") as f:
            f.write(head + block + tail)
        print(f"wrote generated matrix into {os.path.relpath(DOC)}")
        return 0
    if "--check" in argv:
        with open(DOC) as f:
            text = f.read()
        if block not in text:
            print("docs/EXECUTION.md support matrix DRIFTED from the "
                  "capability records — regenerate with "
                  "`python scripts/gen_support_matrix.py --write`",
                  file=sys.stderr)
            return 1
        print("support matrix matches the capability records")
        return 0
    print(block)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
