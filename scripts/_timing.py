"""Shared kernel-timing machinery for the measurement scripts.

``calibrated_ramp`` measures seconds/iteration of a chained-op jit whose
per-op cost may be MICROSECONDS — far below the axon tunnel's ~0.1 s
dispatch RTT, where a small fixed two-point probe cannot resolve the
slope. Method: ramp the chain length exponentially until a call clearly
exceeds the RTT band, two-point fit between the last two ramp lengths
(cancels the constant RTT), then time at the target length and enforce
the device-work floor.

Extracted from sweep_filter_grad.py / sweep_gn_standalone.py (r5 review:
the two copies had already needed one lockstep fix).
"""

import time


def calibrated_ramp(run, floor_s=0.4, target_s=0.6, ramp_cap=1 << 22,
                    iters_cap=1 << 24):
    """Median seconds/iter of ``run(iters)`` (which must block until the
    device work is done, e.g. by returning a host-fetched scalar)."""
    import numpy as np

    def call(iters):
        t0 = time.perf_counter()
        float(run(iters))
        return time.perf_counter() - t0

    call(1)  # compile
    n_prev, t_prev = 1, min(call(1) for _ in range(2))
    n, ramp = 8, []
    # Ramp-exit thresholds derived from the caller's floor/target (r5
    # ADVICE: hardcoded 0.5/0.2 ignored a larger requested floor_s, so
    # the slope could be fitted from calls below the device-work floor
    # the caller asked for): the call must carry most of the target's
    # work AND the last quadrupling must have added clearly more than
    # the RTT band before the two-point fit is trusted.
    exit_t, exit_dt = target_s * 0.8, floor_s / 2
    while n <= ramp_cap:
        t = min(call(n) for _ in range(2))
        ramp.append((n, t))
        if t >= exit_t and t - t_prev > exit_dt:
            break
        n_prev, t_prev = n, t
        n *= 4
    else:
        raise RuntimeError(f"ramp exhausted: {ramp}")
    per_iter = (t - t_prev) / (n - n_prev)
    rtt = max(t_prev - per_iter * n_prev, 0.0)
    for _ in range(5):
        iters = max(1, min(iters_cap, int(np.ceil(target_s / per_iter))))
        meds = sorted(call(iters) for _ in range(5))
        med = meds[2]
        refined = max((med - rtt) / iters, 1e-9)
        if refined * iters >= floor_s:
            return refined
        per_iter = refined
    raise RuntimeError("floor not reached")
