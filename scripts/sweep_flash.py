"""One-off TPU sweep: flash block sizes + dtype vs dense, causal fwd.

Scratch experiment for picking flash_attention defaults from data (r3).
"""
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.ops.flash_attention import flash_attention

H, D = 8, 64


def chained(attn, iters):
    def run(q, k, v):
        out = jax.lax.fori_loop(0, iters, lambda i, a: attn(a, k, v), q)
        return jnp.sum(out)
    return jax.jit(run)


def timed(f, q, k, v, tokens):
    float(f(q, k, v))  # warm + sync
    vals = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(f(q, k, v))
        vals.append(tokens / (time.perf_counter() - t0))
    return statistics.median(vals)


def dense(t):
    def naive(q, k, v):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
        return jnp.einsum("bhqk,bkhd->bqhd",
                          jax.nn.softmax(logits, -1).astype(q.dtype), v)
    return naive


print("backend:", jax.default_backend(), jax.devices()[0].device_kind, flush=True)
for t, b, iters in [(2048, 4, 16), (8192, 2, 4)]:
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, H, D), jnp.bfloat16) for _ in range(3))
    tokens = b * t * iters
    for bq, bk in [(128, 128), (128, 256), (256, 256), (256, 512),
                   (512, 512), (128, 512), (512, 1024)]:
        if bq > t or bk > t:
            continue
        f = chained(lambda q, k, v, bq=bq, bk=bk: flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk), iters)
        try:
            tps = timed(f, q, k, v, tokens)
            print(f"T={t} blk=({bq},{bk}): {tps / 1e6:.3f} Mtok/s", flush=True)
        except Exception as e:
            print(f"T={t} blk=({bq},{bk}): FAIL {type(e).__name__} "
                  f"{str(e)[:120]}", flush=True)
    f = chained(dense(t), iters)
    print(f"T={t} dense-bf16: {timed(f, q, k, v, tokens) / 1e6:.3f} Mtok/s",
          flush=True)
    # fp32 comparison point at T=2048 only (r2 bench config)
    if t == 2048:
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        f = chained(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128), iters)
        print(f"T={t} flash-fp32 (128,128): {timed(f, qf, kf, vf, tokens) / 1e6:.3f} Mtok/s",
              flush=True)
