"""Win-or-retire measurement for ``gn_fused``'s reserved use case (r4
VERDICT #9).

ops/group_norm.py keeps the pallas kernel available "for shapes where a
standalone GN is already memory-bound and unfused (e.g. very wide
channels)" — an untested escape hatch until now. This script times a
STANDALONE GroupNorm (no surrounding convs, so XLA has no conv epilogue
to fuse it into) at wide-channel transformer-ish shapes, pallas kernel
vs flax nn.GroupNorm under jit, fwd-only and fwd+bwd.

Chained iterations (output feeds the next input, so nothing hoists),
two-point RTT-cancelling fit, 0.4 s device-work floor — the repo's
standard kernel-timing machinery.

Run on the real chip: python scripts/sweep_gn_standalone.py
The measured verdict goes in ops/group_norm.py's docstring + ROOFLINE.
"""

import os
import sys

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import flax.linen as nn
import jax
import jax.numpy as jnp

from _timing import calibrated_ramp
from fedml_tpu.ops.group_norm import group_norm

# (B, S, C): standalone wide-channel GN shapes, bf16 input (~17M
# elements each — memory-bound but well inside VMEM-blocked HBM sizes).
SHAPES = [(64, 128, 2048), (32, 128, 4096), (16, 128, 8192)]
GROUPS = 32


def bench_side(apply_fn, x, gamma, beta, with_bwd, cot):
    """apply_fn(x, gamma, beta) -> y, same shape as x. ``cot`` is a fixed
    random cotangent: a trivial (all-ones) cotangent lets XLA simplify
    the mean-subtracted backward algebraically, which the opaque pallas
    kernel could never match — vdot against random data keeps the
    comparison honest."""
    if with_bwd:
        def loss(x, g, b):
            return jnp.vdot(apply_fn(x, g, b).astype(jnp.float32), cot)

        grad = jax.grad(loss, argnums=0)

        def step(x):
            return x + 1e-30 * grad(x, gamma, beta).astype(x.dtype)
    else:
        def step(x):
            return apply_fn(x, gamma, beta).astype(x.dtype)

    def run(iters):
        out = jax.lax.fori_loop(0, jnp.int32(iters),
                                lambda i, acc: step(acc), x)
        return jnp.sum(out.astype(jnp.float32))

    return calibrated_ramp(jax.jit(run), ramp_cap=1 << 20,
                           iters_cap=1 << 22)


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    for b, s, c in SHAPES:
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(b, s, c), jnp.bfloat16)
        gamma = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
        beta = jnp.asarray(rng.randn(c), jnp.float32)
        flax_mod = nn.GroupNorm(num_groups=GROUPS, epsilon=1e-6,
                                dtype=jnp.bfloat16)

        def flax_gn(x, g, bt):
            return flax_mod.apply({"params": {"scale": g, "bias": bt}}, x)

        def fused_gn(x, g, bt):
            return group_norm(x, g, bt, GROUPS)

        gb = x.size * 2 / 1e9
        cot = jnp.asarray(rng.randn(b, s, c), jnp.float32)
        for tag, with_bwd in [("fwd", False), ("fwd+bwd", True)]:
            tf = bench_side(flax_gn, x, gamma, beta, with_bwd, cot)
            try:
                tp = bench_side(fused_gn, x, gamma, beta, with_bwd, cot)
            except Exception as e:  # e.g. VMEM OOM in the bwd kernel at
                # the widest C — itself a measured data point.
                print(f"[{b}x{s}x{c}] {tag}: flax {tf * 1e6:.1f} us "
                      f"({gb / tf:.0f} GB/s in) | pallas FAILED: "
                      f"{str(e)[:160]}", flush=True)
                continue
            print(f"[{b}x{s}x{c}] {tag}: flax {tf * 1e6:.1f} us "
                  f"({gb / tf:.0f} GB/s in) | pallas {tp * 1e6:.1f} us "
                  f"({gb / tp:.0f} GB/s in) | pallas/flax "
                  f"{tp / tf:.2f}x", flush=True)


if __name__ == "__main__":
    main()
