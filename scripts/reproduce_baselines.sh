#!/bin/bash
# Reproduce the reference's published accuracy baselines (BASELINE.md, all
# three tables from /root/reference/benchmark/README.md:10-111) with the
# exact hyperparameters, wired to this framework's CLIs.
#
# Usage:
#   DATA_ROOT=/path/to/datasets scripts/reproduce_baselines.sh [config ...]
#   CI_LITE=1 scripts/reproduce_baselines.sh          # synthetic sanity pass
#
# With DATA_ROOT set, each config points at the reference's on-disk layout
# (docs/DATASETS.md documents the expected tree: $DATA_ROOT/MNIST/{train,test},
# $DATA_ROOT/FederatedEMNIST/datasets, ...). Without it, the loaders fall
# back to small synthetic writer-shaped data — the curves are then sanity
# checks of the pipeline (REPRO.md records them), NOT the published numbers.
#
# CI_LITE=1 shrinks rounds so every config launches in seconds; results land
# under runs/repro/<config>/.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA_ROOT=${DATA_ROOT:-}
CI_LITE=${CI_LITE:-0}

data_arg() { # data_arg <subdir> → --data_dir flag when DATA_ROOT is set
  if [ -n "$DATA_ROOT" ]; then echo "--data_dir $DATA_ROOT/$1"; fi
}

rounds() { # rounds <published> → CI-lite shrink
  if [ "$CI_LITE" = "1" ]; then echo 2; else echo "$1"; fi
}

epochs() { # epochs <published> → CI-lite shrink (20-epoch silo rounds
  if [ "$CI_LITE" = "1" ]; then echo 1; else echo "$1"; fi  # choke CPU CI)
}

gn_model() { # gn_model → fed_cifar100's ResNet-GN, depth-reduced in CI
  # CI_LITE_DEPTH (e.g. 10) swaps resnet18_gn for resnet<depth>_gn — the
  # same 4-stage GN architecture, loader path, and flags at a depth the
  # CPU mesh compiles in minutes, so this row is actually EXERCISED in
  # CI instead of documented as too slow (VERDICT r5 #7; REPRO.md).
  if [ "$CI_LITE" = "1" ] && [ -n "${CI_LITE_DEPTH:-}" ]; then
    echo "resnet${CI_LITE_DEPTH}_gn"
  else
    echo resnet18_gn
  fi
}

run_cfg() { # run_cfg <name> <main> [args...]
  local name=$1 main=$2; shift 2
  echo "=== $name ==="
  mkdir -p "runs/repro/$name"
  python -m "fedml_tpu.exp.$main" "$@" \
    --frequency_of_the_test 25 --run_dir "runs/repro/$name"
}

FILTERS=("$@")
match() { # match <name> → run when no filter given or a filter is a substring
  [ ${#FILTERS[@]} -eq 0 ] && return 0
  for f in "${FILTERS[@]}"; do [[ $1 == *"$f"* ]] && return 0; done
  return 1
}

# ---- Table 1: linear models (benchmark/README.md:10-14) --------------------
match mnist_lr && run_cfg mnist_lr main_fedavg \
  --dataset mnist --model lr $(data_arg MNIST) \
  --client_num_in_total 1000 --client_num_per_round 10 --batch_size 10 \
  --client_optimizer sgd --lr 0.03 --wd 0 --epochs 1 \
  --comm_round "$(rounds 120)"          # published: >75% after >100 rounds

match femnist_lr && run_cfg femnist_lr main_fedavg \
  --dataset femnist --model lr $(data_arg FederatedEMNIST/datasets) \
  --client_num_in_total 200 --client_num_per_round 10 --batch_size 10 \
  --client_optimizer sgd --lr 0.003 --wd 0 --epochs 1 \
  --comm_round "$(rounds 220)"          # published: 10-40% after >200 rounds

match synthetic_lr && run_cfg synthetic_lr main_fedavg \
  --dataset synthetic_1_1 --model lr \
  --client_num_in_total 30 --client_num_per_round 10 --batch_size 10 \
  --client_optimizer sgd --lr 0.01 --wd 0 --epochs 1 \
  --comm_round "$(rounds 220)"          # published: >60% after >200 rounds

# ---- Table 2: shallow NNs (benchmark/README.md:54-58) ----------------------
match femnist_cnn && run_cfg femnist_cnn main_fedavg \
  --dataset femnist --model cnn $(data_arg FederatedEMNIST/datasets) \
  --client_num_in_total 3400 --client_num_per_round 10 --batch_size 20 \
  --client_optimizer sgd --lr 0.1 --wd 0 --epochs 1 \
  --comm_round "$(rounds 1500)"         # published: 84.9%

match fed_cifar100_resnet18 && run_cfg fed_cifar100_resnet18 main_fedavg \
  --dataset fed_cifar100 --model "$(gn_model)" $(data_arg fed_cifar100/datasets) \
  --client_num_in_total 500 --client_num_per_round 10 --batch_size 20 \
  --client_optimizer sgd --lr 0.1 --wd 0 --epochs 1 \
  --comm_round "$(rounds 4000)"         # published: 44.7%

match shakespeare_rnn && run_cfg shakespeare_rnn main_fedavg \
  --dataset shakespeare --model rnn $(data_arg shakespeare) \
  --client_num_in_total 715 --client_num_per_round 10 --batch_size 4 \
  --client_optimizer sgd --lr 1.0 --wd 0 --epochs 1 \
  --comm_round "$(rounds 1200)"         # published: 56.9%

match stackoverflow_rnn && run_cfg stackoverflow_rnn main_fedavg \
  --dataset stackoverflow_nwp --model rnn_stackoverflow \
  $(data_arg stackoverflow/datasets) \
  --client_num_in_total 342477 --client_num_per_round 50 --batch_size 16 \
  --client_optimizer sgd --lr 0.3162 --wd 0 --epochs 1 \
  --comm_round "$(rounds 1500)"         # published: 19.5% (lr = 10^-0.5)

# ---- Table 3: cross-silo DNNs (benchmark/README.md:103-111) ----------------
# LDA alpha=0.5 (hetero) and IID (homo); 10 silos, batch 64, SGD lr=0.001
# wd=0.001, 20 local epochs, 100 rounds.
for dataset in cifar10 cifar100 cinic10; do
  for model in resnet56 mobilenet; do
    for part in homo hetero; do
      name="cross_silo_${dataset}_${model}_${part}"
      match "$name" && run_cfg "$name" main_fedavg \
        --dataset "$dataset" --model "$model" $(data_arg "$dataset") \
        --partition_method "$part" --partition_alpha 0.5 \
        --client_num_in_total 10 --client_num_per_round 10 --batch_size 64 \
        --client_optimizer sgd --lr 0.001 --wd 0.001 --epochs "$(epochs 20)" \
        --comm_round "$(rounds 100)"
    done
  done
done

echo "all requested baseline configs completed"
