#!/usr/bin/env python
"""fedlint CLI — AST analysis for the JAX pitfalls this repo has hit.

Usage:
    python scripts/fedlint.py fedml_tpu                # gate (baseline)
    python scripts/fedlint.py fedml_tpu --format=json
    python scripts/fedlint.py fedml_tpu --fix --dry-run
    python scripts/fedlint.py fedml_tpu --write-baseline

Exit 0 when every unsuppressed finding is covered by the checked-in
``fedlint.baseline.json`` (kept empty: the tree is clean); nonzero on
any new finding. See docs/LINT.md for the rules and workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
