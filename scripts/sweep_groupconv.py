"""Lever A/B (r3): vmapped per-client-filter conv vs ONE grouped conv.

A federated round vmaps local training over clients, so convs carry a
per-client filter stack. The same math can be phrased as a single conv
with feature_group_count=C on a channel-stacked input:
    x_g[b, h, w, c*ch + j] = x[c, b, h, w, j]
Times ITERS chained iterations inside one jit (single dispatch + one
host fetch) — per-call timing through the axon tunnel measures the
~100ms dispatch RTT, not the kernel.
"""
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 32
C = 8  # clients in the vmap (bench: 8/round)


def timed(f, *args, reps=3):
    float(f(*args))  # warm + sync
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(*args))
        vals.append(time.perf_counter() - t0)
    return statistics.median(vals)


def chain_fwd(conv_fn):
    """y feeds the next x (shapes match: ch_in == ch_out, SAME)."""
    def run(x, w):
        out = jax.lax.fori_loop(
            0, ITERS, lambda i, acc: conv_fn(acc, w), x)
        return jnp.sum(out.astype(jnp.float32))
    return jax.jit(run)


def chain_bwd(conv_fn):
    """Chained on the WEIGHTS (w -= eps * grad): fwd+bwd per step."""
    g = jax.grad(lambda w, x: jnp.sum(conv_fn(x, w).astype(jnp.float32) ** 2))

    def run(x, w):
        out = jax.lax.fori_loop(
            0, ITERS, lambda i, wi: wi - 1e-6 * g(wi, x).astype(wi.dtype), w)
        return jnp.sum(out.astype(jnp.float32))
    return jax.jit(run)


print("backend:", jax.default_backend(), flush=True)
for ch, hw, B in [(16, 32, 32), (32, 16, 32), (64, 8, 32), (16, 32, 128)]:
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(C, B, hw, hw, ch), jnp.bfloat16)
    w = jnp.asarray(rng.randn(C, 3, 3, ch, ch) * 0.05, jnp.bfloat16)

    def conv(xi, wi):
        return jax.lax.conv_general_dilated(
            xi, wi, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def vmapped(x, w):
        return jax.vmap(conv)(x, w)

    def grouped(x, w, hw=hw, ch=ch, B=B):
        xg = jnp.transpose(x, (1, 2, 3, 0, 4)).reshape(B, hw, hw, C * ch)
        wg = jnp.transpose(w, (1, 2, 3, 0, 4)).reshape(3, 3, ch, C * ch)
        yg = jax.lax.conv_general_dilated(
            xg, wg, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=C)
        return jnp.transpose(
            yg.reshape(B, hw, hw, C, ch), (3, 0, 1, 2, 4))

    # grouped-conv math == vmap math
    ref = np.asarray(jax.jit(vmapped)(x, w), np.float32)
    got = np.asarray(jax.jit(grouped)(x, w), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-1)

    gflop = 2 * C * B * hw * hw * 9 * ch * ch * ITERS / 1e9
    tv, tg = timed(chain_fwd(vmapped), x, w), timed(chain_fwd(grouped), x, w)
    tvb, tgb = timed(chain_bwd(vmapped), x, w), timed(chain_bwd(grouped), x, w)
    print(f"ch={ch} hw={hw} B={B}: fwd vmap={gflop/tv:.0f} "
          f"grouped={gflop/tg:.0f} GFLOP/s (g/v={tv/tg:.2f}x) | "
          f"fwd+bwd vmap={3*gflop/tvb:.0f} grouped={3*gflop/tgb:.0f} GFLOP/s "
          f"(g/v={tvb/tgb:.2f}x)", flush=True)
