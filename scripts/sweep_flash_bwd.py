"""Sweep flash fwd+bwd (training) block configs at long T, bf16 causal.

r4: the backward kernels take their own block sizes (``bwd_block_q/k``),
so the sweep covers (a) joint fwd=bwd configs (the r3 grid) and (b) the
fwd blocks pinned at auto with ONLY the bwd blocks varied — the
attribution that tells whether bwd wants different tiling than fwd.
Chained-iteration timing with a calibrated trip count (>=0.4 s device
work per timed call, dynamic iters so no recompile across lengths)."""
import statistics, time
import jax, jax.numpy as jnp, numpy as np
from fedml_tpu.ops.flash_attention import flash_attention

H, D = 8, 64
FLOOR_S, TARGET_S = 0.4, 0.6

def timed(f, q, k, v, tokens_per_iter):
    def call(iters):
        t0 = time.perf_counter(); float(f(q, k, v, iters))
        return time.perf_counter() - t0
    call(1)
    t1 = min(call(1) for _ in range(2))
    t2 = min(call(5) for _ in range(2))
    per_iter = max((t2 - t1) / 4, 1e-4)
    rtt = max(t1 - per_iter, 0.0)
    for _ in range(4):
        iters = max(1, min(4096, int(np.ceil(TARGET_S / per_iter))))
        med = sorted(call(iters) for _ in range(5))[2]
        refined = max((med - rtt) / iters, 1e-4)
        if refined * iters >= FLOOR_S:
            return tokens_per_iter * iters / med
        per_iter = refined
    raise RuntimeError("floor not reached")

def train_chain(bq, bk, bwd_bq=None, bwd_bk=None):
    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk,
                            bwd_block_q=bwd_bq, bwd_block_k=bwd_bk)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    g = jax.grad(loss, argnums=(0, 1, 2))
    def run(q, k, v, iters):
        def body(i, c):
            gq, gk, gv = g(c, k, v)
            return c - (1e-6 * gq).astype(c.dtype)
        out = jax.lax.fori_loop(0, iters, body, q)
        return jnp.sum(out.astype(jnp.float32))
    return jax.jit(run)

for t, b in [(4096, 2), (8192, 1)]:
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, H, D), jnp.bfloat16) for _ in range(3))
    for bq, bk in [(None, None), (128, 128), (256, 256), (256, 512),
                   (512, 512), (512, 256), (1024, 512), (512, 1024)]:
        try:
            tps = timed(train_chain(bq, bk), q, k, v, b * t)
            print(f"T={t} blk=({bq},{bk}): {tps/1e3:.1f} ktok/s (fwd+bwd)", flush=True)
        except Exception as e:
            print(f"T={t} blk=({bq},{bk}): FAIL {str(e)[:80]}", flush=True)
    # fwd pinned at auto, bwd blocks varied independently
    for bwd_bq, bwd_bk in [(128, 128), (128, 512), (256, 256), (256, 512),
                           (256, 1024), (512, 512), (512, 1024),
                           (1024, 256), (1024, 512)]:
        try:
            tps = timed(train_chain(None, None, bwd_bq, bwd_bk), q, k, v, b * t)
            print(f"T={t} bwd=({bwd_bq},{bwd_bk}): {tps/1e3:.1f} ktok/s", flush=True)
        except Exception as e:
            print(f"T={t} bwd=({bwd_bq},{bwd_bk}): FAIL {str(e)[:80]}", flush=True)

    # dense comparison
    def dense_loss(q, k, v, t=t):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, -1).astype(q.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))
    def rund(q, k, v, iters):
        def body(i, c):
            gq, gk, gv = gd(c, k, v)
            return c - (1e-6 * gq).astype(c.dtype)
        return jnp.sum(jax.lax.fori_loop(0, iters, body, q).astype(jnp.float32))
    try:
        print(f"T={t} dense: {timed(jax.jit(rund), q, k, v, b * t)/1e3:.1f} ktok/s", flush=True)
    except Exception as e:
        print(f"T={t} dense: FAIL {str(e)[:80]}", flush=True)
