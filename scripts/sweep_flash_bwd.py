"""Sweep flash fwd+bwd (training) block configs at long T, bf16 causal."""
import statistics, time
import jax, jax.numpy as jnp, numpy as np
from fedml_tpu.ops.flash_attention import flash_attention

H, D = 8, 64

def timed(f, q, k, v, tokens):
    float(f(q, k, v))
    vals = []
    for _ in range(3):
        t0 = time.perf_counter(); float(f(q, k, v))
        vals.append(tokens / (time.perf_counter() - t0))
    return statistics.median(vals)

for t, b, iters in [(4096, 2, 4), (8192, 1, 2)]:
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, H, D), jnp.bfloat16) for _ in range(3))
    tokens = b * t * iters
    for bq, bk in [(None, None), (128, 128), (256, 256), (256, 512),
                   (512, 512), (512, 256), (1024, 512), (512, 1024)]:
        def loss(q, k, v, bq=bq, bk=bk):
            o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        g = jax.grad(loss, argnums=(0, 1, 2))
        def run(q, k, v):
            def body(i, c):
                gq, gk, gv = g(c, k, v)
                return c - (1e-6 * gq).astype(c.dtype)
            out = jax.lax.fori_loop(0, iters, body, q)
            return jnp.sum(out.astype(jnp.float32))
        f = jax.jit(run)
        try:
            tps = timed(f, q, k, v, tokens)
            print(f"T={t} blk=({bq},{bk}): {tps/1e3:.1f} ktok/s (fwd+bwd)", flush=True)
        except Exception as e:
            print(f"T={t} blk=({bq},{bk}): FAIL {str(e)[:80]}", flush=True)

    # dense comparison
    def dense_loss(q, k, v, t=t):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, -1).astype(q.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))
    def rund(q, k, v):
        def body(i, c):
            gq, gk, gv = gd(c, k, v)
            return c - (1e-6 * gq).astype(c.dtype)
        return jnp.sum(jax.lax.fori_loop(0, iters, body, q).astype(jnp.float32))
    try:
        print(f"T={t} dense: {timed(jax.jit(rund), q, k, v, tokens)/1e3:.1f} ktok/s", flush=True)
    except Exception as e:
        print(f"T={t} dense: FAIL {str(e)[:80]}", flush=True)
