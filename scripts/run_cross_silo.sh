#!/bin/bash
# Launch a full cross-silo federation on one machine: 1 server + W silo
# OS processes over the native TCP transport (or gRPC).
#
# Role parity with the reference's mpirun wrappers
# (fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:21
# does `mpirun -np $PROCESS_NUM ... python3 ./main_fedavg.py`): same
# one-command launch, no MPI required — each rank is a plain python
# process and the rank table is ports, not a hostfile.
#
# Usage:
#   scripts/run_cross_silo.sh <num_silos> [extra main_cross_silo args...]
# Example:
#   scripts/run_cross_silo.sh 3 --model lr --dataset mnist \
#       --comm_round 10 --epochs 1 --lr 0.1 --comm_backend GRPC
set -euo pipefail

W=${1:?usage: run_cross_silo.sh <num_silos> [args...]}
shift
SIZE=$((W + 1))
PORT_BASE=${PORT_BASE:-50100}

pids=()
for rank in $(seq 1 "$W"); do
    python -m fedml_tpu.exp.main_cross_silo \
        --rank "$rank" --size "$SIZE" --port_base "$PORT_BASE" "$@" &
    pids+=($!)
done
# Server in the foreground: its JSON summary line is this script's output.
python -m fedml_tpu.exp.main_cross_silo \
    --rank 0 --size "$SIZE" --port_base "$PORT_BASE" "$@"
status=0
for pid in "${pids[@]}"; do
    wait "$pid" || status=$?
done
exit "$status"
