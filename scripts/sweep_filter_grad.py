"""Measure the LAST unmeasured perf conjecture (r4 VERDICT #2): is the
s2d round's backward residual really "conv filter-gradient tiling"?

docs/ROOFLINE.md closed the s2d attribution with "backward conv-gradient
tiling (a per-shape XLA property we inherit)" — an inference from the
fwd/bwd split (fwd 13.1 ms vs bwd 29.8 ms per round), never timed at the
op level. This script times, per s2d stage shape at the exact bench
batch (8 vmapped clients x 32 = 256 effective conv batch, bf16):

  conv_dw   — the filter-gradient contraction exactly as XLA builds it
              (jax.grad of a linear-in-w conv loss: the forward conv is
              DCE'd, leaving only dW = contract(x, dy))
  gemm_nat  — the SAME contraction phrased as a single GEMM in its
              natural shape [KH*KW*I, B*H*W] @ [B*H*W, O] (im2col-free
              random operands; isolates conv lowering vs plain GEMM)
  gemm_sq   — an ideal-layout square GEMM of IDENTICAL FLOPs (the
              hardware's realistic ceiling for that much work)

Chained iterations inside one jit with a data-dependent scale defeating
loop-invariant hoisting; two-point RTT-cancelling fit with the 0.4 s
device-work floor (same machinery as scripts/sweep_s2d_attrib.py).

Run on the real chip: python scripts/sweep_filter_grad.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
from jax import lax

from _timing import calibrated_ramp

DN = ("NHWC", "HWIO", "NHWC")

# (name, B, H, W, I, O): the s2d resnet56 stage shapes at bench batch.
SHAPES = [
    ("stem 16x16 12->32", 256, 16, 16, 12, 32),
    ("stage1 16x16 32ch", 256, 16, 16, 32, 32),
    ("stage2 8x8 64ch", 256, 8, 8, 64, 64),
    ("stage3 4x4 128ch", 256, 4, 4, 128, 128),
]


def chain(f, out_reduce=jnp.sum):
    """iters chained evaluations of s -> f(s): each iteration's scale
    depends on the previous result, so XLA cannot hoist the op."""
    def run(iters):
        def body(i, acc):
            s = (1.0 + 1e-30 * acc).astype(jnp.bfloat16)
            return out_reduce(f(s)).astype(jnp.float32)
        return jax.lax.fori_loop(0, jnp.int32(iters), body,
                                 jnp.float32(0.0))
    return jax.jit(run)


def measure_shape(name, b, h, w, i, o):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, h, w, i), jnp.bfloat16)
    dy = jnp.asarray(rng.randn(b, h, w, o), jnp.bfloat16)
    w0 = jnp.asarray(rng.randn(3, 3, i, o), jnp.bfloat16)
    flops = 2.0 * b * h * w * 9 * i * o

    def conv_dw(s):
        def loss(wgt):
            out = lax.conv_general_dilated(
                x * s, wgt, (1, 1), "SAME", dimension_numbers=DN)
            return jnp.vdot(out.astype(jnp.float32),
                            dy.astype(jnp.float32))
        return jax.grad(loss)(w0)

    m, k, n = 9 * i, b * h * w, o
    a_nat = jnp.asarray(rng.randn(m, k), jnp.bfloat16)
    b_nat = jnp.asarray(rng.randn(k, n), jnp.bfloat16)

    def gemm_nat(s):
        return (a_nat * s) @ b_nat

    sq = int(np.ceil((flops / 2.0) ** (1 / 3) / 128) * 128)
    a_sq = jnp.asarray(rng.randn(sq, sq), jnp.bfloat16)
    b_sq = jnp.asarray(rng.randn(sq, sq), jnp.bfloat16)
    sq_flops = 2.0 * sq ** 3

    def gemm_sq(s):
        return (a_sq * s) @ b_sq

    row = {"shape": name, "flops_g": round(flops / 1e9, 3)}
    for label, f, fl in [("conv_dw", conv_dw, flops),
                         ("gemm_nat", gemm_nat, flops),
                         ("gemm_sq", gemm_sq, sq_flops)]:
        sec = calibrated_ramp(chain(f))
        row[label + "_us"] = round(sec * 1e6, 2)
        row[label + "_tflops"] = round(fl / sec / 1e12, 2)
    row["dw_vs_nat"] = round(row["conv_dw_us"] / row["gemm_nat_us"], 2)
    row["dw_vs_ideal_eff"] = round(
        row["conv_dw_tflops"] / row["gemm_sq_tflops"], 3)
    return row


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    rows = [measure_shape(*s) for s in SHAPES]
    for r in rows:
        print(r, flush=True)
    total_dw = sum(r["conv_dw_us"] for r in rows)
    total_nat = sum(r["gemm_nat_us"] for r in rows)
    print(f"sum conv_dw {total_dw:.1f} us vs natural-GEMM "
          f"{total_nat:.1f} us per instance "
          f"(ratio {total_dw / total_nat:.2f})", flush=True)


if __name__ == "__main__":
    main()
