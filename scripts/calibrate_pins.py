"""Calibrate the FEMNIST-CNN-shaped and char-LM convergence pins
(r3 VERDICT #4): find the synthetic-task difficulty where the curve at
the reference hyperparameters is non-trivial (not saturated by round 30,
clearly converging by the pinned round count). Run on the CPU mesh."""
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def femnist_curve(alpha, rounds=150):
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import batch_global
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.models.cnn import CNNDropOut

    C, K, batch = 3400, 62, 20
    rng = np.random.RandomState(0)
    counts = np.maximum(4, rng.lognormal(3.0, 0.6, C).astype(int))  # ~22
    tot = int(counts.sum())
    y = rng.randint(0, K, size=tot + 2000).astype(np.int32)
    protos = rng.randn(K, 28, 28, 1).astype(np.float32)
    x_all = (alpha * protos[y]
             + rng.randn(len(y), 28, 28, 1).astype(np.float32))
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(C)}
    store = FederatedStore(x_all[:tot], y[:tot], parts, batch_size=batch)
    test = batch_global(x_all[tot:], y[tot:], 100)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=10,
                    comm_round=rounds, epochs=1, batch_size=batch, lr=0.1,
                    frequency_of_the_test=10_000)
    api = FedAvgAPI(CNNDropOut(num_classes=K), store, test, cfg)
    print(f"alpha={alpha} acc0={api.evaluate()['accuracy']:.3f}", flush=True)
    t0 = time.time()
    for r in range(rounds):
        m = api.train_one_round(r)
        if (r + 1) % 30 == 0:
            print(f"  r{r+1}: loss={m['train_loss']:.3f} "
                  f"acc={api.evaluate()['accuracy']:.3f} "
                  f"({time.time()-t0:.0f}s)", flush=True)


def charlm_curve(peak, rounds=60):
    """peak = probability mass on each symbol's top successor."""
    from functools import partial

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.partition import partition_homo
    from fedml_tpu.models.rnn import RNNOriginalFedAvg
    from fedml_tpu.trainer.local import seq_softmax_ce

    C, T, V, batch = 715, 80, 90, 4
    rng = np.random.RandomState(0)
    # Order-1 Markov chain over symbols 1..V-1 (0 = pad): each symbol has
    # one likely successor (prob ``peak``) and uniform remainder.
    succ = rng.randint(1, V, size=V)
    n_seq = C * 8
    seqs = np.empty((n_seq, T + 1), np.int32)
    state = rng.randint(1, V, size=n_seq)
    for t in range(T + 1):
        seqs[:, t] = state
        follow = rng.rand(n_seq) < peak
        state = np.where(follow, succ[state], rng.randint(1, V, size=n_seq))
    x, y = seqs[:, :T], seqs[:, 1:]
    fed = build_federated_arrays(x, y, partition_homo(n_seq, C), batch)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=10,
                    comm_round=rounds, epochs=1, batch_size=batch, lr=1.0,
                    frequency_of_the_test=10_000)
    api = FedAvgAPI(RNNOriginalFedAvg(vocab_size=V), fed, None, cfg,
                    loss_fn=partial(seq_softmax_ce, pad_id=0))
    # entropy of the chain ~ peak*ln(1/peak) + (1-peak)*ln(V/(1-peak))
    print(f"peak={peak} (chain CE floor ~"
          f"{-peak*np.log(peak)+(1-peak)*np.log((V-1)/(1-peak)):.2f} nats, "
          f"init CE ~ ln({V})={np.log(V):.2f})", flush=True)
    t0 = time.time()
    for r in range(rounds):
        m = api.train_one_round(r)
        if (r + 1) % 10 == 0:
            print(f"  r{r+1}: loss={m['train_loss']:.3f} "
                  f"({time.time()-t0:.0f}s)", flush=True)


if __name__ == "__main__":
    which = sys.argv[1]
    level = float(sys.argv[2])
    rounds = int(sys.argv[3]) if len(sys.argv) > 3 else None
    if which == "femnist":
        femnist_curve(level, rounds or 150)
    else:
        charlm_curve(level, rounds or 60)
