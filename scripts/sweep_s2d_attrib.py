"""Attribute the s2d round's time to fwd / bwd / GN / optimizer+agg.

r3 VERDICT #2: the s2d stem variant measures ~6% MFU against a ~26%
lane-fill ceiling and the residual was closed by conjecture ("bwd-pass
layout tuning and GN fusion") rather than measurement. This script times,
at the exact s2d bench config (8 vmapped clients x 256 samples, B=32,
bf16, 1 local epoch = 8 SGD steps/client):

  full       — the shipped round_fn (fwd+bwd+SGD+shuffle+aggregation)
  fwd_only   — per-step masked loss, no grad (params perturbed by
               eps*loss to defeat loop-invariant hoisting)
  fwd_bwd    — value_and_grad per step, update = p - eps*g (an axpy,
               cost-identical to the real SGD step, so fwd_bwd isolates
               gradient cost, not optimizer cost)
  agg_only   — tree_weighted_mean over the 8 client param stacks
  full_nogn  — full round with Norm swapped for identity (norm="none")
  full_noshuf— full round with the per-epoch reshuffle disabled

and prints a table whose rows decompose the measured round time:
bwd = fwd_bwd - fwd_only, GN = full - full_nogn, shuffle = full -
full_noshuf, plumbing residual = full - fwd_bwd - agg_only.

All timings are chained iterations inside one jit with a DYNAMIC trip
count (no recompile across chain lengths), calibrated per variant so a
timed call carries >=0.4 s of device work — the same machinery as
bench.py's flash sweep (two-point fit cancels the tunnel dispatch RTT).
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from fedml_tpu.models.resnet import resnet56
from fedml_tpu.trainer.local import (NetState, make_local_train_fn,
                                     model_fns, softmax_ce)
from fedml_tpu.parallel.shard import make_vmap_round, client_rngs
from fedml_tpu.core.tree import tree_weighted_mean
import optax

C, S, B = 8, 8, 32          # clients, steps/client, batch
SAMPLES = C * S * B          # per round
FLOOR_S, TARGET_S = 0.4, 0.6
EPS = 1e-38


def calibrated(f, *args):
    """Median seconds/iter of f(*args, iters) with the floor enforced.
    A host scalar fetch ends every call (the only reliable sync through
    the axon tunnel); the two-point fit cancels the dispatch RTT."""
    def call(iters):
        t0 = time.perf_counter()
        out = f(*args, iters)
        float(jnp.asarray(jax.tree.leaves(out)[0]).ravel()[0])
        return time.perf_counter() - t0

    call(1)  # warm/compile
    t1 = min(call(1) for _ in range(2))
    t2 = min(call(5) for _ in range(2))
    per_iter = max((t2 - t1) / 4, 1e-4)
    rtt = max(t1 - per_iter, 0.0)
    for _ in range(4):
        iters = max(1, min(1 << 17, int(np.ceil(TARGET_S / per_iter))))
        meds = sorted(call(iters) for _ in range(5))
        med = meds[2]
        refined = max((med - rtt) / iters, 1e-4)
        if refined * iters >= FLOOR_S:
            return refined
        per_iter = refined
    raise RuntimeError("floor not reached")


def make_data():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(C, S, B, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, (C, S, B)), jnp.int32)
    mask = jnp.ones((C, S, B), jnp.float32)
    w = jnp.ones((C,), jnp.float32)
    return x, y, mask, w


def chain_round(round_fn):
    """Chained full rounds: avg params feed the next round."""
    def run(net, x, y, mask, w, rng, iters):
        def body(i, carry):
            net, rng = carry
            rng, sub = jax.random.split(rng)
            avg, loss = round_fn(net, x, y, mask, w, w, sub)
            return avg, rng
        net, _ = jax.lax.fori_loop(0, iters, body, (net, rng))
        return net.params
    return jax.jit(run)


def chain_clients(client_fn):
    """Chained vmapped per-client passes over a STACKED per-client net
    (the carry stays [C, ...]-shaped across iterations — no aggregation
    in this variant, that is ``agg_only``'s job); params perturbed by
    the pass's output so iterations stay sequentially dependent."""
    def run(net_stacked, x, y, mask, rng, iters):
        def body(i, carry):
            net, rng = carry
            rng, sub = jax.random.split(rng)
            rngs = client_rngs(sub, C, 0)
            new_net = jax.vmap(client_fn)(net, x, y, mask, rngs)
            return new_net, rng
        net, _ = jax.lax.fori_loop(0, iters, body, (net_stacked, rng))
        return net.params
    return jax.jit(run)


def main():
    fns = model_fns(resnet56(num_classes=10, dtype="bf16", stem="s2d"))
    fns_nogn = model_fns(resnet56(num_classes=10, dtype="bf16", stem="s2d",
                                  norm="none"))
    x, y, mask, w = make_data()
    key = jax.random.PRNGKey(0)
    net = fns.init(key, np.zeros((B, 32, 32, 3), np.float32))
    net_nogn = fns_nogn.init(key, np.zeros((B, 32, 32, 3), np.float32))
    opt = optax.sgd(0.1)

    results = {}

    def full_round(fns_, shuffle=True):
        lt = make_local_train_fn(fns_.apply, opt, 1, softmax_ce,
                                 shuffle=shuffle)
        return make_vmap_round(lt)

    fns_fused = model_fns(resnet56(num_classes=10, dtype="bf16",
                                   stem="s2d", norm="gn_fused"))
    # gn and gn_fused share param trees (same names/shapes), so the
    # fused variant reuses net — an identical-numerics A/B.
    # --- full round variants -------------------------------------------
    for name, fns_, n0, shuf in [("full", fns, net, True),
                                 ("full_fusedgn", fns_fused, net, True),
                                 ("full_nogn", fns_nogn, net_nogn, True),
                                 ("full_noshuf", fns, net, False)]:
        f = chain_round(full_round(fns_, shuf))
        results[name] = calibrated(f, n0, x, y, mask, w, key)
        print(f"{name:12s} {results[name]*1e3:8.2f} ms/round "
              f"({SAMPLES/results[name]:,.0f} samples/s)", flush=True)

    # --- fwd-only ------------------------------------------------------
    def fwd_client(net, cx, cy, cmask, rng):
        def step(carry, inp):
            net, rng = carry
            xb, yb, mb = inp
            rng, sub = jax.random.split(rng)
            logits, new_state = fns.apply(net, xb, train=True, rng=sub)
            per = softmax_ce(logits, yb)
            loss = jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0)
            # eps*loss keeps iterations sequentially dependent without
            # changing numerics (denormal-scale perturbation)
            p = jax.tree.map(lambda a: a + EPS * loss, net.params)
            return (NetState(p, new_state), rng), loss
        (net, _), _ = jax.lax.scan(step, (net, rng), (cx, cy, cmask))
        return net

    net_stacked = jax.tree.map(
        lambda p: jnp.stack([p] * C),
        NetState(net.params, net.model_state))
    results["fwd_only"] = calibrated(chain_clients(fwd_client),
                                     net_stacked, x, y, mask, key)
    print(f"{'fwd_only':12s} {results['fwd_only']*1e3:8.2f} ms/round",
          flush=True)

    # --- fwd+bwd (grad, axpy update, no optimizer state) ---------------
    def grad_client(net, cx, cy, cmask, rng):
        def step(carry, inp):
            net, rng = carry
            xb, yb, mb = inp
            rng, sub = jax.random.split(rng)

            def masked_loss(p):
                logits, new_state = fns.apply(
                    NetState(p, net.model_state), xb, train=True, rng=sub)
                per = softmax_ce(logits, yb)
                return (jnp.sum(per * mb)
                        / jnp.maximum(jnp.sum(mb), 1.0)), new_state

            (loss, new_state), g = jax.value_and_grad(
                masked_loss, has_aux=True)(net.params)
            p = jax.tree.map(lambda a, b: a - EPS * b, net.params, g)
            return (NetState(p, new_state), rng), loss
        (net, _), _ = jax.lax.scan(step, (net, rng), (cx, cy, cmask))
        return net

    results["fwd_bwd"] = calibrated(chain_clients(grad_client),
                                    net_stacked, x, y, mask, key)
    print(f"{'fwd_bwd':12s} {results['fwd_bwd']*1e3:8.2f} ms/round",
          flush=True)

    # --- aggregation only ---------------------------------------------
    stacked = jax.tree.map(lambda p: jnp.stack([p] * C), net.params)

    def agg(stacked, w, iters):
        def body(i, st):
            avg = tree_weighted_mean(st, w * (1 + EPS * i))
            return jax.tree.map(lambda s, a: s + EPS * a, st, avg)
        return jax.tree.leaves(jax.lax.fori_loop(0, iters, body, stacked))[0]

    results["agg_only"] = calibrated(jax.jit(agg), stacked, w)
    print(f"{'agg_only':12s} {results['agg_only']*1e3:8.2f} ms/round",
          flush=True)

    # --- the bench path: sampling + cohort gather + whole-run scan -----
    # (what `bench_resnet56_s2d` actually times). Two-point fit over scan
    # lengths cancels the RTT + scan entry cost; the difference vs `full`
    # is the per-round price of on-device subsampled cohort gathering.
    import bench as bench_mod

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI

    fed = bench_mod._synthetic_cifar_fed(128, 256, B)
    cfg = FedConfig(client_num_in_total=128, client_num_per_round=C,
                    comm_round=1, epochs=1, batch_size=B, lr=0.1)
    api = FedAvgAPI(resnet56(num_classes=10, dtype="bf16", stem="s2d"),
                    fed, None, cfg)

    def scan_time(r):
        api.train_rounds_on_device(r)  # compile + warm
        vals = []
        for _ in range(3):
            t0 = time.perf_counter()
            losses = api.train_rounds_on_device(r)
            float(np.asarray(losses).sum())
            vals.append(time.perf_counter() - t0)
        return sorted(vals)[1]

    r1, r2 = 8, 24
    results["bench_path"] = (scan_time(r2) - scan_time(r1)) / (r2 - r1)
    print(f"{'bench_path':12s} {results['bench_path']*1e3:8.2f} ms/round "
          f"({SAMPLES/results['bench_path']:,.0f} samples/s)", flush=True)

    # --- decomposition table ------------------------------------------
    R, F, G = results["full"], results["fwd_only"], results["fwd_bwd"]
    A = results["agg_only"]
    print("\n=== decomposition (ms/round) ===")
    rows = [
        ("forward", F * 1e3, F / R),
        ("backward (fwd_bwd - fwd)", (G - F) * 1e3, (G - F) / R),
        ("aggregation", A * 1e3, A / R),
        ("optimizer+shuffle+plumbing (residual)", (R - G - A) * 1e3,
         (R - G - A) / R),
        ("TOTAL (= full round)", R * 1e3, 1.0),
    ]
    for name, ms, frac in rows:
        print(f"{name:40s} {ms:8.2f} ms  {frac*100:5.1f}%")
    print("\n=== ablations (ms/round) ===")
    print(f"{'GN cost (full - full_nogn)':40s} "
          f"{(R - results['full_nogn'])*1e3:8.2f} ms "
          f"{(R - results['full_nogn'])/R*100:5.1f}%")
    print(f"{'shuffle cost (full - full_noshuf)':40s} "
          f"{(R - results['full_noshuf'])*1e3:8.2f} ms "
          f"{(R - results['full_noshuf'])/R*100:5.1f}%")
    bp = results["bench_path"]
    print(f"{'cohort gather+scan (bench_path - full)':40s} "
          f"{(bp - R)*1e3:8.2f} ms {(bp - R)/bp*100:5.1f}% of bench round")
    print(f"\nfull round: {SAMPLES/R:,.0f} samples/s; bench path: "
          f"{SAMPLES/bp:,.0f} samples/s; fwd:bwd ratio 1:{(G-F)/F:.2f}")


if __name__ == "__main__":
    main()
