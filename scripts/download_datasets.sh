#!/bin/bash
# Dataset acquisition — consolidated equivalent of the reference's
# per-dataset data/*/download_*.sh scripts (e.g. data/MNIST/
# download_and_unzip.sh, data/fed_cifar100/download_fedcifar100.sh).
# Produces the DATA_ROOT tree the loaders and scripts/
# reproduce_baselines.sh expect (docs/DATASETS.md; REPRO.md).
#
# Usage:
#   DATA_ROOT=/data scripts/download_datasets.sh            # everything
#   DATA_ROOT=/data scripts/download_datasets.sh mnist femnist
#
# Sources are the ones the reference pins: the FedML S3 mirrors of the
# TFF h5 splits, LEAF Google-Drive archives, and the datasets' canonical
# hosts. Requires network access (this script is the one component that
# cannot run in a zero-egress environment — everything else degrades to
# synthetic same-shape data). Idempotent: completed artifacts are kept
# and skipped on re-run; partial downloads are never cached (temp-name +
# mv on success).
set -euo pipefail

DATA_ROOT=${DATA_ROOT:?set DATA_ROOT to the dataset destination directory}
mkdir -p "$DATA_ROOT"
DATA_ROOT=$(cd "$DATA_ROOT" && pwd)  # absolute: do_* helpers cd around

S3=https://fedml.s3-us-west-1.amazonaws.com

fetch() { # fetch <url> <dest-file> — atomic: partials never cached
  [ -f "$2" ] && { echo "have $2"; return; }
  wget --no-check-certificate -O "$2.part" "$1"
  mv "$2.part" "$2"
}

gdrive() { # gdrive <file-id> <dest-file> — Drive's big-file confirm dance
  [ -f "$2" ] && { echo "have $2"; return; }
  local confirm url
  confirm=$(wget --quiet --save-cookies /tmp/gd_cookies.txt \
    --keep-session-cookies --no-check-certificate \
    "https://docs.google.com/uc?export=download&id=$1" -O- |
    sed -rn 's/.*confirm=([0-9A-Za-z_]+).*/\1/p' | head -1)
  # Empty confirm: small file (served directly) or a changed interstitial
  # — try the plain export URL and verify we did not save an HTML page.
  url="https://docs.google.com/uc?export=download&confirm=${confirm:-t}&id=$1"
  wget --load-cookies /tmp/gd_cookies.txt --no-check-certificate \
    "$url" -O "$2.part"
  rm -f /tmp/gd_cookies.txt
  if head -c 256 "$2.part" | grep -qi "<html"; then
    rm -f "$2.part"
    echo "ERROR: Google Drive returned an HTML page for id=$1 (quota or" \
         "changed download flow); fetch it manually to $2" >&2
    return 1
  fi
  mv "$2.part" "$2"
}

untar_into() { # untar_into <archive> <dir>
  mkdir -p "$2" && tar -xf "$1" -C "$2"
}

do_mnist() { # LEAF power-law MNIST (1000 clients)
  mkdir -p "$DATA_ROOT/MNIST" && cd "$DATA_ROOT/MNIST"
  gdrive 1cU_LcBAUZvfZWveOMhG4G5Fg9uFXhVdf MNIST.zip  # kept: re-run guard
  unzip -o MNIST.zip
  rm -rf train test
  mv mnist/train train && mv mnist/test test
  rm -rf mnist
}

do_femnist() { # TFF FederatedEMNIST h5 (3400 writers)
  mkdir -p "$DATA_ROOT/FederatedEMNIST" && cd "$DATA_ROOT/FederatedEMNIST"
  fetch "$S3/fed_emnist.tar.bz2" fed_emnist.tar.bz2
  untar_into fed_emnist.tar.bz2 datasets
}

do_fed_cifar100() { # TFF CIFAR-100 h5 (500/100 clients)
  mkdir -p "$DATA_ROOT/fed_cifar100" && cd "$DATA_ROOT/fed_cifar100"
  fetch "$S3/fed_cifar100.tar.bz2" fed_cifar100.tar.bz2
  untar_into fed_cifar100.tar.bz2 datasets
}

do_fed_shakespeare() { # TFF Shakespeare h5
  mkdir -p "$DATA_ROOT/fed_shakespeare" && cd "$DATA_ROOT/fed_shakespeare"
  fetch "$S3/shakespeare.tar.bz2" shakespeare.tar.bz2
  untar_into shakespeare.tar.bz2 datasets
}

do_shakespeare() { # LEAF Shakespeare JSON (715 roles)
  mkdir -p "$DATA_ROOT/shakespeare/train" "$DATA_ROOT/shakespeare/test"
  cd "$DATA_ROOT/shakespeare"
  gdrive 1mD6_4ju7n2WFAahMKDtozaGxUASaHAPH \
    train/all_data_niid_2_keep_0_train_8.json
  gdrive 1GERQ9qEJjXk_0FXnw1JbjuGCI-zmmfsk \
    test/all_data_niid_2_keep_0_test_8.json
}

do_stackoverflow() { # TFF StackOverflow h5 + vocab side files (342k users)
  mkdir -p "$DATA_ROOT/stackoverflow" && cd "$DATA_ROOT/stackoverflow"
  local f
  for f in stackoverflow.tar.bz2 stackoverflow.word_count.tar.bz2 \
           stackoverflow.tag_count.tar.bz2; do
    fetch "$S3/$f" "$f"
    untar_into "$f" datasets
  done
  fetch "$S3/stackoverflow_nwp.pkl" datasets/stackoverflow_nwp.pkl
}

do_cifar10() {
  mkdir -p "$DATA_ROOT/cifar10" && cd "$DATA_ROOT/cifar10"
  fetch https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz \
    cifar-10-python.tar.gz
  tar -xzf cifar-10-python.tar.gz
}

do_cifar100() {
  mkdir -p "$DATA_ROOT/cifar100" && cd "$DATA_ROOT/cifar100"
  fetch https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz \
    cifar-100-python.tar.gz
  tar -xzf cifar-100-python.tar.gz
}

do_cinic10() {
  mkdir -p "$DATA_ROOT/cinic10" && cd "$DATA_ROOT/cinic10"
  fetch https://datashare.is.ed.ac.uk/bitstream/handle/10283/3192/CINIC-10.tar.gz \
    CINIC-10.tar.gz
  tar -xzf CINIC-10.tar.gz
}

do_gld() { # Google Landmarks federated splits (gld23k/gld160k csv maps)
  mkdir -p "$DATA_ROOT/gld" && cd "$DATA_ROOT/gld"
  fetch https://fedcv.s3-us-west-1.amazonaws.com/landmark/data_user_dict.zip \
    data_user_dict.zip
  fetch https://fedcv.s3-us-west-1.amazonaws.com/landmark/images.zip images.zip
  unzip -o data_user_dict.zip && unzip -o images.zip
}

ALL=(mnist femnist fed_cifar100 fed_shakespeare shakespeare stackoverflow
     cifar10 cifar100 cinic10 gld)
TARGETS=("${@:-}")
[ ${#TARGETS[@]} -eq 0 ] || [ -z "${TARGETS[0]}" ] && TARGETS=("${ALL[@]}")

for t in "${TARGETS[@]}"; do
  echo "=== $t -> $DATA_ROOT"
  ( "do_$t" )  # subshell: each helper's cd cannot leak into the next
done
echo "datasets ready under $DATA_ROOT"
