"""Calibrate the FedProx and FedOpt reference-scale pins (r4 VERDICT #3).

Run on the 8-device CPU mesh:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/calibrate_prox_opt_pins.py [prox|opt]

Prints the loss curves for each arm so the pin thresholds in
tests/test_repro_convergence.py are measured numbers, not hopes — the
same method the r4 pins used (module docstring there records the
calibration sweeps).

FedProx arm: the Shakespeare char-LM regime (2-layer LSTM, batch 4, SGD
lr 1.0 — BASELINE.md row hyperparameters) with heterogeneity BOOSTED:
clients are split into KGROUP disjoint order-1 Markov chains with
different successor tables, so sampled cohorts pull the global model
toward incompatible local optima. μ is the drift control; the pin
asserts the documented FedProx effect (μ>0 tightens late-round loss
variance and does not lose final loss) at reference scale.

FedOpt arm: the FEMNIST-CNN row's task shape (62-class CNNDropOut,
batch 20, 10/round) with client lr and task separation tuned so plain
FedAvg descends SLOWLY — the regime "Adaptive Federated Optimization"
(Reddi'20) targets — and server-Adam at the reference's --server_lr 0.1
(main_fedopt.py:54-60; adam eps=1e-3 per the paper) must descend
measurably faster by the asserted round.
"""

import sys
import time
from functools import partial

import numpy as np


def charlm_hetero_fed(C=256, T=80, V=90, batch=4, kgroup=8, seqs_per_client=8,
                      peak=0.95, seed=0):
    """Heterogeneity-boosted char-LM federation: kgroup disjoint successor
    tables; client c follows table c % kgroup."""
    from fedml_tpu.data.batching import build_federated_arrays

    rng = np.random.RandomState(seed)
    succ = rng.randint(1, V, size=(kgroup, V))
    n_seq = C * seqs_per_client
    group = (np.arange(n_seq) // seqs_per_client) % kgroup
    seqs = np.empty((n_seq, T + 1), np.int32)
    state = rng.randint(1, V, size=n_seq)
    for t in range(T + 1):
        seqs[:, t] = state
        follow = rng.rand(n_seq) < peak
        state = np.where(follow, succ[group, state],
                         rng.randint(1, V, size=n_seq))
    parts = {c: np.arange(c * seqs_per_client, (c + 1) * seqs_per_client)
             for c in range(C)}
    return build_federated_arrays(seqs[:, :T], seqs[:, 1:], parts, batch)


def run_prox(mu, rounds=40, epochs=2, C=256):
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedprox import FedProxAPI
    from fedml_tpu.models.rnn import RNNOriginalFedAvg
    from fedml_tpu.trainer.local import seq_softmax_ce

    fed = charlm_hetero_fed(C=C)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=10,
                    comm_round=rounds, epochs=epochs, batch_size=4, lr=1.0,
                    fedprox_mu=mu, frequency_of_the_test=10_000)
    api = FedProxAPI(RNNOriginalFedAvg(vocab_size=90), fed, None, cfg,
                     loss_fn=partial(seq_softmax_ce, pad_id=0))
    losses = [api.train_one_round(r)["train_loss"] for r in range(rounds)]
    return np.asarray(losses)


def femnist_shaped(C=200, K=62, batch=20, alpha=0.4, per=22, seed=0):
    from fedml_tpu.data.batching import batch_global
    from fedml_tpu.data.store import FederatedStore

    rng = np.random.RandomState(seed)
    counts = np.maximum(4, rng.lognormal(np.log(per), 0.5, C).astype(int))
    tot = int(counts.sum())
    y = rng.randint(0, K, size=tot + 2000).astype(np.int32)
    protos = rng.randn(K, 28, 28, 1).astype(np.float32)
    x_all = alpha * protos[y] + rng.randn(len(y), 28, 28, 1).astype(np.float32)
    edges = np.concatenate([[0], np.cumsum(counts)])
    parts = {c: np.arange(edges[c], edges[c + 1]) for c in range(C)}
    store = FederatedStore(x_all[:tot], y[:tot], parts, batch_size=batch)
    test = batch_global(x_all[tot:], y[tot:], 100)
    return store, test


def run_opt(server, rounds=40, lr=0.03, server_lr=0.1, alpha=0.4):
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.algos.fedopt import FedOptAPI
    from fedml_tpu.models.cnn import CNNDropOut

    store, test = femnist_shaped(alpha=alpha)
    cfg = FedConfig(client_num_in_total=200, client_num_per_round=10,
                    comm_round=rounds, epochs=1, batch_size=20, lr=lr,
                    server_optimizer=server, server_lr=server_lr,
                    frequency_of_the_test=10_000)
    cls = FedAvgAPI if server == "none" else FedOptAPI
    api = cls(CNNDropOut(num_classes=62), store, test, cfg)
    losses = [api.train_one_round(r)["train_loss"] for r in range(rounds)]
    return np.asarray(losses), api.evaluate()["accuracy"]


def fmt(a):
    return "[" + ", ".join(f"{v:.3f}" for v in a) + "]"


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("prox", "both"):
        for mu in [0.0, 0.01, 0.1]:
            t0 = time.time()
            ls = run_prox(mu)
            late = ls[-10:]
            print(f"prox mu={mu}: final10 mean={late.mean():.4f} "
                  f"std={late.std():.4f} max={late.max():.4f} "
                  f"curve10={fmt(ls[::4])} ({time.time()-t0:.0f}s)",
                  flush=True)
    if which in ("opt", "both"):
        for server in ["none", "adam"]:
            t0 = time.time()
            ls, acc = run_opt(server)
            print(f"opt server={server}: acc={acc:.4f} "
                  f"loss@10={ls[9]:.3f} loss@20={ls[19]:.3f} "
                  f"loss@40={ls[-1]:.3f} curve={fmt(ls[::4])} "
                  f"({time.time()-t0:.0f}s)", flush=True)
