"""Calibrate the FedProx and FedOpt reference-scale pins (r4 VERDICT #3).

Usage (runs on whatever backend is live — the sweeps below were run on
the real v5e, ~40x faster per arm than the 1-core CPU mesh; the final
thresholds were then validated once on the 8-device CPU mesh, the
suite's environment):

  python scripts/calibrate_prox_opt_pins.py prox [epochs peak kgroup cpr rounds per]
  python scripts/calibrate_prox_opt_pins.py opt  [lr alpha rounds server_lr per maxper]

The shipped pins were calibrated with:
  prox 6 0.98 16 10 12 4        (and the 2x-work cross-check: ... 24 8)
  opt  0.003 1.0 30 0.05 22 20

Prints per-arm loss curves AND the pin observables so the thresholds in
tests/test_repro_convergence.py are measured numbers, not hopes — the
same method the r4 pins used.

FedProx arm: the Shakespeare char-LM regime (2-layer LSTM, batch 4, SGD
lr 1.0 — BASELINE.md row hyperparameters) with heterogeneity BOOSTED:
clients split over KGROUP disjoint order-1 Markov chains, so sampled
cohorts pull toward incompatible optima. The pin observable is DRIFT:
``w_{t+1} − w_t = avg_c(w_c − w_t)``, so the global update norm is the
cohort-average client drift — the exact quantity μ penalizes. Measured
(v5e 2026-07-31, E=6 peak=0.98 k=16 cpr=10, 24 rounds): mean drift
1.538 (μ=0) / 1.467 (μ=0.01) / 1.048 (μ=0.1) — monotone, 0.68 ratio at
μ=0.1, with bounded CE cost (final-5: 1.03 vs 1.64). Earlier attempts
that asserted LOSS variance failed both directions: sampled-cohort loss
reads HIGHER variance under μ>0 (clients held near the compromise model
score worse on their own chain), so it is the wrong observable.

FedOpt arm: the FEMNIST-CNN task shape (62-class CNNDropOut, batch 20,
10/round) in the Reddi'20 regime — client steps too small to progress
alone (SGD lr 0.003), server-Adam (eps 1e-3 per the paper) re-scales
the pseudo-gradient per-coordinate and learns. Measured (v5e
2026-07-31): at the pin's config (alpha=1.0, maxper=20, server_lr
0.05) FedAvg is near chance through 30 rounds (acc 0.058) vs Adam acc
0.33; the uncapped alpha=0.6 / server_lr 0.03 variant reaches Adam acc
0.22 @ 40 / 0.49 @ 60 vs FedAvg 0.018. Negative results kept for the
record: at the flag-default server_lr 0.1, server-Adam does NOT
descend at any client lr tried (0.003/0.0316/0.1); at client lr 0.1
plain FedAvg learns and needs no server optimizer.
"""

import sys
import time
from functools import partial

import numpy as np


def charlm_hetero_fed(C=256, batch=4, kgroup=8, seqs_per_client=8,
                      peak=0.95, seed=0):
    from fedml_tpu.data.batching import build_federated_arrays
    from fedml_tpu.data.synthetic import make_hetero_charlm

    x, y, parts = make_hetero_charlm(
        n_clients=C, kgroup=kgroup, seqs_per_client=seqs_per_client,
        peak=peak, seed=seed)
    return build_federated_arrays(x, y, parts, batch)


def run_prox(mu, rounds=40, epochs=2, C=256, kgroup=8, peak=0.95, cpr=10,
             per=8):
    import jax

    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedprox import FedProxAPI
    from fedml_tpu.models.rnn import RNNOriginalFedAvg
    from fedml_tpu.trainer.local import seq_softmax_ce

    fed = charlm_hetero_fed(C=C, kgroup=kgroup, peak=peak,
                            seqs_per_client=per)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=cpr,
                    comm_round=rounds, epochs=epochs, batch_size=4, lr=1.0,
                    fedprox_mu=mu, frequency_of_the_test=10_000)
    api = FedProxAPI(RNNOriginalFedAvg(vocab_size=90), fed, None, cfg,
                     loss_fn=partial(seq_softmax_ce, pad_id=0))

    def flat(net):
        return np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(net.params)])

    losses, dnorms, prev = [], [], flat(api.net)
    for r in range(rounds):
        losses.append(api.train_one_round(r)["train_loss"])
        cur = flat(api.net)
        # ||w_{t+1} - w_t|| = ||avg_c(w_c - w_t)||: the global update
        # norm IS the cohort-average client drift — the quantity mu
        # penalizes, measured from outside the API.
        dnorms.append(float(np.linalg.norm(cur - prev)))
        prev = cur
    return np.asarray(losses), np.asarray(dnorms)


def femnist_shaped(C=200, batch=20, alpha=0.4, per=22, seed=0,
                   maxper=None):
    from fedml_tpu.data.batching import batch_global
    from fedml_tpu.data.store import FederatedStore
    from fedml_tpu.data.synthetic import make_femnist_shaped

    x, y, parts, xt, yt = make_femnist_shaped(
        n_clients=C, alpha=alpha, per=per, maxper=maxper, seed=seed)
    store = FederatedStore(x, y, parts, batch_size=batch)
    return store, batch_global(xt, yt, 100)


def run_opt(server, rounds=40, lr=0.03, server_lr=0.1, alpha=0.4, per=22,
            maxper=None):
    from fedml_tpu.algos.config import FedConfig
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.algos.fedopt import FedOptAPI
    from fedml_tpu.models.cnn import CNNDropOut

    store, test = femnist_shaped(alpha=alpha, per=per, maxper=maxper)
    cfg = FedConfig(client_num_in_total=200, client_num_per_round=10,
                    comm_round=rounds, epochs=1, batch_size=20, lr=lr,
                    server_optimizer=server, server_lr=server_lr,
                    frequency_of_the_test=10_000)
    cls = FedAvgAPI if server == "none" else FedOptAPI
    api = cls(CNNDropOut(num_classes=62), store, test, cfg)
    losses = [api.train_one_round(r)["train_loss"] for r in range(rounds)]
    return np.asarray(losses), api.evaluate()["accuracy"]


def fmt(a):
    return "[" + ", ".join(f"{v:.3f}" for v in a) + "]"


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else ""
    if which not in ("prox", "opt"):
        sys.exit("usage: calibrate_prox_opt_pins.py prox|opt [args] "
                 "(the two modes take different positional args; "
                 "no combined mode)")
    if which == "prox":
        epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 2
        peak = float(sys.argv[3]) if len(sys.argv) > 3 else 0.95
        kgroup = int(sys.argv[4]) if len(sys.argv) > 4 else 8
        cpr = int(sys.argv[5]) if len(sys.argv) > 5 else 10
        rounds = int(sys.argv[6]) if len(sys.argv) > 6 else 40
        per = int(sys.argv[7]) if len(sys.argv) > 7 else 8
        for mu in [0.0, 0.01, 0.1]:
            t0 = time.time()
            ls, dn = run_prox(mu, epochs=epochs, peak=peak, kgroup=kgroup,
                              cpr=cpr, rounds=rounds, per=per)
            late = ls[-10:]
            print(f"prox mu={mu} E={epochs} peak={peak} k={kgroup} cpr={cpr}: "
                  f"final10 mean={late.mean():.4f} "
                  f"std={late.std():.4f} max={late.max():.4f} "
                  f"drift10={dn[-10:].mean():.4f} driftall={dn.mean():.4f} "
                  f"drift4on={dn[4:].mean():.4f} last5={fmt(ls[-5:])} "
                  f"curve10={fmt(ls[::4])} ({time.time()-t0:.0f}s)",
                  flush=True)
    if which == "opt":
        lr = float(sys.argv[2]) if len(sys.argv) > 2 else 0.03
        alpha = float(sys.argv[3]) if len(sys.argv) > 3 else 0.4
        rounds = int(sys.argv[4]) if len(sys.argv) > 4 else 40
        server_lr = float(sys.argv[5]) if len(sys.argv) > 5 else 0.1
        per = int(sys.argv[6]) if len(sys.argv) > 6 else 22
        maxper = int(sys.argv[7]) if len(sys.argv) > 7 else None
        for server in ["none", "adam"]:
            t0 = time.time()
            ls, acc = run_opt(server, rounds=rounds, lr=lr,
                              server_lr=server_lr, alpha=alpha, per=per,
                              maxper=maxper)
            print(f"opt server={server} lr={lr} a={alpha} slr={server_lr}: acc={acc:.4f} "
                  f"loss@10={ls[min(9, len(ls)-1)]:.3f} loss@20={ls[min(19, len(ls)-1)]:.3f} "
                  f"loss@40={ls[-1]:.3f} curve={fmt(ls[::4])} "
                  f"({time.time()-t0:.0f}s)", flush=True)
