"""Calibrate the FedProx and FedOpt reference-scale pins (r4 VERDICT #3).

Usage (runs on whatever backend is live — the sweeps below were run on
the real v5e, ~40x faster per arm than the 1-core CPU mesh; the final
thresholds were then validated once on the 8-device CPU mesh, the
suite's environment):

  python scripts/calibrate_prox_opt_pins.py prox [epochs peak kgroup cpr rounds per]
  python scripts/calibrate_prox_opt_pins.py opt  [lr alpha rounds server_lr per maxper]

The shipped pins were calibrated with:
  prox 6 0.98 16 10 12 4        (and the 2x-work cross-check: ... 24 8)
  opt  0.003 1.0 30 0.05 22 20

Prints per-arm loss curves AND the pin observables so the thresholds in
tests/test_repro_convergence.py are measured numbers, not hopes — the
same method the r4 pins used. The run harness itself is SHARED with
the pins (tests/pin_harness.py), so sweep and suite cannot silently
diverge.

FedProx arm: the Shakespeare char-LM regime (2-layer LSTM, batch 4, SGD
lr 1.0 — BASELINE.md row hyperparameters) with heterogeneity BOOSTED:
clients split over KGROUP disjoint order-1 Markov chains, so sampled
cohorts pull toward incompatible optima. The pin observable is DRIFT:
``w_{t+1} − w_t = avg_c(w_c − w_t)``, so the global update norm is the
cohort-average client drift — the exact quantity μ penalizes. Measured
(v5e 2026-07-31, E=6 peak=0.98 k=16 cpr=10, 24 rounds): mean drift
1.538 (μ=0) / 1.467 (μ=0.01) / 1.048 (μ=0.1) — monotone, 0.68 ratio at
μ=0.1, with bounded CE cost (final-5: 1.03 vs 1.64). Earlier attempts
that asserted LOSS variance failed both directions: sampled-cohort loss
reads HIGHER variance under μ>0 (clients held near the compromise model
score worse on their own chain), so it is the wrong observable.

FedOpt arm: the FEMNIST-CNN task shape (62-class CNNDropOut, batch 20,
10/round) in the Reddi'20 regime — client steps too small to progress
alone (SGD lr 0.003), server-Adam (eps 1e-3 per the paper) re-scales
the pseudo-gradient per-coordinate and learns. Measured (v5e
2026-07-31): at the pin's config (alpha=1.0, maxper=20, server_lr
0.05) FedAvg is near chance through 30 rounds (acc 0.058) vs Adam acc
0.33; the uncapped alpha=0.6 / server_lr 0.03 variant reaches Adam acc
0.22 @ 40 / 0.49 @ 60 vs FedAvg 0.018. Negative results kept for the
record: at the flag-default server_lr 0.1, server-Adam does NOT
descend at any client lr tried (0.003/0.0316/0.1); at client lr 0.1
plain FedAvg learns and needs no server optimizer.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests"))
from pin_harness import run_opt, run_prox  # noqa: E402  (shared harness)


def fmt(a):
    return "[" + ", ".join(f"{v:.3f}" for v in a) + "]"


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else ""
    if which not in ("prox", "opt"):
        sys.exit("usage: calibrate_prox_opt_pins.py prox|opt [args] "
                 "(the two modes take different positional args; "
                 "no combined mode)")
    if which == "prox":
        epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 2
        peak = float(sys.argv[3]) if len(sys.argv) > 3 else 0.95
        kgroup = int(sys.argv[4]) if len(sys.argv) > 4 else 8
        cpr = int(sys.argv[5]) if len(sys.argv) > 5 else 10
        rounds = int(sys.argv[6]) if len(sys.argv) > 6 else 40
        per = int(sys.argv[7]) if len(sys.argv) > 7 else 8
        for mu in [0.0, 0.01, 0.1]:
            t0 = time.time()
            ls, dn = run_prox(mu, epochs=epochs, peak=peak, kgroup=kgroup,
                              cpr=cpr, rounds=rounds, per=per)
            late = ls[-10:]
            print(f"prox mu={mu} E={epochs} peak={peak} k={kgroup} cpr={cpr}: "
                  f"final10 mean={late.mean():.4f} "
                  f"std={late.std():.4f} max={late.max():.4f} "
                  f"drift10={dn[-10:].mean():.4f} driftall={dn.mean():.4f} "
                  f"drift4on={dn[4:].mean():.4f} last5={fmt(ls[-5:])} "
                  f"curve10={fmt(ls[::4])} ({time.time()-t0:.0f}s)",
                  flush=True)
    if which == "opt":
        lr = float(sys.argv[2]) if len(sys.argv) > 2 else 0.03
        alpha = float(sys.argv[3]) if len(sys.argv) > 3 else 0.4
        rounds = int(sys.argv[4]) if len(sys.argv) > 4 else 40
        server_lr = float(sys.argv[5]) if len(sys.argv) > 5 else 0.1
        per = int(sys.argv[6]) if len(sys.argv) > 6 else 22
        maxper = int(sys.argv[7]) if len(sys.argv) > 7 else None
        for server in ["none", "adam"]:
            t0 = time.time()
            ls, acc = run_opt(server, rounds=rounds, lr=lr,
                              server_lr=server_lr, alpha=alpha, per=per,
                              maxper=maxper)
            print(f"opt server={server} lr={lr} a={alpha} slr={server_lr}: acc={acc:.4f} "
                  f"loss@10={ls[min(9, len(ls)-1)]:.3f} loss@20={ls[min(19, len(ls)-1)]:.3f} "
                  f"loss@40={ls[-1]:.3f} curve={fmt(ls[::4])} "
                  f"({time.time()-t0:.0f}s)", flush=True)
