#!/bin/bash
# One-command simulated-federation launch — the reference's
# run_fedavg_standalone_pytorch.sh role (CI-script-fedavg.sh:32-37 style
# positional-free invocation) for the on-device simulator.
#
# Usage:
#   scripts/run_simulation.sh <algorithm> [runner args...]
# Examples:
#   scripts/run_simulation.sh FedAvg --model resnet56 --dataset cifar10 \
#       --client_num_in_total 10 --comm_round 100
#   scripts/run_simulation.sh Scaffold --model lr --dataset mnist
#   scripts/run_simulation.sh FedOpt --server_optimizer adam --num_devices 8
set -euo pipefail

ALGO=${1:?usage: run_simulation.sh <algorithm> [args...]}
shift
exec python -m fedml_tpu.exp.run --algorithm "$ALGO" "$@"
